"""Benchmark: MD-step throughput (atoms/sec/chip) for MACE on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures steady-state post-compile MD steps in the framework's production
configuration: Verlet skin-radius graph reuse (BENCH_SKIN, default 0.5 Å) —
host rebuilds amortize across steps exactly as in a real MD run. Set
BENCH_SKIN=0 to time the reference-style rebuild-every-step pipeline
(reference pes.py:50-146). Throughput is divided by the device count.
vs_baseline compares against BASELINE_LOCAL.json when present (reference
numbers are not published in-repo, see BASELINE.md).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

_METRIC = "mace_mp0_md_step_atoms_per_sec_per_chip"

# Wedge-state telemetry published in the JSON artifact on EVERY exit path
# (success, structured failure, watchdog firing) so a chip-starved round is
# machine-distinguishable from a perf regression (VERDICT r4 item 9).
_TELEMETRY = {
    "probe_attempts": 0,     # canary launches this run
    "wedge_suspected": False,  # a canary neither exited nor failed in budget
    "canary": "not_run",     # not_run | ok | unavailable | killed
    "wedge_reprobes": 0,     # bounded re-probes after a wedged canary
}


def _result_json(value, vs=0.0, error=None, **extra):
    out = {
        "metric": _METRIC,
        "value": round(float(value), 1),
        "unit": "atoms/s",
        "vs_baseline": round(float(vs), 3),
    }
    if error:
        out["error"] = error
    out.update(_TELEMETRY)
    out.update(extra)
    return json.dumps(out)


def _vs_baseline(atoms_per_sec):
    base_path = os.path.join(os.path.dirname(__file__), "BASELINE_LOCAL.json")
    if os.path.exists(base_path):
        ref = json.load(open(base_path)).get("mace_mp0_md_atoms_per_sec")
        if ref:
            return atoms_per_sec / ref
    return 0.0


class _Watchdog:
    """Deadline watchdog guaranteeing the bench always self-exits with JSON.

    The round-3 failure mode: `jax.devices()` on a wedged axon chip grant
    neither raises nor returns — it HANGS, defeating the retry loop, so the
    driver timeout-kills the process with no JSON emitted (BENCH_r03 rc=124,
    parsed=null) and the SIGKILL of a mid-claim process renews the wedge.

    Two deadlines run at once: a per-phase budget (claim, setup, warmup,
    each step — re-armed as phases progress, so a hang is caught quickly
    with a phase-specific message) and a GLOBAL budget from process start
    (BENCH_TOTAL_TIMEOUT_S, default 1200 s) so a degraded-but-not-hung run
    that stays under every per-phase budget still self-exits before the
    driver's kill window (observed > 25 min). Firing and finish() are
    serialized under one lock, so a success line and a watchdog line can
    never both be printed. If measured steps completed before the firing,
    their median is reported as a partial result instead of 0.0.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._deadline = None
        self._msg = ""
        self._finished = False
        total = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "1200"))
        self._global_deadline = time.monotonic() + total
        self._global_msg = f"total run exceeded {total:.0f}s"
        # main() publishes measurement context here for partial reporting
        self.times = []
        self.n_atoms = 0
        self.n_devices = 1
        self._stop = threading.Event()
        threading.Thread(target=self._run, daemon=True).start()

    def phase(self, msg, budget_s):
        with self._lock:
            self._msg = msg
            self._deadline = time.monotonic() + budget_s

    def finish(self):
        """Atomically disarm: after this returns, the watchdog can no longer
        print (a firing in progress would have os._exit'd before the lock
        was released to us)."""
        with self._lock:
            self._finished = True
        self._stop.set()

    def _fire(self, msg):
        if self.times and self.n_atoms:
            dt = float(np.median(self.times))
            aps = self.n_atoms / dt / max(self.n_devices, 1)
            line = _result_json(
                aps, _vs_baseline(aps),
                error=f"watchdog: {msg}; partial result from "
                      f"{len(self.times)} completed steps",
                partial=True)
        else:
            line = _result_json(0.0, error=f"watchdog: {msg}")
        print(line, flush=True)
        sys.stderr.flush()
        # exit 0 so the artifact parses and the driver never SIGKILLs a
        # mid-claim process (which re-wedges the chip)
        os._exit(0)

    def _run(self):
        while not self._stop.wait(1.0):
            with self._lock:
                if self._finished:
                    return
                now = time.monotonic()
                if now > self._global_deadline:
                    self._fire(self._global_msg)
                if self._deadline is not None and now > self._deadline:
                    self._fire(self._msg)


# The canary is tools/probe_canary.py — the single chip-probe
# implementation shared with tools/tpu_probe_forever.sh: it claims the
# chip, runs one tiny matmul, writes the /tmp/tpu_up marker (so a waiting
# tools/when_up.sh battery fires too), and exits 0. Tests inject an inline
# snippet via _CANARY_SRC instead.
_CANARY_SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "probe_canary.py")
_CANARY_SRC = None

_CANARY_LOG = os.environ.get("BENCH_CANARY_LOG", "/tmp/bench_canary.log")


def _launch_canary():
    """Start the disposable canary subprocess (own session, file-backed
    output so an orphaned canary never SIGPIPEs; inherit the environment —
    never pass env= dicts while axon is live)."""
    cmd = ([sys.executable, "-c", _CANARY_SRC] if _CANARY_SRC
           else [sys.executable, _CANARY_SCRIPT])
    with open(_CANARY_LOG, "ab") as log:
        return subprocess.Popen(
            cmd, stdout=log, stderr=log,
            start_new_session=True)  # survives parent process-group kill


def _canary_claim(watchdog):
    """Probe the chip grant with a DISPOSABLE subprocess before claiming.

    The canary/kill/re-probe machinery (round-4/5/6 lessons: a wedged
    grant HANGS the claim, the parent must never die mid-claim, a stuck
    canary must be killed not leaked, and one bounded re-probe may
    recover a kill-released lease) lives in
    ``distmlip_tpu.utils.health.CanaryProber`` — shared with the serving
    fleet's replica-health monitor. Budgets come from the BENCH_* env
    knobs (``ProbeConfig.from_env``), telemetry lands in ``_TELEMETRY``.

    Returns (ok: bool, detail: str). Never raises.
    """
    from distmlip_tpu.utils.health import CanaryProber

    return CanaryProber(_launch_canary, telemetry=_TELEMETRY,
                        phase=watchdog.phase, log_path=_CANARY_LOG).run()


def _claim_backend(watchdog):
    """Canary-gated backend init: in-process claim only after a healthy probe.

    On canary failure returns (None, detail) so main() emits a structured
    "backend unavailable" JSON (with wedge telemetry) instead of rc=1 — and,
    crucially, without this process ever starting a claim it might die in.
    With BENCH_CANARY=0 (escape hatch) the pre-round-5 behavior applies:
    claim in-process under the full BENCH_CLAIM_TIMEOUT_S with retries for
    transient refusals (round-2 lesson).
    """
    use_canary = os.environ.get("BENCH_CANARY", "1") != "0"
    if use_canary:
        ok, detail = _canary_claim(watchdog)
        if not ok:
            return None, detail
        # the grant just served the canary; a hang here is unexpected but
        # the watchdog still covers it
        budget = float(os.environ.get("BENCH_POST_CANARY_TIMEOUT_S", "180"))
        watchdog.phase(
            f"in-process claim did not return within {budget:.0f}s "
            "despite a healthy canary", budget)
    else:
        budget = float(os.environ.get("BENCH_CLAIM_TIMEOUT_S", "420"))
        watchdog.phase(
            f"backend claim did not return within {budget:.0f}s "
            "(chip grant wedged; claim hangs instead of raising)", budget)
    t_end = time.monotonic() + budget
    retries = max(1, int(os.environ.get("BENCH_RETRIES", "3")))
    backoff = float(os.environ.get("BENCH_RETRY_BACKOFF_S", "30"))
    last = None
    for attempt in range(retries):
        try:
            import jax

            return jax.devices(), None  # forces backend init / chip claim
        except Exception as e:  # noqa: BLE001 - backend init raises anything
            last = e
            print(f"# in-process claim attempt {attempt + 1}/{retries} "
                  f"failed: {e}", file=sys.stderr)
            wait = backoff * (attempt + 1)
            if attempt + 1 < retries and time.monotonic() + wait < t_end:
                time.sleep(wait)
            else:
                break  # out of claim budget; fail structured, don't hang
    tag = "after healthy canary" if use_canary else "(canary disabled)"
    return None, (f"in-process claim failed {tag}: "
                  f"{type(last).__name__}: {last}")


def main():
    # the watchdog covers hangs; this covers raises (an XlaRuntimeError/OOM
    # after the claim must also end in a parseable JSON line, not rc=1)
    try:
        _main_measured()
    except Exception as e:  # noqa: BLE001 - emit JSON for ANY failure
        print(_result_json(0.0, error=f"{type(e).__name__}: {e}"), flush=True)
        import traceback

        traceback.print_exc()


def _main_measured():
    os.environ.setdefault("DISTMLIP_TPU_NUM_THREADS", str(os.cpu_count() or 8))
    watchdog = _Watchdog()
    devs, err = _claim_backend(watchdog)
    if devs is None:
        # structured failure: the driver records WHY instead of a traceback
        watchdog.finish()
        print(_result_json(0.0, error=f"backend unavailable: {err}"))
        return
    # claim returned: re-arm for host-side setup + on-device param init so a
    # slow late-retry claim doesn't leave setup running on the claim budget's
    # residue (a healthy chip would be falsely reported as a wedged claim)
    setup_budget = float(os.environ.get("BENCH_SETUP_TIMEOUT_S", "300"))
    watchdog.phase(f"model/system setup exceeded {setup_budget:.0f}s",
                   setup_budget)
    import jax

    from distmlip_tpu import geometry
    from distmlip_tpu.calculators import Atoms, DistPotential
    from distmlip_tpu.models import MACE, MACEConfig
    from distmlip_tpu.telemetry import AggregatingSink, JsonlSink, Telemetry

    reps = int(os.environ.get("BENCH_REPS", "16"))
    steps = int(os.environ.get("BENCH_STEPS", "5"))
    # bf16 is the production TPU configuration (error characterized in
    # ROADMAP.md: ~3e-4 eV/atom, ~1% relative forces); BENCH_DTYPE=float32
    # reproduces the round-1 precision setting
    bench_dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    # ~4*reps^3 atom perturbed Si-like crystal (16 -> 16384 atoms)
    rng = np.random.default_rng(0)
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * 3.9, (reps, reps, reps))
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(0, 0.04, (len(frac), 3))
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart, cell=lattice)

    # MACE-MP-0-medium-faithful configuration (the BASELINE.md north-star
    # model): a_lmax = l_max = 3 per PARITY.md — benching a smaller a_lmax
    # would inflate atoms/s by shrinking the CG path set
    # BENCH_REMAT: "1" full remat (default), "0" none, or a checkpoint
    # policy name ("dots" keeps GEMM outputs resident in the backward)
    remat_env = os.environ.get("BENCH_REMAT", "1")
    remat = {"1": True, "0": False}.get(remat_env, remat_env)
    cfg = MACEConfig(
        num_species=95, channels=128, l_max=3,
        a_lmax=int(os.environ.get("BENCH_A_LMAX", "3")), hidden_lmax=1,
        correlation=3, num_interactions=2, num_bessel=8, radial_mlp=64,
        cutoff=5.0, avg_num_neighbors=14.0, remat=remat,
        edge_chunk=int(os.environ.get("BENCH_EDGE_CHUNK", "32768")),
        node_chunk=int(os.environ.get("BENCH_NODE_CHUNK", "4096")),
    )
    model = MACE(cfg)
    params = model.init(jax.random.PRNGKey(0))
    # telemetry: per-phase aggregation always; JSONL artifact when
    # BENCH_TELEMETRY_JSONL names a path (feed tools/telemetry_report.py)
    agg = AggregatingSink()
    telemetry = Telemetry([agg])
    jsonl_path = os.environ.get("BENCH_TELEMETRY_JSONL")
    if jsonl_path:
        telemetry.add_sink(JsonlSink(jsonl_path))
    halo_mode = os.environ.get("BENCH_HALO_MODE", "coalesced")
    pot = DistPotential(model, params, num_partitions=len(jax.devices()),
                        compute_stress=True,
                        skin=float(os.environ.get("BENCH_SKIN", "0.5")),
                        compute_dtype=bench_dtype, halo_mode=halo_mode,
                        telemetry=telemetry)
    watchdog.n_atoms = len(atoms)
    watchdog.n_devices = len(jax.devices())

    # a wedged chip grant can pass the claim (jax.devices() returns) yet
    # hang the first compile/execute — or drop mid-run — forever (round-3
    # lesson): keep the watchdog armed through warmup and every step
    warm_timeout = float(os.environ.get("BENCH_WARMUP_TIMEOUT_S", "600"))
    watchdog.phase(
        f"compile/warmup exceeded {warm_timeout:.0f}s "
        "(chip claimed but not serving)", warm_timeout)
    pot.calculate(atoms)
    # steady state: perturb positions each step like MD
    # per-step budget must absorb a mid-run XLA recompile (sticky-capacity
    # bucket growth on a position perturbation recompiles legitimately)
    step_budget = float(os.environ.get("BENCH_STEP_TIMEOUT_S", "300"))
    for i in range(steps):
        watchdog.phase(
            f"measured step {i + 1}/{steps} exceeded {step_budget:.0f}s",
            step_budget)
        atoms.positions += rng.normal(0, 0.01, atoms.positions.shape)
        t0 = time.perf_counter()
        pot.calculate(atoms)
        watchdog.times.append(time.perf_counter() - t0)

    # batched-engine throughput (serving regime): structures/sec at batch
    # sizes {1, 8} over small structures through ONE BatchedPotential (its
    # shape-bucketed compile cache covers both batch sizes). Every batched
    # step emits a StepRecord carrying structures_per_sec/bucket_key to the
    # same telemetry sinks (JSONL artifact included). BENCH_BATCHED=0 skips.
    batched_extras = {}
    if os.environ.get("BENCH_BATCHED", "1") != "0":
        b_budget = float(os.environ.get("BENCH_BATCHED_TIMEOUT_S", "600"))
        watchdog.phase(
            f"batched throughput measurement exceeded {b_budget:.0f}s",
            b_budget)
        try:
            from distmlip_tpu.calculators import BatchedPotential
            from distmlip_tpu.partition import BucketPolicy

            b_reps = int(os.environ.get("BENCH_BATCHED_REPS", "2"))
            b_steps = int(os.environ.get("BENCH_BATCHED_STEPS", "3"))
            frac_b, lat_b = geometry.make_supercell(
                unit, np.eye(3) * 3.9, (b_reps, b_reps, b_reps))
            # pot.model carries the bench compute dtype (bf16 by default)
            bpot = BatchedPotential(
                pot.model, pot.params, caps=BucketPolicy(),
                skin=float(os.environ.get("BENCH_SKIN", "0.5")),
                telemetry=telemetry)
            for B in (1, 8):
                structs = []
                for _ in range(B):
                    cart_b = geometry.frac_to_cart(frac_b, lat_b) + \
                        rng.normal(0, 0.04, (len(frac_b), 3))
                    structs.append(Atoms(numbers=np.full(len(cart_b), 14),
                                         positions=cart_b, cell=lat_b))
                bpot.calculate(structs)  # compile + first pack
                t0 = time.perf_counter()
                for _ in range(b_steps):
                    for a in structs:
                        a.positions += rng.normal(
                            0, 0.01, a.positions.shape)
                    bpot.calculate(structs)
                dt_b = (time.perf_counter() - t0) / max(b_steps, 1)
                batched_extras[f"structures_per_sec_b{B}"] = round(
                    B / dt_b, 2)
            batched_extras["batched_compiles"] = bpot.compile_count
            # static-HBM-planner accuracy on real hardware: predicted
            # per-device peak vs the backend's measured peak residency
            # (the JSONL StepRecords carry the same fields per step, so
            # telemetry_report's hbm_estimator_drift check sees them;
            # this scalar keeps the ratio in the BENCH round artifact)
            from distmlip_tpu.utils.memory import measured_peak_bytes

            est_b = int(getattr(bpot, "last_est_peak_bytes", 0))
            measured_b = measured_peak_bytes()
            if est_b:
                batched_extras["est_peak_bytes"] = est_b
            if est_b and measured_b:
                batched_extras["hbm_est_over_measured"] = round(
                    est_b / measured_b, 3)
        except Exception as e:  # noqa: BLE001 - batched is additive
            batched_extras["batched_error"] = f"{type(e).__name__}: {e}"[:160]

    # serving-engine throughput: open-loop burst (submit everything, then
    # harvest — maximum queueing pressure) through a ServeEngine at
    # max_batch ∈ {1, 8}, requests/sec + p95 latency. Runs in THIS process
    # after the canary-gated claim, so the wedge hardening above covers it;
    # per-batch StepRecords ride the shared telemetry sinks. BENCH_SERVE=0
    # skips.
    serve_extras = {}
    if os.environ.get("BENCH_SERVE", "1") != "0":
        s_budget = float(os.environ.get("BENCH_SERVE_TIMEOUT_S", "600"))
        watchdog.phase(
            f"serve throughput measurement exceeded {s_budget:.0f}s",
            s_budget)
        try:
            from distmlip_tpu.calculators import BatchedPotential
            from distmlip_tpu.partition import BucketPolicy
            from distmlip_tpu.serve import ServeEngine, run_open_loop

            s_reps = int(os.environ.get("BENCH_SERVE_REPS", "2"))
            n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", "24"))
            frac_s, lat_s = geometry.make_supercell(
                unit, np.eye(3) * 3.9, (s_reps, s_reps, s_reps))
            pool = []
            for _ in range(8):
                cart_s = geometry.frac_to_cart(frac_s, lat_s) + \
                    rng.normal(0, 0.04, (len(frac_s), 3))
                pool.append(Atoms(numbers=np.full(len(cart_s), 14),
                                  positions=cart_s, cell=lat_s))
            for B in (1, 8):
                engine = ServeEngine(
                    BatchedPotential(
                        pot.model, pot.params, caps=BucketPolicy(),
                        skin=float(os.environ.get("BENCH_SKIN", "0.5"))),
                    max_batch=B, max_wait_s=0.005, admission="block",
                    telemetry=telemetry)
                run_open_loop(engine, pool, n_req, rate_hz=0.0)  # warm
                rep = run_open_loop(engine, pool, n_req, rate_hz=0.0)
                p95 = rep.latency_percentiles()["p95_s"]
                serve_extras[f"serve_structs_per_sec_b{B}"] = round(
                    rep.structures_per_sec, 2)
                serve_extras[f"serve_p95_ms_b{B}"] = round(1e3 * p95, 2)
                serve_extras[f"serve_compiles_b{B}"] = engine.compile_count
                engine.close()
        except Exception as e:  # noqa: BLE001 - serving is additive
            serve_extras["serve_error"] = f"{type(e).__name__}: {e}"[:160]

    class _MeshPhaseSkipped(Exception):
        """No configured mesh placement fits this host's device count."""

    # 2-D mesh placements: structures/sec for ONE batch of structures
    # across (batch x spatial) placements at EQUAL chip count — e.g. on 8
    # chips, 8x1 (pure batch-parallel), 4x2 and 2x4 (each structure
    # spatially split over 2/4 slabs with halo exchange on the spatial
    # axis). Per-step StepRecords (mesh_shape/spatial_parts fields) ride
    # the shared telemetry sinks. BENCH_MESH=0 skips.
    mesh_extras = {}
    if os.environ.get("BENCH_MESH", "1") != "0":
        m_budget = float(os.environ.get("BENCH_MESH_TIMEOUT_S", "900"))
        watchdog.phase(
            f"mesh placement measurement exceeded {m_budget:.0f}s", m_budget)
        try:
            from distmlip_tpu.calculators import BatchedPotential
            from distmlip_tpu.parallel import device_mesh
            from distmlip_tpu.partition import BucketPolicy

            n_dev = len(jax.devices())
            placements = []
            for spec in os.environ.get("BENCH_MESH_PLACEMENTS",
                                       "8,1;4,2;2,4").split(";"):
                b_m, s_m = (int(x) for x in spec.split(","))
                if b_m * s_m <= n_dev:
                    placements.append((b_m, s_m))
            if not placements:
                # its own key, distinct from BENCH_MESH=0 (no mesh_* keys
                # at all) and from mesh_error (a genuine failure): no
                # configured placement fits this host's device count
                mesh_extras["mesh_skipped"] = (
                    f"no placement in BENCH_MESH_PLACEMENTS fits "
                    f"{n_dev} device(s)")
                raise _MeshPhaseSkipped
            m_steps = int(os.environ.get("BENCH_MESH_STEPS", "3"))
            n_struct = int(os.environ.get("BENCH_MESH_STRUCTURES", "8"))
            m_skin = float(os.environ.get("BENCH_SKIN", "0.5"))
            s_max = max((s for _b, s in placements), default=1)
            # slab rule: per-slab width must exceed 2x the build cutoff,
            # so the shared structure pool is sized for the LARGEST S
            r_build = float(model.cfg.cutoff) + m_skin
            reps_x = max(int(np.ceil(2.0 * s_max * r_build / 3.9)) + 1, 4)
            frac_m, lat_m = geometry.make_supercell(
                unit, np.eye(3) * 3.9, (reps_x, 2, 2))
            structs_m = []
            for _ in range(n_struct):
                cart_m = geometry.frac_to_cart(frac_m, lat_m) + \
                    rng.normal(0, 0.04, (len(frac_m), 3))
                structs_m.append(Atoms(numbers=np.full(len(cart_m), 14),
                                       positions=cart_m, cell=lat_m))
            for b_m, s_m in placements:
                mpot = BatchedPotential(
                    pot.model, pot.params, caps=BucketPolicy(), skin=m_skin,
                    mesh=device_mesh(b_m, s_m), telemetry=telemetry)
                mpot.calculate(structs_m)  # compile + first pack
                t0 = time.perf_counter()
                for _ in range(m_steps):
                    for a in structs_m:
                        a.positions += rng.normal(0, 0.01, a.positions.shape)
                    mpot.calculate(structs_m)
                dt_m = (time.perf_counter() - t0) / max(m_steps, 1)
                mesh_extras[f"mesh_structs_per_sec_{b_m}x{s_m}"] = round(
                    n_struct / dt_m, 2)
            mesh_extras["mesh_atoms_per_structure"] = len(frac_m)
        except _MeshPhaseSkipped:
            pass  # mesh_skipped already recorded
        except Exception as e:  # noqa: BLE001 - mesh phase is additive
            mesh_extras["mesh_error"] = f"{type(e).__name__}: {e}"[:160]

    # training subsystem: examples/sec + step time through the accumulated
    # train step (distmlip_tpu.train) at accumulation windows {1, 4} —
    # synthetic labels (throughput, not fitting), per-step TrainRecords
    # ride the shared telemetry sinks (JSONL artifact included), and the
    # static HBM planner's estimate of the step program is recorded.
    # BENCH_TRAIN=0 skips.
    train_extras = {}
    if os.environ.get("BENCH_TRAIN", "1") != "0":
        t_budget = float(os.environ.get("BENCH_TRAIN_TIMEOUT_S", "900"))
        watchdog.phase(
            f"train-phase measurement exceeded {t_budget:.0f}s", t_budget)
        try:
            import optax

            from distmlip_tpu.calculators import Atoms as _Atoms
            from distmlip_tpu.train import Sample, TrainConfig, Trainer

            n_struct = int(os.environ.get("BENCH_TRAIN_STRUCTURES", "8"))
            t_steps = int(os.environ.get("BENCH_TRAIN_STEPS", "3"))
            t_reps = int(os.environ.get("BENCH_TRAIN_REPS", "3"))
            frac_t, lat_t = geometry.make_supercell(
                unit, np.eye(3) * 3.9, (t_reps, t_reps, t_reps))
            samples_t = []
            for _ in range(n_struct):
                cart_t = geometry.frac_to_cart(frac_t, lat_t) + \
                    rng.normal(0, 0.04, (len(frac_t), 3))
                samples_t.append(Sample(
                    _Atoms(numbers=np.full(len(cart_t), 14),
                           positions=cart_t, cell=lat_t),
                    0.0, np.zeros((len(cart_t), 3), np.float32)))
            train_extras["train_atoms_per_structure"] = len(frac_t)
            for accum in (1, 4):
                if n_struct < 2 * accum:
                    continue
                b_t = max(n_struct // (2 * accum), 1)
                trainer = Trainer(
                    model.energy_fn, pot.params, optax.adam(1e-3),
                    samples_t, float(model.cfg.cutoff),
                    micro_batch_size=b_t,
                    config=TrainConfig(accum_steps=accum),
                    hbm_budget_frac=0.95, telemetry=telemetry,
                    loader_kwargs={"species_fn":
                                   lambda z: np.zeros(len(z), np.int32)})
                trainer.fit(steps=1)  # compile + warm
                t0 = time.perf_counter()
                trainer.fit(steps=t_steps)
                dt_t = (time.perf_counter() - t0) / max(t_steps, 1)
                train_extras[f"train_examples_per_sec_accum{accum}"] = \
                    round(accum * b_t / dt_t, 2)
                train_extras[f"train_step_s_accum{accum}"] = round(dt_t, 4)
                train_extras["train_est_peak_mib"] = round(
                    trainer.est_peak_bytes / 2**20, 1)
                trainer.close()
        except Exception as e:  # noqa: BLE001 - train phase is additive
            train_extras["train_error"] = f"{type(e).__name__}: {e}"[:160]

    # cost-model packing A/B: naive single-cap vs tiered edge-balanced
    # packing on a synthetic LONG-TAIL dataset (lognormal structure
    # sizes) — examples/sec, measured padding_waste_frac and per-tier
    # compile counts land in the round artifact so the BENCH trajectory
    # captures the data-distribution win (CPU dryrun populates the same
    # fields). Small TensorNet: the A/B is data-distribution-bound, not
    # model-bound. BENCH_TRAIN=0 or BENCH_TRAIN_PACKING=0 skips.
    if (os.environ.get("BENCH_TRAIN", "1") != "0"
            and os.environ.get("BENCH_TRAIN_PACKING", "1") != "0"):
        p_budget = float(os.environ.get("BENCH_TRAIN_PACKING_TIMEOUT_S",
                                        "600"))
        watchdog.phase(
            f"train packing A/B exceeded {p_budget:.0f}s", p_budget)
        try:
            import optax

            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            from pack_audit import synth_longtail_samples

            from distmlip_tpu.models.tensornet import (TensorNet,
                                                       TensorNetConfig)
            from distmlip_tpu.train import Trainer, structure_needs

            n_lt = int(os.environ.get("BENCH_TRAIN_PACKING_STRUCTURES",
                                      "200"))
            lt_steps = int(os.environ.get("BENCH_TRAIN_PACKING_STEPS", "6"))
            b_lt = int(os.environ.get("BENCH_TRAIN_PACKING_BATCH", "8"))
            lt_cut = 3.5
            tiny = TensorNet(TensorNetConfig(
                num_species=4, units=16, num_rbf=6, num_layers=2,
                cutoff=lt_cut))
            p_lt = tiny.init(jax.random.PRNGKey(2))
            samples_lt = synth_longtail_samples(
                n_lt, seed=5, mu=3.0, sigma=1.0, min_atoms=4,
                max_atoms=600)
            needs_lt = structure_needs([s.atoms for s in samples_lt],
                                       lt_cut)
            packing = {}
            for mode, extra_kw in (("naive", {}),
                                   ("cost_model",
                                    {"packing": "cost_model",
                                     "num_tiers": 3})):
                tr = Trainer(
                    tiny.energy_fn, p_lt, optax.adam(1e-3), samples_lt,
                    lt_cut, micro_batch_size=b_lt, hbm_budget_frac=0.95,
                    loader_kwargs={
                        "seed": 1, "precomputed_needs": needs_lt,
                        "species_fn":
                            lambda z: np.zeros(len(z), np.int32),
                        **extra_kw})
                # warm until EVERY tier's first step has run — the
                # measured window must see zero compiles
                tr.fit(steps=max(
                    tr.loader.tier_first_steps().values()) + 1)
                t0 = time.perf_counter()
                hist = tr.fit(steps=lt_steps)[-lt_steps:]
                dt_p = (time.perf_counter() - t0) / max(lt_steps, 1)
                tier_steps = {}
                for h in hist:
                    tier_steps[h["tier"]] = tier_steps.get(
                        h["tier"], 0) + 1
                packing[mode] = {
                    "examples_per_sec": round(b_lt / dt_p, 2),
                    "padding_waste_frac": round(float(np.mean(
                        [h["padding_waste_frac"] for h in hist])), 4),
                    "edge_balance": round(float(min(
                        h["edge_balance"] for h in hist)), 4),
                    "compiles": tr.compile_count,
                    "tiers": tr.loader.num_tiers,
                    "tier_steps": {str(k): v
                                   for k, v in sorted(tier_steps.items())},
                    "tier_est_peak_mib": {
                        str(k): round(v / 2**20, 1)
                        for k, v in sorted(tr.tier_peak_bytes.items())},
                }
                tr.close()
            train_extras["train_packing"] = packing
            w_n = packing["naive"]["padding_waste_frac"]
            w_c = packing["cost_model"]["padding_waste_frac"]
            train_extras["train_padding_waste_naive"] = w_n
            train_extras["train_padding_waste_cost_model"] = w_c
            if w_c > 0:
                train_extras["train_packing_waste_ratio"] = round(
                    w_n / w_c, 2)
            train_extras["train_examples_per_sec_naive"] = \
                packing["naive"]["examples_per_sec"]
            train_extras["train_examples_per_sec_cost_model"] = \
                packing["cost_model"]["examples_per_sec"]
        except Exception as e:  # noqa: BLE001 - packing A/B is additive
            train_extras["train_packing_error"] = \
                f"{type(e).__name__}: {e}"[:160]

    # device-resident MD: steps/sec through DeviceMD with the neighbor
    # rebuild ON DEVICE (in-loop cell list, zero host syncs) vs the host
    # FPIS rebuild at EQUAL skin, plus a rebuilds/sec microbench of the
    # jitted cell-list kernel alone. Per-phase telemetry of the device mode
    # must show no host FPIS time (neighbor_s ~ 0 after the first build).
    # BENCH_DEVICE_MD=0 skips.
    dmd_extras = {}
    if os.environ.get("BENCH_DEVICE_MD", "1") != "0":
        d_budget = float(os.environ.get("BENCH_DEVICE_MD_TIMEOUT_S", "600"))
        watchdog.phase(
            f"device-MD throughput measurement exceeded {d_budget:.0f}s",
            d_budget)
        try:
            from distmlip_tpu.calculators import DeviceMD, DistPotential
            from distmlip_tpu.neighbors.device import (build_cell_list_spec,
                                                       device_neighbor_list)
            from distmlip_tpu.telemetry import AggregatingSink as _Agg
            from distmlip_tpu.telemetry import Telemetry as _Tel

            d_reps = int(os.environ.get("BENCH_DEVICE_MD_REPS", "4"))
            d_steps = int(os.environ.get("BENCH_DEVICE_MD_STEPS", "50"))
            d_skin = float(os.environ.get("BENCH_DEVICE_MD_SKIN", "0.3"))
            frac_d, lat_d = geometry.make_supercell(
                unit, np.eye(3) * 3.9, (d_reps, d_reps, d_reps))
            # ONE perturbed configuration shared by both arms: rebuild
            # cadence depends on it, so differing draws would turn the
            # equal-skin A/B into an artifact of the rng
            cart_d = geometry.frac_to_cart(frac_d, lat_d) + \
                rng.normal(0, 0.04, (len(frac_d), 3))
            for mode in ("device", "host"):
                atoms_d = Atoms(numbers=np.full(len(cart_d), 14),
                                positions=cart_d.copy(), cell=lat_d)
                atoms_d.set_maxwell_boltzmann_velocities(
                    600.0, rng=np.random.default_rng(3))
                agg_d = _Agg()
                pot_d = DistPotential(
                    pot.model, pot.params, num_partitions=1, skin=d_skin,
                    device_rebuild=(mode == "device"))
                md = DeviceMD(pot_d, atoms_d, timestep=2.0,
                              device_rebuild=(mode == "device"))
                md.run(5)  # compile + warm (includes the one host build)
                # attach telemetry AFTER warmup so the per-phase breakdown
                # covers only the measured steady state — the acceptance
                # bar for device mode is ~zero host FPIS (neighbor_s) there
                pot_d.telemetry = _Tel([agg_d])
                t0 = time.perf_counter()
                md.run(d_steps)
                dt_d = time.perf_counter() - t0
                dmd_extras[f"device_md_steps_per_sec_{mode}"] = round(
                    d_steps / dt_d, 2)
                dmd_extras[f"device_md_rebuilds_{mode}"] = (
                    f"host={md.rebuilds} device={md.rebuilds_on_device} "
                    f"overflow={md.rebuild_overflows}")
                # host FPIS share of the measured phase table: the device
                # mode's acceptance bar is ~0 here
                dmd_extras[f"device_md_host_fpis_s_{mode}"] = round(
                    agg_d.totals.get("neighbor_s", 0.0), 4)
            # rebuilds/sec: the jitted cell-list kernel alone, steady
            # state. e_cap is sized from the kernel's own exact count (a
            # probe call with a generous cap), and the overflow flag gates
            # the published number — a truncated rebuild must never be
            # timed as a valid one.
            n_d = len(frac_d)
            pos_pad = np.asarray(
                geometry.frac_to_cart(frac_d, lat_d), dtype=np.float32)
            st_p, arr_p = build_cell_list_spec(
                lat_d, [1, 1, 1], 5.5, n_d, n_d, 256 * max(n_d, 128),
                positions=pos_pad)
            probe = device_neighbor_list(st_p, arr_p, pos_pad)
            if bool(probe[4]):
                raise RuntimeError("rebuild microbench probe overflowed")
            e_cap_d = int(int(probe[3]) * 1.2) + 128
            st_d, arr_d = build_cell_list_spec(
                lat_d, [1, 1, 1], 5.5, n_d, n_d, e_cap_d, positions=pos_pad)
            jax.block_until_ready(
                device_neighbor_list(st_d, arr_d, pos_pad)[0])  # compile
            k = int(os.environ.get("BENCH_REBUILD_ITERS", "20"))
            t0 = time.perf_counter()
            for _ in range(k):
                out_d = device_neighbor_list(st_d, arr_d, pos_pad)
            jax.block_until_ready(out_d[0])
            dt_reb = time.perf_counter() - t0
            if bool(out_d[4]):
                dmd_extras["device_rebuild_error"] = "kernel overflow"
            else:
                dmd_extras["device_rebuilds_per_sec"] = round(k / dt_reb, 2)
                dmd_extras["device_rebuild_atoms"] = n_d
        except Exception as e:  # noqa: BLE001 - device-MD bench is additive
            dmd_extras["device_md_error"] = f"{type(e).__name__}: {e}"[:160]

    # --- fused-kernel microbench (PR 8): fused vs unfused edge-aggregate
    # at a sweep of (E, width), MFU via the shared analytic FLOP count so
    # the Pallas win is RECORDED in BENCH_*.json, not asserted.
    # BENCH_KERNELS=0 skips.
    kern_extras = {}
    if os.environ.get("BENCH_KERNELS", "1") != "0":
        k_budget = float(os.environ.get("BENCH_KERNELS_TIMEOUT_S", "420"))
        watchdog.phase(
            f"fused-kernel microbench exceeded {k_budget:.0f}s", k_budget)
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            from kernel_bench import run_sweep as _kernel_sweep

            k_sizes = [int(s) for s in os.environ.get(
                "BENCH_KERNELS_E", "100000,400000").split(",") if s]
            k_widths = [int(s) for s in os.environ.get(
                "BENCH_KERNELS_W", "64,128").split(",") if s]
            k_iters = int(os.environ.get("BENCH_KERNELS_ITERS", "20"))
            # real Pallas on TPU backends; interpreter kernels are a test
            # lane, not a benchmark — on CPU hosts record the unfused
            # numbers only unless explicitly forced
            on_tpu = jax.default_backend() == "tpu"
            if on_tpu or os.environ.get("BENCH_KERNELS_INTERPRET") == "1":
                kern_extras["kernel_bench"] = _kernel_sweep(
                    k_sizes, k_widths, iters=k_iters, interpret=not on_tpu)
            else:
                kern_extras["kernel_bench"] = {
                    "skipped": "no TPU backend (interpreter kernels are "
                               "not a benchmark; BENCH_KERNELS_INTERPRET=1 "
                               "forces the plumbing smoke)"}
        except Exception as e:  # noqa: BLE001 - kernel bench is additive
            kern_extras["kernel_bench_error"] = (
                f"{type(e).__name__}: {e}"[:160])
    watchdog.finish()  # from here on the watchdog cannot print
    dt = float(np.median(watchdog.times))
    atoms_per_sec = len(atoms) / dt / max(len(jax.devices()), 1)

    # overlap-pipeline accounting: collective count of the measured mode AND
    # its A/B counterpart (host-side jaxpr traces — no device work), plus
    # the analytic-FLOP mfu for the measured steps
    extras = {"halo_mode": halo_mode, **batched_extras, **serve_extras,
              **mesh_extras, **train_extras, **dmd_extras, **kern_extras}
    try:
        from distmlip_tpu.parallel import make_potential_fn
        from distmlip_tpu.parallel.audit import count_collectives

        graph = pot._cache[0] if pot._cache else None
        if graph is not None:
            for mode in ("coalesced", "legacy"):
                p_mode = make_potential_fn(
                    model.energy_fn, pot.mesh, halo_mode=mode)
                jaxpr = jax.make_jaxpr(p_mode)(pot.params, graph,
                                               graph.positions)
                extras[f"collectives_{mode}"] = sum(
                    count_collectives(jaxpr).values())
    except Exception as e:  # noqa: BLE001 - accounting must not fail the run
        extras["collectives_error"] = str(e)[:120]
    try:
        from distmlip_tpu.utils.flops import mfu as _mfu
        from distmlip_tpu.utils.flops import model_flop_estimate

        stats = (pot._cache[1].stats or {}) if pot._cache else {}
        flops = model_flop_estimate(
            model, len(atoms), sum(stats.get("n_edges_per_part", [])))
        extras["mfu"] = round(
            _mfu(flops, dt, max(len(jax.devices()), 1)), 4)
        extras["flops_per_step"] = float(f"{flops:.3e}")
    except Exception as e:  # noqa: BLE001
        extras["mfu_error"] = str(e)[:120]

    print(_result_json(atoms_per_sec, _vs_baseline(atoms_per_sec),
                       dtype=bench_dtype, a_lmax=cfg.a_lmax, **extras))
    # the structured per-phase breakdown replaces the old hand-formatted
    # pot.last_timings line; the same records went to the JSONL sink when
    # BENCH_TELEMETRY_JSONL is set (render with tools/telemetry_report.py)
    print(f"# n_atoms={len(atoms)} step={dt*1e3:.1f}ms "
          f"rebuilds={pot.rebuild_count} prefetch_hits={pot.prefetch_hits} "
          f"devices={jax.devices()}", file=sys.stderr)
    for line in agg.summary().splitlines():
        print(f"# {line}", file=sys.stderr)
    telemetry.close()


if __name__ == "__main__":
    main()
