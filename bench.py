"""Benchmark: MD-step throughput (atoms/sec/chip) for MACE on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures steady-state post-compile MD steps in the framework's production
configuration: Verlet skin-radius graph reuse (BENCH_SKIN, default 0.5 Å) —
host rebuilds amortize across steps exactly as in a real MD run. Set
BENCH_SKIN=0 to time the reference-style rebuild-every-step pipeline
(reference pes.py:50-146). Throughput is divided by the device count.
vs_baseline compares against BASELINE_LOCAL.json when present (reference
numbers are not published in-repo, see BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np


def _claim_backend():
    """Initialize the JAX backend, retrying transient claim failures.

    The axon TPU tunnel can refuse a claim transiently; a bare traceback
    here costs the whole measurement (round-2 lesson). Retries with backoff,
    and on final failure returns the exception so main() can emit a
    structured "backend unavailable" JSON instead of rc=1.
    """
    import time as _time

    retries = max(1, int(os.environ.get("BENCH_RETRIES", "3")))
    backoff = float(os.environ.get("BENCH_RETRY_BACKOFF_S", "30"))
    last = None
    for attempt in range(retries):
        try:
            import jax

            devs = jax.devices()  # forces backend init / chip claim
            return devs, None
        except Exception as e:  # noqa: BLE001 - backend init raises anything
            last = e
            print(f"# backend claim attempt {attempt + 1}/{retries} failed: "
                  f"{e}", file=sys.stderr)
            if attempt + 1 < retries:
                _time.sleep(backoff * (attempt + 1))
    return None, last


def main():
    os.environ.setdefault("DISTMLIP_TPU_NUM_THREADS", str(os.cpu_count() or 8))
    devs, err = _claim_backend()
    if devs is None:
        # structured failure: the driver records WHY instead of a traceback
        print(json.dumps({
            "metric": "mace_mp0_md_step_atoms_per_sec_per_chip",
            "value": 0.0,
            "unit": "atoms/s",
            "vs_baseline": 0.0,
            "error": f"backend unavailable: {type(err).__name__}: {err}",
        }))
        return
    import jax

    from distmlip_tpu import geometry
    from distmlip_tpu.calculators import Atoms, DistPotential
    from distmlip_tpu.models import MACE, MACEConfig

    reps = int(os.environ.get("BENCH_REPS", "16"))
    steps = int(os.environ.get("BENCH_STEPS", "5"))
    # bf16 is the production TPU configuration (error characterized in
    # ROADMAP.md: ~3e-4 eV/atom, ~1% relative forces); BENCH_DTYPE=float32
    # reproduces the round-1 precision setting
    bench_dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    # ~4*reps^3 atom perturbed Si-like crystal (16 -> 16384 atoms)
    rng = np.random.default_rng(0)
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * 3.9, (reps, reps, reps))
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(0, 0.04, (len(frac), 3))
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart, cell=lattice)

    # MACE-MP-0-medium-faithful configuration (the BASELINE.md north-star
    # model): a_lmax = l_max = 3 per PARITY.md — benching a smaller a_lmax
    # would inflate atoms/s by shrinking the CG path set
    cfg = MACEConfig(
        num_species=95, channels=128, l_max=3,
        a_lmax=int(os.environ.get("BENCH_A_LMAX", "3")), hidden_lmax=1,
        correlation=3, num_interactions=2, num_bessel=8, radial_mlp=64,
        cutoff=5.0, avg_num_neighbors=14.0,
    )
    model = MACE(cfg)
    params = model.init(jax.random.PRNGKey(0))
    pot = DistPotential(model, params, num_partitions=len(jax.devices()),
                        compute_stress=True,
                        skin=float(os.environ.get("BENCH_SKIN", "0.5")),
                        compute_dtype=bench_dtype)

    # run the measurement under a watchdog: a wedged chip grant can pass
    # the claim (jax.devices() returns) yet hang the first compile/execute
    # — or drop mid-run — forever (round-3 lesson). Emit structured
    # failure instead of letting the driver record a bare timeout with no
    # JSON. Deadline: warmup budget + a generous per-step allowance.
    import threading

    warm_timeout = float(os.environ.get("BENCH_WARMUP_TIMEOUT_S", "600"))
    deadline = warm_timeout + 60.0 * steps
    done = threading.Event()

    def _watchdog():
        if not done.wait(deadline):
            print(json.dumps({
                "metric": "mace_mp0_md_step_atoms_per_sec_per_chip",
                "value": 0.0,
                "unit": "atoms/s",
                "vs_baseline": 0.0,
                "error": f"backend wedged: compile/execute exceeded "
                         f"{deadline:.0f}s (chip claimed but not serving)",
            }), flush=True)
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()
    pot.calculate(atoms)
    # steady state: perturb positions each step like MD
    times = []
    for _ in range(steps):
        atoms.positions += rng.normal(0, 0.01, atoms.positions.shape)
        t0 = time.perf_counter()
        res = pot.calculate(atoms)
        times.append(time.perf_counter() - t0)
    done.set()  # before printing: a late watchdog firing must not emit a
    #             second, contradictory JSON line after the success line
    dt = float(np.median(times))
    atoms_per_sec = len(atoms) / dt / max(len(jax.devices()), 1)

    vs = 0.0
    base_path = os.path.join(os.path.dirname(__file__), "BASELINE_LOCAL.json")
    if os.path.exists(base_path):
        base = json.load(open(base_path))
        ref = base.get("mace_mp0_md_atoms_per_sec")
        if ref:
            vs = atoms_per_sec / ref

    print(json.dumps({
        "metric": "mace_mp0_md_step_atoms_per_sec_per_chip",
        "value": round(atoms_per_sec, 1),
        "unit": "atoms/s",
        "vs_baseline": round(vs, 3),
        "dtype": bench_dtype,
        "a_lmax": cfg.a_lmax,
    }))
    print(f"# n_atoms={len(atoms)} step={dt*1e3:.1f}ms rebuilds={pot.rebuild_count} "
          f"(nl={pot.last_timings['neighbor_s']*1e3:.1f}ms "
          f"part={pot.last_timings['partition_s']*1e3:.1f}ms "
          f"dev={pot.last_timings['device_s']*1e3:.1f}ms) "
          f"devices={jax.devices()}", file=sys.stderr)


if __name__ == "__main__":
    main()
