"""Weight ingestion from upstream torch checkpoints.

The reference's ``from_existing(model)`` copies a trained upstream torch
module's ``__dict__`` into its distributed subclass (reference
chgnet.py:551-560, models.py:252-263). The TPU-native equivalent maps a
torch ``state_dict`` onto this framework's parameter pytrees.

Generic machinery here; per-architecture name maps live in MAPPINGS. Exact
upstream-name coverage is validated opportunistically: ``convert`` reports
unmapped/unused tensors so partial maps fail loudly instead of silently
producing a half-initialized model.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable

import numpy as np


def _t(x):
    """torch tensor / numpy -> numpy array."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


@dataclass
class Rule:
    """Maps one torch tensor onto one pytree leaf path.

    path: tuple of keys/indices into the params pytree. ``path=None`` marks a
    consume-only rule: the tensor is accounted for (buffers like cutoff
    constants, e3nn output masks, U matrices) and ``transform``, if given,
    runs as a validation hook.
    transform: applied to the torch array (default: linear weights transpose,
    since torch nn.Linear stores (out, in) and this framework uses (in, out)).
    """

    torch_name: str
    path: tuple | None
    transform: Callable[[np.ndarray], np.ndarray] | None = None


def set_in(tree, path, value):
    node = tree
    for p in path[:-1]:
        node = node[p]
    leaf = node[path[-1]]
    if np.shape(leaf) != value.shape:
        raise ValueError(
            f"shape mismatch at {path}: torch {value.shape} vs model {np.shape(leaf)}"
        )
    node[path[-1]] = value.astype(np.asarray(leaf).dtype)


def convert(state_dict: dict, params, rules: list[Rule], strict: bool = True):
    """Apply mapping rules; returns (params, report)."""
    used = set()
    for r in rules:
        if r.torch_name not in state_dict:
            if strict:
                raise KeyError(f"torch checkpoint missing {r.torch_name!r}")
            continue
        arr = _t(state_dict[r.torch_name])
        if r.path is None:
            if r.transform is not None:
                r.transform(arr)  # validation hook
            used.add(r.torch_name)
            continue
        if r.transform is not None:
            arr = r.transform(arr)
        set_in(params, r.path, arr)
        used.add(r.torch_name)
    unused = sorted(set(state_dict) - used)
    report = {"mapped": len(used), "unused_torch": unused}
    if strict and unused:
        raise ValueError(
            f"{len(unused)} torch tensors unmapped (first 10): {unused[:10]}"
        )
    return params, report


def linear_rule(torch_prefix: str, path: tuple, bias: bool = True) -> list[Rule]:
    """nn.Linear -> {'w': (in,out), 'b': (out,)}"""
    rules = [Rule(f"{torch_prefix}.weight", path + ("w",), lambda a: a.T)]
    if bias:
        rules.append(Rule(f"{torch_prefix}.bias", path + ("b",), None))
    return rules


# ---------------------------------------------------------------------------
# Per-architecture maps. These cover this framework's own parameterization;
# upstream checkpoints additionally need the architecture hyperparameters to
# match (units/blocks/rbf sizes). Populated incrementally as upstream
# checkpoints become loadable in the environment; `convert` fails loudly on
# any gap.
# ---------------------------------------------------------------------------

MAPPINGS: dict[str, Callable] = {}


def register_mapping(name: str):
    def deco(fn):
        MAPPINGS[name] = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# MACE (mace-torch ScaleShiftMACE) mapping
# ---------------------------------------------------------------------------

def _silu_2mom_gain() -> float:
    """e3nn's normalize2mom(silu) constant — shared with ops/nn.py's
    variance-preserving init (single source of truth). e3nn estimates the
    same constant by sampling, so folded weights agree to ~1e-3 relative
    (documented in PARITY.md)."""
    from ..ops.nn import silu_2mom_gain

    return silu_2mom_gain()


def _scaled(alpha):
    return lambda a: a * alpha


def _find_u_buffer(sd: dict, prefix: str, S_A: int, nu: int):
    """Locate the U-matrix buffer for correlation ``nu`` under a mace
    symmetric-contraction prefix and canonicalize it to ((S_A^nu * d), k):
    upstream stores (d?, S..., S, k) with the output axis leading; ours is
    (S,)*nu + (d, k). Preference order: a key whose trailing digits name the
    correlation (``U_matrix_{nu}``); fallback: axis-shape matching."""
    import re

    candidates = [
        k for k in sd
        if k.startswith(prefix)
        and ("U_matrix" in k.rsplit(".", 1)[-1]
             or "U_tensors" in k.rsplit(".", 1)[-1])
    ]

    def canonical(arr):
        s_axes = [i for i, s in enumerate(arr.shape) if s == S_A][:nu]
        if len(s_axes) < nu:
            return None
        d_axes = [i for i in range(arr.ndim - 1)
                  if i not in s_axes and i != arr.ndim - 1]
        if len(d_axes) > 1:
            return None
        order = s_axes + d_axes + [arr.ndim - 1]
        can = np.transpose(arr, order)
        return can.reshape(-1, can.shape[-1])

    # exact name match first
    for key in candidates:
        m = re.search(r"(\d+)$", key)
        if m and int(m.group(1)) == nu:
            can = canonical(_t(sd[key]))
            if can is not None:
                return can
    # shape-based fallback
    for key in candidates:
        arr = _t(sd[key])
        if sum(1 for s in arr.shape if s == S_A) == nu:
            can = canonical(arr)
            if can is not None:
                return can
    return None


def _basis_change(U_ours: np.ndarray, U_up_flat: np.ndarray) -> np.ndarray:
    """T with U_up = U_ours @ T (both bases of the same coupling space).

    U_ours has orthonormal columns, so T = U_ours^T U_up and the solve is
    exact whenever upstream's basis spans the same space — verified by the
    residual check (loud failure otherwise)."""
    flat = U_ours.reshape(-1, U_ours.shape[-1])
    T = flat.T @ U_up_flat
    resid = np.linalg.norm(U_up_flat - flat @ T)
    denom = max(np.linalg.norm(U_up_flat), 1e-12)
    if resid / denom > 1e-5:
        raise ValueError(
            f"upstream U matrix is not in the span of the native symmetric "
            f"basis (relative residual {resid / denom:.2e}); irreps/"
            f"correlation mismatch?"
        )
    return T


def _path_signs(sd: dict, inter: dict, a_ls: tuple, paths=None):
    """Per-path ±1 from ``__cg_sign__`` calibration entries, in the message
    path order (None when the export carries no calibration). ``paths`` is
    authoritative when the caller passes the model; otherwise the set is
    reconstructed from the weight shapes (must be unambiguous)."""
    if not any(k.startswith("__cg_sign__") for k in sd):
        return None
    if paths is None:
        from .mace import _message_paths

        h_ls_in = sorted(int(l) for l in inter["lin_up"])
        C = np.shape(inter["lin_up"][str(h_ls_in[0])]["w"])[0]
        n_paths = np.shape(inter["radial"][-1]["w"])[1] // C
        matching = {
            tuple(p)
            for lm in range(7)
            if len(p := _message_paths(h_ls_in, lm, list(a_ls))) == n_paths
        }
        if len(matching) != 1:
            raise ValueError(
                "cannot reconstruct the message-path set from weight shapes; "
                "pass the model to from_torch(..., model=model) so CG sign "
                "calibration can be applied unambiguously"
            )
        paths = list(next(iter(matching)))
    signs = np.ones(len(paths))
    for i, (lh, ly, lo) in enumerate(paths):
        key = f"__cg_sign__.{lh}.{ly}.{lo}"
        if key not in sd:
            # calibration IS present but misses this path: defaulting to +1
            # would be the silent wrong-sign failure calibration exists to
            # prevent
            raise ValueError(
                f"export carries __cg_sign__ calibration but no entry for "
                f"message path (l_h={lh}, l_Y={ly}, l_out={lo}); re-export "
                f"with tools/export_upstream.py covering l_max >= "
                f"{max(lh, ly, lo)}"
            )
        signs[i] = float(np.ravel(_t(sd[key]))[0])
    return signs


@register_mapping("mace")
def mace_mapping(params, sd, model=None):
    """mace-torch ``ScaleShiftMACE.state_dict()`` -> MACE params.

    Exact-name coverage of the MACE-MP-0 family layout (the reference wraps
    these checkpoints via from_existing, mace/models.py:252-263):
    e3nn flat Linear weights are split into per-irrep blocks with the
    1/sqrt(fan_in) path normalization folded in; the radial FullyConnectedNet
    folds e3nn's normalize2mom(silu) gain into post-activation layers; the
    symmetric-contraction weights are basis-changed exactly against the
    checkpoint's own U-matrix buffers (_basis_change). See PARITY.md for the
    two documented approximations (sampled vs quadrature silu gain; CG sign
    conventions calibrated via tools/export_upstream.py when needed).
    """
    from ..ops.so3 import symmetric_coupling_basis

    S, C = np.shape(params["species_emb"]["w"])
    H = np.shape(params["species_ref"]["w"])[0]
    gain = _silu_2mom_gain()
    rules: list[Rule] = []

    def consume(name, validate=None):
        if name in sd:
            rules.append(Rule(name, None, validate))

    def expect(name, value, what, atol=1e-6):
        """Checkpoint constants must agree with the model config — a silent
        mismatch (cutoff, envelope power, bessel frequencies) would evaluate
        the converted weights with the wrong physics."""
        def check(a, _v=np.asarray(value, dtype=np.float64)):
            got = np.asarray(a, dtype=np.float64).reshape(_v.shape)
            if not np.allclose(got, _v, atol=atol):
                raise ValueError(
                    f"checkpoint {what} = {got} does not match the model "
                    f"config ({_v}); rebuild the model with matching "
                    f"hyperparameters"
                )
        return check

    cfg = model.cfg if model is not None else None
    if cfg is None:
        import warnings

        warnings.warn(
            "from_torch('mace', ...) called without model=: checkpoint "
            "constants (cutoff, envelope power p, bessel frequencies, "
            "avg_num_neighbors) will NOT be validated against the model "
            "config — pass model=your_mace_instance",
            stacklevel=3,
        )

    # model-level buffers
    consume("atomic_numbers",
            expect("atomic_numbers", cfg.atomic_numbers, "atomic_numbers")
            if cfg is not None and cfg.atomic_numbers is not None else None)
    consume("r_max", expect("r_max", cfg.cutoff, "r_max (cutoff)")
            if cfg is not None else None)
    for name in ("num_interactions", "heads"):
        consume(name)

    # embeddings
    rules.append(Rule(
        "node_embedding.linear.weight", ("species_emb", "w"),
        lambda a: a.reshape(S, C) / np.sqrt(S),
    ))
    rules.append(Rule(
        "atomic_energies_fn.atomic_energies", ("species_ref", "w"),
        lambda a: np.broadcast_to(a.reshape(-1, S), (H, S)).copy(),
    ))
    consume(
        "radial_embedding.bessel_fn.bessel_weights",
        expect("bessel_weights",
               np.pi * np.arange(1, cfg.num_bessel + 1),
               "bessel frequencies (this framework's basis is fixed n*pi; a "
               "checkpoint with trained frequencies cannot be represented)",
               atol=1e-4)
        if cfg is not None else None,
    )
    consume("radial_embedding.cutoff_fn.p",
            expect("p", float(cfg.cutoff_p), "cutoff envelope power p")
            if cfg is not None else None)
    consume("radial_embedding.cutoff_fn.r_max",
            expect("r_max", cfg.cutoff, "radial cutoff r_max")
            if cfg is not None else None)

    for t, inter in enumerate(params["interactions"]):
        pre = f"interactions.{t}."
        h_ls_in = sorted(int(l) for l in inter["lin_up"])
        a_ls = tuple(sorted(int(l) for l in inter["lin_A"]))

        # linear_up: flat per-l (C, C) blocks, alpha = 1/sqrt(C)
        def up_tf(l_index, _h=tuple(h_ls_in)):
            def tf(a):
                blocks = a.reshape(len(_h), C, C)
                return blocks[l_index] / np.sqrt(C)
            return tf
        for i, l in enumerate(h_ls_in):
            rules.append(Rule(
                pre + "linear_up.weight",
                ("interactions", t, "lin_up", str(l), "w"), up_tf(i),
            ))

        # radial MLP (e3nn FullyConnectedNet): fold 1/sqrt(fan_in), the
        # normalize2mom(silu) gain into post-activation layers, and — on the
        # output layer — the per-path CG sign calibration exported by
        # tools/export_upstream.py (__cg_sign__ entries), aligning e3nn's
        # wigner_3j sign convention with real_clebsch_gordan's
        n_layers = len(inter["radial"])
        path_signs = _path_signs(
            sd, inter, a_ls,
            paths=model.msg_paths[t] if model is not None else None,
        )
        for li in range(n_layers):
            key = pre + f"conv_tp_weights.layer{li}.weight"
            g = (gain if li > 0 else 1.0)
            d_in = np.shape(inter["radial"][li]["w"])[0]
            if li == n_layers - 1 and path_signs is not None:
                def last_tf(a, _g=g, _d=d_in, _s=path_signs):
                    out = a * (_g / np.sqrt(_d))
                    return (out.reshape(_d, len(_s), C)
                            * _s[None, :, None]).reshape(_d, -1)
                rules.append(Rule(
                    key, ("interactions", t, "radial", li, "w"), last_tf,
                ))
            else:
                rules.append(Rule(
                    key, ("interactions", t, "radial", li, "w"),
                    _scaled(g / np.sqrt(d_in)),
                ))

        # post-conv_tp linear: per-path (C, C) blocks in instruction order
        # (sorted by output irrep — same order as lin_A's path axis),
        # alpha = 1/sqrt(P_l * C)
        offsets = {}
        off = 0
        for l in a_ls:
            P_l = np.shape(inter["lin_A"][str(l)])[0]
            offsets[l] = (off, P_l)
            off += P_l
        n_paths_tot = off

        def lin_tf(l, _offsets=dict(offsets), _tot=n_paths_tot):
            o, P_l = _offsets[l]
            def tf(a):
                blocks = a.reshape(_tot, C, C)
                return blocks[o:o + P_l] / np.sqrt(P_l * C)
            return tf
        for l in a_ls:
            rules.append(Rule(
                pre + "linear.weight",
                ("interactions", t, "lin_A", str(l)), lin_tf(l),
            ))

        # skip_tp (FullyConnectedTensorProduct with species one-hot):
        # flat per-l (C, S, C) blocks, alpha = 1/sqrt(C * S)
        res_ls = sorted(int(l) for l in inter["lin_res"])
        def res_tf(l_index, _n=len(res_ls)):
            def tf(a):
                blocks = a.reshape(_n, C, S, C)
                return blocks[l_index].transpose(1, 0, 2) / np.sqrt(C * S)
            return tf
        for i, l in enumerate(res_ls):
            rules.append(Rule(
                pre + "skip_tp.weight",
                ("interactions", t, "lin_res", str(l)), res_tf(i),
            ))
        # (per-module output_mask buffers are consumed by the catch-all below)
        consume(pre + "avg_num_neighbors",
                expect("avg_num_neighbors", cfg.avg_num_neighbors,
                       "avg_num_neighbors", atol=1e-3)
                if cfg is not None else None)

        # products: symmetric-contraction weights with exact U basis change
        ppre = f"products.{t}."
        out_ls = sorted(int(l) for l in inter["product"])
        S_A = sum(2 * l + 1 for l in a_ls)
        for i, l in enumerate(out_ls):
            cpre = ppre + f"symmetric_contractions.contractions.{i}."
            wts = inter["product"][str(l)]
            nus = sorted(int(k[1:]) for k in wts)
            numax = max(nus)

            def prod_tf(l=l, nu=None, _a=a_ls, _cpre=cpre):
                def tf(a):
                    U_ours = symmetric_coupling_basis(_a, l, nu)
                    u_flat = _find_u_buffer(sd, _cpre, S_A, nu)
                    if u_flat is None:
                        raise ValueError(
                            f"no U_matrix buffer found under {_cpre!r} for "
                            f"correlation {nu}; cannot basis-change the "
                            f"symmetric-contraction weights. Export the "
                            f"checkpoint with U buffers included."
                        )
                    T = _basis_change(U_ours, u_flat)
                    return np.einsum("pq,zqc->zpc", T, a)
                return tf

            rules.append(Rule(
                cpre + "weights_max",
                ("interactions", t, "product", str(l), f"w{numax}"),
                prod_tf(nu=numax),
            ))
            # lower correlations, descending, only for orders the model has
            # (symmetric_coupling_basis can be empty for some (l, nu))
            lower = [n for n in sorted(nus, reverse=True) if n != numax]
            for j, nu in enumerate(lower):
                rules.append(Rule(
                    cpre + f"weights.{j}",
                    ("interactions", t, "product", str(l), f"w{nu}"),
                    prod_tf(nu=nu),
                ))
            # U buffers themselves are consumed (used via the transforms)
            for key in list(sd):
                if key.startswith(cpre) and (
                    "U_matrix" in key or "U_tensors" in key
                ):
                    consume(key)

        # product linear: per-l (C, C) blocks, alpha = 1/sqrt(C)
        def msg_tf(l_index, _n=len(out_ls)):
            def tf(a):
                blocks = a.reshape(_n, C, C)
                return blocks[l_index] / np.sqrt(C)
            return tf
        for i, l in enumerate(out_ls):
            rules.append(Rule(
                ppre + "linear.weight",
                ("interactions", t, "lin_msg", str(l), "w"), msg_tf(i),
            ))

        # readouts
        rpre = f"readouts.{t}."
        if t == len(params["interactions"]) - 1:
            d_mid = np.shape(inter["readout"][0]["w"])[1]
            rules.append(Rule(
                rpre + "linear_1.weight",
                ("interactions", t, "readout", 0, "w"),
                lambda a, _d=d_mid: a.reshape(C, _d) / np.sqrt(C),
            ))
            rules.append(Rule(
                rpre + "linear_2.weight",
                ("interactions", t, "readout", 1, "w"),
                lambda a, _d=d_mid: a.reshape(_d, H) * (gain / np.sqrt(_d)),
            ))
        else:
            rules.append(Rule(
                rpre + "linear.weight",
                ("interactions", t, "readout", 0, "w"),
                lambda a: a.reshape(C, H) / np.sqrt(C),
            ))

    rules.append(Rule("scale_shift.scale", ("scale",),
                      lambda a: np.broadcast_to(np.ravel(a), (H,)).copy()))
    rules.append(Rule("scale_shift.shift", ("shift",),
                      lambda a: np.broadcast_to(np.ravel(a), (H,)).copy()))

    # optional ZBL pair repulsion
    if "zbl" in params:
        rules.append(Rule("pair_repulsion_fn.a_exp", ("zbl", "a_exp"),
                          lambda a: a.reshape(())))
        rules.append(Rule("pair_repulsion_fn.a_prefactor",
                          ("zbl", "a_prefactor"), lambda a: a.reshape(())))
        # our ZBL evaluator hard-codes the universal screening coefficients,
        # the Cordero covalent-radii table, and ties the envelope power to
        # cfg.cutoff_p (upstream ZBLBasis ties it to num_polynomial_cutoff) —
        # a checkpoint trained with different constants would silently
        # evaluate the wrong pair physics, so check instead of just consuming
        from .pair import COVALENT_RADII, _ZBL_C

        consume("pair_repulsion_fn.c",
                expect("pair_repulsion_fn.c", _ZBL_C,
                       "ZBL screening coefficients", atol=1e-6))
        consume("pair_repulsion_fn.p",
                expect("pair_repulsion_fn.p", float(cfg.cutoff_p),
                       "ZBL envelope power p (tied to cutoff_p)")
                if cfg is not None else None)

        def check_radii(a):
            got = np.ravel(np.asarray(a, dtype=np.float64))
            ours = COVALENT_RADII
            n = min(got.size, ours.size)
            # index 0 is the unused placeholder (ase uses 0.2 for 'X', we
            # use 0.0) — compare real elements only
            close = np.isclose(got[1:n], ours[1:n], atol=2e-2)
            if not close.all():
                bad = int(np.argmax(~close)) + 1
                raise ValueError(
                    f"checkpoint covalent radii differ from the built-in "
                    f"Cordero table (first mismatch at Z={bad}: "
                    f"{got[bad]} vs {ours[bad]}); the ZBL cutoff would be "
                    f"wrong for those species"
                )
            # species beyond the built-in table (Z > {ours.size-1}) cannot
            # be validated AND the runtime radii lookup would clamp to the
            # last entry — refuse rather than evaluate wrong pair physics
            if cfg is not None and cfg.atomic_numbers is not None:
                over = [z for z in cfg.atomic_numbers if z >= ours.size]
                if over:
                    raise ValueError(
                        f"ZBL covalent-radii table covers Z<="
                        f"{ours.size - 1}; model species {over} are outside "
                        f"it — extend COVALENT_RADII in models/pair.py"
                    )

        consume("pair_repulsion_fn.covalent_radii", check_radii)

    # remaining bookkeeping entries: e3nn output masks, CG sign calibration
    seen = {r.torch_name for r in rules}
    for key in sd:
        if key in seen:
            continue
        if key.endswith("output_mask") or key.startswith("__cg_sign__"):
            consume(key)
    return rules


# ---------------------------------------------------------------------------
# CHGNet (matgl) mapping
# ---------------------------------------------------------------------------

def _torch_mlp_rules(sd: dict, prefix: str, path: tuple,
                     seq: str = "layers") -> list[Rule]:
    """matgl ``MLP`` (nn.ModuleList/Sequential ``seq`` of Linears interleaved
    with activation modules) -> this framework's layer list. Linear indices
    are discovered from the state dict (activations carry no params), so
    hidden-depth and activation placement never need guessing."""
    import re

    idxs = sorted({
        int(m.group(1))
        for k in sd
        if (m := re.fullmatch(
            re.escape(prefix) + r"\." + seq + r"\.(\d+)\.weight", k))
    })
    if not idxs:
        raise KeyError(f"no Linear layers found under {prefix}.{seq}")
    rules = []
    for j, k in enumerate(idxs):
        rules.append(Rule(f"{prefix}.{seq}.{k}.weight", path + (j, "w"),
                          lambda a: a.T))
        if f"{prefix}.{seq}.{k}.bias" in sd:
            rules.append(Rule(f"{prefix}.{seq}.{k}.bias", path + (j, "b")))
    return rules


def _torch_gated_mlp_rules(sd: dict, prefix: str, path: tuple) -> list[Rule]:
    """matgl ``GatedMLP`` (two nn.Sequentials: ``layers`` core w/ silu,
    ``gates`` w/ sigmoid-last) -> {'core': [...], 'gate': [...]}."""
    return (_torch_mlp_rules(sd, prefix, path + ("core",), seq="layers")
            + _torch_mlp_rules(sd, prefix, path + ("gate",), seq="gates"))


def _potential_extra_rules(sd: dict, species_ref_shape: tuple) -> list[Rule]:
    """matgl ``Potential.state_dict()`` extras, shared by the chgnet and
    tensornet mappings: ``element_refs.property_offset`` -> species_ref,
    ``data_std`` -> data_std; a nonzero ``data_mean`` (a per-structure
    offset this per-atom parameterization cannot carry exactly) is refused.
    """
    S = species_ref_shape[0]
    rules: list[Rule] = []
    if "element_refs.property_offset" in sd:
        rules.append(Rule(
            "element_refs.property_offset", ("species_ref", "w"),
            lambda a: np.reshape(a, (-1,))[:S].reshape(species_ref_shape)))
    if "data_std" in sd:
        rules.append(Rule("data_std", ("data_std",),
                          lambda a: np.reshape(a, ())))
    if "data_mean" in sd:
        def expect_zero(a):
            if not np.allclose(np.asarray(a, dtype=np.float64), 0.0,
                               atol=1e-12):
                raise ValueError(
                    f"data_mean = {np.ravel(a)} is nonzero: matgl applies it "
                    f"once per structure, which this per-atom "
                    f"parameterization cannot represent exactly — fold it "
                    f"into element_refs upstream or re-reference the "
                    f"checkpoint"
                )
        rules.append(Rule("data_mean", None, expect_zero))
    return rules


@register_mapping("chgnet")
def chgnet_mapping(params, sd, model=None):
    """matgl ``CHGNet.state_dict()`` -> CHGNet params (the reference wraps
    these checkpoints via from_existing, chgnet.py:551-560; module inventory
    pinned by enable_distributed_mode, chgnet.py:455-549).

    Also accepts a matgl ``Potential.state_dict()`` dump (``model.``-prefixed
    keys): ``element_refs.property_offset`` -> species_ref, ``data_std`` ->
    data_std; a nonzero ``data_mean`` is refused (it is a per-structure
    offset this per-atom parameterization cannot carry exactly).
    """
    C = np.shape(params["atom_emb"]["w"])[1]
    S = np.shape(params["atom_emb"]["w"])[0]
    p = "model." if any(k.startswith("model.") for k in sd) else ""
    rules: list[Rule] = []

    # learnable basis frequencies (matgl RadialBessel/FourierExpansion)
    rules.append(Rule(p + "bond_expansion.frequencies", ("freq_bond",)))
    if "freq_three" in params and p + "threebody_bond_expansion.frequencies" in sd:
        rules.append(Rule(p + "threebody_bond_expansion.frequencies",
                          ("freq_three",)))
        rules.append(Rule(p + "angle_expansion.frequencies", ("freq_angle",)))

    # embeddings: atom_embedding is nn.Embedding (weight used as-is); a
    # one-hot single-layer MLP variant is folded into the same table
    if p + "atom_embedding.weight" in sd:
        rules.append(Rule(p + "atom_embedding.weight", ("atom_emb", "w")))
    else:
        def onehot_fold(a):
            W = a.T  # (S, C)
            b = sd.get(p + "atom_embedding.layers.0.bias")
            if b is not None:
                W = W + np.asarray(_t(b))[None, :]
            return W
        rules.append(Rule(p + "atom_embedding.layers.0.weight",
                          ("atom_emb", "w"), onehot_fold))
        if p + "atom_embedding.layers.0.bias" in sd:
            rules.append(Rule(p + "atom_embedding.layers.0.bias", None))
    rules += _torch_mlp_rules(sd, p + "bond_embedding", ("bond_emb",))
    if "freq_angle" in params and any(
            k.startswith(p + "angle_embedding.") for k in sd):
        rules += _torch_mlp_rules(sd, p + "angle_embedding", ("angle_emb",))

    # shared rbf message weights (bias-free linears)
    for tname, ours in (("atom_bond_weights", "atom_bond_w"),
                        ("bond_bond_weights", "bond_bond_w"),
                        ("threebody_bond_weights", "three_bond_w")):
        if p + f"{tname}.weight" in sd:
            if ours in params:
                rules.append(Rule(p + f"{tname}.weight", (ours, "w"),
                                  lambda a: a.T))
            else:
                raise ValueError(
                    f"checkpoint has {tname} but the model config disables it "
                    f"(shared_bond_weights); rebuild with a matching config"
                )

    def conv_rules(tpre, bpath, blk):
        out = _torch_gated_mlp_rules(
            sd, tpre + "node_update_func", bpath + ("node_update",))
        if tpre + "node_out_func.weight" in sd:
            out.append(Rule(tpre + "node_out_func.weight",
                            bpath + ("node_out", "w"), lambda a: a.T))
        else:
            # upstream variant without the out linear: identity (match the
            # leaf's dtype so float64 parity paths stay float64)
            blk["node_out"]["w"] = np.eye(
                C, dtype=np.asarray(blk["node_out"]["w"]).dtype)
        return out

    # atom graph blocks
    for i, blk in enumerate(params["atom_blocks"]):
        tpre = p + f"atom_graph_layers.{i}.conv_layer."
        rules += conv_rules(tpre, ("atom_blocks", i), blk)
        has_eu = any(k.startswith(tpre + "edge_update_func.") for k in sd)
        if has_eu != ("edge_update" in blk):
            raise ValueError(
                f"atom_graph_layers.{i} edge update presence mismatch "
                f"(checkpoint {has_eu} vs config bond_update_hidden); "
                f"rebuild with a matching config"
            )
        if has_eu:
            rules += _torch_gated_mlp_rules(
                sd, tpre + "edge_update_func", ("atom_blocks", i, "edge_update"))
            if tpre + "edge_out_func.weight" in sd:
                rules.append(Rule(tpre + "edge_out_func.weight",
                                  ("atom_blocks", i, "edge_out", "w"),
                                  lambda a: a.T))
            else:
                blk["edge_out"]["w"] = np.eye(
                    C, dtype=np.asarray(blk["edge_out"]["w"]).dtype)

    # bond graph blocks (line-graph conv + angle update)
    for i, blk in enumerate(params["bond_blocks"]):
        tpre = p + f"bond_graph_layers.{i}.conv_layer."
        rules += conv_rules(tpre, ("bond_blocks", i), blk)
        if any(k.startswith(tpre + "edge_update_func.") for k in sd):
            rules += _torch_gated_mlp_rules(
                sd, tpre + "edge_update_func", ("bond_blocks", i, "angle_update"))
        else:
            # no angle update in the checkpoint: zero ours (residual no-op)
            blk["angle_update"] = jax_zero_like(blk["angle_update"])

    # readouts
    if p + "sitewise_readout.weight" in sd:
        rules += linear_rule(p + "sitewise_readout", ("sitewise",),
                             bias=p + "sitewise_readout.bias" in sd)
    if any(k.startswith(p + "final_layer.gates.") for k in sd):
        raise ValueError(
            "checkpoint final_layer is a GatedMLP (final_mlp_type='gated'); "
            "only the MLP readout is supported — file an issue"
        )
    rules += _torch_mlp_rules(sd, p + "final_layer", ("final",))

    # Potential-level extras (matgl Potential.state_dict dumps)
    if p:
        rules += _potential_extra_rules(sd, (S, 1))
    return rules


# ---------------------------------------------------------------------------
# TensorNet (matgl / torchmd-net port) mapping
# ---------------------------------------------------------------------------

def _ln_rules(prefix: str, path: tuple) -> list[Rule]:
    """nn.LayerNorm -> {'g', 'b'}."""
    return [Rule(f"{prefix}.weight", path + ("g",)),
            Rule(f"{prefix}.bias", path + ("b",))]


@register_mapping("tensornet")
def tensornet_mapping(params, sd, model=None):
    """matgl ``TensorNet.state_dict()`` -> TensorNet params (the reference
    wraps these checkpoints via from_existing, tensornet.py:204-214; module
    inventory pinned by enable_distributed_mode :179-197 and the readout by
    dist_forward :131-159). Accepts matgl ``Potential.state_dict()`` dumps
    the same way as the CHGNet mapping.
    """
    p = "model." if any(k.startswith("model.") for k in sd) else ""
    S = np.shape(params["species_emb"]["w"])[0]
    rules: list[Rule] = []
    tpre = p + "tensor_embedding."

    rules.append(Rule(tpre + "emb.weight", ("species_emb", "w")))
    rules += linear_rule(tpre + "emb2", ("emb2",),
                         bias=tpre + "emb2.bias" in sd)
    for i in range(3):
        pre = tpre + f"distance_proj{i + 1}"
        rules += linear_rule(pre, ("dist_proj", i), bias=pre + ".bias" in sd)
    for i in range(2):
        pre = tpre + f"linears_scalar.{i}"
        rules += linear_rule(pre, ("emb_lin_scalar", i),
                             bias=pre + ".bias" in sd)
    for i in range(3):
        rules.append(Rule(tpre + f"linears_tensor.{i}.weight",
                          ("emb_lin_tensor", i, "w"), lambda a: a.T))
    rules += _ln_rules(tpre + "init_norm", ("init_norm",))

    for t, _ in enumerate(params["layers"]):
        lpre = p + f"layers.{t}."
        for i in range(3):
            pre = lpre + f"linears_scalar.{i}"
            rules += linear_rule(pre, ("layers", t, "lin_scalar", i),
                                 bias=pre + ".bias" in sd)
        for i in range(6):
            rules.append(Rule(lpre + f"linears_tensor.{i}.weight",
                              ("layers", t, "lin_tensor", i, "w"),
                              lambda a: a.T))

    rules += _ln_rules(p + "out_norm", ("out_norm",))
    rules += linear_rule(p + "linear", ("linear",),
                         bias=p + "linear.bias" in sd)
    rules += _torch_mlp_rules(sd, p + "final_layer.gated", ("final",))

    # radial-basis buffers: this framework's basis is the fixed n*pi bessel
    # set — a checkpoint with trained or non-bessel frequencies cannot be
    # represented, so validate instead of silently consuming
    cfg = model.cfg if model is not None else None
    for key in list(sd):
        tail = key[len(p):] if key.startswith(p) else key
        if tail.startswith("bond_expansion."):
            if "frequenc" in tail and cfg is not None:
                def check_freq(a, _n=cfg.num_rbf):
                    got = np.ravel(np.asarray(a, dtype=np.float64))
                    want = np.pi * np.arange(1, _n + 1)
                    if got.size != want.size or not np.allclose(
                            got, want, atol=1e-4):
                        raise ValueError(
                            "checkpoint bond_expansion frequencies differ "
                            "from the fixed n*pi bessel basis; trained "
                            "frequencies are not representable"
                        )
                rules.append(Rule(key, None, check_freq))
            else:
                rules.append(Rule(key, None))

    if p:
        rules += _potential_extra_rules(sd, (S, 1))
    return rules


# ---------------------------------------------------------------------------
# eSCN / UMA (fairchem eSCNMDBackbone) mapping
# ---------------------------------------------------------------------------


def _rad_rules(prefix: str, path: tuple) -> list[Rule]:
    """RadialFunction (Linear -> LayerNorm -> SiLU -> Linear) under
    fairchem's Sequential numbering: net.0 Linear, net.1 LayerNorm,
    net.3 final Linear. ESCNMD stores linears torch-shaped (out, in), so
    no transpose."""
    return [
        Rule(f"{prefix}.net.0.weight", path + ("lins", 0, "w")),
        Rule(f"{prefix}.net.0.bias", path + ("lins", 0, "b")),
        Rule(f"{prefix}.net.1.weight", path + ("lns", 0, "g")),
        Rule(f"{prefix}.net.1.bias", path + ("lns", 0, "b")),
        Rule(f"{prefix}.net.3.weight", path + ("lins", 1, "w")),
        Rule(f"{prefix}.net.3.bias", path + ("lins", 1, "b")),
    ]


def _so2_rules(prefix: str, path: tuple, m_max: int,
               internal: bool) -> list[Rule]:
    """SO2_Convolution: fc_m0 (+bias) and per-|m| so2_m_conv.{m-1}.fc
    weights (bias-free complex pairs, output = [real | imag] halves).
    MOLE checkpoints carry the same names with a leading expert axis —
    shapes are validated against the params leaf by set_in. ``internal``
    marks fairchem's internal_weights=True convs (no rad_func)."""
    rules = [
        Rule(f"{prefix}.fc_m0.weight", path + ("m0",)),
        Rule(f"{prefix}.fc_m0.bias", path + ("m0_b",)),
    ]
    for m in range(1, m_max + 1):
        rules.append(Rule(f"{prefix}.so2_m_conv.{m - 1}.fc.weight",
                          path + (f"m{m}",)))
    if not internal:
        rules += _rad_rules(f"{prefix}.rad_func", path + ("rad",))
    return rules


@register_mapping("escn")
def escn_mapping(params, sd, model=None):
    """fairchem ``eSCNMDBackbone.state_dict()`` -> ESCNMD params.

    Closes the last unconverted family (the reference's UMA flagship,
    from_existing at implementations/uma/escn_md.py:559-569). The key
    layout follows the surface visible through the reference wrapper
    (sphere/source/target embeddings, distance_expansion, csd_embedding,
    edge_degree_embedding, blocks[i] with SO(2) convolutions, final norm
    — escn_md.py:221-247,319-330,443-516) with block internals
    reconstructed from the public equiformer_v2/eSCN lineage; the float64
    torch oracle in tests/test_convert_escn.py is the golden contract.
    A ``backbone.`` prefix (whole-model UMA dumps) is handled; head
    tensors map onto the energy head when present.

    Framework-local parameters (NOT populated from any checkpoint):
    ``species_ref`` (per-element reference energies; fit via
    ``train.fit_species_ref`` or leave zero) and ``mole_gate`` (the MOLE
    expert-routing MLP — this framework routes on a psum-consistent
    system composition + csd vector, escn_md.py:363-371, a different
    input space from fairchem's routing net, so upstream routing weights
    CANNOT be transplanted). A checkpoint that carries MOLE-routing
    tensors is refused loudly below rather than converted into a model
    whose expert mixtures would be silently random (ADVICE r4).
    """
    # word-boundary match: "mole" as a standalone token (mole_coefficients,
    # blocks.0.mole.net...) or any "routing" — NOT substrings of unrelated
    # names like "molecule_embedding". Expert WEIGHTS (a leading expert
    # axis on so2 tensors) are convertible and unaffected by this guard.
    mole_keys = [k for k in sd
                 if re.search(r"(?<![a-zA-Z])mole(?![a-zA-Z])", k,
                              re.IGNORECASE)
                 or "routing" in k.lower()]
    if mole_keys:
        raise ValueError(
            f"state dict carries {len(mole_keys)} MOLE expert-routing "
            f"tensors (first 5: {mole_keys[:5]}) which have no equivalent "
            "here: this framework's expert gate (params['mole_gate']) "
            "routes on system composition + csd and must be retrained "
            "(train.py distillation recipe, PARITY.md 'UMA endgame'). "
            "Remove the routing tensors from the dict to convert the "
            "expert weights themselves — the resulting gate is "
            "fresh-initialized, NOT the upstream routing.")
    p = "backbone." if any(k.startswith("backbone.") for k in sd) else ""
    cfg = model.cfg if model is not None else None
    n_blocks = len(params["blocks"])
    # ESCNMD clamps m_max = min(mmax, lmax) (CoeffLayout); the rules must
    # match or a config with mmax > lmax would demand m-weights no
    # checkpoint (or params tree) carries
    m_max = (min(cfg.mmax, cfg.lmax) if cfg is not None
             else len([k for k in sd
                       if f"{p}blocks.0.so2_conv_1.so2_m_conv." in k
                       and k.endswith(".fc.weight")]))

    rules: list[Rule] = [
        Rule(p + "sphere_embedding.weight", ("sphere_embedding", "w")),
        Rule(p + "source_embedding.weight", ("source_embedding", "w")),
        Rule(p + "target_embedding.weight", ("target_embedding", "w")),
        Rule(p + "csd_embedding.charge_embedding.weight",
             ("csd", "charge", "w")),
        Rule(p + "csd_embedding.spin_embedding.weight", ("csd", "spin", "w")),
        Rule(p + "csd_embedding.dataset_embedding.weight",
             ("csd", "dataset", "w")),
        Rule(p + "csd_embedding.mix_csd.weight", ("csd", "mix", "w")),
        Rule(p + "csd_embedding.mix_csd.bias", ("csd", "mix", "b")),
        Rule(p + "norm.affine_weight", ("norm", "w")),
    ]
    rules += _rad_rules(p + "edge_degree_embedding.rad_func",
                        ("edge_deg_rad",))

    # distance_expansion: a gaussian-offset buffer, not weights — validate
    # it matches the linspace(0, cutoff, num_distance_basis) this framework
    # hardcodes rather than consuming it silently
    if p + "distance_expansion.offset" in sd and cfg is not None:
        def check_offsets(a, _cfg=cfg):
            want = np.linspace(0.0, _cfg.cutoff, _cfg.num_distance_basis)
            got = np.ravel(np.asarray(a, dtype=np.float64))
            if got.size != want.size or not np.allclose(got, want, atol=1e-5):
                raise ValueError(
                    "checkpoint gaussian offsets differ from "
                    "linspace(0, cutoff, num_distance_basis)")
        rules.append(Rule(p + "distance_expansion.offset", None,
                          check_offsets))

    for i in range(n_blocks):
        bp = f"{p}blocks.{i}."
        path = ("blocks", i)
        rules.append(Rule(bp + "norm_1.affine_weight", path + ("norm1", "w")))
        rules += _so2_rules(bp + "so2_conv_1", path + ("so2_1",), m_max,
                            internal=False)
        rules += _so2_rules(bp + "so2_conv_2", path + ("so2_2",), m_max,
                            internal=True)
        rules.append(Rule(bp + "ff_norm.affine_weight",
                          path + ("ff_norm", "w")))
        rules.append(Rule(bp + "ff.so3_linear_1.weight",
                          path + ("ff", "lin1", "w")))
        rules.append(Rule(bp + "ff.so3_linear_1.bias",
                          path + ("ff", "lin1", "b")))
        rules.append(Rule(bp + "ff.gating_linear.weight",
                          path + ("ff", "gate", "w")))
        rules.append(Rule(bp + "ff.gating_linear.bias",
                          path + ("ff", "gate", "b")))
        rules.append(Rule(bp + "ff.so3_linear_2.weight",
                          path + ("ff", "lin2", "w")))
        rules.append(Rule(bp + "ff.so3_linear_2.bias",
                          path + ("ff", "lin2", "b")))

    # energy head (fairchem heads are separate modules; a whole-model dump
    # carries them as heads.energy.*)
    for hp in ("heads.energy.mlp.", "energy_head.mlp."):
        if any(k.startswith(hp) for k in sd):
            rules += [
                Rule(hp + "0.weight", ("energy_head", "lin1", "w")),
                Rule(hp + "0.bias", ("energy_head", "lin1", "b")),
                Rule(hp + "2.weight", ("energy_head", "lin2", "w")),
                Rule(hp + "2.bias", ("energy_head", "lin2", "b")),
            ]
            break
    return rules


def jax_zero_like(tree):
    import jax

    return jax.tree.map(lambda x: np.zeros_like(np.asarray(x)), tree) \
        if tree is not None else None


def from_torch(arch: str, state_dict: dict, params, strict: bool = True,
               model=None):
    """Map an upstream torch ``state_dict`` onto this framework's ``params``.

    The reference's ``from_existing`` capability (chgnet.py:551-560,
    models.py:252-263): the returned params evaluate the pretrained model.
    Mappings receive the state dict too, so transforms can consult
    checkpoint-borne constants (e.g. MACE's U-matrix buffers drive an exact
    product-basis change). Pass ``model`` (the framework model instance) to
    additionally validate checkpoint constants (cutoff, envelope power,
    bessel frequencies, avg_num_neighbors) against the model config and to
    resolve CG sign calibration unambiguously. strict=True fails loudly on
    any unmapped tensor.
    """
    if arch not in MAPPINGS:
        raise KeyError(f"no mapping registered for {arch!r}; have {sorted(MAPPINGS)}")
    rules = MAPPINGS[arch](params, state_dict, model)
    return convert(state_dict, params, rules, strict=strict)
