"""Weight ingestion from upstream torch checkpoints.

The reference's ``from_existing(model)`` copies a trained upstream torch
module's ``__dict__`` into its distributed subclass (reference
chgnet.py:551-560, models.py:252-263). The TPU-native equivalent maps a
torch ``state_dict`` onto this framework's parameter pytrees.

Generic machinery here; per-architecture name maps live in MAPPINGS. Exact
upstream-name coverage is validated opportunistically: ``convert`` reports
unmapped/unused tensors so partial maps fail loudly instead of silently
producing a half-initialized model.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np


def _t(x):
    """torch tensor / numpy -> numpy array."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


@dataclass
class Rule:
    """Maps one torch tensor onto one pytree leaf path.

    path: tuple of keys/indices into the params pytree.
    transform: applied to the torch array (default: linear weights transpose,
    since torch nn.Linear stores (out, in) and this framework uses (in, out)).
    """

    torch_name: str
    path: tuple
    transform: Callable[[np.ndarray], np.ndarray] | None = None


def set_in(tree, path, value):
    node = tree
    for p in path[:-1]:
        node = node[p]
    leaf = node[path[-1]]
    if np.shape(leaf) != value.shape:
        raise ValueError(
            f"shape mismatch at {path}: torch {value.shape} vs model {np.shape(leaf)}"
        )
    node[path[-1]] = value.astype(np.asarray(leaf).dtype)


def convert(state_dict: dict, params, rules: list[Rule], strict: bool = True):
    """Apply mapping rules; returns (params, report)."""
    used = set()
    for r in rules:
        if r.torch_name not in state_dict:
            if strict:
                raise KeyError(f"torch checkpoint missing {r.torch_name!r}")
            continue
        arr = _t(state_dict[r.torch_name])
        if r.transform is not None:
            arr = r.transform(arr)
        set_in(params, r.path, arr)
        used.add(r.torch_name)
    unused = sorted(set(state_dict) - used)
    report = {"mapped": len(used), "unused_torch": unused}
    if strict and unused:
        raise ValueError(
            f"{len(unused)} torch tensors unmapped (first 10): {unused[:10]}"
        )
    return params, report


def linear_rule(torch_prefix: str, path: tuple, bias: bool = True) -> list[Rule]:
    """nn.Linear -> {'w': (in,out), 'b': (out,)}"""
    rules = [Rule(f"{torch_prefix}.weight", path + ("w",), lambda a: a.T)]
    if bias:
        rules.append(Rule(f"{torch_prefix}.bias", path + ("b",), None))
    return rules


# ---------------------------------------------------------------------------
# Per-architecture maps. These cover this framework's own parameterization;
# upstream checkpoints additionally need the architecture hyperparameters to
# match (units/blocks/rbf sizes). Populated incrementally as upstream
# checkpoints become loadable in the environment; `convert` fails loudly on
# any gap.
# ---------------------------------------------------------------------------

MAPPINGS: dict[str, Callable] = {}


def register_mapping(name: str):
    def deco(fn):
        MAPPINGS[name] = fn
        return fn

    return deco


def from_torch(arch: str, state_dict: dict, params, strict: bool = True):
    if arch not in MAPPINGS:
        raise KeyError(f"no mapping registered for {arch!r}; have {sorted(MAPPINGS)}")
    rules = MAPPINGS[arch](params)
    return convert(state_dict, params, rules, strict=strict)
