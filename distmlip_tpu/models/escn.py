"""eSCN / UMA-style equivariant spherical channel network.

TPU-native implementation of the eSCN architecture (Passaro & Zitnick 2023)
as used by the reference's UMA path (reference
implementations/uma/escn_md.py:250-523: per-partition Wigner rotation
matrices, SO(2) convolutions in the edge frame, MOLE mixture-of-linear-
experts coefficients replicated into every partition, halo exchange between
layers). Differences from the reference's CUDA/thread-pool design: the edge
Wigner matrices are built on-device inside the jitted program (no host
precompute/upload per graph), and the whole layer loop is one SPMD
program.

Round 5: the Wigner/rotation machinery is the SHARED core ``ops/so3_e3nn``
(per-l Jd-table pipeline, e3nn y-polar basis, pole-safe angles,
gauge-certified by tests/test_escn_md.py) — the same implementation
ESCNMD uses, so there is exactly one edge-frame rotation path to
maintain. What stays deliberately DIFFERENT between the two eSCN stacks
is the SO(2) parameterization — this model is the performance-first
variant (free-form per-|m| expert-stacked weights, any l_max <= 6, no
upstream weight-layout constraints); ``escn_md.ESCNMD`` is the
UMA-convertible variant (fairchem's exact fc_m0/so2_m_conv/RadialFunction
layout for checkpoint ingestion). That split is the permanent contract:
capability/perf here, parity there.

Node features: h (N, S, C) — S = (l_max+1)^2 stacked real spherical-harmonic
coefficients (l <= 6) in the e3nn layout (per l, m = -l..l with the m=0
polar-aligned slot at the block center), channels LAST so C lands in the
TPU lane dimension (S=9..49 in the lane axis would pad to 128 and inflate
HBM traffic 2.6-14x; see the MACE channels-last note, models/mace.py).
Each edge: rotate the sender features into the edge-aligned frame, run
SO(2) convolutions (per-|m| channel-mixing linear maps with the (+m, -m)
complex pair structure, which commutes with rotations about the edge
axis), rotate back, aggregate on the owner partition, gated nonlinearity.

UMA MOLE: with num_experts > 1 the SO(2) weights are convex mixtures of
expert weights with coefficients from a whole-system composition embedding —
computed identically (replicated) on every partition, matching the
reference's recursive_replace_so2_MOLE (escn_md.py:343-357).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..kernels.dispatch import fused_segment_sum, fused_so2_conv
from ..ops import radial
from ..ops.nn import cast_params_subtrees, linear, linear_init, mlp, mlp_init
from ..ops.so3_e3nn import CoeffLayout, wigner_blocks_from_edges


@dataclass(frozen=True)
class ESCNConfig:
    num_species: int = 95
    channels: int = 64
    l_max: int = 2              # <= 6 (SH table limit)
    num_layers: int = 3
    num_bessel: int = 8
    num_experts: int = 1        # > 1 enables UMA-style MOLE weight mixing
    cutoff: float = 5.0
    avg_num_neighbors: float = 14.0
    # UMA charge/spin/dataset (csd) conditioning (reference
    # uma/escn_md.py:255-265): per-system embeddings mixed into the node
    # scalars and the MOLE gate
    num_charges: int = 25       # charge index = charge - charge_min
    charge_min: int = -12
    num_spins: int = 10
    num_datasets: int = 4
    edge_channels: int = 32     # source/target species embeddings feeding the
                                # edge-degree embedding (ref escn_md.py:378-415)
    edge_chunk: int = 32768     # process edges in chunks of this size inside a
                                # lax.scan: the per-edge rotated features
                                # (E, S, C) and Wigner blocks (E, S, S) are
                                # rebuilt per chunk, bounding memory regardless
                                # of system size (0 disables chunking). At
                                # UMA-real l_max=6, S=49: unchunked 1M-edge
                                # systems would need >100 GB for these alone.
    remat: bool | str = True    # rematerialize each chunk in the backward
                                # pass (bool or checkpoint-policy name,
                                # ops/chunk.remat_wrap)
    dtype: str = "float32"

    @property
    def sphere_dim(self) -> int:
        return (self.l_max + 1) ** 2


def _l_slices(l_max):
    out = {}
    o = 0
    for l in range(l_max + 1):
        out[l] = slice(o, o + 2 * l + 1)
        o += 2 * l + 1
    return out


class ESCN:
    supports_compute_dtype = True  # energy_fn honors cfg.dtype="bfloat16"

    def __init__(self, config: ESCNConfig = ESCNConfig()):
        if config.l_max > 6:
            raise NotImplementedError(
                "l_max > 6: extend the SH tables backing ops/so3_e3nn.jd_np")
        self.cfg = config
        # shared-core layout (full, no mmax narrowing): per |m|, the stacked
        # indices of the (l, +m) / (l, -m) pair over l = m..l_max — the
        # complex pairs the SO(2) convolutions mix
        lay = CoeffLayout(config.l_max)
        self.m_idx = {m: (lay.plus_idx[m], lay.minus_idx[m])
                      for m in range(config.l_max + 1)}

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        C, E = cfg.channels, cfg.num_experts
        Ce = cfg.edge_channels
        ks = iter(jax.random.split(key, 16 + cfg.num_layers * (4 * (cfg.l_max + 1) + 8)))
        params = {
            "species_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, C))},
            # csd conditioning: charge/spin/dataset embeddings mixed by an MLP
            "charge_emb": {"w": jax.random.normal(next(ks), (cfg.num_charges, C))},
            "spin_emb": {"w": jax.random.normal(next(ks), (cfg.num_spins, C))},
            "dataset_emb": {"w": jax.random.normal(next(ks), (cfg.num_datasets, C))},
            "csd_mlp": mlp_init(next(ks), [C, C]),
            "sys_node_proj": linear_init(next(ks), C, C),
            # edge-degree embedding: per-edge scalars -> m=0 coefficients
            "source_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, Ce))},
            "target_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, Ce))},
            "edge_deg": linear_init(
                next(ks), cfg.num_bessel + 2 * Ce, C * (cfg.l_max + 1)
            ),
            "mole_gate": mlp_init(next(ks), [2 * C, C, E]) if E > 1 else None,
            "layers": [],
            "energy_mlp": mlp_init(next(ks), [C, C, 1]),
            "species_ref": {"w": jnp.zeros((cfg.num_species,))},
        }
        for _ in range(cfg.num_layers):
            layer = {
                "edge_mlp": mlp_init(
                    next(ks), [cfg.num_bessel + 2 * C, C, C]
                ),
                "so2": {},
                "gate_mlp": mlp_init(next(ks), [C, C, C]),
                "scalar_mlp": mlp_init(next(ks), [C, C, C]),
            }
            for m in range(cfg.l_max + 1):
                nl = cfg.l_max + 1 - m
                d = nl * C
                if m == 0:
                    layer["so2"]["m0"] = (
                        jax.random.normal(next(ks), (E, d, d)) / np.sqrt(d)
                    )
                else:
                    layer["so2"][f"m{m}r"] = (
                        jax.random.normal(next(ks), (E, d, d)) / np.sqrt(d)
                    )
                    layer["so2"][f"m{m}i"] = (
                        jax.random.normal(next(ks), (E, d, d)) / np.sqrt(d)
                    )
            params["layers"].append(layer)
        return params

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        cfg = self.cfg
        C, S = cfg.channels, cfg.sphere_dim
        # compute dtype for features/SO(2) GEMMs (cfg.dtype="bfloat16");
        # geometry and the final energy sum stay in the positions dtype
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        if cfg.dtype == "bfloat16":
            # species_ref (O(10-100) eV reference energies) and the energy
            # readout stay fp32 so the energy path keeps full precision. The
            # cast is O(param bytes) per step — negligible next to the edge
            # activations.
            params = cast_params_subtrees(
                params, dtype, keep_fp32=("species_ref", "energy_mlp")
            )

        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        # rhat stays in the positions dtype: the shared Wigner core
        # (ops/so3_e3nn) builds its trig chains in fp32 regardless and D is
        # downcast per-use in rotate()
        rhat = vec / jnp.maximum(d, 1e-9)[:, None]
        env = (radial.polynomial_cutoff(d, cfg.cutoff) * lg.edge_mask).astype(dtype)
        bessel = radial.spherical_bessel_basis(d, cfg.cutoff, cfg.num_bessel
                                               ).astype(dtype)
        sl = _l_slices(cfg.l_max)

        def rotate(hvecs, D, to_edge=False):
            # hvecs: (E_c, S, C) rotated per l block. D comes from the
            # shared core (lab-from-edge): plain D maps edge-frame
            # coefficients to the lab frame, D^T (to_edge=True) maps lab
            # features into the edge-aligned frame.
            parts = []
            for l in range(cfg.l_max + 1):
                Dl = D[l].astype(hvecs.dtype)
                if to_edge:
                    Dl = jnp.swapaxes(Dl, -1, -2)
                parts.append(jnp.einsum("epq,eqc->epc", Dl, hvecs[:, sl[l], :]))
            return jnp.concatenate(parts, axis=1)

        # --- edge-chunked scan over the per-edge pipeline ---------------
        # The edge-frame Wigner blocks (E, S, S) and rotated features
        # (E, S, C) are the memory giants of eSCN; both are rebuilt per
        # chunk inside a lax.scan (the Jd-pipeline build is 3 z-rotations
        # + 2 constant matmuls per l — noise next to the SO(2) GEMMs), so
        # peak memory is O(chunk), not O(E). Scaffolding shared with MACE
        # (ops/chunk.py).
        from ..ops.chunk import chunk_layout, chunked, scan_accumulate

        e_cap = lg.edge_src.shape[0]
        # chunk boundaries aligned to the interior/frontier split so every
        # chunk's dst stays sorted (indices_are_sorted survives the layout)
        row_idx, row_valid, K, chunk = chunk_layout(
            e_cap, cfg.edge_chunk,
            lg.e_split if lg.has_frontier_split else None)
        take = lambda x: chunked(jnp.asarray(x)[row_idx], K, chunk)
        edge_xs = (
            take(lg.edge_src),
            take(lg.edge_dst),
            chunked(jnp.asarray(lg.edge_mask)[row_idx]
                    & jnp.asarray(row_valid), K, chunk),
            take(rhat),
            take(bessel),
            take(env),
        )
        # single-chunk path: build D once (fp32) and share it across the
        # edge-degree pass and every layer instead of per edge_scan call
        D_shared = (
            wigner_blocks_from_edges(cfg.l_max, edge_xs[3][0])
            if K == 1 else None
        )

        def edge_scan(per_chunk, out_shape):
            """Accumulate sum_chunks per_chunk(...) over the edge chunks.

            per_chunk(srcc, dstc, maskc, D, besc, envc) -> (E_c, ...) message
            rows, segment-summed onto their dst inside the scan."""

            def body(acc, xs):
                srcc, dstc, maskc, rhatc, besc, envc = xs
                D = (
                    D_shared
                    if D_shared is not None
                    else wigner_blocks_from_edges(cfg.l_max, rhatc)
                )
                msg = per_chunk(srcc, dstc, maskc, D, besc, envc)
                return (
                    acc
                    + fused_segment_sum(
                        # sorted within every chunk by chunk_layout;
                        # Pallas dst-tiled scatter on TPU (kernels/dispatch)
                        msg, dstc, lg.n_cap, maskc, indices_are_sorted=True,
                        kernels=lg.kernels,
                    ),
                    None,
                )

            acc0 = jnp.zeros((lg.n_cap,) + out_shape, dtype=dtype)
            return scan_accumulate(body, acc0, edge_xs, remat=cfg.remat)

        # device array: the chunked scan indexes z with traced chunk indices,
        # which a host numpy species array cannot support
        z = jnp.asarray(lg.species)
        zemb = params["species_emb"]["w"][z].astype(dtype)  # (N, C)

        # csd (charge/spin/dataset) system embedding (ref escn_md.py:255-265)
        sys_state = lg.system or {}
        qi = jnp.clip(
            jnp.asarray(sys_state.get("charge", 0)) - cfg.charge_min,
            0, cfg.num_charges - 1,
        )
        si = jnp.clip(jnp.asarray(sys_state.get("spin", 0)), 0, cfg.num_spins - 1)
        di = jnp.clip(
            jnp.asarray(sys_state.get("dataset", 0)), 0, cfg.num_datasets - 1
        )
        csd = mlp(
            params["csd_mlp"],
            (
                params["charge_emb"]["w"][qi]
                + params["spin_emb"]["w"][si]
                + params["dataset_emb"]["w"][di]
            ).astype(dtype),
        )  # (C,)

        h = jnp.zeros((positions.shape[0], S, C), dtype=dtype)
        # node scalars: species embedding + the system (csd) embedding
        # (ref escn_md.py:330 x_message[:, 0, :] += sys_node_embedding)
        h = h.at[:, 0, :].set(zemb + linear(params["sys_node_proj"], csd)[None, :])

        # edge-degree embedding: per-edge scalars (distance expansion +
        # source/target species embeddings) -> m=0 coefficients in the edge
        # frame, rotated back and degree-summed onto the receiver
        # (ref escn_md.py:378-415)
        def deg_chunk(srcc, dstc, maskc, D, besc, envc):
            x_edge = jnp.concatenate(
                [
                    besc,
                    params["source_emb"]["w"][z[srcc]].astype(dtype),
                    params["target_emb"]["w"][z[dstc]].astype(dtype),
                ],
                axis=-1,
            )
            w_deg = linear(params["edge_deg"], x_edge).reshape(
                -1, cfg.l_max + 1, C
            )
            y_deg = jnp.zeros((w_deg.shape[0], S, C), dtype=dtype)
            for l in range(cfg.l_max + 1):
                # (l, m=0): e3nn block center, index l^2 + l
                y_deg = y_deg.at[:, l * l + l, :].set(w_deg[:, l, :])
            return rotate(y_deg, D) * envc[:, None, None]

        h = h + edge_scan(deg_chunk, (S, C)) * jnp.asarray(
            1.0 / cfg.avg_num_neighbors, dtype=dtype
        )
        h = lg.halo_exchange(h)

        # MOLE coefficients: whole-system composition embedding + csd ->
        # softmax gate. Globally consistent across partitions (psum'd mean),
        # replicated — the TPU version of the reference's replicated MOLE
        # coefficients with its csd-driven gating (escn_md.py:255-265,343-357)
        #
        # On a BATCHED (block-diagonally packed) graph the composition is a
        # per-STRUCTURE quantity: pooling over the whole packed array would
        # leak one structure's composition into another's gate — the one
        # place this architecture is not automatically block-diagonal. The
        # batched branch therefore segment-means per struct_id and mixes
        # experts per edge (K small GEMMs) instead of once in weight space.
        batched_gate = (cfg.num_experts > 1
                        and lg.struct_id is not None and lg.batch_size > 0)
        if batched_gate:
            owned = lg.owned_mask.astype(dtype)[:, None]
            B = lg.batch_size
            comp_sum = jax.ops.segment_sum(
                zemb * owned, lg.struct_id, num_segments=B,
                indices_are_sorted=True)                       # (B, C)
            count = jax.ops.segment_sum(
                owned[:, 0], lg.struct_id, num_segments=B,
                indices_are_sorted=True)                       # (B,)
            # 2-D mesh placement (B x S): each spatial slab owns only part
            # of every structure — reduce the composition over the spatial
            # ring so the gate stays psum-consistent across a structure's
            # slabs (identity when the graph is not spatially partitioned)
            comp_sum = lg.psum(comp_sum)
            count = lg.psum(count)
            gate_in = jnp.concatenate(
                [comp_sum / jnp.maximum(count, 1.0)[:, None],
                 jnp.broadcast_to(csd, (B,) + csd.shape)], axis=-1)
            mole = jax.nn.softmax(mlp(params["mole_gate"], gate_in), axis=-1)
        elif cfg.num_experts > 1:
            owned = lg.owned_mask.astype(dtype)[:, None]
            comp_sum = lg.psum(jnp.sum(zemb * owned, axis=0))
            count = lg.psum(jnp.sum(owned))
            gate_in = jnp.concatenate(
                [comp_sum / jnp.maximum(count, 1.0), csd], axis=-1
            )
            mole = jax.nn.softmax(mlp(params["mole_gate"], gate_in))
        else:
            mole = jnp.ones((1,), dtype=dtype)

        if batched_gate:
            def so2_apply(f, Wk, mole_e):
                # per-edge expert mixture: evaluate the K expert GEMMs and
                # combine with the edge's structure gate — equivalent to
                # f @ (sum_k mole[s(e), k] W_k) without materializing a
                # per-edge weight matrix
                yk = jnp.einsum("ea,kab->ekb", f, Wk.astype(f.dtype))
                return jnp.einsum("ekb,ek->eb", yk, mole_e.astype(f.dtype))
        else:
            def so2_apply(f, Wk, mole_e):
                return f @ jnp.einsum("k,kab->ab", mole, Wk)

        inv_avg = jnp.asarray(1.0 / cfg.avg_num_neighbors, dtype=dtype)
        for layer in params["layers"]:
            if not batched_gate:
                # globally consistent gate: mix experts ONCE in weight
                # space per layer (K small GEMMs) — the fused SO(2) kernel
                # then runs every per-|m| GEMM in one VMEM-resident
                # pallas_call (kernels/so3; XLA fallback is the same math)
                mixw = lambda Wk: jnp.einsum("k,kab->ab", mole, Wk)
                ws_mixed = [mixw(layer["so2"]["m0"])]
                for m in range(1, cfg.l_max + 1):
                    ws_mixed.append(mixw(layer["so2"][f"m{m}r"]))
                    ws_mixed.append(mixw(layer["so2"][f"m{m}i"]))
            else:
                ws_mixed = None

            def so2_chunk(srcc, dstc, maskc, D, besc, envc, layer=layer,
                          ws_mixed=ws_mixed):
                # edge conditioning scalars
                ef = jnp.concatenate(
                    [besc, zemb[srcc], zemb[dstc]], axis=-1
                )
                g_e = mlp(layer["edge_mlp"], ef) * envc[:, None]  # (E_c, C)

                h_rot = rotate(h[srcc], D, to_edge=True)  # (E_c, S, C)
                # inject edge scalars into the l=0 channel
                h_rot = h_rot.at[:, 0, :].add(g_e)

                # per-edge structure gate (dst rows are always real atoms)
                mole_e = mole[lg.struct_id[dstc]] if batched_gate else None

                if not batched_gate:
                    # fused path: all per-|m| complex-pair GEMMs in one
                    # kernel on the pre-mixed weights
                    return rotate(
                        fused_so2_conv(h_rot, ws_mixed, self.m_idx,
                                       C, kernels=lg.kernels,
                                       diff_params=lg.kernels_diff_params),
                        D) * envc[:, None, None]

                # batched (per-edge expert) gate: the weight mixture is
                # per edge, so the kernel's one-weight-per-m contract does
                # not apply — keep the XLA per-|m| loop
                y = jnp.zeros_like(h_rot)
                for m in range(cfg.l_max + 1):
                    plus, minus = self.m_idx[m]
                    nl = len(plus)
                    if m == 0:
                        f = h_rot[:, plus, :].reshape(-1, nl * C)
                        y = y.at[:, plus, :].set(
                            so2_apply(f, layer["so2"]["m0"],
                                      mole_e).reshape(-1, nl, C))
                    else:
                        Wr = layer["so2"][f"m{m}r"]
                        Wi = layer["so2"][f"m{m}i"]
                        fp = h_rot[:, plus, :].reshape(-1, nl * C)
                        fm = h_rot[:, minus, :].reshape(-1, nl * C)
                        yp = so2_apply(fp, Wr, mole_e) - so2_apply(
                            fm, Wi, mole_e)
                        ym = so2_apply(fp, Wi, mole_e) + so2_apply(
                            fm, Wr, mole_e)
                        y = y.at[:, plus, :].set(yp.reshape(-1, nl, C))
                        y = y.at[:, minus, :].set(ym.reshape(-1, nl, C))

                return rotate(y, D) * envc[:, None, None]

            agg = edge_scan(so2_chunk, (S, C)) * inv_avg

            # gated nonlinearity: scalars via MLP, higher l scaled by gates
            s = agg[:, 0, :]
            gates = jax.nn.sigmoid(mlp(layer["gate_mlp"], s))
            upd = agg * gates[:, None, :]
            upd = upd.at[:, 0, :].set(mlp(layer["scalar_mlp"], s))
            h = h + upd
            h = lg.halo_exchange(h)

        # energy sum in the positions dtype (bf16 is too coarse for it)
        e_atom = mlp(params["energy_mlp"], h[:, 0, :])[:, 0].astype(positions.dtype)
        return e_atom + params["species_ref"]["w"][z].astype(positions.dtype)
