from .pair import PairPotential, PairConfig
from .tensornet import TensorNet, TensorNetConfig
from .chgnet import CHGNet, CHGNetConfig
from .mace import MACE, MACEConfig
from .escn import ESCN, ESCNConfig

__all__ = [
    "PairPotential", "PairConfig",
    "TensorNet", "TensorNetConfig",
    "CHGNet", "CHGNetConfig",
    "MACE", "MACEConfig",
    "ESCN", "ESCNConfig",
]
