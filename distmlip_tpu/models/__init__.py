from .pair import PairPotential, PairConfig
from .tensornet import TensorNet, TensorNetConfig
from .chgnet import CHGNet, CHGNetConfig
from .mace import MACE, MACEConfig
from .escn import ESCN, ESCNConfig
from .escn_md import ESCNMD, ESCNMDConfig

__all__ = [
    "PairPotential", "PairConfig",
    "TensorNet", "TensorNetConfig",
    "CHGNet", "CHGNetConfig",
    "MACE", "MACEConfig",
    "ESCN", "ESCNConfig",
    "ESCNMD", "ESCNMDConfig",
]
