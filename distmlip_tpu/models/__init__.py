from .pair import PairPotential, PairConfig
from .tensornet import TensorNet, TensorNetConfig
from .chgnet import CHGNet, CHGNetConfig

__all__ = [
    "PairPotential", "PairConfig",
    "TensorNet", "TensorNetConfig",
    "CHGNet", "CHGNetConfig",
]
