"""CHGNet: charge-informed message passing with bond and angle graphs.

TPU-native implementation of the CHGNet architecture in **matgl's exact
parameterization** (the reference distributes matgl's CHGNet via
``from_existing`` __dict__ copy, reference
implementations/matgl/models/chgnet.py:551-560), so pretrained matgl
checkpoints convert weight-for-weight (``convert.MAPPINGS["chgnet"]``).

Structure mirrored from the reference wrapper's usage of the upstream
modules (reference chgnet.py:116-197, 231-453 and chgnet_layers.py:16-119):

  - learnable radial bessel bases for bonds (``bond_expansion``) and
    threebody bonds (``threebody_bond_expansion``), learnable Fourier angle
    basis (``angle_expansion``); matgl's polynomial-cutoff-on-expansion
    quirk replicated (reference chgnet.py:119-124, 174-182)
  - shared per-edge/per-bond rbf weight linears (``atom_bond_weights``,
    ``bond_bond_weights``, ``threebody_bond_weights``, reference
    chgnet.py:267-294)
  - per block: atom-graph conv (gated-MLP messages [v_src|v_dst|e],
    weighted, summed to dst, bias-free out linear, residual), then the
    2-phase bond-graph conv (reference chgnet_layers.py:96-119): node phase
    updates bond features from line-graph messages [b_src|b_dst|angle|
    v_center] with per-bond rbf weights, edge phase updates angle features
  - sitewise readout (magmoms) runs BEFORE the final atom conv; the final
    MLP readout after it (reference chgnet.py:391-440)

Distributed flow per layer (atom conv -> edge_to_bond -> ONE coalesced
atom+bond halo exchange -> line-graph node conv -> bond_to_edge -> bond
halo -> angle phase) matches reference chgnet.py:296-368; the node/edge
conv split of reference chgnet_layers.py:16-119 falls out naturally here
because the line graph only draws in-lines to locally-computed bond
nodes. The atom conv runs through the interior/frontier split
(LocalGraph.overlapped_edge_sum): interior-edge messages read the
pre-exchange features so XLA can overlap them with the in-flight
ppermute, and the sitewise readout rides the energy forward via
``energy_and_aux_fn`` instead of a second full pass.

Geometry for halo bond nodes (their endpoints may not be present locally)
arrives by bond-halo exchange of (vec, dist), matching the reference's
bond_transfer of bond_dist/bond_vec (chgnet.py:126-164). Angles use
theta at the shared center atom: bond1 = (s->d), bond2 = (d->k),
cos = -v1.v2/|v1||v2| (the reference's src_bond_sign=-1, chgnet.py:190).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..kernels.dispatch import Gather, fused_edge_aggregate
from ..ops import radial
from ..ops.nn import (cast_params_subtrees, embedding, gated_mlp,
                      gated_mlp_init, linear, linear_init, mlp, mlp_init)


@dataclass(frozen=True)
class CHGNetConfig:
    """matgl CHGNet hyperparameters (names kept close to this framework's
    conventions; the matgl equivalents are noted)."""

    num_species: int = 95     # len(element_types)
    units: int = 64           # dim_atom/bond/angle_embedding (matgl: all 64)
    num_rbf: int = 9          # max_n — radial bessel basis size
    num_angle: int = 4        # max_f — Fourier angle basis -> 2*max_f+1 feats
    num_blocks: int = 4
    cutoff: float = 5.0
    bond_cutoff: float = 3.0  # threebody_cutoff
    cutoff_exponent: int = 5
    atom_conv_hidden: tuple | None = None    # default (units,)
    bond_conv_hidden: tuple | None = None    # default (units,)
    angle_update_hidden: tuple = ()          # matgl default: single layer
    bond_update_hidden: tuple | None = None  # matgl default: no atom-graph
    #                                          edge update (bonds evolve via
    #                                          the bond-graph conv only)
    shared_bond_weights: str | None = "both"  # None|"bond"|"threebody"|"both"
    final_hidden: tuple | None = None        # default (units, units)
    num_site_targets: int = 1                # sitewise_readout width (magmom)
    use_bond_graph: bool = True
    dtype: str = "float32"

    @property
    def angle_dim(self) -> int:
        return 2 * self.num_angle + 1

    @property
    def _atom_hidden(self):
        return self.atom_conv_hidden if self.atom_conv_hidden is not None else (self.units,)

    @property
    def _bond_hidden(self):
        return self.bond_conv_hidden if self.bond_conv_hidden is not None else (self.units,)

    @property
    def _final_hidden(self):
        return self.final_hidden if self.final_hidden is not None else (self.units, self.units)


class CHGNet:
    def __init__(self, config: CHGNetConfig = CHGNetConfig()):
        self.cfg = config

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        C, R, A = cfg.units, cfg.num_rbf, cfg.angle_dim
        ks = iter(jax.random.split(key, 16 + 8 * cfg.num_blocks))
        params = {
            # learnable basis frequencies (matgl learn_basis=True)
            "freq_bond": jnp.pi * jnp.arange(1, R + 1, dtype=jnp.float32),
            "freq_three": jnp.pi * jnp.arange(1, R + 1, dtype=jnp.float32),
            "freq_angle": jnp.arange(0, cfg.num_angle + 1, dtype=jnp.float32),
            "atom_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, C))},
            "bond_emb": mlp_init(next(ks), [R, C]),
            "angle_emb": mlp_init(next(ks), [A, C]),
            "atom_blocks": [],
            "bond_blocks": [],
            "sitewise": linear_init(next(ks), C, cfg.num_site_targets),
            "final": mlp_init(next(ks), [C] + list(cfg._final_hidden) + [1]),
            "species_ref": {"w": jnp.zeros((cfg.num_species, 1))},
            "data_std": jnp.ones(()),
        }
        sw = cfg.shared_bond_weights
        if sw in ("bond", "both"):
            params["atom_bond_w"] = linear_init(next(ks), R, C, bias=False)
            params["bond_bond_w"] = linear_init(next(ks), R, C, bias=False)
        if sw in ("threebody", "both"):
            params["three_bond_w"] = linear_init(next(ks), R, C, bias=False)
        for _ in range(cfg.num_blocks):
            blk = {
                "node_update": gated_mlp_init(
                    next(ks), 3 * C, list(cfg._atom_hidden) + [C]),
                "node_out": linear_init(next(ks), C, C, bias=False),
            }
            if cfg.bond_update_hidden is not None:
                blk["edge_update"] = gated_mlp_init(
                    next(ks), 3 * C, list(cfg.bond_update_hidden) + [C])
                blk["edge_out"] = linear_init(next(ks), C, C, bias=False)
            params["atom_blocks"].append(blk)
        if cfg.use_bond_graph:
            for _ in range(cfg.num_blocks - 1):
                params["bond_blocks"].append({
                    "node_update": gated_mlp_init(
                        next(ks), 4 * C, list(cfg._bond_hidden) + [C]),
                    "node_out": linear_init(next(ks), C, C, bias=False),
                    "angle_update": gated_mlp_init(
                        next(ks), 4 * C, list(cfg.angle_update_hidden) + [C]),
                })
        return params

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        v, _ = self._trunk(params, lg, positions)
        e_atom = mlp(params["final"], v)[:, 0]
        e_ref = params["species_ref"]["w"][lg.species, 0]
        return params["data_std"] * e_atom + e_ref

    def energy_and_aux_fn(self, params, lg, positions):
        """Fused readout: per-atom energies plus the sitewise outputs
        (magmoms) from the SAME forward pass — the runtime's ``aux=True``
        contract. Replaces make_site_fn's separate full forward for
        magmom-every-step MD (the parity oracle lives in
        tests/test_halo_overlap.py)."""
        v, site = self._trunk(params, lg, positions)
        e_atom = mlp(params["final"], v)[:, 0]
        e_ref = params["species_ref"]["w"][lg.species, 0]
        energy = params["data_std"] * e_atom + e_ref
        return energy, {"magmoms": jnp.abs(site[:, 0])}

    def magmom_fn(self, params, lg, positions):
        """Site-wise magnetic moments (absolute value), CHGNet's charge proxy.

        Standalone readout (runs its own forward) — prefer the fused
        ``energy_and_aux_fn`` when energies are being computed anyway."""
        _, site = self._trunk(params, lg, positions)
        return jnp.abs(site[:, 0])

    supports_compute_dtype = True  # _trunk honors cfg.dtype

    def _expansion(self, d, freq, cutoff):
        """matgl bond_expansion semantics: learnable bessel basis with the
        polynomial cutoff applied elementwise to the *expansion values*
        (reference chgnet.py:119-124 — matgl's own quirk, replicated for
        checkpoint parity; numerically ~1 so the smooth vanishing at the
        cutoff comes from the sin basis itself)."""
        rbf = radial.radial_bessel(d, freq, cutoff)
        env = radial.matgl_polynomial_cutoff(rbf, cutoff, self.cfg.cutoff_exponent)
        return env * rbf

    def _trunk(self, params, lg, positions):
        """Returns (atom features after the LAST conv, sitewise readout taken
        BEFORE it — matgl's ordering, reference chgnet.py:391-419)."""
        cfg = self.cfg
        C = cfg.units
        # features/GEMMs in the compute dtype; geometry, basis frequencies
        # and the readout heads stay fp32
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        fp = params
        if cfg.dtype == "bfloat16":
            params = cast_params_subtrees(
                params, dtype,
                keep_fp32=("freq_bond", "freq_three", "freq_angle",
                           "sitewise", "final", "species_ref", "data_std"))

        # --- geometry + bases ---
        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        # matgl's graph simply has no edges beyond the cutoff; our neighbor
        # list may carry skin-shell edges (cutoff < d <= cutoff+skin) for MD
        # reuse, and the learnable bessel basis does not vanish out there —
        # so in-cutoff membership is enforced explicitly, both on the basis
        # (-> shared weights, embeddings) and on the message masks below.
        # At d = cutoff this matches matgl exactly (its basis is ~0 there
        # for near-n*pi frequencies; the hard edge-set boundary is matgl's).
        in_r = lg.edge_mask & (d <= cfg.cutoff)
        rbf = (self._expansion(d, fp["freq_bond"], cfg.cutoff)
               * in_r[:, None]).astype(dtype)

        # --- feature init ---
        # v: pre-exchange view (owned rows authoritative); vx: post-exchange
        # view. Interior edges (both endpoints owned) read v so their
        # compute is data-independent of the in-flight ppermute producing
        # vx — the interior/frontier overlap scheduling (parallel/halo.py).
        v = embedding(params["atom_emb"], lg.species)     # (N, C)
        e = mlp(params["bond_emb"], rbf)                  # (E, C)

        # shared rbf message weights (reference chgnet.py:267-294)
        abw = linear(params["atom_bond_w"], rbf) if "atom_bond_w" in params else None
        bbw = linear(params["bond_bond_w"], rbf) if "bond_bond_w" in params else None

        use_bg = cfg.use_bond_graph and lg.has_bond_graph and params["bond_blocks"]
        if use_bg:
            # bond-node geometry: seed owned from edges, exchange halo rows
            # (reference bond_transfer of bond_dist/bond_vec, chgnet.py:
            # 126-164) — COALESCED with the atom-feature init exchange: both
            # refreshes ride one ppermute per ring shift
            bgeo = jnp.zeros((lg.b_cap, 4), dtype=positions.dtype)
            edge_geo = jnp.concatenate([vec, d[:, None]], axis=-1)
            bgeo = lg.edge_to_bond(edge_geo, bgeo)
            (vx,), (bgeo,) = lg.exchange_all((v,), (bgeo,))
            b_vec, b_d = bgeo[:, :3], bgeo[:, 3]
            # padded bond rows have d=0; skin-shell bonds (d > bond_cutoff)
            # are excluded like skin-shell edges above
            b_real = (b_d > 1e-6) & (b_d <= cfg.bond_cutoff)
            rbf3 = (self._expansion(
                jnp.where(b_d > 1e-6, b_d, 1.0), fp["freq_three"],
                cfg.bond_cutoff) * b_real[:, None]).astype(dtype)
            tbw = (linear(params["three_bond_w"], rbf3)
                   if "three_bond_w" in params else None)

            # line edges are live only when BOTH bonds are real and within
            # the threebody cutoff (matgl's line graph contains only such
            # pairs; skin-shell bonds must contribute nothing)
            line_ok = lg.line_mask & b_real[lg.line_src] & b_real[lg.line_dst]

            # angle features on line-graph edges (theta at the center atom;
            # reference src_bond_sign=-1 + compute_theta, chgnet.py:184-197)
            v1 = b_vec[lg.line_src]
            v2 = b_vec[lg.line_dst]
            d1 = jnp.maximum(b_d[lg.line_src], 1e-6)
            d2 = jnp.maximum(b_d[lg.line_dst], 1e-6)
            cos_t = -jnp.sum(v1 * v2, axis=-1) / (d1 * d2)
            cos_t = jnp.clip(cos_t, -1.0 + 1e-6, 1.0 - 1e-6)
            theta = jnp.arccos(cos_t)
            a = mlp(params["angle_emb"],
                    radial.matgl_fourier_expansion(
                        theta, fp["freq_angle"]).astype(dtype))  # (L, C)

            # bond-node features are (re-)seeded from edge features at the
            # top of every block (reference dist_forward re-seeds the same
            # way, :253-264, :315-321), so no separate init pass is needed
            b = jnp.zeros((lg.b_cap, C), dtype=e.dtype)
        else:
            vx = lg.halo_exchange(v)

        # --- message-passing blocks (reference chgnet.py:296-389) ---
        for i in range(cfg.num_blocks - 1):
            v, e = self._atom_conv(params["atom_blocks"][i], lg, v, vx, e,
                                   abw, bbw, in_r)
            if use_bg:
                b = lg.edge_to_bond(e, b)
                # atom + bond refresh at one sync point -> one collective
                (vx,), (b,) = lg.exchange_all((v,), (b,))
                blk = params["bond_blocks"][i]
                b = self._bond_node_conv(blk, lg, vx, b, a, tbw, line_ok)
                e = lg.bond_to_edge(b, e)
                if i + 2 < cfg.num_blocks:
                    # the refreshed b / updated a feed the NEXT block's bond
                    # conv; after the last bond block nothing reads them, so
                    # the exchange would be pure dead communication (XLA
                    # can't DCE a collective) — the dead_compute contract
                    # pass flags exactly this
                    _, (b,) = lg.exchange_all((), (b,))
                    a = self._angle_conv(blk, lg, vx, b, a, line_ok)
            else:
                vx = lg.halo_exchange(v)

        # sitewise readout BEFORE the last atom conv (reference :391-398);
        # owned rows of v and vx are identical — vx keeps halo-row parity
        # with the historical post-exchange readout
        site = linear(fp["sitewise"], vx.astype(positions.dtype))

        # final atom conv (reference :400-419). No trailing halo exchange:
        # the energy/site readouts only consume owned rows (owned_sum /
        # gather_owned mask the rest), so refreshing halo rows after the
        # last conv was dead communication.
        v, e = self._atom_conv(params["atom_blocks"][-1], lg, v, vx, e, abw,
                               bbw, in_r)
        return v.astype(positions.dtype), site

    # ---- layers ----
    def _atom_conv(self, blk, lg, v, vx, e, abw, bbw, in_r):
        """matgl CHGNetGraphConv: optional gated edge update, then gated node
        messages weighted per edge, summed to dst (owner-computes), bias-free
        out linear, residual. ``in_r`` masks padded AND skin-shell edges.

        ``v`` is the pre-exchange view, ``vx = exchange(v)`` — the node
        phase runs through ``lg.overlapped_edge_sum`` so interior-edge
        GEMMs don't wait on the ppermute producing ``vx``. Returns the new
        pre-exchange ``v`` (halo rows carry the residual base's stale
        values; every consumer re-exchanges first)."""
        if "edge_update" in blk:
            # per-edge output (no dst aggregation): full edge list on the
            # post-exchange view, no overlap structure
            feats = jnp.concatenate([vx[lg.edge_src], vx[lg.edge_dst], e],
                                    axis=-1)
            m = linear(blk["edge_out"], gated_mlp(blk["edge_update"], feats))
            if bbw is not None:
                m = m * bbw
            e = e + m * in_r[:, None].astype(m.dtype)

        def node_msg(vs, vd, e_sl, *w_sl):
            m = gated_mlp(blk["node_update"],
                          jnp.concatenate([vs, vd, e_sl], axis=-1))
            return m * w_sl[0] if w_sl else m

        edge_data = (e,) if abw is None else (e, abw)
        agg = lg.overlapped_edge_sum(node_msg, v, vx, edge_data, mask=in_r)
        v = vx + linear(blk["node_out"], agg)
        return v, e

    def _bond_node_conv(self, blk, lg, v, b, a, tbw, line_ok):
        """Line-graph node phase (matgl CHGNetLineGraphConv node update,
        reference chgnet_layers.py:101-105): messages [b_src|b_dst|angle|
        v_center] summed to the dst bond, out linear, per-bond rbf weights
        applied post-aggregation, residual. Only locally-computed bond nodes
        receive in-lines (the partitioner's needs_in_line rule); halo bonds
        are refreshed by the surrounding exchanges.

        The line-graph message (gathers + gated MLP + dst-sorted sum) goes
        through the kernel dispatcher: on the Pallas path it fuses per dst
        tile and the (L, 4C) concat / (L, C) message intermediates never
        materialize; the XLA path is the historical program."""

        def line_msg(b_src, b_dst, a_row, v_ctr):
            return gated_mlp(blk["node_update"], jnp.concatenate(
                [b_src, b_dst, a_row, v_ctr], axis=-1))

        agg = fused_edge_aggregate(
            line_msg,
            [Gather(b, lg.line_src), Gather(b, lg.line_dst), a,
             Gather(v, lg.line_center)],
            lg.line_dst, lg.b_cap, line_ok, indices_are_sorted=True,
            kernels=lg.kernels, diff_params=lg.kernels_diff_params)
        upd = linear(blk["node_out"], agg)
        if tbw is not None:
            upd = upd * tbw
        return b + upd

    def _angle_conv(self, blk, lg, v, b, a, line_ok):
        """Line-graph edge phase (angle update from the refreshed bond
        features, reference chgnet_layers.py:109-118): gated update on
        [b_src|b_dst|angle|v_center], residual, no weights."""
        feats = jnp.concatenate(
            [b[lg.line_src], b[lg.line_dst], a, v[lg.line_center]], axis=-1
        )
        m = gated_mlp(blk["angle_update"], feats)
        return a + m * line_ok[:, None].astype(m.dtype)
