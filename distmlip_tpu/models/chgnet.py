"""CHGNet: charge-informed message passing with bond and angle graphs.

TPU-native implementation of the CHGNet architecture (Deng et al. 2023, as
re-implemented on DGL by matgl) — the model family the reference distributes
with the most intricate machinery (reference
implementations/matgl/models/chgnet.py:21-453): per-layer it runs an
atom-graph conv, seeds bond-node features from edge features
(``edge_to_bond``), refreshes halo bond/atom features, runs the bond-graph
(angle) conv, and writes bond features back (``bond_to_edge``) — the 2-phase
split of reference chgnet_layers.py:16-119 falls out naturally here because
the line graph only draws in-lines to locally-computed bond nodes.

Feature streams:
  v (atoms, N_cap x C), e (edges, E_cap x C), b (bond nodes, B_cap x C),
  a (angles = line-graph edges, L_cap x A).

Geometry for halo bond nodes (their endpoints may not be present locally)
arrives by bond-halo exchange of (vec, dist), matching the reference's
bond_transfer of bond_dist/bond_vec (chgnet.py:126-164). Angles use
cos(theta) at the shared center atom: bond1 = (s->d), bond2 = (d->k),
cos = -v1.v2/|v1||v2| (the reference's src_bond_sign=-1, chgnet.py:190).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..ops import radial
from ..ops.nn import (cast_params_subtrees, embedding, embedding_init, gated_mlp, gated_mlp_init,
                      layernorm, layernorm_init, linear, linear_init, mlp,
                      mlp_init)
from ..ops.segment import masked_segment_sum


@dataclass(frozen=True)
class CHGNetConfig:
    num_species: int = 95
    units: int = 64
    num_rbf: int = 9          # radial basis size (atom-graph bonds)
    num_angle: int = 9        # Fourier angle basis size -> 2*max_f+1 features
    num_blocks: int = 4
    cutoff: float = 5.0
    bond_cutoff: float = 3.0  # threebody / bond-graph cutoff
    use_bond_graph: bool = True
    dtype: str = "float32"

    @property
    def angle_dim(self) -> int:
        return 2 * self.num_angle + 1


class CHGNet:
    def __init__(self, config: CHGNetConfig = CHGNetConfig()):
        self.cfg = config

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        C, R, A = cfg.units, cfg.num_rbf, cfg.angle_dim
        ks = iter(jax.random.split(key, 8 + 8 * cfg.num_blocks))
        params = {
            "atom_emb": embedding_init(next(ks), cfg.num_species, C),
            "bond_basis": linear_init(next(ks), R, C),
            "angle_basis": linear_init(next(ks), A, C),
            "blocks": [],
            "readout": mlp_init(next(ks), [C, C, 1]),
            "readout_ln": layernorm_init(C),
            "magmom": mlp_init(next(ks), [C, 1]),
            "species_ref": {"w": jnp.zeros((cfg.num_species, 1))},
        }
        for i in range(cfg.num_blocks):
            blk = {
                "atom_conv": gated_mlp_init(next(ks), 3 * C, [C, C]),
                "atom_ln": layernorm_init(C),
            }
            if cfg.use_bond_graph and i < cfg.num_blocks - 1:
                blk["bond_conv"] = gated_mlp_init(next(ks), 4 * C, [C, C])
                blk["bond_ln"] = layernorm_init(C)
                blk["angle_update"] = gated_mlp_init(next(ks), 3 * C, [C, C])
                blk["angle_proj"] = linear_init(next(ks), C, C)
            params["blocks"].append(blk)
        return params

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        v = self._trunk_features(params, lg, positions)
        h = layernorm(params["readout_ln"], v)
        e_atom = mlp(params["readout"], h)[:, 0]
        e_ref = params["species_ref"]["w"][lg.species, 0]
        return e_atom + e_ref

    def magmom_fn(self, params, lg, positions):
        """Site-wise magnetic moments (absolute value), CHGNet's charge proxy."""
        v = self._trunk_features(params, lg, positions)
        return jnp.abs(mlp(params["magmom"], v)[:, 0])

    supports_compute_dtype = True  # _trunk_features honors cfg.dtype

    def _trunk_features(self, params, lg, positions):
        cfg = self.cfg
        C = cfg.units
        # features/GEMMs in the compute dtype; geometry and the readout
        # (applied by the callers on the returned scalars) stay fp32
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        if cfg.dtype == "bfloat16":
            # readout/magmom heads run in the CALLERS on the original
            # (uncast) params; the trunk returns fp32 features, so the whole
            # trunk param tree can go bf16
            params = cast_params_subtrees(params, dtype)

        # --- geometry ---
        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        env = (radial.polynomial_cutoff(d, cfg.cutoff) * lg.edge_mask).astype(dtype)
        rbf = radial.spherical_bessel_basis(d, cfg.cutoff, cfg.num_rbf).astype(dtype)

        # --- feature init ---
        v = embedding(params["atom_emb"], lg.species)          # (N, C)
        e = linear(params["bond_basis"], rbf) * env[:, None]   # (E, C)
        v = lg.halo_exchange(v)

        use_bg = cfg.use_bond_graph and lg.has_bond_graph
        if use_bg:
            # bond-node geometry: seed owned from edges, exchange halo rows
            bgeo = jnp.zeros((lg.b_cap + 0, 4), dtype=positions.dtype)
            edge_geo = jnp.concatenate([vec, d[:, None]], axis=-1)
            bgeo = lg.edge_to_bond(edge_geo, bgeo)
            bgeo = lg.bond_halo_exchange(bgeo)
            b_vec, b_d = bgeo[:, :3], bgeo[:, 3]
            b_env = radial.polynomial_cutoff(b_d, cfg.bond_cutoff) * (
                b_d > 1e-6
            )  # padded bond rows have d=0 -> env forced to 0

            # angle features on line-graph edges
            v1 = b_vec[lg.line_src]
            v2 = b_vec[lg.line_dst]
            d1 = jnp.maximum(b_d[lg.line_src], 1e-6)
            d2 = jnp.maximum(b_d[lg.line_dst], 1e-6)
            cos_t = -jnp.sum(v1 * v2, axis=-1) / (d1 * d2)
            cos_t = jnp.clip(cos_t, -1.0 + 1e-6, 1.0 - 1e-6)
            theta = jnp.arccos(cos_t)
            a = linear(
                params["angle_basis"],
                radial.fourier_expansion(theta, cfg.num_angle).astype(dtype),
            )                                                  # (L, C)
            line_w = (
                b_env[lg.line_src] * b_env[lg.line_dst] * lg.line_mask
            ).astype(dtype)

        # --- blocks ---
        for i, blk in enumerate(params["blocks"]):
            v, e = self._atom_conv(blk, lg, v, e, env)
            v = lg.halo_exchange(v)
            if use_bg and "bond_conv" in blk:
                b = jnp.zeros((lg.b_cap, C), dtype=v.dtype)
                b = lg.edge_to_bond(e, b)
                b = lg.bond_halo_exchange(b)
                b, a = self._bond_conv(blk, lg, v, b, a, line_w)
                # bond_to_edge reads owned bond rows only; halo rows are
                # rebuilt from the exchanged edge features next block
                e = lg.bond_to_edge(b, e)

        # readout layernorm statistics need full precision
        return v.astype(positions.dtype)

    # ---- layers ----
    def _atom_conv(self, blk, lg, v, e, env):
        """Gated message passing on the atom graph (owner-computes on dst)."""
        feats = jnp.concatenate([v[lg.edge_src], v[lg.edge_dst], e], axis=-1)
        m = gated_mlp(blk["atom_conv"], feats) * env[:, None]
        agg = masked_segment_sum(m, lg.edge_dst, lg.n_cap, lg.edge_mask,
                                 indices_are_sorted=True)
        v = v + layernorm(blk["atom_ln"], agg)
        return v, e

    def _bond_conv(self, blk, lg, v, b, a, line_w):
        """Angle-mediated bond update on the line graph.

        Line edge (b1 -> b2) with center atom c updates bond b2 from
        [b1, b2, angle, v_c]; only locally-computed bond nodes receive
        in-lines (the partitioner's needs_in_line rule), halo bonds are
        refreshed by the surrounding exchanges.
        """
        feats = jnp.concatenate(
            [b[lg.line_src], b[lg.line_dst], a, v[lg.line_center]], axis=-1
        )
        m = gated_mlp(blk["bond_conv"], feats) * line_w[:, None]
        agg = masked_segment_sum(m, lg.line_dst, lg.b_cap, lg.line_mask,
                                 indices_are_sorted=True)
        b = b + layernorm(blk["bond_ln"], agg)

        # angle update from the refreshed bond features
        feats_a = jnp.concatenate(
            [b[lg.line_src] + b[lg.line_dst], a, v[lg.line_center]], axis=-1
        )
        a = a + gated_mlp(blk["angle_update"], feats_a) * line_w[:, None]
        a = linear(blk["angle_proj"], a)
        return b, a
