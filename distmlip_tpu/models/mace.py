"""MACE: higher-order equivariant message passing (ACE product basis).

TPU-native implementation of the MACE architecture (Batatia et al. 2022) —
the reference's flagship distributed family (reference
implementations/mace/models.py:45-220: per-partition embeddings ->
interaction -> product -> readout loop with an atom_transfer after every
interaction). Built entirely on this repo's SO(3) module (real spherical
harmonics + real coupling tensors, ops/so3.py) instead of e3nn.

Feature layout: equivariant node features are a dict {l: (N, 2l+1, C)} —
channels LAST so the C=128 axis lands in the TPU lane dimension. TPU
arrays tile their trailing two axes to (sublane, lane)=(8|16, 128); with
channels last the small spherical axes (3..16) pad only the sublane axis
(<=2x) instead of the lane axis (8..32x), which round-3 profiling showed
was inflating every hot tensor's HBM traffic by an order of magnitude.
Message construction (density projection):
    A_i^{l3} = (1/avg_n) sum_j sum_{l1,l2} R^{l1l2l3}(r_ij) *
               CG[(l1,l2,l3)] (h_j^{l1}, Y^{l2}(r_ij))
followed by a species-weighted symmetric contraction in MACE's exact
U-matrix parameterization (orthonormal symmetric coupling basis per
(l_out, correlation) — ops/so3.py:symmetric_coupling_basis) and linear
updates with species-dependent residual connections (upstream's skip_tp).
Per-layer invariant readouts accumulate into the site energy, matching
MACE's scale/shift + E0s structure.

TPU mapping: the density projection folds every (l_h, l_Y, l_out) CG path
into one dense block matrix so each edge chunk is a single MXU GEMM
(_projection_tables); the symmetric contraction runs Horner-style over
node chunks; segment sums ride the sorted-dst fast path.

Distributed contract: one halo exchange of the packed node features after
each interaction (same cadence as the reference's atom_transfer,
models.py:165).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..kernels.dispatch import fused_segment_sum
from ..ops import radial
from ..ops.nn import linear, linear_init, linear_init_vp, mlp, mlp_init, mlp_init_vp
from ..ops.so3 import (
    real_clebsch_gordan,
    spherical_harmonics,
    symmetric_coupling_basis,
)


@dataclass(frozen=True)
class MACEConfig:
    num_species: int = 95
    channels: int = 64
    l_max: int = 3            # spherical-harmonic order on edges
    a_lmax: int = 2           # irreps kept in the density A / product basis
    hidden_lmax: int = 1      # irreps of hidden node features (0..L)
    correlation: int = 3      # body order - 1 (ACE correlation)
    num_interactions: int = 2
    scalar_last: bool = True  # upstream MACE keeps only scalar (l=0) hidden
                              # features out of the final interaction/product
    num_bessel: int = 8
    radial_mlp: int = 64
    radial_layers: int = 3    # hidden layers in the radial MLP (upstream MACE
                              # uses [64, 64, 64], no biases)
    radial_scale: float = 16.0  # INIT-time gain folded into the radial
                                # MLP's output layer: keeps the density
                                # projection A healthy at init (the cutoff
                                # envelope shrinks near-cutoff edges) so
                                # correlation-2/3 products carry weight.
                                # Not applied at runtime — converted
                                # upstream weights are used verbatim.
    cutoff: float = 5.0
    cutoff_p: int = 6         # polynomial-envelope power (upstream MACE
                              # checkpoints commonly use 5)
    avg_num_neighbors: float = 14.0
    num_heads: int = 1        # multi-head readouts (upstream MACE heads:
    head: int = 0             # per-head E0s/scale/shift/readout columns);
                              # ``head`` selects the column evaluated
    zbl: bool = False         # ZBL screened pair repulsion under the
                              # learned potential (ref mace/models.py:121-128)
    atomic_numbers: tuple | None = None  # species index -> Z (for ZBL);
                                         # default: index + 1
    remat: bool | str = True  # rematerialize in the backward pass: True
                              # (full), False, or a checkpoint-policy name
                              # ("dots": keep GEMM outputs, recompute glue
                              # — ops/chunk.remat_wrap)
    edge_chunk: int = 32768  # process edges in chunks of this size inside a
                             # lax.scan: bounds the per-edge path-tensor and
                             # radial-weight memory regardless of system size
                             # (0 disables chunking)
    node_chunk: int = 4096   # same for the per-node symmetric contraction
                             # (the Horner intermediates are (n, d, S, S, C))
    dtype: str = "float32"


def _triangle(l1, l2, l3):
    return abs(l1 - l2) <= l3 <= l1 + l2


def _message_paths(h_ls, l_max, out_ls):
    """(l_h, l_Y, l_out) combos for the density projection.

    Parity-filtered (l_h + l_Y + l_out even): node features and spherical
    harmonics carry SH parity, and upstream MACE's conv_tp keeps only the
    parity-consistent instructions, so odd-sum paths do not exist there —
    matching the path set (and radial-MLP output width) exactly is required
    for weight parity. Order matches upstream's instruction sort: by output
    irrep first (stable within an l_out by enumeration order)."""
    paths = [
        (lh, ly, lo)
        for lh in h_ls
        for ly in range(l_max + 1)
        for lo in out_ls
        if _triangle(lh, ly, lo) and (lh + ly + lo) % 2 == 0
    ]
    return sorted(paths, key=lambda p: p[2])


def _projection_tables(h_ls, l_max, paths):
    """Density projection tables: fold ALL (l_h, l_Y, l_out) CG couplings
    into one dense block matrix.

        W[(l_h m) * S_Y + (l_Y n), q(path, p)] = CG^{l_h l_Y l_out}[m, n, p]

    Per edge chunk the contraction is factored through the channel-free
    intermediate T[e, m, q] = sum_n Y[e, n] W[(m, n), q] (tiny), then
    M[e, q, c] = sum_m T[e, m, q] h_src[e, m, c] — S_h fused multiply-adds
    per output element, with no (E, S_h*S_Y, C) outer product materialized
    (replaces the per-path ``ecm,en,mnp->ecp`` einsums of round 1 and the
    outer-product GEMM of round 2).

    Returns dict with: W (K, Q) float64, q_path (Q,) path index per column,
    h_off {l: row-block offset}, S_h, S_Y, and lo_cols {l_out: (P_l, 2l+1)}
    column groups for the per-path output mixing.
    """
    S_Y = (l_max + 1) ** 2
    h_off = {}
    off = 0
    for l in h_ls:
        h_off[l] = off
        off += 2 * l + 1
    S_h = off
    y_off = {l: l * l for l in range(l_max + 1)}

    Q = sum(2 * lo + 1 for (_, _, lo) in paths)
    W = np.zeros((S_h * S_Y, Q))
    q_path = np.zeros(Q, dtype=np.int32)
    cols_by_lo: dict[int, list] = {}
    q = 0
    for pi, (lh, ly, lo) in enumerate(paths):
        cg = real_clebsch_gordan(lh, ly, lo)  # (2lh+1, 2ly+1, 2lo+1)
        mi = h_off[lh] + np.arange(2 * lh + 1)
        ni = y_off[ly] + np.arange(2 * ly + 1)
        rows = (mi[:, None] * S_Y + ni[None, :]).reshape(-1)
        W[np.ix_(rows, np.arange(q, q + 2 * lo + 1))] = cg.reshape(-1, 2 * lo + 1)
        q_path[q : q + 2 * lo + 1] = pi
        cols_by_lo.setdefault(lo, []).append(np.arange(q, q + 2 * lo + 1))
        q += 2 * lo + 1
    lo_cols = {lo: np.stack(cols) for lo, cols in cols_by_lo.items()}
    return {
        "W": W, "q_path": q_path, "h_off": h_off, "S_h": S_h, "S_Y": S_Y,
        "lo_cols": lo_cols,
    }


class MACE:
    supports_compute_dtype = True  # energy_fn honors cfg.dtype="bfloat16"

    def __init__(self, config: MACEConfig = MACEConfig()):
        self.cfg = config
        c = config
        if not 0 <= c.head < c.num_heads:
            raise ValueError(
                f"head={c.head} out of range for num_heads={c.num_heads}"
            )
        self.h_ls0 = [0]
        self.h_ls = list(range(c.hidden_lmax + 1))
        self.a_ls = list(range(c.a_lmax + 1))
        # per-interaction input/output irrep sets: embeddings are scalar, the
        # final layer emits scalars only when scalar_last (upstream MACE's
        # "select only scalars for last layer")
        self.h_ls_in: list[list[int]] = []
        self.h_ls_out: list[list[int]] = []
        prev = self.h_ls0
        for t in range(c.num_interactions):
            self.h_ls_in.append(prev)
            out = (
                [0]
                if (c.scalar_last and t == c.num_interactions - 1)
                else self.h_ls
            )
            self.h_ls_out.append(out)
            prev = out
        self.msg_paths = [
            _message_paths(self.h_ls_in[t], c.l_max, self.a_ls)
            for t in range(c.num_interactions)
        ]
        self.proj = [
            _projection_tables(self.h_ls_in[t], c.l_max, self.msg_paths[t])
            for t in range(c.num_interactions)
        ]
        # ACE product basis: orthonormal symmetric U tensors per
        # (l_out, correlation), shared across interactions (the A irreps are
        # the same every layer) — MACE's U-matrix symmetric contraction
        self.prod_U = {
            l: {
                nu: symmetric_coupling_basis(tuple(self.a_ls), l, nu)
                for nu in range(1, c.correlation + 1)
            }
            for l in self.h_ls
        }

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        C = cfg.channels
        n_keys = 8 + cfg.num_interactions * 32
        ks = iter(jax.random.split(key, n_keys))
        params = {
            "species_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, C))},
            "species_ref": {"w": jnp.zeros((cfg.num_heads, cfg.num_species))},
            "scale": jnp.ones((cfg.num_heads,)),
            "shift": jnp.zeros((cfg.num_heads,)),
            "interactions": [],
        }
        if cfg.zbl:
            params["zbl"] = {
                "a_exp": jnp.float32(0.300),
                "a_prefactor": jnp.float32(0.4543),
            }
        for t in range(cfg.num_interactions):
            n_paths = len(self.msg_paths[t])
            in_ls, out_ls = self.h_ls_in[t], self.h_ls_out[t]
            inter = {
                # per-l channel mixing of the sender features
                "lin_up": {
                    str(l): linear_init_vp(next(ks), C, C) for l in in_ls
                },
                # radial_scale is folded into the OUTPUT layer at init only;
                # the forward pass applies the MLP verbatim (conversion
                # overwrites these weights with upstream values unscaled)
                "radial": (lambda r: r[:-1] + [
                    {"w": r[-1]["w"] * cfg.radial_scale}
                ])(mlp_init_vp(
                    next(ks),
                    [cfg.num_bessel]
                    + [cfg.radial_mlp] * cfg.radial_layers
                    + [n_paths * C],
                )),
                # per-path output mixing (upstream MACE's post-conv_tp
                # e3nn Linear: one C x C block per (path, l_out) pair)
                "lin_A": {
                    str(l): jax.random.normal(
                        next(ks), (self.proj[t]["lo_cols"][l].shape[0], C, C)
                    )
                    / np.sqrt(self.proj[t]["lo_cols"][l].shape[0] * C)
                    for l in self.a_ls
                },
                # species-dependent U-basis product weights (MACE's
                # symmetric-contraction weights: (num_elements, n_paths, C)
                # per output irrep and correlation order)
                "product": {
                    str(l): {
                        f"w{nu}": jax.random.normal(
                            next(ks),
                            (cfg.num_species, U.shape[-1], C),
                        )
                        / np.sqrt(U.shape[-1])
                        for nu, U in self.prod_U[l].items()
                        if U is not None
                    }
                    for l in out_ls
                },
                "lin_msg": {
                    str(l): linear_init_vp(next(ks), C, C) for l in out_ls
                },
                # species-dependent residual (upstream's skip_tp:
                # FullyConnectedTensorProduct(h, species one-hot) — one C x C
                # block per species per (l common to input and output)
                "lin_res": {
                    str(l): jax.random.normal(
                        next(ks), (cfg.num_species, C, C)
                    )
                    / np.sqrt(C)
                    for l in out_ls
                    if l in in_ls
                },
                # bias-free like upstream's Linear/NonLinearReadoutBlock
                "readout": (
                    mlp_init(next(ks), [C, 16, cfg.num_heads], bias=False)
                    if t == cfg.num_interactions - 1
                    else [linear_init(next(ks), C, cfg.num_heads, bias=False)]
                ),
            }
            params["interactions"].append(inter)
        return params

    # ---- packing helpers for the halo exchange ----
    def _pack(self, h):
        return jnp.concatenate(
            [h[l].reshape(h[l].shape[0], -1) for l in sorted(h)], axis=-1
        )

    def _unpack(self, flat, ls, C):
        out = {}
        o = 0
        for l in ls:
            d = C * (2 * l + 1)
            out[l] = flat[:, o : o + d].reshape(-1, 2 * l + 1, C)
            o += d
        return out

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        cfg = self.cfg
        C = cfg.channels
        # geometry stays in the positions dtype; features/messages run in the
        # configured compute dtype (cfg.dtype="bfloat16" puts every GEMM on
        # the MXU's native precision); per-atom energy terms accumulate in
        # the positions dtype below
        dtype = (
            jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        )
        acc_dtype = positions.dtype

        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        rhat = vec / jnp.maximum(d, 1e-9)[:, None]
        env = (
            radial.polynomial_cutoff(d, cfg.cutoff, p=cfg.cutoff_p) * lg.edge_mask
        ).astype(dtype)
        # envelope multiplies the bessel features BEFORE the radial MLP
        # (upstream's RadialEmbeddingBlock); the bias-free MLP maps 0 -> 0,
        # so messages still vanish smoothly at the cutoff
        bessel = (
            radial.spherical_bessel_basis(d, cfg.cutoff, cfg.num_bessel)
            * env[:, None]
        ).astype(dtype)
        Y = {l: spherical_harmonics(l, rhat) for l in range(cfg.l_max + 1)}

        z = lg.species
        h = {0: params["species_emb"]["w"][z][:, None, :].astype(dtype)}
        h = self._unpack(lg.halo_exchange(self._pack(h)), [0], C)

        head = cfg.head
        # site/readout energies accumulate in the positions dtype: bf16 has
        # too few mantissa bits for per-atom energy sums
        e_site = params["species_ref"]["w"][head][z].astype(acc_dtype)
        # ZBL joins the *interaction* energies: upstream ScaleShiftMACE puts
        # pair_node_energy into node_es_list and scale-shifts the sum
        # (reference mace/models.py:131,174-175), so it must sit inside
        # scale*(...)+shift, not alongside the unscaled E0 reference
        acc = jnp.zeros(positions.shape[0], dtype=acc_dtype)
        if cfg.zbl:
            acc = acc + self._zbl_site(params, lg, d, acc_dtype)

        for t, inter in enumerate(params["interactions"]):
            body = partial(self._interaction, lg=lg, Y=Y, bessel=bessel,
                           z=z, t=t)
            if cfg.remat is True:
                # full-remat mode only: with a policy, the inner edge/node
                # scans carry the policy themselves and double-wrapping
                # would discard their saved dots
                body = jax.checkpoint(body)
            h = body(inter, h)
            h = self._unpack(lg.halo_exchange(self._pack(h)), self.h_ls_out[t], C)

            # invariant readout (head column selected)
            scalars = h[0][:, 0, :]
            if t == cfg.num_interactions - 1:
                r_out = mlp(inter["readout"], scalars)[:, head]
            else:
                r_out = linear(inter["readout"][0], scalars)[:, head]
            acc = acc + r_out.astype(acc_dtype)

        scale = params["scale"][head].astype(acc_dtype)
        shift = params["shift"][head].astype(acc_dtype)
        return e_site + scale * acc + shift

    def _zbl_site(self, params, lg, d, dtype):
        """Per-atom ZBL pair repulsion (half per directed edge), added under
        the learned potential exactly as the reference aggregates its
        per-partition pair energies (mace/models.py:121-128)."""
        from .pair import zbl_edge_energy

        cfg = self.cfg
        if cfg.atomic_numbers is not None:
            # cfg.atomic_numbers is a host config value, not a device array
            # contract: allow(DML001)
            z_of = jnp.asarray(np.asarray(cfg.atomic_numbers, dtype=np.int32))
        else:
            z_of = jnp.arange(1, cfg.num_species + 1, dtype=jnp.int32)
        z_num = z_of[lg.species]
        e_edge = zbl_edge_energy(
            z_num[lg.edge_src], z_num[lg.edge_dst], d.astype(dtype),
            a_exp=params["zbl"]["a_exp"], a_prefactor=params["zbl"]["a_prefactor"],
            p=cfg.cutoff_p,
        )
        e_edge = jnp.where(lg.edge_mask, e_edge, 0.0)
        # aggregate_edges: per-segment sorted sums under the
        # interior/frontier edge layout
        return 0.5 * lg.aggregate_edges(e_edge[:, None])[:, 0]

    def _interaction(self, inter, h, *, lg, Y, bessel, z, t):
        """One MACE interaction: density projection + symmetric contraction +
        linear update. Rematerialized under grad when cfg.remat (the per-edge
        per-path tensors dominate activation memory)."""
        cfg = self.cfg
        C = cfg.channels
        dtype = bessel.dtype
        # run the whole interaction in the compute dtype: cast the parameter
        # subtree so mixed-precision promotion can't silently upcast the
        # GEMMs back to fp32 (O(param bytes) per step — negligible next to
        # the per-edge activations; a no-op when params are already cast)
        inter = jax.tree.map(
            lambda x: x.astype(dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            inter,
        )
        n_nodes = h[0].shape[0]
        h_ls = self.h_ls_in[t]
        out_ls = self.h_ls_out[t]
        paths = self.msg_paths[t]
        proj = self.proj[t]
        Wp = jnp.asarray(proj["W"], dtype=dtype)          # (S_h*S_Y, Q)
        q_path = jnp.asarray(proj["q_path"])              # (Q,)
        nQ = proj["W"].shape[1]

        # sender features, channel-mixed per l, packed (N, S_h, C)
        hu = jnp.concatenate(
            [
                jnp.einsum("nmc,cd->nmd", h[l], inter["lin_up"][str(l)]["w"])
                for l in h_ls
            ],
            axis=1,
        )
        Y_full = jnp.concatenate(
            [Y[l] for l in range(cfg.l_max + 1)], axis=-1
        ).astype(dtype)                                   # (E, S_Y)

        # density projection A, accumulated over edge chunks (memory-bounded):
        # per chunk, outer(h_src, Y) -> one GEMM over every CG path -> radial
        # weight -> ONE sorted segment sum carrying all Q path components.
        # chunk_layout aligns chunk boundaries to the interior/frontier
        # split so every chunk's dst stays sorted (fast-path hint holds)
        from ..ops.chunk import chunk_layout, chunked, scan_accumulate

        e_cap = lg.edge_src.shape[0]
        row_idx, row_valid, K, chunk = chunk_layout(
            e_cap, cfg.edge_chunk,
            lg.e_split if lg.has_frontier_split else None)
        take = lambda x: chunked(jnp.asarray(x)[row_idx], K, chunk)
        src_ch = take(lg.edge_src)
        dst_ch = take(lg.edge_dst)
        mask_ch = chunked(
            jnp.asarray(lg.edge_mask)[row_idx] & jnp.asarray(row_valid),
            K, chunk)
        bes_ch = take(bessel)
        Y_ch = take(Y_full)

        Wp3 = Wp.reshape(proj["S_h"], proj["S_Y"], nQ)

        def chunk_body(A_acc, xs):
            srcc, dstc, maskc, Yc, besc = xs
            Rc = mlp(inter["radial"], besc).reshape(chunk, len(paths), C)
            # factor the CG contraction: T[e,m,q] = sum_n Y[e,n] W[(m,n),q]
            # is channel-free and tiny (E_c, S_h, Q); contracting it with
            # h_src over m (<= S_h) then costs S_h fused multiply-adds per
            # (q, c) — no (E_c, S_h*S_Y, C) outer product ever materializes
            # (the outer was ~0.5 GB/chunk and 16x the FLOPs)
            T = jnp.einsum("en,mnq->emq", Yc, Wp3)
            M = jnp.einsum("emq,emc->eqc", T, hu[srcc])   # (E_c, Q, C)
            M = M * Rc[:, q_path, :]                      # per-path radial
            return (
                A_acc
                + fused_segment_sum(
                    # sorted within every chunk by chunk_layout
                    # construction; dispatches to the dst-tiled Pallas
                    # scatter kernel on TPU (kernels/dispatch)
                    M, dstc, n_nodes, maskc, indices_are_sorted=True,
                    kernels=lg.kernels,
                ),
                None,
            )

        A0 = jnp.zeros((n_nodes, nQ, C), dtype=dtype)
        A_all = scan_accumulate(
            chunk_body, A0, (src_ch, dst_ch, mask_ch, Y_ch, bes_ch),
            remat=cfg.remat,
        )
        # per-path output mixing on nodes (upstream's post-conv_tp linear):
        # A[l] = sum_paths A_all[:, :, cols(path)] @ W_path — (P_l*C) GEMMs
        inv_avg = jnp.asarray(1.0 / cfg.avg_num_neighbors, dtype=dtype)
        A = {
            l: jnp.einsum(
                "npmc,pcd->nmd",
                A_all[:, proj["lo_cols"][l]] * inv_avg,
                inter["lin_A"][str(l)].astype(dtype),
            )
            for l in self.a_ls
        }

        # ---- symmetric contraction (ACE product basis, U-matrix form) ----
        # node-chunked: the Horner intermediates are (n, d, S, S, C)
        A_flat = jnp.concatenate([A[l] for l in self.a_ls], axis=1)  # (N,S_A,C)
        h_in_ls = [l for l in h_ls if l in h]
        h_flat = jnp.concatenate([h[l] for l in h_in_ls], axis=1)
        nchunk = cfg.node_chunk if cfg.node_chunk > 0 else n_nodes
        nchunk = min(nchunk, n_nodes)
        Kn = -(-n_nodes // nchunk)
        padn = Kn * nchunk - n_nodes

        def padn_c(x):
            if padn == 0:
                return x
            widths = [(0, padn)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths)

        A_ch = padn_c(A_flat).reshape(Kn, nchunk, -1, C)
        z_ch = padn_c(z).reshape(Kn, nchunk)
        h_ch = padn_c(h_flat).reshape(Kn, nchunk, -1, C)

        def node_body(_, xs):
            Ac, zc, hc = xs
            outs = []
            for l in out_ls:
                B = self._sym_contract(
                    inter["product"][str(l)], self.prod_U[l], Ac, zc, dtype
                )
                m = jnp.einsum("nmc,cd->nmd", B, inter["lin_msg"][str(l)]["w"])
                if l in h_in_ls and str(l) in inter["lin_res"]:
                    off = sum(2 * ll + 1 for ll in h_in_ls if ll < l)
                    hl = hc[:, off : off + 2 * l + 1, :]
                    Wr = inter["lin_res"][str(l)][zc].astype(dtype)  # (n,C,C)
                    m = m + jnp.einsum("nmc,ncd->nmd", hl, Wr)
                outs.append(m)
            return None, jnp.concatenate(outs, axis=1)

        from ..ops.chunk import remat_wrap

        body = remat_wrap(node_body, cfg.remat)
        if Kn == 1:
            # single-chunk path keeps the remat mode too (same contract as
            # scan_accumulate: a system just under one node chunk must have
            # the same backward memory bound as one just over)
            _, out_flat = body(None, (A_ch[0], z_ch[0], h_ch[0]))
        else:
            _, out_flat = jax.lax.scan(body, None, (A_ch, z_ch, h_ch))
            out_flat = out_flat.reshape(Kn * nchunk, -1, C)[:n_nodes]

        h_new = {}
        o = 0
        for l in out_ls:
            d = 2 * l + 1
            h_new[l] = out_flat[:, o : o + d, :]
            o += d
        return h_new

    def _sym_contract(self, wts, Us, Ac, zc, dtype):
        """B(A)[n, d, c] = sum_nu W_nu[z_n] . U_nu . A^(x nu) — evaluated
        highest correlation first in Horner form (mace's contraction order:
        each step adds the next-lower U.W block, then contracts one A index).
        Ac: (n, S_A, C); returns (n, 2l+1, C). Channels stay in the trailing
        (lane) axis through every intermediate."""
        numax = max(nu for nu, U in Us.items() if U is not None)
        letters = "uvwxy"
        # U stored (S,)*nu + (d, k) -> transpose to (d, S..., k)
        U_t = {
            nu: jnp.asarray(np.moveaxis(U, -2, 0), dtype=dtype)
            for nu, U in Us.items()
            if U is not None
        }
        w = {nu: wts[f"w{nu}"][zc].astype(dtype) for nu in U_t}  # (n, k, C)

        s_in = letters[: numax - 1]
        # G[n,k,q,c] = w[n,k,c] A[n,q,c]: fold the path and last tensor index
        # into one MXU contraction of U against G
        G = jnp.einsum("nkc,nqc->nkqc", w[numax], Ac)
        t = jnp.einsum(f"d{s_in}qk,nkqc->nd{s_in}c", U_t[numax], G)
        for nu in range(numax - 1, 0, -1):
            s_cur = letters[:nu]
            if nu in U_t:
                t = t + jnp.einsum(
                    f"d{s_cur}k,nkc->nd{s_cur}c", U_t[nu], w[nu]
                )
            t = jnp.einsum(
                f"nd{s_cur}c,n{s_cur[-1]}c->nd{s_cur[:-1]}c", t, Ac
            )
        return t
