"""MACE: higher-order equivariant message passing (ACE product basis).

TPU-native implementation of the MACE architecture (Batatia et al. 2022) —
the reference's flagship distributed family (reference
implementations/mace/models.py:45-220: per-partition embeddings ->
interaction -> product -> readout loop with an atom_transfer after every
interaction). Built entirely on this repo's SO(3) module (real spherical
harmonics + real coupling tensors, ops/so3.py) instead of e3nn.

Feature layout: equivariant node features are a dict {l: (N, C, 2l+1)}.
Message construction (density projection):
    A_i^{l3} = (1/avg_n) sum_j sum_{l1,l2} R^{l1l2l3}(r_ij) *
               CG[(l1,l2,l3)] (h_j^{l1}, Y^{l2}(r_ij))
followed by a species-weighted symmetric contraction (correlation <= 3,
iterated pairwise couplings — spans the ACE product basis) and linear
updates with residual connections. Per-layer invariant readouts accumulate
into the site energy, matching MACE's scale/shift + E0s structure.

Distributed contract: one halo exchange of the packed node features after
each interaction (same cadence as the reference's atom_transfer,
models.py:165).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import radial
from ..ops.nn import linear, linear_init, linear_init_vp, mlp, mlp_init
from ..ops.segment import masked_segment_sum
from ..ops.so3 import real_clebsch_gordan, spherical_harmonics


@dataclass(frozen=True)
class MACEConfig:
    num_species: int = 95
    channels: int = 64
    l_max: int = 3            # spherical-harmonic order on edges
    a_lmax: int = 2           # irreps kept in the density A / product basis
    hidden_lmax: int = 1      # irreps of hidden node features (0..L)
    correlation: int = 3      # body order - 1 (ACE correlation)
    num_interactions: int = 2
    num_bessel: int = 8
    radial_mlp: int = 64
    radial_scale: float = 4.0  # output gain on the radial MLP: keeps the
                               # density projection A at O(1) so correlation-2/3
                               # products carry weight at init
    cutoff: float = 5.0
    avg_num_neighbors: float = 14.0
    remat: bool = True   # rematerialize each interaction in the backward pass
    edge_chunk: int = 32768  # process edges in chunks of this size inside a
                             # lax.scan: bounds the per-edge path-tensor and
                             # radial-weight memory regardless of system size
                             # (0 disables chunking)
    dtype: str = "float32"


def _triangle(l1, l2, l3):
    return abs(l1 - l2) <= l3 <= l1 + l2


def _message_paths(h_ls, l_max, out_ls):
    """(l_h, l_Y, l_out) combos for the density projection."""
    return [
        (lh, ly, lo)
        for lh in h_ls
        for ly in range(l_max + 1)
        for lo in out_ls
        if _triangle(lh, ly, lo)
    ]


def _pair_paths(a_ls):
    """(la, lb, li) pairwise couplings, la <= lb, dropping identically-zero
    antisymmetric couplings of identical inputs."""
    out = []
    for la in a_ls:
        for lb in a_ls:
            if lb < la:
                continue
            for li in range(abs(la - lb), min(la + lb, max(a_ls)) + 1):
                if la == lb and (la + lb + li) % 2 == 1:
                    continue
                out.append((la, lb, li))
    return out


def _triple_paths(pairs, a_ls, out_ls):
    """(pair_index, lc, lout) couplings for correlation 3."""
    return [
        (pi, lc, lo)
        for pi, (la, lb, li) in enumerate(pairs)
        for lc in a_ls
        for lo in out_ls
        if _triangle(li, lc, lo)
    ]


class MACE:
    def __init__(self, config: MACEConfig = MACEConfig()):
        self.cfg = config
        c = config
        self.h_ls0 = [0]
        self.h_ls = list(range(c.hidden_lmax + 1))
        self.a_ls = list(range(c.a_lmax + 1))
        self.msg_paths = []  # per interaction
        for t in range(c.num_interactions):
            h_ls = self.h_ls0 if t == 0 else self.h_ls
            self.msg_paths.append(_message_paths(h_ls, c.l_max, self.a_ls))
        self.pairs = _pair_paths(self.a_ls)
        self.pairs_out = [p for p in self.pairs if p[2] <= c.hidden_lmax]
        self.triples = (
            _triple_paths(self.pairs, self.a_ls, self.h_ls)
            if c.correlation >= 3
            else []
        )

    def _cg(self, l1, l2, l3, dtype):
        return jnp.asarray(real_clebsch_gordan(l1, l2, l3), dtype=dtype)

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        C = cfg.channels
        n_keys = 8 + cfg.num_interactions * 32
        ks = iter(jax.random.split(key, n_keys))
        params = {
            "species_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, C))},
            "species_ref": {"w": jnp.zeros((cfg.num_species,))},
            "scale": jnp.ones(()),
            "shift": jnp.zeros(()),
            "interactions": [],
        }
        for t in range(cfg.num_interactions):
            n_paths = len(self.msg_paths[t])
            inter = {
                # per-l channel mixing of the sender features
                "lin_up": {
                    str(l): linear_init_vp(next(ks), C, C)
                    for l in (self.h_ls0 if t == 0 else self.h_ls)
                },
                "radial": mlp_init(
                    next(ks), [cfg.num_bessel, cfg.radial_mlp, n_paths * C]
                ),
                "lin_A": {
                    str(l): linear_init_vp(next(ks), C, C) for l in self.a_ls
                },
                # species-dependent product-basis weights
                "w1": jax.random.normal(next(ks), (cfg.num_species, len(self.h_ls), C))
                * 0.5,
                "w2": jax.random.normal(
                    next(ks), (cfg.num_species, max(len(self.pairs_out), 1), C)
                )
                * 0.5,
                "w3": jax.random.normal(
                    next(ks), (cfg.num_species, max(len(self.triples), 1), C)
                )
                * 0.5,
                "lin_msg": {
                    str(l): linear_init_vp(next(ks), C, C) for l in self.h_ls
                },
                "lin_res": {
                    str(l): linear_init_vp(next(ks), C, C)
                    for l in (self.h_ls0 if t == 0 else self.h_ls)
                },
                "readout": (
                    mlp_init(next(ks), [C, 16, 1])
                    if t == cfg.num_interactions - 1
                    else [linear_init(next(ks), C, 1)]
                ),
            }
            params["interactions"].append(inter)
        return params

    # ---- packing helpers for the halo exchange ----
    def _pack(self, h):
        return jnp.concatenate(
            [h[l].reshape(h[l].shape[0], -1) for l in sorted(h)], axis=-1
        )

    def _unpack(self, flat, ls, C):
        out = {}
        o = 0
        for l in ls:
            d = C * (2 * l + 1)
            out[l] = flat[:, o : o + d].reshape(-1, C, 2 * l + 1)
            o += d
        return out

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        cfg = self.cfg
        C = cfg.channels
        dtype = positions.dtype

        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        rhat = vec / jnp.maximum(d, 1e-9)[:, None]
        env = (radial.polynomial_cutoff(d, cfg.cutoff) * lg.edge_mask).astype(dtype)
        bessel = radial.spherical_bessel_basis(d, cfg.cutoff, cfg.num_bessel)
        Y = {l: spherical_harmonics(l, rhat) for l in range(cfg.l_max + 1)}

        z = lg.species
        h = {0: params["species_emb"]["w"][z][:, :, None]}  # (N, C, 1)
        h = self._unpack(lg.halo_exchange(self._pack(h)), [0], C)

        e_site = params["species_ref"]["w"][z].astype(dtype)
        acc = jnp.zeros(positions.shape[0], dtype=dtype)

        for t, inter in enumerate(params["interactions"]):
            body = partial(self._interaction, lg=lg, Y=Y, bessel=bessel, env=env,
                           z=z, t=t)
            if cfg.remat:
                body = jax.checkpoint(body)
            h = body(inter, h)
            h = self._unpack(lg.halo_exchange(self._pack(h)), self.h_ls, C)

            # invariant readout
            scalars = h[0][:, :, 0]
            if t == cfg.num_interactions - 1:
                acc = acc + mlp(inter["readout"], scalars)[:, 0]
            else:
                acc = acc + linear(inter["readout"][0], scalars)[:, 0]

        return e_site + params["scale"] * acc + params["shift"]

    def _interaction(self, inter, h, *, lg, Y, bessel, env, z, t):
        """One MACE interaction: density projection + symmetric contraction +
        linear update. Rematerialized under grad when cfg.remat (the per-edge
        per-path tensors dominate activation memory)."""
        cfg = self.cfg
        C = cfg.channels
        dtype = env.dtype
        n_nodes = h[0].shape[0]
        h_ls = self.h_ls0 if t == 0 else self.h_ls
        paths = self.msg_paths[t]

        # sender features, channel-mixed per l
        hu = {
            l: jnp.einsum("ncm,cd->ndm", h[l], inter["lin_up"][str(l)]["w"])
            for l in h_ls
        }

        # density projection A, accumulated over edge chunks (memory-bounded)
        e_cap = lg.edge_src.shape[0]
        chunk = cfg.edge_chunk if cfg.edge_chunk > 0 else e_cap
        chunk = min(chunk, e_cap)
        K = -(-e_cap // chunk)
        pad = K * chunk - e_cap

        def pad_c(x, fill=0):
            if pad == 0:
                return x
            widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths, constant_values=fill)

        def pad_edge(x):
            # pad with the last element: dst stays sorted for the
            # indices_are_sorted segment-sum fast path (padding is masked)
            if pad == 0:
                return x
            return jnp.concatenate([x, jnp.broadcast_to(x[-1], (pad,))])

        src_ch = pad_edge(lg.edge_src).reshape(K, chunk)
        dst_ch = pad_edge(lg.edge_dst).reshape(K, chunk)
        mask_ch = pad_c(lg.edge_mask).reshape(K, chunk)
        env_ch = pad_c(env).reshape(K, chunk)
        bes_ch = pad_c(bessel).reshape(K, chunk, -1)
        Y_ch = {l: pad_c(Y[l]).reshape(K, chunk, -1) for l in Y}

        def chunk_body(A_acc, xs):
            srcc, dstc, maskc, envc, besc, Yc = xs
            Rc = mlp(inter["radial"], besc).reshape(chunk, len(paths), C) * (
                cfg.radial_scale * envc
            )[:, None, None]
            for pi, (lh, ly, lo) in enumerate(paths):
                cgt = self._cg(lh, ly, lo, dtype)
                m = jnp.einsum(
                    "ecm,en,mnp->ecp", hu[lh][srcc], Yc[ly], cgt
                ) * Rc[:, pi, :, None]
                A_acc[lo] = A_acc[lo] + masked_segment_sum(
                    m, dstc, A_acc[lo].shape[0], maskc, indices_are_sorted=True
                )
            return A_acc, None

        A0 = {
            l: jnp.zeros((n_nodes, C, 2 * l + 1), dtype=dtype)
            for l in self.a_ls
        }
        if K == 1:
            A, _ = chunk_body(A0, (src_ch[0], dst_ch[0], mask_ch[0], env_ch[0],
                                   bes_ch[0], {l: Y_ch[l][0] for l in Y_ch}))
        else:
            body = jax.checkpoint(chunk_body) if cfg.remat else chunk_body
            A, _ = jax.lax.scan(
                body, A0,
                (src_ch, dst_ch, mask_ch, env_ch, bes_ch, Y_ch),
            )
        inv_avg = jnp.asarray(1.0 / cfg.avg_num_neighbors, dtype=dtype)
        A = {
            l: jnp.einsum("ncm,cd->ndm", A[l] * inv_avg, inter["lin_A"][str(l)]["w"])
            for l in self.a_ls
        }

        # symmetric contraction (correlation <= 3), species-weighted
        w1 = inter["w1"][z]  # (N, |h_ls|, C)
        w2 = inter["w2"][z]
        w3 = inter["w3"][z]
        B = {l: w1[:, i, :, None] * A[l] for i, l in enumerate(self.h_ls)}
        if cfg.correlation >= 2:
            P = []
            out_i = 0
            for la, lb, li in self.pairs:
                cgt = self._cg(la, lb, li, dtype)
                p = jnp.einsum("ncm,ncq,mqp->ncp", A[la], A[lb], cgt)
                P.append((li, p))
                if li <= cfg.hidden_lmax:
                    B[li] = B[li] + w2[:, out_i, :, None] * p
                    out_i += 1
            if cfg.correlation >= 3:
                for ti, (pi, lc, lo) in enumerate(self.triples):
                    li, p = P[pi]
                    cgt = self._cg(li, lc, lo, dtype)
                    q = jnp.einsum("ncm,ncq,mqp->ncp", p, A[lc], cgt)
                    B[lo] = B[lo] + w3[:, ti, :, None] * q

        # message linear + residual update
        h_new = {}
        for l in self.h_ls:
            m = jnp.einsum("ncm,cd->ndm", B[l], inter["lin_msg"][str(l)]["w"])
            if l in h and str(l) in inter["lin_res"]:
                m = m + jnp.einsum(
                    "ncm,cd->ndm", h[l], inter["lin_res"][str(l)]["w"]
                )
            h_new[l] = m
        return h_new
