"""Simple analytic pair potentials (Lennard-Jones, Morse, ZBL).

Useful as fast baselines, MD integrator test oracles, and runtime
smoke-tests — and as the minimal example of the model contract:
``energy_fn(params, lg, positions) -> per-atom energies``. The ZBL
universal screened-Coulomb repulsion here is the pair baseline MACE adds
under its learned potential (reference mace/models.py:121-128).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..ops import radial

# Covalent radii in Å (Cordero et al. 2008), indexed by atomic number Z;
# index 0 unused. Used for the per-pair ZBL cutoff r_max = r_cov(Zu)+r_cov(Zv).
COVALENT_RADII = np.array([
    0.00,
    0.31, 0.28, 1.28, 0.96, 0.84, 0.76, 0.71, 0.66, 0.57, 0.58,
    1.66, 1.41, 1.21, 1.11, 1.07, 1.05, 1.02, 1.06, 2.03, 1.76,
    1.70, 1.60, 1.53, 1.39, 1.39, 1.32, 1.26, 1.24, 1.32, 1.22,
    1.22, 1.20, 1.19, 1.20, 1.20, 1.16, 2.20, 1.95, 1.90, 1.75,
    1.64, 1.54, 1.47, 1.46, 1.42, 1.39, 1.45, 1.44, 1.42, 1.39,
    1.39, 1.38, 1.39, 1.40, 2.44, 2.15, 2.07, 2.04, 2.03, 2.01,
    1.99, 1.98, 1.98, 1.96, 1.94, 1.92, 1.92, 1.89, 1.90, 1.87,
    1.87, 1.75, 1.70, 1.62, 1.51, 1.44, 1.41, 1.36, 1.36, 1.32,
    1.45, 1.46, 1.48, 1.40, 1.50, 1.50, 2.60, 2.21, 2.15, 2.06,
    2.00, 1.96, 1.90, 1.87, 1.80, 1.69,
])

# ZBL universal screening function coefficients
_ZBL_C = (0.18175, 0.50986, 0.28022, 0.02817)
_ZBL_D = (3.19980, 0.94229, 0.40290, 0.20162)
_COULOMB_EV_ANG = 14.399645  # e^2 / (4 pi eps0) in eV*Å


def zbl_edge_energy(z_u, z_v, d, a_exp=0.300, a_prefactor=0.4543, p: int = 6):
    """ZBL screened nuclear repulsion per directed edge, in eV.

    V(r) = (14.3996 eV*Å) Zu Zv / r * phi(r / a),
    a = a_prefactor * a0 / (Zu^a_exp + Zv^a_exp),
    smoothly cut at r_max = r_cov(Zu) + r_cov(Zv) by the polynomial
    envelope. a_exp/a_prefactor are trainable in upstream MACE; defaults
    match its init.
    """
    z_u = z_u.astype(d.dtype)
    z_v = z_v.astype(d.dtype)
    a = a_prefactor * 0.529177 / (z_u**a_exp + z_v**a_exp)
    x = d / a
    phi = sum(c * jnp.exp(-dd * x) for c, dd in zip(_ZBL_C, _ZBL_D))
    v = _COULOMB_EV_ANG * z_u * z_v / jnp.maximum(d, 1e-6) * phi
    cov = jnp.asarray(COVALENT_RADII, dtype=d.dtype)
    r_max = cov[z_u.astype(jnp.int32)] + cov[z_v.astype(jnp.int32)]
    env = radial.polynomial_cutoff(d, r_max, p=p) * (d < r_max)
    return v * env


@dataclass(frozen=True)
class PairConfig:
    cutoff: float = 5.0
    kind: str = "lj"  # "lj" | "morse"


class PairPotential:
    def __init__(self, config: PairConfig = PairConfig()):
        self.cfg = config

    def init(self, key=None) -> dict:
        if self.cfg.kind == "lj":
            return {"eps": jnp.float32(1.0), "sigma": jnp.float32(2.2)}
        return {"D": jnp.float32(1.0), "a": jnp.float32(1.5), "r0": jnp.float32(2.2)}

    def energy_fn(self, params, lg, positions):
        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        env = radial.cosine_cutoff(d, self.cfg.cutoff)
        if self.cfg.kind == "lj":
            x = (params["sigma"] / d) ** 6
            e_edge = 4.0 * params["eps"] * (x * x - x)
        else:
            ex = jnp.exp(-params["a"] * (d - params["r0"]))
            e_edge = params["D"] * (ex * ex - 2.0 * ex)
        e_edge = jnp.where(lg.edge_mask, e_edge * env, 0.0)
        # half: every pair appears as two directed edges; aggregate_edges
        # honors the interior/frontier edge layout (per-segment sorted)
        return 0.5 * lg.aggregate_edges(e_edge[:, None])[:, 0]
