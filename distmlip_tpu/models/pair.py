"""Simple analytic pair potentials (Lennard-Jones, Morse).

Useful as fast baselines, MD integrator test oracles, and runtime
smoke-tests — and as the minimal example of the model contract:
``energy_fn(params, lg, positions) -> per-atom energies``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..ops import radial
from ..ops.segment import masked_segment_sum


@dataclass(frozen=True)
class PairConfig:
    cutoff: float = 5.0
    kind: str = "lj"  # "lj" | "morse"


class PairPotential:
    def __init__(self, config: PairConfig = PairConfig()):
        self.cfg = config

    def init(self, key=None) -> dict:
        if self.cfg.kind == "lj":
            return {"eps": jnp.float32(1.0), "sigma": jnp.float32(2.2)}
        return {"D": jnp.float32(1.0), "a": jnp.float32(1.5), "r0": jnp.float32(2.2)}

    def energy_fn(self, params, lg, positions):
        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        env = radial.cosine_cutoff(d, self.cfg.cutoff)
        if self.cfg.kind == "lj":
            x = (params["sigma"] / d) ** 6
            e_edge = 4.0 * params["eps"] * (x * x - x)
        else:
            ex = jnp.exp(-params["a"] * (d - params["r0"]))
            e_edge = params["D"] * (ex * ex - 2.0 * ex)
        e_edge = jnp.where(lg.edge_mask, e_edge * env, 0.0)
        # half: every pair appears as two directed edges
        return 0.5 * masked_segment_sum(e_edge[:, None], lg.edge_dst, lg.n_cap,
                                        indices_are_sorted=True)[:, 0]
