"""ESCNMD — UMA/fairchem-parameterized eSCN backbone (weight-ingestible).

Where ``models/escn.py`` implements the eSCN *capabilities* in this repo's
own parameterization, this model reconstructs the fairchem ``eSCNMDBackbone``
surface tensor-for-tensor so pretrained UMA-family checkpoints can be
converted (MAPPINGS["escn"], models/convert.py) — the same discipline the
CHGNet/TensorNet rewrites applied to matgl. The reconstruction is pinned by
the reference wrapper's visible usage (reference
implementations/uma/escn_md.py):

- per-edge Wigner matrices via the Jd-table pipeline ``X(a) J X(b) J``
  in e3nn's y-polar basis (escn_md.py:74-130) — ops/so3_e3nn, tables
  derived from scratch and validated against the shipped Jd.pt;
- m-major coefficient packing for the SO(2) convolutions with (cos, sin)
  pairs mixed by (W_r, W_i) blocks (the to_m mapping, escn_md.py:117-129);
- mmax narrowing of edge-frame coefficients (escn_md.py:111-114);
- node features (N, (lmax+1)^2, C) with scalars initialized from the
  species embedding plus the per-system csd (charge/spin/dataset)
  embedding (escn_md.py:319-330);
- edge scalars = cat(gaussian distance expansion, source species emb,
  target species emb) feeding both the edge-degree embedding and the
  SO(2) radial scaling (escn_md.py:221-247);
- MOLE: SO(2) weights as per-system convex expert mixtures, coefficients
  replicated/psum-consistent across partitions (escn_md.py:343-357).

Internals fairchem does NOT expose through the wrapper (block wiring,
norm/activation/FFN details, RadialFunction shape) are reconstructed from
the public equiformer_v2/eSCN lineage and documented inline; every such
choice is mirrored exactly by the float64 torch oracle in
tests/test_convert_escn.py, which is the converter's golden contract.
Layout is channels-LAST (C in the TPU lane axis) per the round-3 finding.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import radial
from ..ops.nn import cast_params_subtrees
from ..kernels.dispatch import fused_segment_sum
from ..ops.so3_e3nn import CoeffLayout, wigner_blocks_from_edges


@dataclass(frozen=True)
class ESCNMDConfig:
    max_num_elements: int = 100
    sphere_channels: int = 64       # C
    lmax: int = 2
    mmax: int = 2
    num_layers: int = 2
    hidden_channels: int = 64       # SO(2) conv hidden width
    edge_channels: int = 32         # species embeddings + rad_func hidden
    num_distance_basis: int = 64    # gaussian smearing resolution
    # fairchem's GaussianSmearing(start, stop, num, basis_width_scalar) uses
    # sigma = basis_width_scalar * offset spacing; the eSCN/equiformer_v2/UMA
    # lineage constructs it with basis_width_scalar=2.0. The scalar is a
    # module attr, NOT a checkpoint tensor, so conversion cannot recover it —
    # it must match here by construction (PARITY.md calibration point).
    basis_width_scalar: float = 2.0
    cutoff: float = 5.0
    avg_degree: float = 14.0        # edge-degree + message rescale factor
    num_experts: int = 1            # > 1: MOLE mixtures on SO(2) weights
    # csd conditioning (UMA charge/spin/dataset, escn_md.py:255-265)
    num_charges: int = 25
    charge_min: int = -12
    num_spins: int = 10
    num_datasets: int = 4
    use_envelope: bool = True       # smooth cutoff on messages + edge-degree
    edge_chunk: int = 32768         # lax.scan edge chunking (0 = off)
    remat: bool | str = True    # bool or checkpoint-policy name (ops/chunk)
    dtype: str = "float32"

    @property
    def sphere_dim(self) -> int:
        return (self.lmax + 1) ** 2


def _rand(key, shape, scale):
    return scale * jax.random.normal(key, shape)


def _linear_init(key, d_in, d_out, bias=True):
    k1, k2 = jax.random.split(key)
    lim = 1.0 / np.sqrt(d_in)
    p = {"w": jax.random.uniform(k1, (d_out, d_in), minval=-lim, maxval=lim)}
    if bias:
        p["b"] = jax.random.uniform(k2, (d_out,), minval=-lim, maxval=lim)
    return p


def _linear(p, x):
    y = x @ p["w"].T
    if "b" in p:
        y = y + p["b"]
    return y


def _rad_init(key, dims):
    """RadialFunction (equiformer_v2 lineage): Linear -> LayerNorm -> SiLU
    per intermediate stage, bare Linear last. dims = [in, hidden, out]."""
    ks = jax.random.split(key, len(dims))
    p = {"lins": [], "lns": []}
    for i in range(len(dims) - 1):
        p["lins"].append(_linear_init(ks[i], dims[i], dims[i + 1]))
        if i < len(dims) - 2:
            p["lns"].append({"g": jnp.ones((dims[i + 1],)),
                             "b": jnp.zeros((dims[i + 1],))})
    return p


def _rad_apply(p, x):
    n = len(p["lins"])
    for i in range(n):
        x = _linear(p["lins"][i], x)
        if i < n - 1:
            ln = p["lns"][i]
            mu = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            x = (x - mu) * jax.lax.rsqrt(var + 1e-5) * ln["g"] + ln["b"]
            x = jax.nn.silu(x)
    return x


class ESCNMD:
    supports_compute_dtype = True

    def __init__(self, config: ESCNMDConfig = ESCNMDConfig()):
        if config.lmax > 6:
            raise NotImplementedError("lmax > 6: extend ops/so3 tables")
        self.cfg = config
        self.lay = CoeffLayout(config.lmax, config.mmax)
        # rad_func per-coefficient scaling vector length (input channels
        # per coefficient x paired coefficients per |m|), m = 0..mmax
        self._rad_splits = [
            self.lay.m_size(m) for m in range(self.lay.m_max + 1)
        ]

    # ---- parameters (shapes mirror the fairchem state dict 1:1) ----
    def init(self, key) -> dict:
        cfg = self.cfg
        C, H, Ce = cfg.sphere_channels, cfg.hidden_channels, cfg.edge_channels
        Dx = cfg.num_distance_basis + 2 * Ce
        K = cfg.num_experts
        lay = self.lay
        ks = iter(jax.random.split(key, 32 + cfg.num_layers * 16))

        def so2_weights(c_in, c_out, extra_m0, internal):
            p = {}
            m0_in = lay.m_size(0) * c_in
            m0_out = lay.m_size(0) * c_out + extra_m0
            shape0 = (K, m0_out, m0_in) if K > 1 else (m0_out, m0_in)
            lim = 1.0 / np.sqrt(m0_in)
            p["m0"] = jax.random.uniform(next(ks), shape0, minval=-lim,
                                         maxval=lim)
            p["m0_b"] = jnp.zeros((m0_out,))
            for m in range(1, lay.m_max + 1):
                nl = lay.m_size(m)
                shape = ((K, 2 * nl * c_out, nl * c_in) if K > 1
                         else (2 * nl * c_out, nl * c_in))
                lim = 1.0 / np.sqrt(nl * c_in)
                p[f"m{m}"] = jax.random.uniform(next(ks), shape, minval=-lim,
                                                maxval=lim)
            if not internal:
                p["rad"] = _rad_init(
                    next(ks), [Dx, Ce, sum(self._rad_splits) * c_in])
            return p

        params = {
            "sphere_embedding": {"w": _rand(next(ks), (cfg.max_num_elements, C), 1.0)},
            "source_embedding": {"w": _rand(next(ks), (cfg.max_num_elements, Ce), 1.0)},
            "target_embedding": {"w": _rand(next(ks), (cfg.max_num_elements, Ce), 1.0)},
            "csd": {
                "charge": {"w": _rand(next(ks), (cfg.num_charges, C), 1.0)},
                "spin": {"w": _rand(next(ks), (cfg.num_spins, C), 1.0)},
                "dataset": {"w": _rand(next(ks), (cfg.num_datasets, C), 1.0)},
                "mix": _linear_init(next(ks), 3 * C, C),
            },
            "edge_deg_rad": _rad_init(next(ks), [Dx, Ce, (cfg.lmax + 1) * C]),
            "blocks": [],
            "norm": {"w": jnp.ones((cfg.lmax + 1, C))},
            "energy_head": {
                "lin1": _linear_init(next(ks), C, C),
                "lin2": _linear_init(next(ks), C, 1),
            },
            "species_ref": {"w": jnp.zeros((cfg.max_num_elements,))},
        }
        if K > 1:
            params["mole_gate"] = {
                "lin1": _linear_init(next(ks), 2 * C, C),
                "lin2": _linear_init(next(ks), C, K),
            }
        for _ in range(cfg.num_layers):
            params["blocks"].append({
                "norm1": {"w": jnp.ones((cfg.lmax + 1, C))},
                "so2_1": so2_weights(2 * C, H, cfg.lmax * H, internal=False),
                "so2_2": so2_weights(H, C, 0, internal=True),
                "ff_norm": {"w": jnp.ones((cfg.lmax + 1, C))},
                "ff": {
                    "lin1": {"w": _rand(next(ks), (cfg.lmax + 1, H, C),
                                        1.0 / np.sqrt(C)),
                             "b": jnp.zeros((H,))},
                    "gate": _linear_init(next(ks), C, cfg.lmax * H),
                    "lin2": {"w": _rand(next(ks), (cfg.lmax + 1, C, H),
                                        1.0 / np.sqrt(H)),
                             "b": jnp.zeros((C,))},
                },
            })
        return params

    # ---- building blocks -------------------------------------------------
    def _rms_norm_sh(self, w, x):
        """Degree-balanced RMS norm with per-(l, channel) affine weight
        (rms_norm_sh: each coefficient weighted 1/(2l+1)/(lmax+1) so every
        degree contributes equally to the norm; no centering, no bias)."""
        cfg = self.cfg
        bal = np.zeros((cfg.sphere_dim,), dtype=np.float64)
        o = 0
        for l in range(cfg.lmax + 1):
            bal[o:o + 2 * l + 1] = 1.0 / ((2 * l + 1) * (cfg.lmax + 1))
            o += 2 * l + 1
        bal_j = jnp.asarray(bal, dtype=x.dtype)
        ms = jnp.mean(jnp.sum(x * x * bal_j[:, None], axis=-2), axis=-1)
        x = x * jax.lax.rsqrt(ms + 1e-12)[..., None, None]
        w_full = jnp.repeat(w.astype(x.dtype),
                            np.array([2 * l + 1 for l in range(cfg.lmax + 1)]),
                            axis=0)
        return x * w_full

    def _so2_mix(self, W, mole):
        """Collapse the expert axis with the per-system MOLE coefficients."""
        if self.cfg.num_experts > 1:
            return jnp.einsum("k,kab->ab", mole.astype(W.dtype), W)
        return W

    def _so2_conv(self, p, fr, rad_scale, mole, c_in, c_out, extra_m0):
        """SO(2) convolution on edge-frame features fr (E_c, S_nar, c_in).

        Per |m|, the (l >= m) coefficients flatten l-major to (nl * c_in)
        and pass through one linear map; m > 0 uses the (W_r, W_i) complex
        pair structure y+ = W_r f+ - W_i f-, y- = W_r f- + W_i f+ (the
        fairchem SO2_m_Convolution packing: fc output = [real | imag]
        halves). ``rad_scale``: optional per-coefficient input scaling from
        the radial function, same scale for the +m and -m partners."""
        lay = self.lay
        E = fr.shape[0]
        y = jnp.zeros((E, lay.size, c_out), dtype=fr.dtype)
        extra = None
        off = 0
        for m in range(lay.m_max + 1):
            nl = lay.m_size(m)
            if m == 0:
                f0 = fr[:, lay.plus_idx[0], :].reshape(E, nl * c_in)
                if rad_scale is not None:
                    f0 = f0 * rad_scale[:, off:off + nl * c_in]
                W0 = self._so2_mix(p["m0"], mole)
                out0 = f0 @ W0.T + p["m0_b"].astype(fr.dtype)
                main, extra = (out0[:, :nl * c_out], out0[:, nl * c_out:])
                y = y.at[:, lay.plus_idx[0], :].set(
                    main.reshape(E, nl, c_out))
            else:
                fp = fr[:, lay.plus_idx[m], :].reshape(E, nl * c_in)
                fm = fr[:, lay.minus_idx[m], :].reshape(E, nl * c_in)
                if rad_scale is not None:
                    s = rad_scale[:, off:off + nl * c_in]
                    fp, fm = fp * s, fm * s
                W = self._so2_mix(p[f"m{m}"], mole)
                d_out = nl * c_out
                Wr, Wi = W[:d_out], W[d_out:]
                yp = fp @ Wr.T - fm @ Wi.T
                ym = fm @ Wr.T + fp @ Wi.T
                y = y.at[:, lay.plus_idx[m], :].set(yp.reshape(E, nl, c_out))
                y = y.at[:, lay.minus_idx[m], :].set(ym.reshape(E, nl, c_out))
            off += nl * c_in
        return (y, extra) if extra_m0 else y

    def _gate_act(self, x, gates, full_layout=False):
        """Gate activation: scalars -> silu, l > 0 coefficients scaled by
        sigmoid(per-l gate scalars) broadcast over m. ``full_layout``
        selects (lmax+1)^2 node-block slices instead of the mmax-narrowed
        edge-frame slices."""
        cfg, lay = self.cfg, self.lay
        E, H = gates.shape[0], cfg.hidden_channels
        g = jax.nn.sigmoid(gates.reshape(E, cfg.lmax, H))
        y = x.at[:, 0, :].set(jax.nn.silu(x[:, 0, :]))
        for l in range(1, cfg.lmax + 1):
            sl = (slice(l * l, l * l + 2 * l + 1) if full_layout
                  else lay.block_slices[l])
            y = y.at[:, sl, :].multiply(g[:, l - 1][:, None, :])
        return y

    def _ffn(self, p, x):
        """Feed-forward: per-l SO3 linear -> gate activation -> SO3 linear
        (gate-type FFN; scalars get the l=0 bias)."""
        cfg, lay = self.cfg, self.lay
        gates = _linear(p["gate"], x[:, 0, :])  # from input scalars
        h = jnp.einsum("nsc,shc->nsh", x, self._expand_lweights(p["lin1"]["w"], x.dtype))
        h = h.at[:, 0, :].add(p["lin1"]["b"].astype(x.dtype))
        h = self._gate_act(h, gates, full_layout=True)
        y = jnp.einsum("nsh,sch->nsc", h, self._expand_lweights(p["lin2"]["w"], x.dtype))
        y = y.at[:, 0, :].add(p["lin2"]["b"].astype(x.dtype))
        return y

    def _expand_lweights(self, w, dtype):
        """(lmax+1, a, b) per-degree weights -> (S, a, b) per-coefficient."""
        reps = np.array([2 * l + 1 for l in range(self.cfg.lmax + 1)])
        return jnp.repeat(w.astype(dtype), reps, axis=0)

    # ---- forward ---------------------------------------------------------
    def energy_fn(self, params, lg, positions):
        cfg, lay = self.cfg, self.lay
        C, H, S = cfg.sphere_channels, cfg.hidden_channels, cfg.sphere_dim
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        if cfg.dtype == "bfloat16":
            params = cast_params_subtrees(
                params, dtype, keep_fp32=("species_ref", "energy_head"))

        # fairchem's edge vector points src -> ... pos[src] - pos[dst]
        # (reference compute.py:169-173); lg.edge_vectors is dst - src
        vec = -lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        # masked (padding) edges get a fixed safe direction: their rhat is
        # (0,0,0), and atan2's gradient at the origin is NaN — which would
        # poison the whole force array through the 0-weighted messages
        safe = jnp.asarray([0.0, 0.0, 1.0], dtype=positions.dtype)
        rhat = jnp.where(lg.edge_mask[:, None],
                         vec / jnp.maximum(d, 1e-9)[:, None], safe)
        env = (
            radial.polynomial_cutoff(d, cfg.cutoff) * lg.edge_mask
            if cfg.use_envelope else lg.edge_mask.astype(positions.dtype)
        ).astype(dtype)
        # gaussian smearing over [0, cutoff]; sigma = basis_width_scalar x
        # center spacing (fairchem GaussianSmearing convention)
        centers = jnp.linspace(0.0, cfg.cutoff, cfg.num_distance_basis)
        width = (cfg.basis_width_scalar * cfg.cutoff
                 / (cfg.num_distance_basis - 1))
        gauss = jnp.exp(-0.5 * ((d[:, None] - centers) / width) ** 2
                        ).astype(dtype)

        z = jnp.asarray(lg.species)
        zemb = params["sphere_embedding"]["w"][z].astype(dtype)

        # csd (charge/spin/dataset) system embedding
        sys_state = lg.system or {}
        qi = jnp.clip(jnp.asarray(sys_state.get("charge", 0)) - cfg.charge_min,
                      0, cfg.num_charges - 1)
        si = jnp.clip(jnp.asarray(sys_state.get("spin", 0)), 0, cfg.num_spins - 1)
        di = jnp.clip(jnp.asarray(sys_state.get("dataset", 0)), 0,
                      cfg.num_datasets - 1)
        csd = _linear(params["csd"]["mix"], jnp.concatenate([
            params["csd"]["charge"]["w"][qi],
            params["csd"]["spin"]["w"][si],
            params["csd"]["dataset"]["w"][di],
        ], axis=-1).astype(dtype))  # (C,)

        h = jnp.zeros((positions.shape[0], S, C), dtype=dtype)
        h = h.at[:, 0, :].set(zemb + csd[None, :])

        # MOLE coefficients: psum-consistent composition + csd gate
        if cfg.num_experts > 1:
            if lg.struct_id is not None and lg.batch_size > 0:
                # the composition pool below spans the WHOLE graph — on a
                # packed batch that would silently mix structures' gates.
                # The base models/escn.py ESCN implements per-structure
                # gating; this UMA-MD variant does not (yet).
                raise NotImplementedError(
                    "ESCNMD's MOLE gate pools composition per system; "
                    "batched (packed) graphs would mix structures. Use "
                    "models.escn.ESCN for batched inference, or "
                    "num_experts=1.")
            owned = lg.owned_mask.astype(dtype)[:, None]
            comp = lg.psum(jnp.sum(zemb * owned, axis=0))
            count = lg.psum(jnp.sum(owned))
            gate_in = jnp.concatenate([comp / jnp.maximum(count, 1.0), csd])
            g = jax.nn.silu(_linear(params["mole_gate"]["lin1"], gate_in))
            mole = jax.nn.softmax(_linear(params["mole_gate"]["lin2"], g))
        else:
            mole = None

        # --- edge-chunked scan scaffolding (shared with models/escn.py);
        # chunk_layout keeps every chunk inside one dst-sorted edge segment
        from ..ops.chunk import chunk_layout, chunked, scan_accumulate

        e_cap = lg.edge_src.shape[0]
        row_idx, row_valid, K_ch, chunk = chunk_layout(
            e_cap, cfg.edge_chunk,
            lg.e_split if lg.has_frontier_split else None)
        take = lambda x: chunked(jnp.asarray(x)[row_idx], K_ch, chunk)
        edge_xs = (
            take(lg.edge_src),
            take(lg.edge_dst),
            chunked(jnp.asarray(lg.edge_mask)[row_idx]
                    & jnp.asarray(row_valid), K_ch, chunk),
            take(rhat),
            take(gauss),
            take(env),
        )

        # per-l lab-from-edge blocks; ops/so3_e3nn builds them at >= fp32
        # with pole-safe angles, downcast per-use in rotate_in/rotate_out
        wigner_blocks = partial(wigner_blocks_from_edges, cfg.lmax)

        def rotate_in(hvecs, D):
            """Lab (E_c, S_full, c) -> edge frame (E_c, S_nar, c): transpose
            blocks, keep the center 2*min(l,mmax)+1 rows."""
            parts = []
            for l in range(cfg.lmax + 1):
                rows = lay.block_rows(l)
                Dl = D[l][:, :, rows].astype(hvecs.dtype)  # (E, 2l+1, nar)
                o = l * l
                parts.append(jnp.einsum(
                    "epn,epc->enc", Dl, hvecs[:, o:o + 2 * l + 1, :]))
            return jnp.concatenate(parts, axis=1)

        def rotate_out(y, D):
            """Edge frame (E_c, S_nar, c) -> lab (E_c, S_full, c)."""
            parts = []
            for l in range(cfg.lmax + 1):
                rows = lay.block_rows(l)
                Dl = D[l][:, :, rows].astype(y.dtype)
                parts.append(jnp.einsum("epn,enc->epc", Dl,
                                        y[:, lay.block_slices[l], :]))
            return jnp.concatenate(parts, axis=1)

        def edge_scan(per_chunk, out_shape):
            def body(acc, xs):
                srcc, dstc, maskc, rhatc, gaussc, envc = xs
                D = wigner_blocks(rhatc)
                msg = per_chunk(srcc, dstc, maskc, D, gaussc, envc)
                return (
                    acc + fused_segment_sum(
                        # sorted within every chunk by chunk_layout;
                        # Pallas dst-tiled scatter on TPU (kernels/dispatch)
                        msg, dstc, lg.n_cap, maskc,
                        indices_are_sorted=True, kernels=lg.kernels),
                    None,
                )

            acc0 = jnp.zeros((lg.n_cap,) + out_shape, dtype=dtype)
            return scan_accumulate(body, acc0, edge_xs, remat=cfg.remat)

        def edge_scalars(srcc, dstc, gaussc):
            return jnp.concatenate([
                gaussc,
                params["source_embedding"]["w"][z[srcc]].astype(dtype),
                params["target_embedding"]["w"][z[dstc]].astype(dtype),
            ], axis=-1)

        # --- edge-degree embedding (escn_md.py:221-247): radial weights
        # placed in the edge frame's m=0 slots, rotated to the lab frame,
        # degree-summed onto the receiver, / avg_degree
        def deg_chunk(srcc, dstc, maskc, D, gaussc, envc):
            w = _rad_apply(params["edge_deg_rad"], edge_scalars(srcc, dstc, gaussc))
            w = w.reshape(-1, cfg.lmax + 1, C)
            y = jnp.zeros((w.shape[0], lay.size, C), dtype=dtype)
            y = y.at[:, lay.plus_idx[0], :].set(w)
            return rotate_out(y, D) * env_mult(envc)

        def env_mult(envc):
            return envc[:, None, None]

        inv_deg = jnp.asarray(1.0 / cfg.avg_degree, dtype=dtype)
        h = h + edge_scan(deg_chunk, (S, C)) * inv_deg
        h = lg.halo_exchange(h)

        for blk in params["blocks"]:

            def so2_chunk(srcc, dstc, maskc, D, gaussc, envc, blk=blk):
                xe = edge_scalars(srcc, dstc, gaussc)
                rad = _rad_apply(blk["so2_1"]["rad"], xe)  # per-coeff scales
                xn_src = hn[srcc]
                xn_dst = hn[dstc]
                fr = jnp.concatenate([
                    rotate_in(xn_src, D), rotate_in(xn_dst, D)], axis=-1)
                y, gates = self._so2_conv(
                    blk["so2_1"], fr, rad, mole, 2 * C, H, cfg.lmax * H)
                y = self._gate_act(y, gates)
                y = self._so2_conv(blk["so2_2"], y, None, mole, H, C, 0)
                return rotate_out(y, D) * env_mult(envc)

            # message path reads the NORMALIZED features (with the system
            # embedding re-injected into the scalars); residual keeps h
            hn = self._rms_norm_sh(blk["norm1"]["w"], h)
            hn = hn.at[:, 0, :].add(csd[None, :])
            h = h + edge_scan(so2_chunk, (S, C)) * inv_deg
            # FFN with pre-norm and residual
            h = h + self._ffn(blk["ff"], self._rms_norm_sh(blk["ff_norm"]["w"], h))
            h = lg.halo_exchange(h)

        h = self._rms_norm_sh(params["norm"]["w"], h)
        s = h[:, 0, :]
        e = _linear(params["energy_head"]["lin2"],
                    jax.nn.silu(_linear(params["energy_head"]["lin1"],
                                        s.astype(positions.dtype))))[:, 0]
        return e + params["species_ref"]["w"][z].astype(positions.dtype)
