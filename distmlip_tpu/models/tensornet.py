"""TensorNet: O(3)-equivariant message passing on rank-2 tensor features.

A TPU-native implementation of the TensorNet architecture (Simeon & De
Fabritiis 2023) as deployed for MLIPs by matgl, matching the capability the
reference wraps in its distributed TensorNet path (reference
implementations/matgl/models/tensornet.py:10-161: per-partition interaction
layers with an atom-feature halo exchange after each, then an invariant
readout). Here each node carries X_i in R^{C x 3 x 3}; messages scale the
neighbor tensor's irreducible components by radial weights; the update is a
matrix polynomial — all dense (C,3,3) einsums that map straight onto the MXU.

Distributed contract: edges live with their dst owner, so every in-edge of an
owned node is local; after each layer the updated tensors of border nodes are
refreshed on neighbors via ``lg.halo_exchange`` (one call per layer — same
cadence as the reference's ``atom_transfer``, tensornet.py:121-128).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..ops import radial
from ..ops.nn import (cast_params_subtrees, embedding, embedding_init, layernorm, layernorm_init,
                      linear, linear_init, mlp, mlp_init)
from ..ops.segment import masked_segment_sum


@dataclass(frozen=True)
class TensorNetConfig:
    num_species: int = 95
    units: int = 64
    num_rbf: int = 32
    num_layers: int = 2
    cutoff: float = 5.0
    dtype: str = "float32"


def decompose(X):
    """Split (..., 3, 3) into (trace-part I, antisymmetric A, sym-traceless S)."""
    trace = jnp.trace(X, axis1=-2, axis2=-1)[..., None, None]
    eye = jnp.eye(3, dtype=X.dtype)
    I = trace / 3.0 * eye
    A = 0.5 * (X - jnp.swapaxes(X, -1, -2))
    S = 0.5 * (X + jnp.swapaxes(X, -1, -2)) - I
    return I, A, S


def tensor_norm(X):
    """Per-channel squared Frobenius norm: (..., C, 3, 3) -> (..., C)."""
    return jnp.sum(X * X, axis=(-2, -1))


def tensor_rms_norm(X):
    """Bounded-gain normalization: divide by (RMS of channel norms + 1).

    Gain is <= 1 everywhere — vanishing features stay vanishing (no
    1/sqrt(eps) amplification that would create spurious forces at the
    cutoff), while O(1)+ features are normalized to O(1). Returns
    (X_normalized, per-channel squared norms of X_normalized).
    """
    n = tensor_norm(X)
    scale = 1.0 / (jnp.sqrt(jnp.mean(n, axis=-1, keepdims=True)) + 1.0)
    Xn = X * scale[..., None, None]
    return Xn, n * scale**2


def magnitude_gate(n, c: float = 0.01):
    """Smooth per-atom gate in [0,1): mean-norm / (mean-norm + c).

    Multiplies LayerNorm-driven MLP outputs so they (and their position
    gradients) vanish smoothly as an atom's features vanish — keeps the
    isolated-atom / cutoff limit force-free instead of letting LayerNorm
    amplify vanishing signals.
    """
    nbar = jnp.mean(n, axis=-1, keepdims=True)
    return nbar / (nbar + c)


def _vector_to_skew(v):
    """(..., 3) -> (..., 3, 3) antisymmetric [v]_x."""
    zero = jnp.zeros_like(v[..., 0])
    rows = [
        jnp.stack([zero, -v[..., 2], v[..., 1]], axis=-1),
        jnp.stack([v[..., 2], zero, -v[..., 0]], axis=-1),
        jnp.stack([-v[..., 1], v[..., 0], zero], axis=-1),
    ]
    return jnp.stack(rows, axis=-2)


class TensorNet:
    def __init__(self, config: TensorNetConfig = TensorNetConfig()):
        self.cfg = config

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        ks = iter(jax.random.split(key, 16 + 8 * cfg.num_layers))
        C, R = cfg.units, cfg.num_rbf
        params = {
            "species_emb": embedding_init(next(ks), cfg.num_species, C),
            "edge_embed": mlp_init(next(ks), [2 * C + R, C, 3 * C]),
            "emb_norm_mlp": mlp_init(next(ks), [C, C, 3 * C]),
            "emb_ln": layernorm_init(C),
            "layers": [],
            "readout": mlp_init(next(ks), [3 * C, C, 1]),
            "readout_ln": layernorm_init(3 * C),
            "species_ref": {"w": jnp.zeros((cfg.num_species, 1))},
        }
        for _ in range(cfg.num_layers):
            params["layers"].append(
                {
                    "rbf_w": linear_init(next(ks), R, 3 * C),
                    "norm_mlp": mlp_init(next(ks), [C, C, 3 * C]),
                    "ln": layernorm_init(C),
                    "mix_in": [linear_init(next(ks), C, C, bias=False) for _ in range(3)],
                    "mix_out": [linear_init(next(ks), C, C, bias=False) for _ in range(3)],
                }
            )
        return params

    supports_compute_dtype = True  # energy_fn honors cfg.dtype="bfloat16"

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        cfg = self.cfg
        C = cfg.units
        # features/GEMMs in the compute dtype; geometry + energy sum in the
        # positions dtype (same policy as MACE/eSCN)
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        if cfg.dtype == "bfloat16":
            params = cast_params_subtrees(
                params, dtype, keep_fp32=("species_ref", "readout", "readout_ln")
            )
        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        rhat = vec / jnp.maximum(d, 1e-9)[:, None]
        env = (radial.polynomial_cutoff(d, cfg.cutoff) * lg.edge_mask).astype(dtype)
        rbf = radial.spherical_bessel_basis(d, cfg.cutoff, cfg.num_rbf).astype(dtype)

        eye = jnp.eye(3, dtype=dtype)
        rhat = rhat.astype(dtype)
        A_e = _vector_to_skew(rhat)                       # (E, 3, 3)
        S_e = rhat[:, :, None] * rhat[:, None, :] - eye / 3.0

        # --- embedding: per-edge tensors weighted by species + radial ---
        z = embedding(params["species_emb"], lg.species)  # (N, C)
        ef = jnp.concatenate([z[lg.edge_src], z[lg.edge_dst], rbf], axis=-1)
        w = mlp(params["edge_embed"], ef).reshape(-1, 3, C) * env[:, None, None]
        comps = jnp.stack(
            [jnp.broadcast_to(eye, A_e.shape), A_e, S_e], axis=1
        )                                                 # (E, 3, 3, 3)
        edge_X = jnp.einsum("ekc,ekij->ecij", w, comps)   # (E, C, 3, 3)
        X = masked_segment_sum(edge_X, lg.edge_dst, lg.n_cap, lg.edge_mask,
                               indices_are_sorted=True)

        X = self._normalize_mix(params["emb_norm_mlp"], X, params["emb_ln"])
        X = lg.halo_exchange(X)

        # --- interaction layers ---
        for lp in params["layers"]:
            X = self._interaction(lp, lg, X, rbf, env)
            X = lg.halo_exchange(X)

        # --- invariant readout ---
        Xr, nr = tensor_rms_norm(X)
        I, A, S = decompose(Xr)
        inv = jnp.concatenate([tensor_norm(I), tensor_norm(A), tensor_norm(S)], axis=-1)
        # readout in the positions dtype (fp32 energy accumulation)
        inv = inv.astype(positions.dtype)
        e_atom = mlp(params["readout"], layernorm(params["readout_ln"], inv))[:, 0]
        e_atom = e_atom * magnitude_gate(nr)[..., 0].astype(positions.dtype)
        e_ref = params["species_ref"]["w"][lg.species, 0]
        return e_atom + e_ref

    def _normalize_mix(self, norm_mlp, X, ln):
        C = self.cfg.units
        X, n = tensor_rms_norm(X)
        s = mlp(norm_mlp, layernorm(ln, n)).reshape(n.shape[:-1] + (3, C))
        s = s * magnitude_gate(n)[..., None]
        I, A, S = decompose(X)
        return (
            s[..., 0, :, None, None] * I
            + s[..., 1, :, None, None] * A
            + s[..., 2, :, None, None] * S
        )

    def _mix_channels(self, lins, X):
        """Per-component channel-mixing linear maps (C -> C)."""
        I, A, S = decompose(X)
        out = []
        for lin, comp in zip(lins, (I, A, S)):
            # (..., C, 3, 3) channel mix: contract channel axis
            out.append(jnp.einsum("...cij,cd->...dij", comp, lin["w"]))
        return out[0] + out[1] + out[2]

    def _interaction(self, lp, lg, X, rbf, env):
        C = self.cfg.units
        # normalize + per-channel mix
        Xn, _ = tensor_rms_norm(X)
        Xm = self._mix_channels(lp["mix_in"], Xn)

        # radial message weights per component/channel
        f = linear(lp["rbf_w"], rbf).reshape(-1, 3, C) * env[:, None, None]
        I_j, A_j, S_j = decompose(Xm[lg.edge_src])
        M = (
            f[:, 0, :, None, None] * I_j
            + f[:, 1, :, None, None] * A_j
            + f[:, 2, :, None, None] * S_j
        )
        Y = masked_segment_sum(M, lg.edge_dst, lg.n_cap, lg.edge_mask,
                               indices_are_sorted=True)

        # matrix-polynomial node update
        Y2 = jnp.einsum("...ij,...jk->...ik", Y, Y)
        B = Y + Y2
        Bn, bn = tensor_rms_norm(B)
        s = mlp(lp["norm_mlp"], layernorm(lp["ln"], bn)).reshape(bn.shape[:-1] + (3, C))
        s = s * magnitude_gate(bn)[..., None]
        I_b, A_b, S_b = decompose(Bn)
        dX = (
            s[..., 0, :, None, None] * I_b
            + s[..., 1, :, None, None] * A_b
            + s[..., 2, :, None, None] * S_b
        )
        dX = self._mix_channels(lp["mix_out"], dX)
        return X + dX
