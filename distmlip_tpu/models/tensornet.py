"""TensorNet: O(3)-equivariant message passing on rank-2 tensor features.

TPU-native implementation of TensorNet (Simeon & De Fabritiis 2023) in
**matgl's exact parameterization** (torchmd-net port), so pretrained matgl
checkpoints convert weight-for-weight (``convert.MAPPINGS["tensornet"]``).
The reference distributes matgl's TensorNet via ``from_existing`` __dict__
copy (reference implementations/matgl/models/tensornet.py:204-214); its
module inventory is pinned by enable_distributed_mode (:179-197) and the
readout math by dist_forward (:131-159): tensor_embedding -> interaction
layers (atom_transfer after each) -> decompose/tensor_norm invariants ->
out_norm LayerNorm -> linear -> final_layer.gated MLP -> sum.

Per-node state X_i in R^{3 x 3 x C}, channels LAST: TPU arrays tile their
trailing two axes to (sublane, lane=128), so keeping C in the lane axis
(instead of a 3-wide matrix axis padded to 128) cuts the physical footprint
of every tensor-valued intermediate ~40x. The scalar-gate unflatten keeps
torchmd-net's (C, 3) order so matgl weights convert unchanged. Distributed
contract: edges live with their dst
owner, so every in-edge of an owned node is local; after the embedding and
each interaction layer the updated tensors of border nodes are refreshed on
neighbors via ``lg.halo_exchange`` (same cadence as the reference's
``atom_transfer``, tensornet.py:121-128).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..kernels.dispatch import Gather
from ..ops import radial
from ..ops.nn import (cast_params_subtrees, embedding, gather_rows,
                      layernorm, layernorm_init, linear, linear_init, mlp,
                      mlp_init)


@dataclass(frozen=True)
class TensorNetConfig:
    num_species: int = 95
    units: int = 64           # hidden_channels
    num_rbf: int = 32
    num_layers: int = 2
    cutoff: float = 5.0
    final_hidden: tuple | None = None  # final_layer.gated dims, default (units, units)
    dtype: str = "float32"

    @property
    def _final_hidden(self):
        return self.final_hidden if self.final_hidden is not None else (self.units, self.units)


def decompose(X):
    """Split (..., 3, 3, C) into (trace-part I, antisymmetric A,
    sym-traceless S); the matrix lives in axes (-3, -2)."""
    trace = (X[..., 0, 0, :] + X[..., 1, 1, :] + X[..., 2, 2, :])[
        ..., None, None, :
    ]
    eye = jnp.eye(3, dtype=X.dtype)[:, :, None]
    I = trace / 3.0 * eye
    Xt = jnp.swapaxes(X, -3, -2)
    A = 0.5 * (X - Xt)
    S = 0.5 * (X + Xt) - I
    return I, A, S


def tensor_norm(X):
    """Per-channel squared Frobenius norm: (..., 3, 3, C) -> (..., C)."""
    return jnp.sum(X * X, axis=(-3, -2))


def _vector_to_skew(v):
    """(..., 3) -> (..., 3, 3) antisymmetric [v]_x (torchmd-net
    vector_to_skewtensor convention)."""
    zero = jnp.zeros_like(v[..., 0])
    rows = [
        jnp.stack([zero, -v[..., 2], v[..., 1]], axis=-1),
        jnp.stack([v[..., 2], zero, -v[..., 0]], axis=-1),
        jnp.stack([-v[..., 1], v[..., 0], zero], axis=-1),
    ]
    return jnp.stack(rows, axis=-2)


def _mix(lin, comp):
    """torchmd-net channel mix: Linear over the channel axis of a
    (..., 3, 3, C) component (torch permutes around nn.Linear; here the
    channel axis is already last, so it is one lane-resident GEMM)."""
    return jnp.einsum("...ijc,cd->...ijd", comp, lin["w"])


class TensorNet:
    def __init__(self, config: TensorNetConfig = TensorNetConfig()):
        self.cfg = config

    # ---- parameters ----
    def init(self, key) -> dict:
        cfg = self.cfg
        ks = iter(jax.random.split(key, 24 + 10 * cfg.num_layers))
        C, R = cfg.units, cfg.num_rbf
        params = {
            # tensor_embedding.*
            "species_emb": {"w": jax.random.normal(next(ks), (cfg.num_species, C))},
            "emb2": linear_init(next(ks), 2 * C, C),
            "dist_proj": [linear_init(next(ks), R, C) for _ in range(3)],
            "emb_lin_scalar": [linear_init(next(ks), C, 2 * C),
                               linear_init(next(ks), 2 * C, 3 * C)],
            "emb_lin_tensor": [linear_init(next(ks), C, C, bias=False)
                               for _ in range(3)],
            "init_norm": layernorm_init(C),
            "layers": [],
            # readout (reference dist_forward :131-151)
            "out_norm": layernorm_init(3 * C),
            "linear": linear_init(next(ks), 3 * C, C),
            "final": mlp_init(next(ks), [C] + list(cfg._final_hidden) + [1]),
            "species_ref": {"w": jnp.zeros((cfg.num_species, 1))},
            "data_std": jnp.ones(()),
        }
        for _ in range(cfg.num_layers):
            params["layers"].append({
                "lin_scalar": [linear_init(next(ks), R, C),
                               linear_init(next(ks), C, 2 * C),
                               linear_init(next(ks), 2 * C, 3 * C)],
                "lin_tensor": [linear_init(next(ks), C, C, bias=False)
                               for _ in range(6)],
            })
        return params

    supports_compute_dtype = True  # energy_fn honors cfg.dtype="bfloat16"

    # ---- forward ----
    def energy_fn(self, params, lg, positions):
        cfg = self.cfg
        C = cfg.units
        # features/GEMMs in the compute dtype; geometry + readout stack in
        # the positions dtype (same policy as MACE/eSCN/CHGNet)
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else positions.dtype
        fp = params
        if cfg.dtype == "bfloat16":
            params = cast_params_subtrees(
                params, dtype,
                keep_fp32=("species_ref", "out_norm", "linear", "final",
                           "data_std"))

        vec = lg.edge_vectors(positions)
        d = jnp.linalg.norm(jnp.where(lg.edge_mask[:, None], vec, 1.0), axis=-1)
        rhat = (vec / jnp.maximum(d, 1e-9)[:, None]).astype(dtype)
        env = (radial.cosine_cutoff(d, cfg.cutoff) * lg.edge_mask).astype(dtype)
        rbf = radial.spherical_bessel_basis(d, cfg.cutoff, cfg.num_rbf).astype(dtype)

        # --- tensor embedding (torchmd-net TensorEmbedding) ---
        eye = jnp.eye(3, dtype=dtype)[:, :, None]                # (3, 3, 1)
        A_e = _vector_to_skew(rhat)[..., None]                   # (E, 3, 3, 1)
        S_e = (rhat[:, :, None] * rhat[:, None, :])[..., None] - eye / 3.0

        z = embedding(params["species_emb"], lg.species)         # (N, C)
        # gather_rows: on the bf16 path the backward accumulates per-node
        # feature grads from every referencing edge in fp32, not bf16
        Zij = linear(params["emb2"],
                     jnp.concatenate([gather_rows(z, lg.edge_src),
                                      gather_rows(z, lg.edge_dst)], axis=-1))
        W1 = linear(params["dist_proj"][0], rbf) * env[:, None]  # (E, C)
        W2 = linear(params["dist_proj"][1], rbf) * env[:, None]
        W3 = linear(params["dist_proj"][2], rbf) * env[:, None]

        # the (E, 3, 3, C) edge tensor is 9C wide vs the ~4C of its inputs
        # — built INSIDE the fused dst-tile kernel on the Pallas path, so
        # it never materializes in HBM (kernels/dispatch); the XLA path
        # builds it whole and segment-sums with the sorted hint, exactly
        # the historical program
        def embed_msg(zij, w1, w2, w3, ae, se):
            return zij[:, None, None, :] * (
                w1[:, None, None, :] * eye
                + w2[:, None, None, :] * ae
                + w3[:, None, None, :] * se
            )

        X = lg.aggregate_edge_messages(
            embed_msg, (Zij, W1, W2, W3, A_e, S_e), mask=lg.edge_mask)

        norm = layernorm(params["init_norm"], tensor_norm(X))
        for lin in params["emb_lin_scalar"]:
            norm = jax.nn.silu(linear(lin, norm))
        norm = norm.reshape(-1, C, 3)  # torchmd-net's (C, 3) unflatten order
        I, A, S = decompose(X)
        I = _mix(params["emb_lin_tensor"][0], I)
        A = _mix(params["emb_lin_tensor"][1], A)
        S = _mix(params["emb_lin_tensor"][2], S)
        X = (I * norm[:, None, None, :, 0] + A * norm[:, None, None, :, 1]
             + S * norm[:, None, None, :, 2])
        X = lg.halo_exchange(X)

        # --- interaction layers ---
        for lp in params["layers"]:
            X = self._interaction(lp, lg, X, rbf, env)
            X = lg.halo_exchange(X)

        # --- invariant readout (reference dist_forward :131-151) ---
        I, A, S = decompose(X)
        inv = jnp.concatenate(
            [tensor_norm(I), tensor_norm(A), tensor_norm(S)], axis=-1
        ).astype(positions.dtype)
        x = linear(fp["linear"], layernorm(fp["out_norm"], inv))
        e_atom = mlp(fp["final"], x)[:, 0]
        e_ref = fp["species_ref"]["w"][lg.species, 0]
        return fp["data_std"] * e_atom + e_ref

    def _interaction(self, lp, lg, X, rbf, env):
        """torchmd-net TensorNetInteraction (O(3) group): radial edge gates,
        per-channel normalization X/(||X||+1), channel mixes, neighbor
        message M, B = YM + MY, normalized remix, X + dX + dX^2."""
        C = self.cfg.units
        f = rbf
        for lin in lp["lin_scalar"]:
            f = jax.nn.silu(linear(lin, f))
        f = (f * env[:, None]).reshape(-1, C, 3)  # torchmd-net (C, 3) order

        X = X / (tensor_norm(X) + 1.0)[..., None, None, :]
        I, A, S = decompose(X)
        I = _mix(lp["lin_tensor"][0], I)
        A = _mix(lp["lin_tensor"][1], A)
        S = _mix(lp["lin_tensor"][2], S)
        Y = I + A + S

        # 27C of gathered src components fold into a 9C message inside the
        # fused kernel (in-kernel src gather on the Pallas path)
        def int_msg(f_e, i_s, a_s, s_s):
            return (f_e[:, None, None, :, 0] * i_s
                    + f_e[:, None, None, :, 1] * a_s
                    + f_e[:, None, None, :, 2] * s_s)

        M = lg.aggregate_edge_messages(
            int_msg,
            (f, Gather(I, lg.edge_src), Gather(A, lg.edge_src),
             Gather(S, lg.edge_src)),
            mask=lg.edge_mask)

        # batched 3x3 matmuls over (node, channel); the matrix axes are
        # (-3, -2), channels ride the lane axis untouched
        matmul = lambda P, Q: jnp.einsum("nijc,njkc->nikc", P, Q)
        B = matmul(Y, M) + matmul(M, Y)
        I, A, S = decompose(B)
        np1 = (tensor_norm(B) + 1.0)[..., None, None, :]
        I = _mix(lp["lin_tensor"][3], I / np1)
        A = _mix(lp["lin_tensor"][4], A / np1)
        S = _mix(lp["lin_tensor"][5], S / np1)
        dX = I + A + S
        return X + dX + matmul(dX, dX)
