"""Static HBM planner: buffer-liveness peak-memory analysis of traced programs.

Answers the question every OOM postmortem asks — *how many bytes is this
program's peak live set, and which buffers own it* — WITHOUT a chip and
without compiling: one pass over the :mod:`distmlip_tpu.analysis.ir`
walker's view of the jaxpr, before XLA ever sees the program. The result
drives three consumers:

- the ``memory_budget`` contract pass (``analysis/passes/memory_budget.py``)
  gates CI on a program's estimated peak vs the device ``bytes_limit``;
- memory-aware autobatching (``BucketPolicy.calibrate_bytes`` /
  ``serve.scheduler.plan_batch``) fills batches to an HBM budget instead of
  a fixed slot count;
- telemetry (``StepRecord.est_peak_bytes`` / ``hbm_headroom_frac``)
  compares the prediction against the backend's measured ``bytes_in_use``
  so estimator drift is visible on real hardware.

Estimator model
---------------
The walk is a sequential interpretation of the (nested) jaxpr:

- every aval is sized as ``prod(shape) * itemsize``;
- non-donated program inputs and baked consts are resident for the whole
  program (XLA holds caller-owned buffers); a DONATED input dies at its
  last use — its buffer is reusable from there on;
- a temporary lives from the eqn that defines it to its last use; eqn
  *transient* residency counts inputs AND outputs simultaneously (an op
  cannot free its operands before it finishes);
- call-like sub-jaxprs (pjit / remat / custom-vjp / shard_map bodies) are
  INLINED, exactly as XLA inlines them: a buffer crossing the boundary
  dies at its true last use inside the body, not at the call's end — the
  residuals feeding a grad program's transposed shard_map free
  progressively as the backward consumes them;
- ``scan``/``while``/``cond``/``pallas_call`` stay opaque: operands are
  held for the whole call (a loop needs them every iteration), the body's
  standalone peak is charged as call transient, and loops additionally
  charge a second copy of the carry (XLA double-buffers loop state it
  cannot prove aliasable); a scan's stacked ``ys`` are full-length
  outputs at the call site;
- ``shard_map`` bodies carry per-shard avals, so everything produced
  inside (including the residuals aliased out) is per-device sized
  automatically; program *arguments* consumed by a shard_map are scaled
  by the product of the mesh axis sizes their ``in_names`` entry shards
  over, making the reported peak a PER-DEVICE estimate;
- ``pallas_call`` scratch (body refs beyond the operands/outputs) is
  charged as transient VMEM/HBM residency of the call eqn.

Two XLA realities the pure jaxpr walk cannot see are modeled explicitly
(both calibrated against ``compile().memory_analysis()`` on the repo's 22
contract-check programs — the estimator-vs-oracle test pins the 2x band):

- **fusion** (forward bias: overestimate): ``broadcast_in_dim`` / ``iota``
  / shape-only views never materialize — XLA fuses them into consumers —
  so their outputs are charged zero bytes (``VIRTUAL_PRIMS``);
- **scheduler slack** (backward bias: underestimate): XLA's list scheduler
  is not memory-minimizing — in a region dominated by UNFUSABLE ops
  (gather/slice/pad/concatenate/scatter), independent chains' buffers
  coexist far beyond jaxpr-order liveness (measured: the eSCN SO(2)-conv
  backward holds ~24 such buffers at its scheduled peak where jaxpr order
  needs ~6). Each region is therefore charged at least
  ``SCHED_SLACK_FRAC`` x the summed output bytes of its unfusable eqns
  (``UNFUSABLE_PRIMS``) — the fraction of a region's materialized
  working set a greedy schedule realistically keeps live at once.

Nothing here imports the runtime: the module is importable (and the
analysis runnable) with zero devices, zero compiles.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import ir

# prims whose outputs XLA always fuses into consumers — never materialized
VIRTUAL_PRIMS = frozenset({
    "broadcast_in_dim", "iota", "reshape", "squeeze", "expand_dims",
    "rev", "bitcast_convert_type",
})

# ops XLA cannot fuse into elementwise clusters: their outputs genuinely
# materialize, and a region full of them schedules with poor buffer reuse
UNFUSABLE_PRIMS = frozenset({
    "gather", "slice", "dynamic_slice", "dynamic_update_slice", "pad",
    "concatenate", "sort", "copy",
}) | ir.SCATTER_PRIMS

# fraction of a region's unfusable working set charged as simultaneously
# live (scheduler slack; calibrated against XLA memory_analysis on the
# repo's contract-check programs — see tests/test_memory_plan.py)
SCHED_SLACK_FRAC = 0.7

# loop primitives whose carried state XLA double-buffers
LOOP_PRIMS = frozenset({"scan", "while"})


def aval_bytes(aval) -> int:
    """Byte size of one abstract value (0 for tokens/opaque avals)."""
    try:
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is None:
            return 0
        if not shape:
            return int(np.dtype(dtype).itemsize)
        return int(np.prod(shape)) * int(np.dtype(dtype).itemsize)
    except Exception:  # noqa: BLE001 - exotic aval: size unknown
        return 0


def _is_literal(v) -> bool:
    return hasattr(v, "val") and not hasattr(v, "count")


@dataclass
class Contributor:
    """One buffer in the live set at the program's estimated peak."""

    nbytes: int
    shape: tuple
    dtype: str
    kind: str                     # "argument" | "const" | "temp"
    primitive: str = ""           # producing primitive ("" for args/consts)
    location: tuple | None = None  # (file, line) best effort
    path: tuple = ()              # enclosing control-flow path

    def where(self) -> str:
        loc = (f"{self.location[0]}:{self.location[1]}"
               if self.location else "<unknown>")
        via = f" via {'/'.join(self.path)}" if self.path else ""
        return loc + via

    def render(self) -> str:
        src = self.primitive or self.kind
        return (f"{self.nbytes / 2**20:8.2f} MiB  {src:<18} "
                f"{list(self.shape)!s:<20} {self.dtype:<10} {self.where()}")


@dataclass
class TransientWindow:
    """An eqn whose own transient allocation is a large slice of the peak —
    the 2x-residency windows (both sides of a copy/scatter/loop live at
    once) an OOM bisect should look at first."""

    nbytes: int                   # transient bytes charged at this eqn
    primitive: str
    location: tuple | None = None
    path: tuple = ()

    def render(self) -> str:
        loc = (f"{self.location[0]}:{self.location[1]}"
               if self.location else "<unknown>")
        via = f" via {'/'.join(self.path)}" if self.path else ""
        return (f"{self.nbytes / 2**20:8.2f} MiB transient  "
                f"{self.primitive:<18} {loc}{via}")


@dataclass
class MemoryPlan:
    """Per-device peak-memory estimate for one traced program."""

    peak_bytes: int = 0           # estimated per-device peak live set
    arg_bytes: int = 0            # program inputs (per-device where sharded)
    const_bytes: int = 0          # baked consts
    out_bytes: int = 0            # program outputs
    temp_peak_bytes: int = 0      # peak_bytes - resident args/consts
    n_eqns: int = 0               # eqns walked (nested included)
    contributors: list = field(default_factory=list)   # top-k at the peak
    transients: list = field(default_factory=list)     # TransientWindows
    oracle_bytes: int | None = None  # XLA memory_analysis total, if computed

    @property
    def resident_bytes(self) -> int:
        return self.arg_bytes + self.const_bytes

    def headroom_frac(self, bytes_limit: int | None) -> float | None:
        """Remaining fraction of ``bytes_limit`` after the estimated peak
        (negative: the program does not fit). None when no limit known."""
        if not bytes_limit or bytes_limit <= 0:
            return None
        return 1.0 - self.peak_bytes / bytes_limit

    def render(self, top_k: int = 6) -> str:
        lines = [
            f"est peak {self.peak_bytes / 2**20:.2f} MiB per device "
            f"(args {self.arg_bytes / 2**20:.2f} + consts "
            f"{self.const_bytes / 2**20:.2f} + temps "
            f"{self.temp_peak_bytes / 2**20:.2f}; {self.n_eqns} eqns)"
        ]
        if self.oracle_bytes is not None:
            ratio = (self.peak_bytes / self.oracle_bytes
                     if self.oracle_bytes else float("inf"))
            lines.append(
                f"XLA oracle {self.oracle_bytes / 2**20:.2f} MiB "
                f"(estimate/oracle = {ratio:.2f}x)")
        for c in self.contributors[:top_k]:
            lines.append("  " + c.render())
        for t in self.transients[:top_k]:
            lines.append("  " + t.render())
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# the walk
# ---------------------------------------------------------------------------


@dataclass
class _Buf:
    nbytes: int
    kind: str
    primitive: str = ""
    shape: tuple = ()
    dtype: str = ""
    location: tuple | None = None
    path: tuple = ()
    last_use: int = -1

    def contributor(self) -> Contributor:
        return Contributor(nbytes=self.nbytes, shape=self.shape,
                           dtype=str(self.dtype), kind=self.kind,
                           primitive=self.primitive, location=self.location,
                           path=self.path)


@dataclass
class _Step:
    """One flattened program step (inline-call boundaries dissolved)."""

    prim: str
    path: tuple
    region: int                   # owning region index (slack accounting)
    in_roots: list                # canonical buffer ids consumed
    out_roots: list               # canonical buffer ids produced
    out_bytes: int = 0
    extra: int = 0                # opaque body peak + carry/scratch bytes
    location: tuple | None = None
    inner_at_peak: list = field(default_factory=list)


class _Flat:
    """Flattened program: steps + buffer metadata + per-region sums."""

    def __init__(self):
        self.steps: list[_Step] = []
        self.bufs: dict[int, _Buf] = {}
        self.unfusable: dict[int, int] = {}   # region -> byte sum
        self.n_regions = 0
        self._next = 0
        self.n_eqns = 0
        self.transients: list[TransientWindow] = []
        self.const_roots: list[int] = []

    def new_root(self, buf: _Buf) -> int:
        self._next += 1
        self.bufs[self._next] = buf
        return self._next

    def new_region(self) -> int:
        self.n_regions += 1
        return self.n_regions - 1


def _shard_factor(names: dict, mesh) -> int:
    """How many ways one shard_map operand is split: product of the mesh
    axis sizes named by its in_names entry ({dim: (axis, ...)})."""
    factor = 1
    try:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        for axes in names.values():
            for ax in (axes if isinstance(axes, (tuple, list)) else (axes,)):
                factor *= int(sizes.get(ax, 1))
    except Exception:  # noqa: BLE001 - unknown mesh shape: no scaling
        return 1
    return max(factor, 1)


def _arg_shard_factors(jaxpr) -> dict:
    """``{id(invar): factor}`` for top-level program inputs that reach a
    ``shard_map`` eqn — the per-device residency divisor. Follows pjit
    bodies (invar -> body invar identity) so the factor survives jit
    wrapping. Unsharded / unseen args keep factor 1."""
    factors: dict[int, int] = {}

    def visit(jx, outer_ids):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            subs = ir.sub_jaxprs(eqn.params)
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                in_names = eqn.params.get("in_names", ())
                for v, names in zip(eqn.invars, in_names):
                    if _is_literal(v):
                        continue
                    root = outer_ids.get(id(v))
                    if root is not None and isinstance(names, dict):
                        f = _shard_factor(names, mesh)
                        factors[root] = max(factors.get(root, 1), f)
            elif subs and name in ("pjit", "closed_call", "core_call",
                                   "remat2", "custom_jvp_call",
                                   "custom_vjp_call",
                                   "custom_vjp_call_jaxpr"):
                for sub in subs:
                    sub = getattr(sub, "jaxpr", sub)
                    mapped = {}
                    for outer_v, inner_v in zip(eqn.invars, sub.invars):
                        if _is_literal(outer_v):
                            continue
                        root = outer_ids.get(id(outer_v))
                        if root is not None:
                            mapped[id(inner_v)] = root
                    if mapped:
                        visit(sub, mapped)

    top = {id(v): id(v) for v in jaxpr.invars}
    visit(jaxpr, top)
    return factors


def _pallas_scratch_bytes(eqn) -> int:
    """Scratch refs of a pallas_call body: body invars beyond the mapped
    operands and outputs ((in_refs, out_refs, scratch_refs) convention)."""
    subs = ir.sub_jaxprs(eqn.params)
    if not subs:
        return 0
    body = getattr(subs[0], "jaxpr", subs[0])
    n_mapped = len(eqn.invars) + len(eqn.outvars)
    extra = list(body.invars)[n_mapped:]
    return sum(aval_bytes(v.aval) for v in extra)


# call-like primitives XLA inlines: buffers flow through the boundary and
# die at their true last use inside, not at the call's end
INLINE_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "remat2", "remat",
    "custom_jvp_call", "custom_jvp_call_jaxpr",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "shard_map",
})


def _collect(jaxpr, env: dict, path: tuple, region: int, fl: _Flat) -> None:
    """Flatten one (sub)jaxpr into ``fl.steps``, dissolving inline-call
    boundaries. ``env`` maps this jaxpr's var ids to canonical buffer
    roots; inlined bodies get fresh envs (the same body object may be
    inlined at several call sites)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    # constvars are baked buffers (ClosedJaxpr consts): resident throughout
    for var in jaxpr.constvars:
        if id(var) in env:
            continue
        buf = _Buf(nbytes=aval_bytes(var.aval), kind="const",
                   shape=tuple(getattr(var.aval, "shape", ())),
                   dtype=getattr(var.aval, "dtype", ""), path=path)
        root = fl.new_root(buf)
        env[id(var)] = root
        fl.const_roots.append(root)

    for eqn in jaxpr.eqns:
        fl.n_eqns += 1
        prim = eqn.primitive.name
        subs = ir.sub_jaxprs(eqn.params, unwrap=False)

        if prim in INLINE_PRIMS and len(subs) == 1:
            body = getattr(subs[0], "jaxpr", subs[0])
            if len(body.invars) == len(eqn.invars):
                inner_env = {}
                for ov, iv in zip(eqn.invars, body.invars):
                    if _is_literal(ov):
                        inner_env[id(iv)] = fl.new_root(_Buf(
                            nbytes=0, kind="temp", primitive="literal"))
                    else:
                        root = env.get(id(ov))
                        if root is None:
                            root = fl.new_root(_Buf(
                                nbytes=aval_bytes(ov.aval), kind="temp"))
                            env[id(ov)] = root
                        inner_env[id(iv)] = root
                _collect(subs[0], inner_env, path + (prim,),
                         fl.new_region(), fl)
                # alias outer outvars to the body's producing buffers —
                # for shard_map the body avals are PER-SHARD, so sharded
                # outputs are per-device sized automatically
                for ov, bv in zip(eqn.outvars, body.outvars):
                    if _is_literal(bv):
                        env[id(ov)] = fl.new_root(_Buf(
                            nbytes=0, kind="temp", primitive="literal"))
                    else:
                        root = inner_env.get(id(bv))
                        if root is None:
                            root = fl.new_root(_Buf(
                                nbytes=aval_bytes(bv.aval), kind="temp"))
                            inner_env[id(bv)] = root
                        env[id(ov)] = root
                continue

        # opaque eqn: loops / cond / pallas_call / plain primitives.
        # Bodies are analyzed standalone (operands held by THIS step's
        # in_roots for the call duration — correct for loops, which need
        # their operands every iteration).
        extra = 0
        inner_at_peak: list = []
        for s in subs:
            r = _sub_peak(s, path + (prim,), fl)
            if r[0] > extra:
                extra, inner_at_peak = r
        if prim in LOOP_PRIMS:
            # double-buffered carry: XLA keeps the incoming and outgoing
            # loop state simultaneously when it cannot prove aliasing
            num_carry = eqn.params.get("num_carry")
            if num_carry is None:       # while: whole tuple is the carry
                carry_avals = [v.aval for v in eqn.outvars]
            else:
                carry_avals = [v.aval for v in eqn.outvars[:num_carry]]
            extra += sum(aval_bytes(a) for a in carry_avals)
        elif prim == "pallas_call":
            extra += _pallas_scratch_bytes(eqn)

        virtual = prim in VIRTUAL_PRIMS and not subs
        loc = ir.source_location(eqn)
        in_roots = [env[id(v)] for v in eqn.invars
                    if not _is_literal(v) and id(v) in env]
        out_roots = []
        out_b = 0
        for v in eqn.outvars:
            nb = 0 if virtual else aval_bytes(v.aval)
            buf = _Buf(nbytes=nb, kind="temp", primitive=prim,
                       shape=tuple(getattr(v.aval, "shape", ())),
                       dtype=getattr(v.aval, "dtype", ""),
                       location=loc, path=path)
            root = fl.new_root(buf)
            env[id(v)] = root
            out_roots.append(root)
            out_b += nb
        if prim in UNFUSABLE_PRIMS and not subs:
            fl.unfusable[region] = fl.unfusable.get(region, 0) + out_b
        fl.steps.append(_Step(
            prim=prim, path=path, region=region, in_roots=in_roots,
            out_roots=out_roots, out_bytes=out_b, extra=extra,
            location=loc, inner_at_peak=inner_at_peak))


def _sub_peak(sub, path, fl: _Flat):
    """Standalone peak of an opaque body (loop/cond/pallas): its invars
    are charged by the caller, so they enter at zero bytes here."""
    body = getattr(sub, "jaxpr", sub)
    sub_fl = _Flat()
    env = {id(v): sub_fl.new_root(_Buf(nbytes=0, kind="temp"))
           for v in body.invars}
    _collect(sub, env, path, sub_fl.new_region(), sub_fl)
    out_roots = [env[id(v)] for v in body.outvars
                 if not _is_literal(v) and id(v) in env]
    peak, at_peak = _simulate(sub_fl, 0, set(), out_roots)
    fl.n_eqns += sub_fl.n_eqns
    fl.transients.extend(sub_fl.transients)
    return peak, at_peak


def _simulate(fl: _Flat, resident_base: int, donated_roots: set,
              final_roots: list):
    """Liveness simulation over the flattened step list. Returns
    ``(peak_bytes, live buffers at the peak)`` and appends large transient
    windows to ``fl.transients``."""
    n = len(fl.steps)
    last: dict[int, int] = {}
    for i, step in enumerate(fl.steps):
        for r in step.in_roots:
            last[r] = i
    for r in final_roots:
        last[r] = n
    for r in fl.const_roots:
        last[r] = n                # baked consts stay resident

    live: dict[int, int] = {}      # root -> bytes (temps + donated args)
    cur = resident_base
    peak = resident_base
    at_peak: list[_Buf] = []
    region_entry: dict[int, int] = {}        # region -> cur at entry
    region_entry_step: dict[int, int] = {}   # region -> first step index

    for i, step in enumerate(fl.steps):
        if step.region not in region_entry:
            region_entry[step.region] = cur
            region_entry_step[step.region] = i
        transient = cur + step.out_bytes + step.extra
        if transient > peak:
            peak = transient
            at_peak = ([fl.bufs[r] for r in live]
                       + [fl.bufs[r] for r in step.out_roots]
                       + list(step.inner_at_peak))
        if step.extra > 0:
            fl.transients.append(TransientWindow(
                nbytes=step.out_bytes + step.extra, primitive=step.prim,
                location=step.location, path=step.path))
        cur += step.out_bytes
        for r in step.out_roots:
            nb = fl.bufs[r].nbytes
            if last.get(r, -1) <= i:        # unused output: freed at once
                cur -= nb
            elif nb:
                live[r] = nb
        for r in step.in_roots:
            if last.get(r) == i:
                if r in live:
                    cur -= live.pop(r)
                elif r in donated_roots:
                    cur -= fl.bufs[r].nbytes
                    donated_roots.discard(r)
        cur = max(cur, 0)

    # list-scheduler slack: whatever the flattened order says, a region
    # holds a calibrated fraction of its unfusable working set at once on
    # top of whatever was live when it started
    slack_region = None
    for region, unf in fl.unfusable.items():
        entry = region_entry.get(region, resident_base)
        slack = entry + int(SCHED_SLACK_FRAC * unf)
        if slack > peak:
            peak = slack
            slack_region = region
    if slack_region is not None:
        # the slack term set the final peak: the liveness-walk snapshot
        # describes a DIFFERENT (lower) maximum, so re-derive the live
        # set at the winning region's entry and attribute the slack
        # itself — contributor sites (and the memory_budget ERROR anchor
        # / suppression line) must point at the bytes that actually own
        # the peak
        entry_i = region_entry_step[slack_region]
        live2: dict[int, int] = {}
        for j, step in enumerate(fl.steps[:entry_i]):
            for r in step.out_roots:
                nb = fl.bufs[r].nbytes
                if nb and last.get(r, -1) > j:
                    live2[r] = nb
            for r in step.in_roots:
                if last.get(r) == j:
                    live2.pop(r, None)
        first = fl.steps[entry_i]
        slack_buf = _Buf(
            nbytes=peak - region_entry[slack_region], kind="temp",
            primitive="sched-slack",
            location=first.location, path=first.path)
        at_peak = [fl.bufs[r] for r in live2] + [slack_buf]
    return peak, at_peak


def analyze_memory(closed_jaxpr, donated=(), top_k: int = 8) -> MemoryPlan:
    """Estimate the per-device peak live bytes of one traced program.

    Parameters
    ----------
    closed_jaxpr : ClosedJaxpr (``jax.make_jaxpr`` output) or Jaxpr.
    donated : iterable of invar indices (or a bool mask) marking donated
        program inputs — their buffers die at last use instead of staying
        resident (``jax.jit(..., donate_argnums=...)`` semantics; tracing
        does not record donation, so the caller states it).
    top_k : how many live-set contributors / transient windows to keep.
    """
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    invars = list(jaxpr.invars)
    donated = list(donated) if donated is not None else []
    if donated and isinstance(donated[0], (bool, np.bool_)):
        donated_idx = {i for i, d in enumerate(donated) if d}
    else:
        donated_idx = {int(i) for i in donated}

    shard = _arg_shard_factors(jaxpr)
    fl = _Flat()
    env: dict[int, int] = {}
    arg_bytes = 0
    donated_bytes = 0
    donated_roots: set[int] = set()
    for i, v in enumerate(invars):
        nb = aval_bytes(v.aval) // shard.get(id(v), 1)
        root = fl.new_root(_Buf(
            nbytes=nb, kind="argument",
            shape=tuple(getattr(v.aval, "shape", ())),
            dtype=getattr(v.aval, "dtype", "")))
        env[id(v)] = root
        if i in donated_idx:
            donated_bytes += nb
            donated_roots.add(root)
        else:
            arg_bytes += nb

    _collect(closed_jaxpr, env, (), fl.new_region(), fl)
    const_bytes = sum(fl.bufs[r].nbytes for r in fl.const_roots)
    out_bytes = sum(aval_bytes(v.aval) for v in jaxpr.outvars
                    if not _is_literal(v))
    final_roots = [env[id(v)] for v in jaxpr.outvars
                   if not _is_literal(v) and id(v) in env]

    # donated inputs start resident and die at their last use in the walk
    resident = arg_bytes + const_bytes + donated_bytes
    peak, peak_bufs = _simulate(fl, resident, donated_roots, final_roots)

    contributors = [b.contributor() for b in peak_bufs if b.nbytes > 0]
    if arg_bytes:
        contributors.append(Contributor(
            nbytes=arg_bytes, shape=(len(invars),), dtype="",
            kind="argument", primitive="", location=None, path=()))
    if const_bytes:
        contributors.append(Contributor(
            nbytes=const_bytes, shape=(len(fl.const_roots),), dtype="",
            kind="const", primitive="", location=None, path=()))
    contributors.sort(key=lambda c: -c.nbytes)

    transients = sorted(fl.transients, key=lambda t: -t.nbytes)
    # keep only windows that matter: >= 10% of the peak
    floor = max(peak // 10, 1)
    transients = [t for t in transients if t.nbytes >= floor][:top_k]

    return MemoryPlan(
        peak_bytes=int(peak),
        arg_bytes=int(arg_bytes),
        const_bytes=int(const_bytes),
        out_bytes=int(out_bytes),
        temp_peak_bytes=int(max(peak - resident, 0)),
        n_eqns=fl.n_eqns,
        contributors=contributors[:top_k],
        transients=transients,
    )


# ---------------------------------------------------------------------------
# XLA oracle (optional: needs a compile, still chip-free on CPU)
# ---------------------------------------------------------------------------


def _traced_with_x64(closed_jaxpr) -> bool:
    """Whether the program was traced under enable_x64: any 64-bit
    float/int aval (a no-x64 trace cannot contain one; an x64 trace
    carries at least its weak python-scalar literals as f64). The oracle
    replay must match the TRACE's x64 regime — a weak literal lowers to
    the wrong width otherwise."""
    def wide(aval):
        dt = getattr(aval, "dtype", None)
        return (dt is not None and np.dtype(dt).kind in "fiu"
                and np.dtype(dt).itemsize == 8)

    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if wide(v.aval):
            return True
    for eqn in ir.iter_eqns(closed_jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            if wide(getattr(v, "aval", None)):
                return True
    return False


def oracle_peak_bytes(closed_jaxpr) -> int | None:
    """Compile the traced program and return XLA's own peak-memory total
    (argument + output + temp + alias bytes from
    ``lower().compile().memory_analysis()``), or None where the backend
    does not report it. This is the estimator's calibration oracle — a
    REAL compile, so orders of magnitude slower than :func:`analyze_memory`
    (tests and ``tools/memory_audit.py --oracle`` only)."""
    try:
        import jax
        from jax.core import jaxpr_as_fun
        from jax.experimental import enable_x64

        fn = jaxpr_as_fun(closed_jaxpr)
        shapes = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                  for v in closed_jaxpr.jaxpr.invars]
        # replay in the same x64 regime the program was traced under, so
        # every literal and weak scalar lowers at its traced width
        with enable_x64(_traced_with_x64(closed_jaxpr)):
            ma = jax.jit(fn).lower(*shapes).compile().memory_analysis()
        total = (int(ma.argument_size_in_bytes)
                 + int(ma.output_size_in_bytes)
                 + int(ma.temp_size_in_bytes)
                 + int(ma.alias_size_in_bytes))
        return total if total > 0 else None
    except Exception:  # noqa: BLE001 - oracle is best-effort by contract
        return None


__all__ = [
    "MemoryPlan", "Contributor", "TransientWindow", "analyze_memory",
    "oracle_peak_bytes", "aval_bytes",
]
