"""Repo-specific AST lint: rules jaxprs cannot see.

A jaxpr only shows what survived tracing — by the time ``float(jnp_x)``
has forced a device sync, the jaxpr looks innocent. These rules run on
the *source*:

- **DML001 host-pull-in-device-code**: ``.item()``, ``float(...)`` /
  ``int(...)`` / ``bool(...)`` directly wrapping a ``jnp.*``/``jax.*``
  call, or ``np.asarray(...)`` of a non-numpy expression, inside
  device-path functions of the hot modules (``models/``, ``ops/``,
  ``parallel/``). Each forces a blocking device->host transfer per call —
  inside jitted code, a trace-time concretization error at best and a
  silent sync at worst.
- **DML002 wallclock-in-jit**: ``time.time()`` / ``time.perf_counter()``
  inside a function that gets jitted — the value is baked at trace time,
  so the "timestamp" is a constant from the first call.
- **DML003 span-in-jit**: host span/annotation helpers
  (``tracer.span(...)``, ``start_request``, ``emit``-style span
  creation from :mod:`distmlip_tpu.obs`, ``telemetry.annotate`` /
  ``jax.profiler.TraceAnnotation``) inside a jitted/device function.
  Host tracing in a traced region runs once at TRACE time — the span
  measures compilation, not execution — and anything that makes the
  traced function observe host state is a silent recompile hazard.
  ``jax.named_scope`` / ``telemetry.scope`` are exempt: they only attach
  metadata to the HLO.
- **F401 unused-import** (ruff-compatible code): module-level imports
  never referenced (dunder-all re-exports and ``import x as x``
  re-export idiom respected). The one pyflakes rule worth enforcing
  without pyflakes in the image.

Device-path heuristic (documented contract, not magic): a function is
device-path if it is decorated with ``jax.jit``/``jit``/
``partial(jax.jit, ...)``, is passed to ``jax.jit(...)`` by name in the
same module, has a parameter named ``lg`` (the LocalGraph calling
convention every model energy fn uses), or is nested inside such a
function.

Suppression: ``# contract: allow(lint)`` or ``# contract: allow(DML001)``
on the flagged line (or the line above), same syntax as the jaxpr passes.
Ruff handles the generic pycodestyle/pyflakes/isort surface via
``[tool.ruff]`` in pyproject.toml; this lint stays repo-specific so both
run from one ``tools/contract_check.py --lint`` entry point.
"""

from __future__ import annotations

import ast
import os
import re

from .findings import Finding, Severity, apply_suppressions

HOT_MODULE_DIRS = ("models", "ops", "parallel")

_TIME_FUNCS = {"time", "perf_counter", "monotonic", "process_time"}

# span-creating helper names (distmlip_tpu.obs.Tracer surface +
# telemetry.annotate / jax.profiler.TraceAnnotation). Deliberately NOT
# "scope"/"named_scope": those are trace-time metadata only and belong
# inside jitted code.
_SPAN_FUNCS = {"span", "start_request", "adopt_request", "finish_request",
               "begin", "annotate", "TraceAnnotation", "start_trace"}


def _dotted(node) -> str:
    """Best-effort dotted name of an expression ("jnp.sum", "time.time")."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _DeviceFns(ast.NodeVisitor):
    """Collect device-path function defs per the documented heuristic."""

    def __init__(self):
        self.jitted_names: set[str] = set()   # names passed to jax.jit(...)
        self.device_fns: list = []            # FunctionDef nodes

    def collect(self, tree):
        # first sweep: names jitted by call — jax.jit(f), shard_map(f, ...)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                callee = _dotted(node.func)
                if callee.endswith("jit") or callee.endswith("shard_map"):
                    for arg in node.args[:1]:
                        if isinstance(arg, ast.Name):
                            self.jitted_names.add(arg.id)
        self.visit(tree)
        return self.device_fns

    def _is_device_fn(self, node) -> bool:
        for dec in node.decorator_list:
            d = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
            if d.endswith("jit"):
                return True
            if isinstance(dec, ast.Call) and _dotted(dec.func) == "partial":
                for a in dec.args:
                    if _dotted(a).endswith("jit"):
                        return True
        if node.name in self.jitted_names:
            return True
        args = node.args
        names = [a.arg for a in (*args.posonlyargs, *args.args,
                                 *args.kwonlyargs)]
        return "lg" in names

    def visit_FunctionDef(self, node):
        if self._is_device_fn(node):
            self.device_fns.append(node)
            # nested defs inherit device-path status; don't double-visit
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _numpy_rooted(node) -> bool:
    if isinstance(node, ast.Call):
        root = _dotted(node.func).split(".")[0]
        return root in ("np", "numpy")
    return isinstance(node, ast.Constant)


def _lint_device_fn(fn, path: str, in_hot_module: bool) -> list:
    findings = []

    def emit(node, rule, msg):
        findings.append(Finding(
            pass_name="lint", severity=Severity.ERROR, message=msg,
            location=(path, node.lineno), rule=rule))

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        # DML002 applies to every device fn, hot module or not
        if (callee.split(".")[0] == "time"
                and callee.split(".")[-1] in _TIME_FUNCS):
            emit(node, "DML002",
                 f"{callee}() inside a jitted function is baked at trace "
                 "time — hoist it to the host caller")
            continue
        # DML003 applies to every device fn too: span creation is a
        # HOST action — in a traced region it fires once at trace time
        # (measuring compilation, not steps) and is a recompile hazard
        leaf = callee.split(".")[-1] if callee else \
            (node.func.attr if isinstance(node.func, ast.Attribute)
             else "")
        if leaf in _SPAN_FUNCS:
            emit(node, "DML003",
                 f"{callee or leaf}() creates a host span/annotation "
                 "inside a jitted/device function — host tracing in a "
                 "traced region runs at trace time only and risks "
                 "silent recompiles; hoist it to the host caller "
                 "(named_scope/scope is the in-jit alternative)")
            continue
        if not in_hot_module:
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            emit(node, "DML001",
                 ".item() in device-path code forces a blocking "
                 "device->host transfer")
        elif (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and node.args and isinstance(node.args[0], ast.Call)
                and _dotted(node.args[0].func).split(".")[0]
                in ("jnp", "jax", "lax")):
            emit(node, "DML001",
                 f"{node.func.id}(jnp...) concretizes a device value in "
                 "device-path code")
        elif (callee in ("np.asarray", "numpy.asarray", "np.array",
                         "numpy.array")
                and node.args and not _numpy_rooted(node.args[0])):
            emit(node, "DML001",
                 f"{callee}(...) on a (potentially) device value in "
                 "device-path code pulls it to the host")
    return findings


_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?", re.I)


def _noqa_lines(source: str) -> dict[int, frozenset]:
    """{lineno: frozenset(codes) | frozenset() for bare noqa} — standard
    pyflakes/ruff suppression, honored so one file can satisfy both this
    lint and ruff with a single comment."""
    out: dict[int, frozenset] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _NOQA_RE.search(line)
        if m:
            codes = m.group("codes")
            out[lineno] = frozenset(
                c.strip() for c in codes.split(",")) if codes else frozenset()
    return out


def _lint_unused_imports(tree, path: str, noqa: dict) -> list:
    def suppressed(node) -> bool:
        codes = noqa.get(node.lineno)
        return codes is not None and (not codes or "F401" in codes)

    imported: dict[str, tuple] = {}  # bound name -> (node, display)
    for node in tree.body:
        if isinstance(node, ast.Import):
            if suppressed(node):
                continue
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if alias.asname == alias.name:
                    continue  # "import x as x" re-export idiom
                imported[bound] = (node, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__" or suppressed(node):
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                if alias.asname == alias.name:
                    continue
                imported[bound] = (node, alias.name)
    if not imported:
        return []
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and not isinstance(node.ctx, ast.Store):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    # names re-exported via __all__ strings count as used
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)):
            for elt in ast.walk(node.value):
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    used.add(elt.value)
    findings = []
    for bound, (node, display) in imported.items():
        if bound not in used:
            findings.append(Finding(
                pass_name="lint", severity=Severity.ERROR,
                message=f"{display!r} imported but unused",
                location=(path, node.lineno), rule="F401"))
    return findings


def lint_file(path: str, package_root: str | None = None) -> list:
    """Lint one Python file; returns (possibly suppressed) findings."""
    try:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        return [Finding(pass_name="lint", severity=Severity.ERROR,
                        message=f"unparseable: {e}", location=(path, 1),
                        rule="E999")]
    rel = os.path.relpath(path, package_root) if package_root else path
    parts = rel.replace(os.sep, "/").split("/")
    in_hot = any(p in HOT_MODULE_DIRS for p in parts[:-1])
    findings = []
    for fn in _DeviceFns().collect(tree):
        findings.extend(_lint_device_fn(fn, path, in_hot))
    findings.extend(_lint_unused_imports(tree, path, _noqa_lines(source)))
    return apply_suppressions(findings)


def lint_paths(paths, package_root: str | None = None) -> list:
    """Lint every .py file under ``paths`` (files or directories)."""
    findings = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        findings.extend(lint_file(
                            os.path.join(dirpath, f), package_root))
        elif p.endswith(".py"):
            findings.extend(lint_file(p, package_root))
    return findings
