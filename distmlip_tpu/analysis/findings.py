"""Typed findings emitted by contract passes and the AST lint.

A :class:`Finding` pins a contract violation to a *place*: the program it
was traced from, the ``jax.named_scope`` stack inside the jaxpr, the stack
of enclosing control-flow primitives (``pjit`` / ``while`` / ``scan`` /
``cond``), and — best effort — the source file:line the offending eqn was
traced from. Severity drives exit codes: ``tools/contract_check.py`` exits
3 on any unsuppressed ERROR.

Suppression contract (audited exceptions): a source line carrying

    # contract: allow(<pass-or-rule>[, <pass-or-rule>...])

(on the flagged line or the line directly above it) downgrades findings of
that pass/rule at that location to ``suppressed=True`` — they still print,
but no longer fail the check. This is deliberately file:line-scoped so an
exception audited for one call site never blankets the repo.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, replace

_ALLOW_RE = re.compile(r"#\s*contract:\s*allow\(([^)]*)\)")


class Severity(enum.IntEnum):
    """Finding severity; only ERROR fails a contract check."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # table rendering
        return self.name.lower()


@dataclass
class Finding:
    """One contract violation (or informational note) with its location."""

    pass_name: str                      # registered pass / lint rule family
    severity: Severity
    message: str
    program: str = ""                   # traced-program name ("" for lint)
    scope: str = ""                     # jax.named_scope stack at the eqn
    path: tuple = ()                    # enclosing control-flow primitives
    location: tuple | None = None       # (file, line) best effort
    rule: str = ""                      # sub-rule id (lint: DML001, F401...)
    suppressed: bool = False

    def where(self) -> str:
        parts = []
        if self.location:
            parts.append(f"{self.location[0]}:{self.location[1]}")
        if self.path:
            parts.append("/".join(self.path))
        if self.scope:
            parts.append(self.scope)
        return " ".join(parts) or "<program>"

    def render(self) -> str:
        sup = " [suppressed]" if self.suppressed else ""
        rule = f"/{self.rule}" if self.rule else ""
        return (f"{str(self.severity).upper():<7} {self.pass_name}{rule}"
                f"{sup}: {self.message}  @ {self.where()}")


def error_count(findings) -> int:
    return sum(1 for f in findings
               if f.severity == Severity.ERROR and not f.suppressed)


def exit_code(findings) -> int:
    """The contract-check CLI exit-code convention: 3 on any unsuppressed
    ERROR finding, else 0 (shared by tools/contract_check.py and the
    seeded-violation tests)."""
    return 3 if error_count(findings) else 0


def warning_count(findings) -> int:
    return sum(1 for f in findings
               if f.severity == Severity.WARNING and not f.suppressed)


def format_findings(findings, header: str | None = None) -> str:
    lines = [] if header is None else [header]
    for f in sorted(findings, key=lambda f: (-int(f.severity), f.pass_name)):
        lines.append("  " + f.render())
    if not findings:
        lines.append("  (clean)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# suppression: "# contract: allow(pass)" comments at the flagged line
# ---------------------------------------------------------------------------

_allow_cache: dict[str, dict[int, frozenset]] = {}


def _allows_in_file(path: str) -> dict[int, frozenset]:
    """{line_number: frozenset(allowed pass/rule names)} for one source file.

    Cached per path — the checker reads each flagged file once.
    """
    cached = _allow_cache.get(path)
    if cached is not None:
        return cached
    allows: dict[int, frozenset] = {}
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            for lineno, line in enumerate(fh, start=1):
                m = _ALLOW_RE.search(line)
                if m:
                    names = frozenset(
                        n.strip() for n in m.group(1).split(",") if n.strip())
                    allows[lineno] = names
    except OSError:
        pass
    _allow_cache[path] = allows
    return allows


def clear_suppression_cache() -> None:
    _allow_cache.clear()


def apply_suppressions(findings) -> list:
    """Mark findings whose source line (or the line above) carries a
    matching ``# contract: allow(...)`` comment. Returns a new list;
    findings without a source location are never suppressible."""
    out = []
    for f in findings:
        if f.location:
            allows = _allows_in_file(str(f.location[0]))
            lineno = int(f.location[1])
            names = allows.get(lineno, frozenset()) | allows.get(
                lineno - 1, frozenset())
            if f.pass_name in names or (f.rule and f.rule in names):
                f = replace(f, suppressed=True)
        out.append(f)
    return out


__all__ = [
    "Severity", "Finding", "error_count", "warning_count", "exit_code",
    "format_findings", "apply_suppressions", "clear_suppression_cache",
]
