"""Static program-contract analysis for the distmlip_tpu runtime.

The performance story rests on invariants that are *stated* everywhere and
were only spot-checked: the dst-sorted padding contract
(``indices_are_sorted=True`` on every hot-path segment sum), zero
batch-axis collectives on the 2-D mesh, the "N MD steps = ONE device
program" guarantee, f32 on the device path, the logarithmic compile
bound. This package proves them statically — on CPU, in CI, with no chip:

- :mod:`~distmlip_tpu.analysis.ir` — one jaxpr walker (recursing into
  pjit/scan/while/cond/remat/shard_map sub-jaxprs, tracking named_scope
  stacks and control-flow paths) that ``parallel/audit.py`` is now a thin
  compatibility shim over;
- :mod:`~distmlip_tpu.analysis.memory` — the static HBM planner:
  buffer-liveness peak-memory analysis (:func:`analyze_memory` ->
  :class:`MemoryPlan`) driving the ``memory_budget`` pass, memory-aware
  autobatching and the ``est_peak_bytes`` telemetry;
- :mod:`~distmlip_tpu.analysis.passes` — the registered
  :class:`ContractPass`es (collective_placement, host_sync,
  dtype_discipline, scatter_hints, recompile_hazard, dead_compute,
  memory_budget), each returning typed :class:`Finding`s with severity
  and scope location;
- :mod:`~distmlip_tpu.analysis.lint` — AST rules jaxprs can't see
  (host pulls in device-path code, wallclock in jit, unused imports);
- ``tools/contract_check.py`` — the CLI that traces the real programs
  across placements and gates CI (exit 3 on any unsuppressed ERROR).

Audited exceptions: ``# contract: allow(<pass>)`` on the flagged source
line (see :mod:`~distmlip_tpu.analysis.findings`).
"""

from .findings import (Finding, Severity, apply_suppressions,
                       clear_suppression_cache, error_count, exit_code,
                       format_findings, warning_count)
from .passes import (REGISTRY, ContractPass, Program, get_passes, register,
                     run_passes)
from . import ir
from .lint import lint_file, lint_paths
from .memory import MemoryPlan, analyze_memory, oracle_peak_bytes

__all__ = [
    "Finding", "Severity", "error_count", "warning_count", "exit_code",
    "format_findings", "apply_suppressions", "clear_suppression_cache",
    "ContractPass", "Program", "REGISTRY", "register", "get_passes",
    "run_passes", "ir", "lint_file", "lint_paths",
    "MemoryPlan", "analyze_memory", "oracle_peak_bytes",
]
