"""Single jaxpr walker shared by every contract pass.

One recursion (into ``pjit`` / ``scan`` / ``while`` / ``cond`` / ``remat``
/ ``shard_map`` / custom-derivative sub-jaxprs) serving collective
counting, host-sync detection, dtype walks, scatter-hint checks, const
inspection and liveness — so each new invariant is a pass over
:func:`iter_sites`, not another hand-rolled tree walk.
``distmlip_tpu.parallel.audit`` is a thin compatibility shim over this
module.

Every yielded :class:`EqnSite` carries the eqn itself plus *where it is*:
the stack of enclosing control-flow primitive names (``("pjit", "while")``
— the host-sync pass keys its "inside the MD while_loop" escalation off
this), the ``jax.named_scope`` name stack (source metadata, best effort),
and the owning (sub)jaxpr so local dataflow (liveness) stays computable.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Any, Iterator

# collective primitives the graph runtime can emit (names as they appear
# in jaxprs across the jax versions this repo supports)
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "psum", "psum2", "all_gather", "all_to_all",
    "reduce_scatter", "pmax", "pmin", "pgather", "collective_permute",
})

# the ring-shift permute primitive's names across jax versions — count both
# wherever a gate compares ppermute counts, or the parity check passes
# vacuously (0 == 0) on a build emitting the other name
PPERMUTE_PRIMS = ("ppermute", "collective_permute")


def ppermute_count(counts) -> int:
    """Ring-permute occurrences in a ``{primitive: count}`` mapping,
    whatever the primitive is called on this jax build."""
    return sum(int(counts.get(p, 0)) for p in PPERMUTE_PRIMS)

# host-synchronizing primitives: anything that stalls the device on the
# host mid-program. Substring matching on "callback" keeps this robust
# across jax versions' primitive renames (pure_callback/io_callback/
# debug_callback all match).
HOST_SYNC_MARKERS = ("callback", "infeed", "outfeed")
HOST_SYNC_EXACT = frozenset({"host_local_array_to_global_array",
                             "debug_print"})

# scatter variants that carry the ``indices_are_sorted`` hint
SCATTER_PRIMS = frozenset({
    "scatter-add", "scatter", "scatter-mul", "scatter-min", "scatter-max",
    "scatter-apply",
})


@dataclass
class EqnSite:
    """One eqn plus its position in the traced program."""

    eqn: Any
    path: tuple          # enclosing control-flow primitive names, outer first
    scope: str           # jax.named_scope stack ("" when metadata is absent)
    jaxpr: Any           # the (sub)jaxpr owning this eqn

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name


def sub_jaxprs(params, unwrap: bool = True) -> list:
    """Collect Jaxpr values from an eqn's params — fallback for jax
    versions without ``jax.core.jaxprs_in_params``. ``unwrap=True`` (the
    walker's view) reduces ClosedJaxpr to its Jaxpr; ``unwrap=False``
    preserves ClosedJaxpr wrappers so their ``consts`` stay reachable
    (:func:`program_consts`)."""
    out = []

    def visit(v):
        if hasattr(v, "eqns"):           # Jaxpr
            out.append(v)
        elif hasattr(v, "jaxpr"):        # ClosedJaxpr
            out.append(v.jaxpr if unwrap else v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    for v in params.values():
        visit(v)
    return out


def scope_of(eqn) -> str:
    """named_scope stack string (best effort: source metadata may be absent
    on some jax builds)."""
    try:
        return str(eqn.source_info.name_stack)
    except Exception:  # noqa: BLE001 - metadata is optional
        return ""


def source_location(eqn):
    """(file, line) the eqn was traced from, or None. Uses jax's private
    source_info_util (stable across the 0.4.x builds this repo supports);
    any API drift degrades to no-location, never to a crash."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None
        return (frame.file_name, int(frame.start_line))
    except Exception:  # noqa: BLE001 - introspection is best effort
        return None


def iter_sites(closed_jaxpr) -> Iterator[EqnSite]:
    """Yield an :class:`EqnSite` for every eqn in the program, recursing
    into all nested sub-jaxprs. Loop/branch bodies are visited ONCE per
    trace — multiply by trip count yourself for dynamic totals."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    yield from _walk(jaxpr, ())


def _walk(jaxpr, path) -> Iterator[EqnSite]:
    for eqn in jaxpr.eqns:
        yield EqnSite(eqn=eqn, path=path, scope=scope_of(eqn), jaxpr=jaxpr)
        subs = sub_jaxprs(eqn.params)
        if subs:
            sub_path = path + (eqn.primitive.name,)
            for sub in subs:
                yield from _walk(sub, sub_path)


def iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and all nested sub-jaxprs (legacy surface of
    parallel/audit.py; prefer :func:`iter_sites` in new code)."""
    for site in _walk(getattr(jaxpr, "jaxpr", jaxpr), ()):
        yield site.eqn


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def eqn_axis_names(eqn) -> tuple:
    """Mesh axis names a collective eqn operates over, from its params.

    Collective primitives carry the axis under different param names across
    primitives and jax versions (``axis_name`` for ppermute/all_gather,
    ``axes`` for psum/pmax, sometimes ``axis_index_groups`` alongside);
    values may be a single name or a tuple. Returns ``("<unknown>",)`` when
    no axis metadata is present.
    """
    for key in ("axis_name", "axes", "named_axes"):
        val = eqn.params.get(key)
        if val is None:
            continue
        if isinstance(val, (tuple, list, frozenset, set)):
            named = tuple(v for v in val if isinstance(v, (str, int)))
            if named or not val:
                # an EMPTY axes tuple is a no-op psum (identity) the
                # partial evaluator sometimes leaves behind — attribute it
                # to no axis. A NON-empty tuple of unparseable axis objects
                # must NOT vanish: fall through to "<unknown>" so silence
                # gates fail loudly instead of vacuously.
                return named
        elif isinstance(val, (str, int)):
            return (val,)
        break
    return ("<unknown>",)


def count_collectives(closed_jaxpr) -> Counter:
    """Counter of collective primitive name -> occurrence count over the
    whole program (nested jaxprs included)."""
    counts: Counter = Counter()
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            counts[name] += 1
    return counts


def collectives_by_axis(closed_jaxpr) -> dict:
    """``{axis_name: Counter(primitive -> count)}`` over the whole program.
    A collective naming several axes counts against each."""
    by_axis: dict[str, Counter] = {}
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        for ax in eqn_axis_names(eqn):
            by_axis.setdefault(str(ax), Counter())[name] += 1
    return by_axis


def count_primitives(closed_jaxpr, names) -> Counter:
    """Occurrences of specific primitive names (nested jaxprs included)."""
    names = frozenset(names)
    counts: Counter = Counter()
    for eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name in names:
            counts[eqn.primitive.name] += 1
    return counts


def is_host_sync(primitive_name: str) -> bool:
    return (primitive_name in HOST_SYNC_EXACT
            or any(m in primitive_name for m in HOST_SYNC_MARKERS))


# ---------------------------------------------------------------------------
# consts
# ---------------------------------------------------------------------------

def program_consts(closed_jaxpr) -> list:
    """[(value, aval)] of every constant baked into the traced program.

    Top-level ClosedJaxpr consts are the interesting ones (make_jaxpr
    hoists closure values there); nested ClosedJaxprs found in params are
    included too when they carry consts of their own.
    """
    out = []
    seen: set[int] = set()

    def collect(cj):
        if id(cj) in seen:
            return
        seen.add(id(cj))
        consts = getattr(cj, "consts", None)
        jaxpr = getattr(cj, "jaxpr", None)
        if consts and jaxpr is not None:
            for var, val in zip(jaxpr.constvars, consts):
                out.append((val, var.aval))
        if jaxpr is None:
            jaxpr = cj
        for eqn in jaxpr.eqns:
            for sub in sub_jaxprs(eqn.params, unwrap=False):
                collect(sub)

    collect(closed_jaxpr)
    return out


# ---------------------------------------------------------------------------
# liveness (per-jaxpr dead-compute detection)
# ---------------------------------------------------------------------------

def dead_eqns(jaxpr) -> list:
    """Eqns of ONE (sub)jaxpr with no dataflow path to its outputs.

    Local to the given jaxpr (callers recurse via :func:`iter_sites` /
    ``sub_jaxprs``): an eqn is live iff any of its outvars feeds the
    jaxpr's outvars transitively, or it has side effects. DropVar outputs
    (jax's own `_:` binders) count as unused.
    """
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    live: set[int] = set()
    for v in jaxpr.outvars:
        if not _is_literal(v):
            live.add(id(v))
    dead = []
    for eqn in reversed(jaxpr.eqns):
        out_live = any(id(v) in live for v in eqn.outvars)
        if out_live or _has_effects(eqn):
            for v in eqn.invars:
                if not _is_literal(v):
                    live.add(id(v))
        else:
            dead.append(eqn)
    dead.reverse()
    return dead


def _is_literal(v) -> bool:
    return hasattr(v, "val") and not hasattr(v, "count")


def _has_effects(eqn) -> bool:
    """True for eqns with REAL side effects (callbacks, io). NamedAxisEffect
    is axis bookkeeping shard_map attaches to every collective — a psum
    with an unused result is still dead compute, so it does not count."""
    try:
        return any("NamedAxis" not in type(e).__name__ for e in eqn.effects)
    except Exception:  # noqa: BLE001 - older jax: no effects attr
        return False
