"""Recompile-hazard pass: values baked into the program that should be args.

Two ways a jitted program quietly recompiles (or bloats) per call:

- a large array closed over instead of passed as an argument is baked into
  the executable as a constant — a new array object with the same values
  re-traces nothing, but a *changed* value means a full recompile, and
  either way the const is embedded in (and shipped with) every executable.
  Static basis tables (spherical harmonics, Wigner blocks) are legitimate
  consts; the size thresholds keep small tables silent and surface the
  pathological ones (``config["const_warn_bytes"]`` default 256 KiB,
  ``config["const_error_bytes"]`` default 4 MiB — audited exceptions:
  ``# contract: allow(recompile_hazard)`` does not help here since consts
  carry no source line; raise the threshold per program instead).
- python scalars closed over become *weak-typed* scalar constants baked
  per VALUE: ``jit(lambda x: x * step_count)`` re-traces for every new
  ``step_count``. Reported as INFO with a count (heuristic — a static
  hyperparameter is fine; a per-step value is not; the jaxpr cannot tell
  them apart).
"""

from __future__ import annotations

import numpy as np

from .. import ir
from . import ContractPass, Program, Severity, register


def _nbytes(val, aval) -> int:
    """Const payload size WITHOUT materializing the value: np.asarray on a
    device-resident const would block on a device->host transfer, and this
    pass also runs in the runtime telemetry path (calculator._contract_audit
    promises a pure host-side walk)."""
    try:
        return int(np.prod(aval.shape) * aval.dtype.itemsize)
    except Exception:  # noqa: BLE001 - aval without shape/dtype
        nb = getattr(val, "nbytes", None)  # attr read, no transfer
        return int(nb) if nb is not None else 0


@register
class RecompileHazardPass(ContractPass):
    name = "recompile_hazard"
    description = ("large closed-over consts baked into the executable; "
                   "python-scalar (weak-type) constant promotion")

    def run(self, program: Program) -> list:
        warn = int(program.config.get("const_warn_bytes", 256 * 1024))
        err = int(program.config.get("const_error_bytes", 4 * 1024 * 1024))
        findings = []
        total = 0
        weak_scalars = 0
        for val, aval in ir.program_consts(program.jaxpr):
            nb = _nbytes(val, aval)
            total += nb
            shape = tuple(getattr(aval, "shape", ()))
            if shape == () and bool(getattr(aval, "weak_type", False)):
                weak_scalars += 1
            if nb >= err:
                findings.append(self.finding(
                    Severity.ERROR,
                    f"const {list(shape)} "
                    f"{getattr(aval, 'dtype', '?')} = {nb / 2**20:.1f} MiB "
                    "baked into the program — pass it as an argument (or "
                    "raise const_error_bytes for an audited static table)",
                    rule="giant-const"))
            elif nb >= warn:
                findings.append(self.finding(
                    Severity.WARNING,
                    f"const {list(shape)} "
                    f"{getattr(aval, 'dtype', '?')} = {nb / 2**10:.0f} KiB "
                    "baked into the program", rule="large-const"))
        if weak_scalars:
            findings.append(self.finding(
                Severity.INFO,
                f"{weak_scalars} weak-typed scalar const(s) — python "
                "scalars closed over re-trace per distinct value",
                rule="weak-scalar"))
        findings.append(self.finding(
            Severity.INFO,
            f"total baked const payload: {total / 2**10:.0f} KiB",
            rule="const-total"))
        return findings
