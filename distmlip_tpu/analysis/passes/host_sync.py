"""Host-sync pass: no callbacks/transfers inside jitted programs.

The "N MD steps = ONE device program" guarantee (PR 5) and the serving
engine's latency model both die the moment a traced program stalls on the
host: any ``pure_callback`` / ``io_callback`` / ``debug_callback`` /
infeed / outfeed forces a device->host round trip per execution — inside a
``while_loop`` body, once per iteration.

Severities:

- ERROR for every host-sync primitive inside a loop body (path contains
  ``while``/``scan``) — and for ALL of them when the program is tagged
  ``device_resident`` (the DeviceMD chunk's mandatory-zero rule);
- ERROR for non-debug callbacks anywhere in a jitted program;
- WARNING for ``debug_callback``/``debug_print`` outside loops (stray
  debug prints still serialize dispatch, but don't change results).
"""

from __future__ import annotations

from .. import ir
from . import ContractPass, Program, Severity, register

_LOOP_PRIMS = ("while", "scan")


@register
class HostSyncPass(ContractPass):
    name = "host_sync"
    description = ("no host callbacks/infeed/outfeed in device programs; "
                   "mandatory-zero inside while_loop bodies")

    def run(self, program: Program) -> list:
        findings = []
        resident = program.tagged("device_resident")
        for site in ir.iter_sites(program.jaxpr):
            prim = site.primitive
            if not ir.is_host_sync(prim):
                continue
            in_loop = any(p in _LOOP_PRIMS for p in site.path)
            debug = "debug" in prim
            if in_loop:
                sev, why = Severity.ERROR, "inside a device loop body"
            elif resident:
                sev, why = Severity.ERROR, "in a device-resident program"
            elif debug:
                sev, why = Severity.WARNING, "in a jitted program"
            else:
                sev, why = Severity.ERROR, "in a jitted program"
            findings.append(self.finding(
                sev, f"host-sync primitive {prim!r} {why}", site=site,
                rule="loop" if in_loop else "jit"))
        return findings
