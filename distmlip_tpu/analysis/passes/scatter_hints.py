"""Scatter-hint pass: hot-path segment sums must declare sorted indices.

The ENTIRE data layout exists to serve one hint: partition/graph.py and
partition/batch.py emit every edge/line array dst-sorted (globally
nondecreasing ``edge_dst``, repeat-last-real padding) precisely so every
``segment_sum``/scatter-add on the hot path can pass
``indices_are_sorted=True`` and take the TPU scatter fast path. A call
site that forgets the hint silently falls back to the general scatter —
correct results, order-of-magnitude slower — which no numeric test will
ever catch. This pass makes the hint a statically checked contract.

Scope: ``requires = {"forward"}``. The *transpose* of an unsorted gather
(``positions[src]``) in a grad program is legitimately an unsorted
scatter-add — src order is not dst order — so the contract is stated on
the forward (hot-path) program, where every scatter-add IS a segment
reduction over a dst-sorted layout.

- ERROR: forward-program ``scatter-add`` with ``indices_are_sorted=False``
  (suppress audited exceptions with ``# contract: allow(scatter_hints)``
  on the call-site line).
- INFO: other scatter variants (scatter-max in segment softmax etc.)
  missing the hint — slower, but not on the per-edge aggregation path.
"""

from __future__ import annotations

from .. import ir
from . import ContractPass, Program, Severity, register


@register
class ScatterHintsPass(ContractPass):
    name = "scatter_hints"
    description = ("forward-program scatter-adds must carry "
                   "indices_are_sorted=True (dst-sorted layout contract)")
    requires = frozenset({"forward"})

    def run(self, program: Program) -> list:
        findings = []
        for site in ir.iter_sites(program.jaxpr):
            prim = site.primitive
            if prim not in ir.SCATTER_PRIMS:
                continue
            hint = site.eqn.params.get("indices_are_sorted")
            if hint is None:
                # a jax version renaming the param must fail LOUDLY — a
                # default of "hinted" would disable this gate vacuously
                findings.append(self.finding(
                    Severity.ERROR,
                    f"{prim} eqn carries no indices_are_sorted param — "
                    "jax renamed it? update analysis/passes/scatter_hints "
                    "(silence gates must never pass vacuously)",
                    site=site, rule="no-hint-param"))
                continue
            if hint:
                continue
            if prim == "scatter-add":
                findings.append(self.finding(
                    Severity.ERROR,
                    "scatter-add without indices_are_sorted=True on the "
                    "forward path — the dst-sorted layout guarantees the "
                    "hint; pass it through (ops/segment.py) or audit with "
                    "# contract: allow(scatter_hints)", site=site,
                    rule="unhinted-add"))
            else:
                findings.append(self.finding(
                    Severity.INFO,
                    f"{prim} without indices_are_sorted hint", site=site,
                    rule="unhinted-other"))
        return findings
