"""Collective-placement pass: which mesh axis carries which collectives.

Generalizes the PR 2/PR 6 audit gates into one registered pass:

- per-mesh-axis collective counts are always reported (INFO);
- ``config["forbidden_axes"]`` (e.g. ``["batch"]``): any collective
  attributed to a listed axis is an ERROR — the 2-D mesh invariant that
  block-diagonal batches add ZERO cross-batch communication;
- ``config["axis_budget"]``: ``{axis: {primitive: count}}`` — audited
  allowance subtracted before the forbidden-axis gate fires. The one
  known legitimate case: a grad program's replicated-input cotangent
  (the strain input is ``P()``-replicated, so its transpose psums over
  EVERY mesh axis — runtime.py keeps the batch extent 1 on all
  DistPotential placements, so that psum moves no bytes). Budgeted
  collectives report as INFO; anything beyond stays an ERROR;
- ``config["require_attributed"]`` (default True when forbidden axes or
  expectations are set): collectives whose axis metadata cannot be parsed
  (a jax version renaming eqn params) are an ERROR — silence gates must
  never pass vacuously;
- ``config["expected_ppermutes"]``: ``{axis_name: count}`` ring-parity
  expectation — the (B, S) placement must pay exactly the 1-D ring's
  ppermutes at P=S, no more (packing adds structures, not communication);
- ``config["max_total_collectives"]``: hard ceiling (0 for the
  single-partition packed program — batching is communication-free);
- ``config["expected_total_collectives"]``: exact-equality gate — the
  ``tools/halo_audit.py --batch`` invariant (collective counts must be
  INDEPENDENT of batch size) pins every B>1 program to the B=1 total.
"""

from __future__ import annotations

from .. import ir
from . import ContractPass, Program, Severity, register


@register
class CollectivePlacementPass(ContractPass):
    name = "collective_placement"
    description = ("per-mesh-axis collective counts; forbidden-axis "
                   "silence, ring parity, and total ceilings")

    def run(self, program: Program) -> list:
        by_axis = ir.collectives_by_axis(program.jaxpr)
        # total gates count every collective EQN once, exactly like
        # ir.count_collectives — summing by_axis instead would drop
        # identity psums (empty axes tuple) and double-count multi-axis
        # collectives, diverging from the reference totals callers pin
        # expected_total_collectives to (tools/halo_audit.py --batch)
        total = sum(ir.count_collectives(program.jaxpr).values())
        findings = [self.finding(
            Severity.INFO,
            "collectives by axis: " + (", ".join(
                f"{ax}={sum(c.values())}"
                + " (" + " ".join(f"{k}:{v}" for k, v in sorted(c.items()))
                + ")"
                for ax, c in sorted(by_axis.items())) or "none"),
            rule="counts")]

        cfg = program.config
        forbidden = tuple(cfg.get("forbidden_axes", ()))
        expected = dict(cfg.get("expected_ppermutes", ()) or {})
        budget = {str(ax): dict(prims)
                  for ax, prims in dict(cfg.get("axis_budget", ())).items()}
        max_total = cfg.get("max_total_collectives")
        require_attr = cfg.get(
            "require_attributed",
            bool(forbidden or expected or max_total is not None))

        for ax in forbidden:
            counts = dict(by_axis.get(str(ax), {}))
            allowed = budget.get(str(ax), {})
            over = {k: v - min(v, int(allowed.get(k, 0)))
                    for k, v in counts.items()}
            within = {k: min(v, int(allowed.get(k, 0)))
                      for k, v in counts.items() if allowed.get(k)}
            n_within = sum(within.values())
            if n_within:
                findings.append(self.finding(
                    Severity.INFO,
                    f"{n_within} budgeted collective(s) on axis {ax!r}: "
                    + " ".join(f"{k}={v}" for k, v in sorted(within.items()))
                    + " (audited allowance, axis_budget)",
                    rule="budgeted-axis"))
            n = sum(over.values())
            if n:
                findings.append(self.finding(
                    Severity.ERROR,
                    f"{n} collective(s) on forbidden mesh axis {ax!r}: "
                    + " ".join(f"{k}={v}" for k, v in
                               sorted(over.items()) if v),
                    rule="forbidden-axis"))
        if require_attr:
            n = sum(by_axis.get("<unknown>", {}).values())
            if n:
                findings.append(self.finding(
                    Severity.ERROR,
                    f"{n} collective(s) with unparseable axis metadata — "
                    "the silence gate would pass vacuously",
                    rule="unattributed"))
        for ax, want in expected.items():
            # alias-robust: a jax build emitting collective_permute instead
            # of ppermute must not make 0 == 0 pass vacuously
            got = ir.ppermute_count(by_axis.get(str(ax), {}))
            if got != int(want):
                findings.append(self.finding(
                    Severity.ERROR,
                    f"axis {ax!r} carries {got} ppermute(s), expected "
                    f"{int(want)} (1-D ring parity)",
                    rule="ring-parity"))
        if max_total is not None and total > int(max_total):
            findings.append(self.finding(
                Severity.ERROR,
                f"{total} collective(s) traced, ceiling is {int(max_total)}",
                rule="total-ceiling"))
        expected_total = cfg.get("expected_total_collectives")
        if expected_total is not None and total != int(expected_total):
            findings.append(self.finding(
                Severity.ERROR,
                f"{total} collective(s) traced, expected exactly "
                f"{int(expected_total)}", rule="total-parity"))
        return findings
