"""Contract-pass registry.

A :class:`ContractPass` inspects one traced :class:`Program` and returns
typed :class:`~distmlip_tpu.analysis.findings.Finding`s. Passes register
themselves with :func:`register`; :func:`run_passes` runs every applicable
registered pass over a program and applies ``# contract: allow(...)``
suppressions. ``tools/contract_check.py`` is the CLI over this registry;
``tools/halo_audit.py``'s mesh/batch gates and the runtime's telemetry
contract counts ride the same passes.

Program *tags* scope applicability: a pass with ``requires = {"forward"}``
only runs on forward-only programs (``scatter_hints`` — the transposed
gather in a grad program legitimately emits unsorted scatter-adds, so the
hint contract is stated on the forward hot path). Common tags:

- ``"forward"`` — forward-only energy program (no autodiff transpose)
- ``"grad"`` — full value_and_grad potential program
- ``"device_resident"`` — must run with ZERO host syncs (DeviceMD chunk)
- ``"mesh"`` — traced under a named-mesh shard_map placement
- ``"x64"`` — traced under enable_x64 (f64 leaks stay visible instead of
  being silently canonicalized to f32)

Per-program expectations ride ``Program.config`` — see each pass's
docstring for the keys it reads.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..findings import (Finding, Severity, apply_suppressions, error_count,
                        format_findings, warning_count)


@dataclass
class Program:
    """One traced program under contract check."""

    name: str
    jaxpr: object                       # ClosedJaxpr from jax.make_jaxpr
    tags: frozenset = frozenset()
    config: dict = field(default_factory=dict)

    def tagged(self, *names) -> bool:
        return frozenset(names) <= self.tags


class ContractPass:
    """Base class: subclass, set ``name``/``description``, implement
    :meth:`run`, and decorate with :func:`register`."""

    name: str = ""
    description: str = ""
    requires: frozenset = frozenset()   # run only when tags cover these

    def applicable(self, program: Program) -> bool:
        return self.requires <= program.tags

    def run(self, program: Program) -> list:
        raise NotImplementedError

    # helper: findings inherit the program name automatically
    def finding(self, severity: Severity, message: str, *, site=None,
                rule: str = "", location=None) -> Finding:
        from .. import ir

        scope, path = "", ()
        if site is not None:
            scope, path = site.scope, site.path
            if location is None:
                location = ir.source_location(site.eqn)
        return Finding(pass_name=self.name, severity=severity,
                       message=message, scope=scope, path=path,
                       location=location, rule=rule)


REGISTRY: dict[str, type] = {}


def register(cls):
    """Class decorator adding a ContractPass to the global registry."""
    if not cls.name:
        raise ValueError(f"{cls.__name__} needs a non-empty .name")
    REGISTRY[cls.name] = cls
    return cls


def get_passes(names=None) -> list:
    """Instantiate registered passes (all, or the named subset in
    registry order). Unknown names raise KeyError."""
    if names is None:
        return [cls() for cls in REGISTRY.values()]
    missing = [n for n in names if n not in REGISTRY]
    if missing:
        raise KeyError(
            f"unknown contract pass(es) {missing}; registered: "
            f"{sorted(REGISTRY)}")
    return [REGISTRY[n]() for n in REGISTRY if n in set(names)]


def run_passes(program: Program, passes=None, suppress: bool = True) -> list:
    """Run every applicable pass over ``program``; findings carry the
    program name and (with ``suppress=True``) honor source-level
    ``# contract: allow(...)`` comments."""
    passes = get_passes() if passes is None else passes
    findings = []
    for p in passes:
        if not p.applicable(program):
            continue
        for f in p.run(program):
            findings.append(replace(f, program=program.name))
    if suppress:
        findings = apply_suppressions(findings)
    return findings


__all__ = [
    "Program", "ContractPass", "REGISTRY", "register", "get_passes",
    "run_passes", "Finding", "Severity", "error_count", "warning_count",
    "format_findings",
]

# importing the submodules registers the built-in passes
from . import collective_placement  # noqa: E402,F401
from . import host_sync             # noqa: E402,F401
from . import dtype_discipline      # noqa: E402,F401
from . import scatter_hints         # noqa: E402,F401
from . import recompile_hazard      # noqa: E402,F401
from . import dead_compute          # noqa: E402,F401
from . import memory_budget         # noqa: E402,F401
