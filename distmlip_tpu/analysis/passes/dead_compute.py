"""Dead-compute pass: eqns with no dataflow path to a program output.

Dead eqns survive tracing (jax's make_jaxpr keeps everything executed;
DCE happens later, per backend, maybe) and usually mean a refactor left a
computation behind — at best wasted trace/compile time, at worst a
forgotten output silently dropped from the return path (CHGNet's dead
trailing halo exchange, removed in PR 2, was exactly this shape).

Findings are grouped by (primitive, source line): the per-field
slice/squeeze unpacking in ``local_graph_from_stacked`` legitimately
leaves a dead eqn per unused graph field, and one finding per *site*
(with a count) keeps the report readable. Severity splits by cost class:

- WARNING when the dead eqn is (or transitively contains, for pjit/
  scan/cond call eqns) a collective, callback, scatter or loop — dead
  communication escapes the other passes' cost models, and a dead
  scatter often means a forgotten output;
- INFO otherwise — XLA's DCE reliably erases dead arithmetic and data
  movement (including the partial-eval leftovers jax's own autodiff
  leaves in shard_map'd grad programs); code-health noise, not a
  hazard.

Liveness is computed per (sub)jaxpr: an eqn inside a scan body is judged
against the body's outputs, not the whole program's. Effectful eqns never
count as dead. ``config["dead_compute_max_report"]`` (default 10) caps
the distinct sites reported per program.
"""

from __future__ import annotations

from .. import ir
from . import ContractPass, Program, Severity, register

_HAZARD_PRIMS = frozenset(
    ir.COLLECTIVE_PRIMS | ir.SCATTER_PRIMS | {"while", "scan"})


def _is_hazard(eqn) -> bool:
    """Dead communication / callbacks / scatters / loops warrant a
    WARNING; anything else XLA's DCE erases for free (INFO)."""
    name = eqn.primitive.name
    if name in _HAZARD_PRIMS or ir.is_host_sync(name):
        return True
    for sub in ir.sub_jaxprs(eqn.params):
        for inner in sub.eqns:
            if _is_hazard(inner):
                return True
    return False


@register
class DeadComputePass(ContractPass):
    name = "dead_compute"
    description = ("eqns with no path to a program output, grouped per "
                   "source site (per sub-jaxpr liveness)")

    def run(self, program: Program) -> list:
        cap = int(program.config.get("dead_compute_max_report", 10))
        # (primitive, location) -> [count, representative site]
        sites: dict[tuple, list] = {}
        seen: set[int] = set()
        top = getattr(program.jaxpr, "jaxpr", program.jaxpr)
        groups = [(top, ())] + [
            (s.jaxpr, s.path) for s in ir.iter_sites(program.jaxpr)]
        n_dead = 0
        for jaxpr, path in groups:
            if id(jaxpr) in seen:
                continue
            seen.add(id(jaxpr))
            for eqn in ir.dead_eqns(jaxpr):
                n_dead += 1
                key = (eqn.primitive.name, ir.source_location(eqn))
                entry = sites.setdefault(key, [0, None])
                entry[0] += 1
                if entry[1] is None:
                    entry[1] = ir.EqnSite(eqn=eqn, path=path,
                                          scope=ir.scope_of(eqn),
                                          jaxpr=jaxpr)
        findings = []
        # hazards sort ahead of the report cap: a single dead psum must
        # never be crowded out by high-count dead-arithmetic sites
        ranked = sorted(
            ((prim, _is_hazard(site.eqn), count, site)
             for (prim, _loc), (count, site) in sites.items()),
            key=lambda t: (not t[1], -t[2]))
        for prim, hazard, count, site in ranked:
            if len(findings) >= cap:
                break
            sev = Severity.WARNING if hazard else Severity.INFO
            many = f" x{count}" if count > 1 else ""
            findings.append(self.finding(
                sev, f"dead eqn {prim!r}{many} — no path to a program "
                "output", site=site, rule="dead-eqn"))
        if len(sites) > cap:
            findings.append(self.finding(
                Severity.INFO,
                f"...and {len(sites) - cap} more dead site(s) "
                f"({n_dead} dead eqn(s) total)", rule="dead-eqn-more"))
        return findings
