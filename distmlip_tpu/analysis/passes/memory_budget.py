"""Memory-budget pass: a traced program's estimated peak vs the HBM limit.

Runs the static HBM planner (:mod:`distmlip_tpu.analysis.memory`) over the
program and gates on the per-device peak live-byte estimate:

- **ERROR** when the estimated peak exceeds ``memory_budget_frac`` (default
  0.9) of the budget — the program is expected to OOM (or to leave the
  runtime no headroom for the prefetch's transient 2x window);
- **WARNING** when a single transient window (both sides of a loop carry /
  scatter copy / speculative build live at once) exceeds
  ``transient_warn_frac`` (default 0.5) of the budget: the program fits at
  steady state but one eqn's spike owns most of the chip;
- **INFO** always: the estimated peak, its top live-set contributor, and
  the headroom fraction — the number ``StepRecord.est_peak_bytes``
  telemetry compares against measured ``bytes_in_use``.

Config keys (``Program.config``):

- ``bytes_limit`` — the per-device HBM budget in bytes. Default: the
  worst device's reported ``bytes_limit``
  (``utils.memory.device_bytes_limit``); on backends reporting none (CPU)
  the pass emits the INFO estimate only — there is nothing to gate.
- ``memory_budget_frac`` — ERROR threshold as a fraction of the budget.
- ``transient_warn_frac`` — WARNING threshold for one transient window.
- ``donated_invars`` — invar indices donated at dispatch (their buffers
  die at last use; tracing does not record donation).

ERROR findings anchor to the top temp contributor's trace site, so
``# contract: allow(memory_budget)`` at that line is the audited-exception
idiom (same as every other pass)."""

from __future__ import annotations

from ..memory import analyze_memory
from . import ContractPass, Program, Severity, register


@register
class MemoryBudgetPass(ContractPass):
    name = "memory_budget"
    description = ("estimated per-device peak live bytes vs the HBM "
                   "budget (static OOM gate)")

    def run(self, program: Program) -> list:
        cfg = program.config
        plan = analyze_memory(program.jaxpr,
                              donated=cfg.get("donated_invars", ()))
        # cache the plan on the program so callers that want the numbers
        # (calculator._contract_audit's est_peak_bytes telemetry,
        # load_test's summary) read it back instead of re-walking a
        # multi-thousand-eqn jaxpr for one integer
        cfg["_memory_plan"] = plan
        limit = cfg.get("bytes_limit")
        if limit is None:
            from ...utils.memory import device_bytes_limit

            limit = device_bytes_limit()
        frac = float(cfg.get("memory_budget_frac", 0.9))
        t_frac = float(cfg.get("transient_warn_frac", 0.5))

        top = plan.contributors[0] if plan.contributors else None
        top_loc = (top.location if top is not None
                   and top.kind == "temp" else None)
        findings = []
        if limit:
            budget = frac * float(limit)
            if plan.peak_bytes > budget:
                owners = "; ".join(
                    c.render().strip() for c in plan.contributors[:3])
                findings.append(self.finding(
                    Severity.ERROR,
                    f"estimated peak {plan.peak_bytes / 2**20:.1f} MiB "
                    f"exceeds {frac:.0%} of the {limit / 2**30:.2f} GiB "
                    f"budget — top live-set contributors: {owners}",
                    rule="over-budget", location=top_loc))
            else:
                for t in plan.transients:
                    if t.nbytes > t_frac * float(limit):
                        findings.append(self.finding(
                            Severity.WARNING,
                            f"transient window of "
                            f"{t.nbytes / 2**20:.1f} MiB "
                            f"({t.primitive}) exceeds {t_frac:.0%} of the "
                            f"budget — one eqn's spike owns most of the "
                            f"chip", rule="large-transient",
                            location=t.location))
                        break           # the largest window suffices
        headroom = plan.headroom_frac(limit)
        hr = (f", headroom {headroom:.0%}" if headroom is not None
              else ", no device bytes_limit reported")
        top_s = f" — top: {top.render().strip()}" if top is not None else ""
        findings.append(self.finding(
            Severity.INFO,
            f"estimated per-device peak {plan.peak_bytes / 2**20:.1f} MiB "
            f"(args {plan.arg_bytes / 2**20:.1f} + consts "
            f"{plan.const_bytes / 2**20:.1f} + temps "
            f"{plan.temp_peak_bytes / 2**20:.1f}){hr}{top_s}",
            rule="peak-estimate"))
        return findings
