"""Dtype-discipline pass: the device path is fp32 (bf16-capable), never f64.

TPUs emulate f64 at ~1/10 throughput; one un-cast ``np.float64`` array
(geometry helpers are float64 by design on the host) silently promotes
every downstream op when x64 tracing is on. Checks:

- ERROR: any float64/complex128 aval flowing through an eqn (grouped per
  primitive so a single leak doesn't emit hundreds of findings). Under
  the default jax config f64 is canonicalized to f32 at trace time, so
  programs should be traced under ``jax.experimental.enable_x64`` (tag
  ``"x64"``) for this check to bite — ``tools/contract_check.py`` does.
- ERROR: a float64 *host constant* baked into the program
  (``np.float64`` closure arrays — visible whenever the const value
  escaped canonicalization).
- WARNING: scatter-add accumulation at half precision (f16/bf16 segment
  sums lose ulps per edge; the contract is fp32 accumulation with
  half-precision storage).
- WARNING: weak-type drift across ``scan``/``while`` carries (a python
  scalar promoting the carry dtype re-traces per call site).
"""

from __future__ import annotations

from collections import Counter

from .. import ir
from . import ContractPass, Program, Severity, register

_BAD = ("float64", "complex128")
_HALF = ("float16", "bfloat16")


def _aval_dtype(v) -> str:
    try:
        return str(v.aval.dtype)
    except Exception:  # noqa: BLE001 - tokens/abstract units have no dtype
        return ""


def _strong_f64(v) -> bool:
    """True for a non-weak float64/complex128 aval. Weak-typed f64 is a
    python scalar under x64 tracing — it does NOT promote f32 operands, so
    only strong f64 (a real np.float64 array on the device path) counts."""
    try:
        aval = v.aval
        return (str(aval.dtype) in _BAD
                and not bool(getattr(aval, "weak_type", False)))
    except Exception:  # noqa: BLE001
        return False


@register
class DtypeDisciplinePass(ContractPass):
    name = "dtype_discipline"
    description = ("no f64 avals or f64 host consts on the device path; "
                   "fp32 scatter accumulation; stable carry weak types")

    def run(self, program: Program) -> list:
        findings = []
        f64_sites: dict[str, tuple] = {}   # primitive -> (count, first site)
        for site in ir.iter_sites(program.jaxpr):
            eqn = site.eqn
            if any(_strong_f64(v) for v in (*eqn.invars, *eqn.outvars)):
                n, first = f64_sites.get(site.primitive, (0, site))
                f64_sites[site.primitive] = (n + 1, first)
            if (site.primitive == "scatter-add"
                    and _aval_dtype(eqn.outvars[0]) in _HALF
                    # unique-index scatter-adds (transposes of static
                    # slices, one-hot writes) add each slot ONCE into the
                    # operand — there is no iterated accumulation to lose
                    # ulps in; only repeatable-index scatters (segment
                    # sums, gather transposes) carry the fp32-accum
                    # contract
                    and not bool(eqn.params.get("unique_indices", False))):
                findings.append(self.finding(
                    Severity.WARNING,
                    f"scatter-add accumulates in "
                    f"{_aval_dtype(eqn.outvars[0])}; accumulate in fp32 "
                    "and cast the result", site=site, rule="half-accum"))
            if site.primitive in ("scan", "while"):
                findings.extend(self._carry_drift(site))
        for prim, (n, first) in sorted(f64_sites.items()):
            findings.append(self.finding(
                Severity.ERROR,
                f"float64 aval(s) through {prim!r} x{n} — the device path "
                "is fp32; cast at the host boundary", site=first,
                rule="f64-aval"))
        counts = Counter()
        for val, aval in ir.program_consts(program.jaxpr):
            if bool(getattr(aval, "weak_type", False)):
                continue  # python scalar — does not promote f32 operands
            # attr reads only: np.asarray(val) on a device-resident const
            # would block on a device->host transfer. The val dtype is the
            # one that matters under DEFAULT tracing (jax canonicalizes the
            # AVAL to f32 but keeps the f64 host array as the const).
            dt = str(getattr(val, "dtype", ""))
            if dt in _BAD or str(getattr(aval, "dtype", "")) in _BAD:
                counts[(dt or str(aval.dtype),
                        tuple(getattr(aval, "shape", ())))] += 1
        for (dt, shape), n in sorted(counts.items()):
            findings.append(self.finding(
                Severity.ERROR,
                f"{n} baked-in host const(s) of dtype {dt} shape "
                f"{list(shape)} — cast before tracing (geometry.py host "
                "helpers are float64 by design; device consumers must "
                "downcast)", rule="f64-const"))
        return findings

    def _carry_drift(self, site) -> list:
        eqn = site.eqn
        out = []
        try:
            if site.primitive == "scan":
                num_consts = int(eqn.params.get("num_consts", 0))
                num_carry = int(eqn.params.get("num_carry", 0))
                ins = eqn.invars[num_consts:num_consts + num_carry]
                outs = eqn.outvars[:num_carry]
            else:  # while: carry = invars minus cond/body consts
                cn = int(eqn.params.get("cond_nconsts", 0))
                bn = int(eqn.params.get("body_nconsts", 0))
                ins = eqn.invars[cn + bn:]
                outs = eqn.outvars
            for i, (vi, vo) in enumerate(zip(ins, outs)):
                ai, ao = getattr(vi, "aval", None), getattr(vo, "aval", None)
                if ai is None or ao is None:
                    continue
                wi = bool(getattr(ai, "weak_type", False))
                wo = bool(getattr(ao, "weak_type", False))
                if wi != wo or _aval_dtype(vi) != _aval_dtype(vo):
                    out.append(self.finding(
                        Severity.WARNING,
                        f"{site.primitive} carry {i} drifts "
                        f"{_aval_dtype(vi)}/weak={wi} -> "
                        f"{_aval_dtype(vo)}/weak={wo}; pin the carry dtype",
                        site=site, rule="carry-drift"))
        except Exception:  # noqa: BLE001 - param layout varies across jax
            pass
        return out
