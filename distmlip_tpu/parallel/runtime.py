"""Graph-parallel potential runtime.

Builds jitted energy / (energy, forces, stress) functions from a model's
per-shard energy function. Forces come from ``jax.grad`` of the sharded
total energy — JAX transposes the halo-exchange ``ppermute`` into the
reverse collective, reproducing the reference's autograd-through-device-
copies force flow (reference pes.py:121-124, models.py:181-193) without any
hand-written backward.

Model contract:
    model_energy_fn(params, lg: LocalGraph, positions) -> per-atom energies
with shape (N_cap,); padded rows may hold garbage — the runtime masks them.

Fused site readout (``aux=True``): the model function instead returns
``(e_atoms, aux)`` where ``aux`` is a pytree of per-atom arrays (leading
axis N_cap — e.g. CHGNet magmoms). The aux rides the SAME forward pass as
the energy (``jax.value_and_grad(..., has_aux=True)``), so sitewise
quantities no longer cost a second full forward the way the separate
``make_site_fn`` program does.

``halo_mode`` selects the halo-exchange implementation
(``"coalesced"`` — one ppermute per ring shift per sync point — or the
historical ``"legacy"`` per-array loop; see parallel/halo.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..geometry import apply_strain
from ..partition.graph import PartitionedGraph
from ..telemetry import scope
from .halo import local_graph_from_stacked
from .mesh import GRAPH_AXIS

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# the "don't require replication-invariance checks" kwarg was renamed
# check_rep -> check_vma across jax versions; detect which one this build has
import inspect as _inspect

_CHECK_KW = ("check_vma" if "check_vma"
             in _inspect.signature(shard_map).parameters else "check_rep")
_NO_CHECK = {_CHECK_KW: False}


def graph_in_specs(graph: PartitionedGraph) -> PartitionedGraph:
    """A pytree of PartitionSpecs matching ``graph``'s treedef.

    Per-partition arrays shard their leading P axis over the graph axis;
    halo tables (S, P, H) shard axis 1; lattice and scalars replicate.
    """
    import dataclasses

    row, table, rep = P(GRAPH_AXIS), P(None, GRAPH_AXIS), P()
    return dataclasses.replace(
        graph,
        positions=row, species=row, node_mask=row, owned_mask=row,
        edge_src=row, edge_dst=row, edge_offset=row, edge_mask=row,
        halo_send_idx=table, halo_send_mask=table, halo_recv_idx=table,
        lattice=rep, n_total_nodes=rep,
        system=None if graph.system is None else {k: rep for k in graph.system},
        line_src=row, line_dst=row, line_mask=row, line_center=row,
        bond_map_edge=row, bond_map_bond=row, bond_map_mask=row,
        bond_halo_send_idx=table, bond_halo_send_mask=table,
        bond_halo_recv_idx=table,
        struct_id=None if graph.struct_id is None else row,
    )


def make_total_energy(model_energy_fn, mesh: Mesh | None,
                      halo_mode: str = "coalesced", aux: bool = False):
    """Sharded total-energy fn: (params, graph, positions, strain) -> scalar
    (or (scalar, aux_pytree) with ``aux=True``).

    ``positions`` is (P, N_cap, 3); only owned rows are read — halo rows are
    refreshed in-jit by the halo exchange so that gradients flow back to the
    owning partition. ``strain`` is a (3, 3) symmetric strain applied to
    positions and lattice (for stress). With ``aux=True`` the model fn must
    return ``(e_atoms, aux)``; aux leaves keep their per-partition leading
    layout ((P, N_cap, ...) outside the shard_map).
    """
    from .halo import validate_halo_mode

    validate_halo_mode(halo_mode)  # fail at build, not first trace

    def local_energy(params, strain, graph_local, positions):
        axis = GRAPH_AXIS if mesh is not None else None
        lg, _ = local_graph_from_stacked(graph_local, axis, halo_mode)
        dtype = positions.dtype
        with scope("apply_strain"):
            pos, lg.lattice = apply_strain(
                positions[0], lg.lattice.astype(dtype), strain.astype(dtype)
            )
        pos = lg.halo_exchange(pos)
        with scope("model_energy"):
            out = model_energy_fn(params, lg, pos)
        if aux:
            e_atoms, aux_out = out
            aux_out = jax.tree.map(lambda a: a[None], aux_out)
            return lg.owned_sum(e_atoms.reshape(-1, 1)), aux_out
        return lg.owned_sum(out.reshape(-1, 1))

    if mesh is None:
        def total_energy(params, graph, positions, strain):
            if graph.num_partitions != 1:
                raise ValueError(
                    f"mesh=None requires a single-partition graph, got "
                    f"P={graph.num_partitions}; pass mesh=graph_mesh(P)."
                )
            return local_energy(params, strain, graph, positions)
        return total_energy

    def total_energy(params, graph, positions, strain):
        out_specs = (P(), P(GRAPH_AXIS)) if aux else P()
        sharded = shard_map(
            local_energy,
            mesh=mesh,
            in_specs=(P(), P(), graph_in_specs(graph), P(GRAPH_AXIS)),
            out_specs=out_specs,
            **_NO_CHECK,
        )
        return sharded(params, strain, graph, positions)

    return total_energy


def make_site_fn(model_site_fn, mesh: Mesh | None,
                 halo_mode: str = "coalesced"):
    """Jitted sharded per-atom readout: (params, graph, positions) ->
    (P, N_cap) site values (e.g. CHGNet magmoms — reference
    PESCalculator_Dist's compute_magmom surface, implementations/matgl/
    ase.py:53-127). Halo rows are refreshed in-jit like the energy path;
    reassemble owned rows with HostGraphData.gather_owned.

    .. deprecated::
        This runs a SEPARATE forward pass from the energy program — for
        magmom-every-step MD that doubles device time. Models exposing
        ``energy_and_aux_fn`` (CHGNet) now ride the sitewise readout on the
        energy forward via ``make_potential_fn(..., aux=True)``;
        DistPotential prefers that path automatically. make_site_fn remains
        for models without a fused readout and as the parity oracle for the
        fused path (tests/test_halo_overlap.py)."""
    from .halo import validate_halo_mode

    validate_halo_mode(halo_mode)

    def local_site(params, graph_local, positions):
        axis = GRAPH_AXIS if mesh is not None else None
        lg, _ = local_graph_from_stacked(graph_local, axis, halo_mode)
        pos = lg.halo_exchange(positions[0])
        with scope("model_site"):
            return model_site_fn(params, lg, pos)[None]

    if mesh is None:
        @jax.jit
        def site_fn(params, graph, positions):
            if graph.num_partitions != 1:
                raise ValueError(
                    f"mesh=None requires a single-partition graph, got "
                    f"P={graph.num_partitions}; pass mesh=graph_mesh(P).")
            return local_site(params, graph, positions)
        return site_fn

    @jax.jit
    def site_fn(params, graph, positions):
        sharded = shard_map(
            local_site,
            mesh=mesh,
            in_specs=(P(), graph_in_specs(graph), P(GRAPH_AXIS)),
            out_specs=P(GRAPH_AXIS),
            **_NO_CHECK,
        )
        return sharded(params, graph, positions)

    return site_fn


def make_potential_fn(model_energy_fn, mesh: Mesh | None,
                      compute_stress: bool = True,
                      halo_mode: str = "coalesced", aux: bool = False):
    """Jitted (params, graph, positions) -> dict(energy, forces, stress).

    forces: (P, N_cap, 3) — per-partition owned rows (reassemble with
    HostGraphData.gather_owned); stress: (3, 3) in eV/Å^3, dE/deps / V.
    With ``aux=True`` (fused site readout) the model fn returns
    ``(e_atoms, aux)`` and the result dict gains an ``"aux"`` pytree of
    (P, N_cap, ...) per-atom outputs computed on the SAME forward pass.
    """
    total_energy = make_total_energy(model_energy_fn, mesh,
                                     halo_mode=halo_mode, aux=aux)

    @jax.jit
    def potential(params, graph, positions):
        strain = jnp.zeros((3, 3), dtype=positions.dtype)
        grad_fn = jax.value_and_grad(
            total_energy,
            argnums=(2, 3) if compute_stress else 2,
            has_aux=aux,
        )
        with scope("energy_and_grad"):
            val, grads = grad_fn(params, graph, positions, strain)
        energy, aux_out = val if aux else (val, None)
        if compute_stress:
            g_pos, g_strain = grads
            with scope("stress"):
                vol = jnp.abs(jnp.linalg.det(graph.lattice.astype(
                    jnp.float64 if graph.lattice.dtype == jnp.float64
                    else positions.dtype)))
                stress = g_strain / vol
        else:
            g_pos = grads
            stress = jnp.zeros((3, 3), dtype=positions.dtype)
        out = {"energy": energy, "forces": -g_pos, "stress": stress}
        if aux:
            out["aux"] = aux_out
        return out

    return potential


def make_batched_potential_fn(model_energy_fn, compute_stress: bool = True,
                              aux: bool = False):
    """Jitted batched potential over a block-diagonally packed graph.

    ``(params, graph, positions) -> dict`` where ``graph`` is a
    single-partition ``PartitionedGraph`` built by
    :func:`distmlip_tpu.partition.pack_structures` (``batch_size`` B slots,
    ``struct_id`` per node, Cartesian edge offsets, identity lattice):

    - ``energies``: (B,) per-structure energies — ONE
      ``segment_sum(e_atoms, struct_id)`` readout over the model's per-atom
      energies (padded rows carry ``struct_id == B`` and are dropped);
      empty slots read 0.
    - ``forces``: (P=1, N_cap, 3) packed per-atom forces from ONE
      ``value_and_grad`` through the whole super-graph. The blocks share no
      edges, so d(sum_b E_b)/dx_i = dE_{struct(i)}/dx_i exactly — batching
      introduces no cross-terms.
    - ``strain_grad``: (B, 3, 3) dE_b/d(strain_b) — each structure gets its
      OWN symmetric strain applied to its positions and (Cartesian) edge
      offsets; divide by per-structure volume on the host for stress.
    - ``aux`` (``aux=True``): the model's fused per-atom outputs (packed
      layout, slice per structure on the host).

    The batched path is deliberately single-partition (``mesh=None``): its
    regime is MANY SMALL structures per device step (the TorchSim batching
    regime, arXiv:2508.06628), which composes with — rather than replaces —
    the halo-partitioned path for one large structure. No collectives are
    traced, so collective counts are independent of B (tools/halo_audit.py
    ``--batch`` asserts this).
    """

    def batched_energy(params, strain, graph, positions):
        lg, _ = local_graph_from_stacked(graph, None, "coalesced")
        B = graph.batch_size
        dtype = positions.dtype
        pos = positions[0]
        sid = lg.struct_id
        with scope("apply_strain"):
            # per-structure symmetric strain: x_i -> x_i @ (I + eps_{s(i)});
            # Cartesian edge offsets deform with their structure's cell.
            # Padded node rows have sid == B — the gather clamps them onto
            # the last real slot, which is harmless (their rows are masked).
            sym = 0.5 * (strain + jnp.swapaxes(strain, -1, -2)).astype(dtype)
            defm = jnp.eye(3, dtype=dtype)[None, :, :] + sym      # (B, 3, 3)
            pos = jnp.einsum("ni,nij->nj", pos, defm[sid])
            esid = sid[lg.edge_dst]  # edge's structure (dst rows are real)
            lg.edge_offset = jnp.einsum(
                "ei,eij->ej", lg.edge_offset.astype(dtype), defm[esid])
        with scope("model_energy"):
            out = model_energy_fn(params, lg, pos)
        e_atoms, aux_out = out if aux else (out, None)
        with scope("batched_readout"):
            e = jnp.where(lg.owned_mask,
                          e_atoms.reshape(-1).astype(dtype), 0)
            # padded rows carry sid == B (out of range -> dropped); real
            # rows are contiguous per structure, so indices are sorted
            energies = jax.ops.segment_sum(
                e, sid, num_segments=B, indices_are_sorted=True)
        return jnp.sum(energies), (energies, aux_out)

    @jax.jit
    def potential(params, graph, positions):
        if graph.num_partitions != 1 or graph.batch_size < 1:
            raise ValueError(
                "make_batched_potential_fn requires a single-partition "
                f"packed graph (got P={graph.num_partitions}, "
                f"batch_size={graph.batch_size}); build it with "
                "pack_structures().")
        B = graph.batch_size
        strain = jnp.zeros((B, 3, 3), dtype=positions.dtype)
        grad_fn = jax.value_and_grad(
            batched_energy, argnums=(3, 1) if compute_stress else 3,
            has_aux=True)
        with scope("energy_and_grad"):
            (_, (energies, aux_out)), grads = grad_fn(
                params, strain, graph, positions)
        if compute_stress:
            g_pos, g_strain = grads
        else:
            g_pos = grads
            g_strain = jnp.zeros((B, 3, 3), dtype=positions.dtype)
        out = {"energies": energies, "forces": -g_pos,
               "strain_grad": g_strain}
        if aux:
            out["aux"] = aux_out
        return out

    return potential
