"""Graph-parallel potential runtime.

Builds jitted energy / (energy, forces, stress) functions from a model's
per-shard energy function. Forces come from ``jax.grad`` of the sharded
total energy — JAX transposes the halo-exchange ``ppermute`` into the
reverse collective, reproducing the reference's autograd-through-device-
copies force flow (reference pes.py:121-124, models.py:181-193) without any
hand-written backward.

Model contract:
    model_energy_fn(params, lg: LocalGraph, positions) -> per-atom energies
with shape (N_cap,); padded rows may hold garbage — the runtime masks them.

Fused site readout (``aux=True``): the model function instead returns
``(e_atoms, aux)`` where ``aux`` is a pytree of per-atom arrays (leading
axis N_cap — e.g. CHGNet magmoms). The aux rides the SAME forward pass as
the energy (``jax.value_and_grad(..., has_aux=True)``), so sitewise
quantities no longer cost a second full forward the way the separate
``make_site_fn`` program does.

``halo_mode`` selects the halo-exchange implementation
(``"coalesced"`` — one ppermute per ring shift per sync point — or the
historical ``"legacy"`` per-array loop; see parallel/halo.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..geometry import apply_strain
from ..partition.graph import PartitionedGraph
from ..telemetry import scope
from .halo import local_graph_from_stacked
from .mesh import (BATCH_AXIS, GRAPH_AXIS, SPATIAL_AXIS, mesh_row_axes,
                   mesh_shape)

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# the "don't require replication-invariance checks" kwarg was renamed
# check_rep -> check_vma across jax versions; detect which one this build has
import inspect as _inspect

_CHECK_KW = ("check_vma" if "check_vma"
             in _inspect.signature(shard_map).parameters else "check_rep")
_NO_CHECK = {_CHECK_KW: False}


def graph_row_axes(graph: PartitionedGraph):
    """Mesh axes the graph's leading partition axis shards over.

    A 2-D-placed graph (``batch_parts > 1``) factors its leading axis as
    (batch, spatial) row-major and shards over BOTH named axes jointly;
    every other graph (single structure, or a packed batch confined to one
    batch row) shards over the spatial axis only and REPLICATES over any
    batch axis the mesh has — which is what lets an oversized request run
    on the spatial sub-axis of the same serving mesh.
    """
    return (BATCH_AXIS, SPATIAL_AXIS) if graph.batch_parts > 1 \
        else SPATIAL_AXIS


def graph_in_specs(graph: PartitionedGraph, axes=None) -> PartitionedGraph:
    """A pytree of PartitionSpecs matching ``graph``'s treedef.

    Per-partition arrays shard their leading P axis over ``axes`` (default
    ``graph_row_axes(graph)`` — the spatial axis, or (batch, spatial)
    jointly for 2-D-placed packed graphs; the runtime passes
    ``mesh_row_axes(mesh)`` so rows never replicate over a present batch
    axis); halo tables (S, P, H) shard axis 1; lattice and scalars
    replicate.
    """
    import dataclasses

    axes = graph_row_axes(graph) if axes is None else axes
    row, table, rep = P(axes), P(None, axes), P()
    return dataclasses.replace(
        graph,
        positions=row, species=row, node_mask=row, owned_mask=row,
        edge_src=row, edge_dst=row, edge_offset=row, edge_mask=row,
        halo_send_idx=table, halo_send_mask=table, halo_recv_idx=table,
        lattice=rep, n_total_nodes=rep,
        system=None if graph.system is None else {k: rep for k in graph.system},
        line_src=row, line_dst=row, line_mask=row, line_center=row,
        bond_map_edge=row, bond_map_bond=row, bond_map_mask=row,
        bond_halo_send_idx=table, bond_halo_send_mask=table,
        bond_halo_recv_idx=table,
        struct_id=None if graph.struct_id is None else row,
    )


def graph_shardings(mesh: Mesh, graph: PartitionedGraph):
    """NamedSharding pytree placing ``graph`` on ``mesh``.

    One definition of placement identity for every lane (DistPotential,
    BatchedPotential): per-partition rows shard over ``mesh_row_axes(mesh)``
    (so rows never replicate over a present batch axis), halo tables shard
    axis 1, scalars replicate — exactly the in_specs the runtime's
    shard_map programs consume.
    """
    from jax.sharding import NamedSharding

    specs = graph_in_specs(graph, mesh_row_axes(mesh))
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_total_energy(model_energy_fn, mesh: Mesh | None,
                      halo_mode: str = "coalesced", aux: bool = False,
                      kernels=None, kernels_diff_params: bool = True):
    """Sharded total-energy fn: (params, graph, positions, strain) -> scalar
    (or (scalar, aux_pytree) with ``aux=True``).

    ``kernels_diff_params`` defaults True (training-safe: loss grads flow
    into model weights through the fused-kernel custom VJPs); the
    force/stress factories below pass False — they differentiate
    positions/strain only, and False keeps the kernel path free of
    weight-cotangent compute and mesh psums (kernels/dispatch).

    ``positions`` is (P, N_cap, 3); only owned rows are read — halo rows are
    refreshed in-jit by the halo exchange so that gradients flow back to the
    owning partition. ``strain`` is a (3, 3) symmetric strain applied to
    positions and lattice (for stress). With ``aux=True`` the model fn must
    return ``(e_atoms, aux)``; aux leaves keep their per-partition leading
    layout ((P, N_cap, ...) outside the shard_map).
    """
    from .halo import validate_halo_mode

    validate_halo_mode(halo_mode)  # fail at build, not first trace

    def local_energy(params, strain, graph_local, positions):
        axis = GRAPH_AXIS if mesh is not None else None
        if not kernels_diff_params:
            # force/stress program: no param grads are ever requested, but
            # the fused kernels' custom VJPs mark every primal perturbed —
            # any param-bound cotangent they emit (embedding tables, node
            # features of the first layer) would cross the shard_map
            # boundary as a replicated-input psum that plain XLA AD never
            # ships. Cut ALL of them here, inside the shard-local fn.
            params = jax.lax.stop_gradient(params)
        lg, _ = local_graph_from_stacked(
            graph_local, axis, halo_mode, kernels=kernels,
            kernels_diff_params=kernels_diff_params)
        dtype = positions.dtype
        with scope("apply_strain"):
            pos, lg.lattice = apply_strain(
                positions[0], lg.lattice.astype(dtype), strain.astype(dtype)
            )
        pos = lg.halo_exchange(pos)
        with scope("model_energy"):
            out = model_energy_fn(params, lg, pos)
        if aux:
            e_atoms, aux_out = out
            aux_out = jax.tree.map(lambda a: a[None], aux_out)
            return lg.owned_sum(e_atoms.reshape(-1, 1)), aux_out
        return lg.owned_sum(out.reshape(-1, 1))

    if mesh is None:
        def total_energy(params, graph, positions, strain):
            if graph.num_partitions != 1:
                raise ValueError(
                    f"mesh=None requires a single-partition graph, got "
                    f"P={graph.num_partitions}; pass mesh=graph_mesh(P)."
                )
            return local_energy(params, strain, graph, positions)
        return total_energy

    def total_energy(params, graph, positions, strain):
        axes = mesh_row_axes(mesh)
        out_specs = (P(), P(axes)) if aux else P()
        sharded = shard_map(
            local_energy,
            mesh=mesh,
            in_specs=(P(), P(), graph_in_specs(graph, axes), P(axes)),
            out_specs=out_specs,
            **_NO_CHECK,
        )
        return sharded(params, strain, graph, positions)

    return total_energy


def make_site_fn(model_site_fn, mesh: Mesh | None,
                 halo_mode: str = "coalesced", kernels=None):
    """Jitted sharded per-atom readout: (params, graph, positions) ->
    (P, N_cap) site values (e.g. CHGNet magmoms — reference
    PESCalculator_Dist's compute_magmom surface, implementations/matgl/
    ase.py:53-127). Halo rows are refreshed in-jit like the energy path;
    reassemble owned rows with HostGraphData.gather_owned.

    .. deprecated::
        This runs a SEPARATE forward pass from the energy program — for
        magmom-every-step MD that doubles device time. Models exposing
        ``energy_and_aux_fn`` (CHGNet) now ride the sitewise readout on the
        energy forward via ``make_potential_fn(..., aux=True)``;
        DistPotential prefers that path automatically. make_site_fn remains
        for models without a fused readout and as the parity oracle for the
        fused path (tests/test_halo_overlap.py)."""
    from .halo import validate_halo_mode

    validate_halo_mode(halo_mode)

    def local_site(params, graph_local, positions):
        axis = GRAPH_AXIS if mesh is not None else None
        # forward-only readout: no grads at all, so no param cotangents
        lg, _ = local_graph_from_stacked(graph_local, axis, halo_mode,
                                         kernels=kernels,
                                         kernels_diff_params=False)
        pos = lg.halo_exchange(positions[0])
        with scope("model_site"):
            return model_site_fn(params, lg, pos)[None]

    if mesh is None:
        @jax.jit
        def site_fn(params, graph, positions):
            if graph.num_partitions != 1:
                raise ValueError(
                    f"mesh=None requires a single-partition graph, got "
                    f"P={graph.num_partitions}; pass mesh=graph_mesh(P).")
            return local_site(params, graph, positions)
        return site_fn

    @jax.jit
    def site_fn(params, graph, positions):
        axes = mesh_row_axes(mesh)
        sharded = shard_map(
            local_site,
            mesh=mesh,
            in_specs=(P(), graph_in_specs(graph, axes), P(axes)),
            out_specs=P(axes),
            **_NO_CHECK,
        )
        return sharded(params, graph, positions)

    return site_fn


def make_potential_fn(model_energy_fn, mesh: Mesh | None,
                      compute_stress: bool = True,
                      halo_mode: str = "coalesced", aux: bool = False,
                      kernels=None):
    """Jitted (params, graph, positions) -> dict(energy, forces, stress).

    forces: (P, N_cap, 3) — per-partition owned rows (reassemble with
    HostGraphData.gather_owned); stress: (3, 3) in eV/Å^3, dE/deps / V.
    With ``aux=True`` (fused site readout) the model fn returns
    ``(e_atoms, aux)`` and the result dict gains an ``"aux"`` pytree of
    (P, N_cap, ...) per-atom outputs computed on the SAME forward pass.
    """
    total_energy = make_total_energy(model_energy_fn, mesh,
                                     halo_mode=halo_mode, aux=aux,
                                     kernels=kernels,
                                     kernels_diff_params=False)

    @jax.jit
    def potential(params, graph, positions):
        strain = jnp.zeros((3, 3), dtype=positions.dtype)
        grad_fn = jax.value_and_grad(
            total_energy,
            argnums=(2, 3) if compute_stress else 2,
            has_aux=aux,
        )
        with scope("energy_and_grad"):
            val, grads = grad_fn(params, graph, positions, strain)
        energy, aux_out = val if aux else (val, None)
        if compute_stress:
            g_pos, g_strain = grads
            with scope("stress"):
                vol = jnp.abs(jnp.linalg.det(graph.lattice.astype(
                    jnp.float64 if graph.lattice.dtype == jnp.float64
                    else positions.dtype)))
                stress = g_strain / vol
        else:
            g_pos = grads
            stress = jnp.zeros((3, 3), dtype=positions.dtype)
        out = {"energy": energy, "forces": -g_pos, "stress": stress}
        if aux:
            out["aux"] = aux_out
        return out

    return potential


def make_packed_energy_fn(model_energy_fn, mesh: Mesh | None = None,
                          diff_params: bool = True,
                          halo_mode: str = "coalesced", kernels=None):
    """Per-structure energies of a packed batch, params-DIFFERENTIABLE.

    ``(params, graph, positions, strain) -> (B_total,)`` energies, where
    ``graph`` is a :func:`distmlip_tpu.partition.pack_structures` pack
    (``mesh=None`` requires the single-partition pack; a 2-D mesh accepts
    the matching (batch x spatial) placement) and ``strain`` is the
    per-structure ``(B_total, 3, 3)`` symmetric strain.

    This is the TRAINING counterpart of
    :func:`make_batched_potential_fn`'s internal energy program: with
    ``diff_params=True`` (default) parameter gradients flow — the loss
    factories in :mod:`distmlip_tpu.train.step` differentiate it twice
    (inner positions/strain grad for forces/stress, outer params grad for
    the update). Not jitted here: callers embed it inside their own jitted
    step (one program per accumulation window).
    """
    local_energy = _local_batched_energy(model_energy_fn, aux=False,
                                         halo_mode=halo_mode,
                                         kernels=kernels,
                                         diff_params=diff_params)

    if mesh is None:
        def packed_energy(params, graph, positions, strain):
            if graph.num_partitions != 1 or graph.batch_size < 1:
                raise ValueError(
                    "make_packed_energy_fn(mesh=None) requires a "
                    f"single-partition packed graph (got "
                    f"P={graph.num_partitions}, "
                    f"batch_size={graph.batch_size}); build it with "
                    "pack_structures(), or pass the 2-D mesh the graph "
                    "was packed for.")
            return local_energy(params, strain, graph, positions)[0]
        return packed_energy

    missing = [ax for ax in (BATCH_AXIS, SPATIAL_AXIS)
               if ax not in mesh.axis_names]
    if missing:
        raise ValueError(
            f"make_packed_energy_fn needs a mesh with named axes "
            f"({BATCH_AXIS!r}, {SPATIAL_AXIS!r}); this mesh "
            f"{tuple(mesh.axis_names)} lacks {missing} — build it with "
            f"parallel.device_mesh(batch, spatial).")
    mesh_bp, mesh_sp = mesh_shape(mesh)

    def packed_energy(params, graph, positions, strain):
        if graph.batch_size < 1 or graph.struct_id is None:
            raise ValueError(
                "make_packed_energy_fn requires a packed graph "
                "(batch_size >= 1); build it with pack_structures().")
        if graph.batch_parts != mesh_bp or graph.spatial_size != mesh_sp:
            raise ValueError(
                f"graph placement {graph.batch_parts}x{graph.spatial_size} "
                f"does not match the {mesh_bp}x{mesh_sp} mesh; pack with "
                f"batch_parts={mesh_bp}, spatial_parts={mesh_sp}.")
        axes = mesh_row_axes(mesh)
        row = P(axes)

        def local_e(params, strain, graph_local, positions):
            return local_energy(params, strain, graph_local, positions)[0]

        sharded = shard_map(
            local_e, mesh=mesh,
            in_specs=(P(), P(BATCH_AXIS), graph_in_specs(graph, axes), row),
            out_specs=P(BATCH_AXIS), **_NO_CHECK)
        return sharded(params, strain, graph, positions)

    return packed_energy


def _local_batched_energy(model_energy_fn, aux, halo_mode="coalesced",
                          kernels=None, diff_params=False):
    """Shard-local batched energy: strain -> halo exchange -> model ->
    per-structure readout. Shared by the single-device packed path and the
    2-D mesh path (where it runs inside shard_map with the spatial axis
    bound).

    ``diff_params=False`` (the batched INFERENCE engine) stop-gradients the
    params — grads are positions/strain only, and the stop keeps the fused
    kernels' custom VJPs free of weight-cotangent compute and mesh psums
    (see make_total_energy). The training path passes True so loss
    gradients flow into the model weights through the same packed program
    (train/step.py)."""

    def local_energy(params, strain, graph_local, positions):
        # graph_local: per-shard (1, ...) slices (or the whole P=1 graph on
        # the meshless path); strain: (B_local, 3, 3) — this batch shard's
        # slots only
        axis = SPATIAL_AXIS if graph_local.spatial_size > 1 else None
        if not diff_params:
            # batched inference engine: grads are positions/strain only —
            # cut param-bound kernel-VJP cotangents before the mesh
            # boundary (see make_total_energy)
            params = jax.lax.stop_gradient(params)
        lg, _ = local_graph_from_stacked(graph_local, axis, halo_mode,
                                         kernels=kernels,
                                         kernels_diff_params=diff_params)
        B = graph_local.batch_size
        dtype = positions.dtype
        pos = positions[0]
        sid = lg.struct_id
        with scope("apply_strain"):
            # per-structure symmetric strain: x_i -> x_i @ (I + eps_{s(i)});
            # Cartesian edge offsets deform with their structure's cell.
            # Padded and halo rows have sid == B — the gather clamps them
            # onto the last real slot, which is harmless (padded rows are
            # masked; halo rows are overwritten by the exchange below with
            # their owner's strained coordinates).
            sym = 0.5 * (strain + jnp.swapaxes(strain, -1, -2)).astype(dtype)
            defm = jnp.eye(3, dtype=dtype)[None, :, :] + sym      # (B, 3, 3)
            pos = jnp.einsum("ni,nij->nj", pos, defm[sid])
            esid = sid[lg.edge_dst]  # edge's structure (dst rows are real)
            lg.edge_offset = jnp.einsum(
                "ei,eij->ej", lg.edge_offset.astype(dtype), defm[esid])
        # spatially partitioned structures refresh their halo rows from the
        # owning slab (strained above); a no-op on S=1 placements
        pos = lg.halo_exchange(pos)
        with scope("model_energy"):
            out = model_energy_fn(params, lg, pos)
        e_atoms, aux_out = out if aux else (out, None)
        with scope("batched_readout"):
            # segment_sum onto batch slots + psum over the SPATIAL axis
            # only — the batch axis never carries a collective
            energies = lg.structure_sum(e_atoms.reshape(-1).astype(dtype))
        return energies, aux_out

    return local_energy


def make_batched_potential_fn(model_energy_fn, compute_stress: bool = True,
                              aux: bool = False, mesh: Mesh | None = None,
                              kernels=None):
    """Jitted batched potential over a block-diagonally packed graph.

    ``(params, graph, positions) -> dict`` where ``graph`` is a
    ``PartitionedGraph`` built by
    :func:`distmlip_tpu.partition.pack_structures` (``batch_size`` slots
    per batch shard, ``struct_id`` per node, Cartesian edge offsets,
    identity lattice):

    - ``energies``: (B_total,) per-structure energies, where ``B_total =
      batch_parts * batch_size`` (flat slot order: shard-major) — ONE
      ``segment_sum(e_atoms, struct_id)`` readout per shard, ``psum``'d
      over the spatial axis (padded rows carry the sentinel slot and are
      dropped); empty slots read 0.
    - ``forces``: (P, N_cap, 3) packed per-atom forces from ONE
      ``value_and_grad`` through the whole super-graph. The blocks share no
      edges, so d(sum_b E_b)/dx_i = dE_{struct(i)}/dx_i exactly — batching
      introduces no cross-terms.
    - ``strain_grad``: (B_total, 3, 3) dE_b/d(strain_b) — each structure
      gets its OWN symmetric strain applied to its positions and
      (Cartesian) edge offsets; divide by per-structure volume on the host
      for stress.
    - ``aux`` (``aux=True``): the model's fused per-atom outputs (packed
      (P, N_cap, ...) layout, slice per structure on the host).

    ``mesh=None`` (default) is the historical single-device path: it
    requires ``P == 1`` and traces NO collectives, so collective counts are
    independent of B (``tools/halo_audit.py --batch`` asserts this).

    With a 2-D ``mesh`` (:func:`distmlip_tpu.parallel.device_mesh`) the
    packed graph may itself be (batch x spatial)-sharded: rows shard over
    ``("batch", "spatial")`` jointly, each packed structure's slabs ride
    the halo ``ppermute`` over the SPATIAL axis only, and per-structure
    energies psum over spatial — the batch axis carries ZERO collectives
    by construction (``tools/halo_audit.py --mesh B,S`` asserts this).
    One executable family covers pure batch-parallel (B x 1), the 1-D ring
    (1 x S) and the mixed B x S placement.
    """
    local_energy = _local_batched_energy(model_energy_fn, aux,
                                         kernels=kernels)

    if mesh is None:
        def batched_energy(params, strain, graph, positions):
            energies, aux_out = local_energy(params, strain, graph,
                                             positions)
            return jnp.sum(energies), (energies, aux_out)

        def check(graph):
            if graph.num_partitions != 1 or graph.batch_size < 1:
                raise ValueError(
                    "make_batched_potential_fn(mesh=None) requires a "
                    f"single-partition packed graph (got "
                    f"P={graph.num_partitions}, "
                    f"batch_size={graph.batch_size}); build it with "
                    "pack_structures(), or pass the 2-D mesh the graph "
                    "was packed for.")
    else:
        # the batched shard_map addresses BOTH named axes (strain/energies
        # shard over "batch"); a user-built mesh missing either name would
        # only fail deep inside jax's axis resolution at first trace
        missing = [ax for ax in (BATCH_AXIS, SPATIAL_AXIS)
                   if ax not in mesh.axis_names]
        if missing:
            raise ValueError(
                f"make_batched_potential_fn needs a mesh with named axes "
                f"({BATCH_AXIS!r}, {SPATIAL_AXIS!r}); this mesh "
                f"{tuple(mesh.axis_names)} lacks {missing} — build it "
                f"with parallel.device_mesh(batch, spatial).")
        mesh_bp, mesh_sp = mesh_shape(mesh)

        def batched_energy(params, strain, graph, positions):
            axes = mesh_row_axes(mesh)
            row = P(axes)
            # strain shards over batch only: every spatial slab of a batch
            # row sees its row's (B_local, 3, 3) slice
            in_specs = (P(), P(BATCH_AXIS), graph_in_specs(graph, axes), row)
            if aux:
                def local_aux(params, strain, graph_local, positions):
                    energies, aux_out = local_energy(
                        params, strain, graph_local, positions)
                    # restore the leading shard axis so aux rows concat
                    # back to the packed (P, N_cap, ...) layout
                    return energies, jax.tree.map(lambda a: a[None], aux_out)

                sharded = shard_map(
                    local_aux, mesh=mesh, in_specs=in_specs,
                    out_specs=(P(BATCH_AXIS), row), **_NO_CHECK)
                energies, aux_out = sharded(params, strain, graph, positions)
            else:
                def local_e(params, strain, graph_local, positions):
                    return local_energy(params, strain, graph_local,
                                        positions)[0]

                sharded = shard_map(
                    local_e, mesh=mesh, in_specs=in_specs,
                    out_specs=P(BATCH_AXIS), **_NO_CHECK)
                energies = sharded(params, strain, graph, positions)
                aux_out = None
            return jnp.sum(energies), (energies, aux_out)

        def check(graph):
            if graph.batch_size < 1 or graph.struct_id is None:
                raise ValueError(
                    "make_batched_potential_fn requires a packed graph "
                    "(batch_size >= 1); build it with pack_structures().")
            if (graph.batch_parts != mesh_bp
                    or graph.spatial_size != mesh_sp):
                raise ValueError(
                    f"graph placement {graph.batch_parts}x"
                    f"{graph.spatial_size} does not match the "
                    f"{mesh_bp}x{mesh_sp} mesh; pack with "
                    f"batch_parts={mesh_bp}, spatial_parts={mesh_sp}.")

    @jax.jit
    def potential(params, graph, positions):
        check(graph)
        B_total = graph.batch_parts * graph.batch_size
        strain = jnp.zeros((B_total, 3, 3), dtype=positions.dtype)
        grad_fn = jax.value_and_grad(
            batched_energy, argnums=(3, 1) if compute_stress else 3,
            has_aux=True)
        with scope("energy_and_grad"):
            (_, (energies, aux_out)), grads = grad_fn(
                params, strain, graph, positions)
        if compute_stress:
            g_pos, g_strain = grads
        else:
            g_pos = grads
            g_strain = jnp.zeros((B_total, 3, 3), dtype=positions.dtype)
        out = {"energies": energies, "forces": -g_pos,
               "strain_grad": g_strain}
        if aux:
            out["aux"] = aux_out
        return out

    return potential
