"""Collective-count auditing of traced programs — compatibility shim.

The jaxpr-walking machinery that used to live here is now
:mod:`distmlip_tpu.analysis.ir` (one walker shared by every contract
pass); this module keeps the historical audit API — the
``collective_count`` telemetry field, the jaxpr-level regression tests
(tests/test_halo_overlap.py, tests/test_mesh2d.py) and the
``tools/halo_audit.py`` CLI all import from here and keep working
unchanged. New invariants should be written as
:class:`distmlip_tpu.analysis.ContractPass`es, not as new counters here.
"""

from __future__ import annotations

from collections import Counter

import jax

from ..analysis.ir import (  # noqa: F401  (re-exported API)
    COLLECTIVE_PRIMS,
    collectives_by_axis,
    count_collectives,
    count_primitives,
    eqn_axis_names as _eqn_axis_names,
    iter_eqns as _iter_eqns,
    is_host_sync,
    sub_jaxprs as _sub_jaxprs,
)


def collective_counts(fn, *args, **kwargs) -> Counter:
    """Trace ``fn(*args, **kwargs)`` (without executing it) and count its
    collectives."""
    return count_collectives(jax.make_jaxpr(fn)(*args, **kwargs))


def count_host_callbacks(closed_jaxpr) -> Counter:
    """Counter of host-callback/transfer primitives in a traced program.

    A program that should be fully device-resident (the DeviceMD chunk
    with its in-loop neighbor rebuild) must show an EMPTY counter: any
    ``pure_callback``/``io_callback``/infeed/outfeed would stall the
    accelerator on the host mid-loop. The ``host_sync`` contract pass is
    the registered form of this check."""
    counts: Counter = Counter()
    for eqn in _iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if is_host_sync(name) and "debug_print" not in name:
            counts[name] += 1
    return counts


def axis_collective_count(closed_jaxpr, axis_name: str) -> int:
    """Total collectives attributed to one mesh axis (0 = the axis is
    communication-free, the batch-axis acceptance gate)."""
    counts = collectives_by_axis(closed_jaxpr).get(str(axis_name))
    return int(sum(counts.values())) if counts else 0


def ppermutes_by_scope(closed_jaxpr) -> Counter:
    """Counter of name-stack string -> ppermute count (best effort: name
    stacks are source metadata and may be absent on some jax builds, in
    which case everything lands under "<unknown>")."""
    by_scope: Counter = Counter()
    for eqn in _iter_eqns(closed_jaxpr):
        if eqn.primitive.name not in ("ppermute", "collective_permute"):
            continue
        try:
            scope = str(eqn.source_info.name_stack) or "<toplevel>"
        except Exception:  # noqa: BLE001 - metadata is optional
            scope = "<unknown>"
        by_scope[scope] += 1
    return by_scope
