"""Collective-count auditing of traced programs.

The overlap-aware halo pipeline's first-order win is COUNT: one coalesced
``ppermute`` per ring shift per sync point instead of one per (shift,
array), and zero extra forwards for sitewise readouts. This module makes
that measurable without a chip — it walks a traced jaxpr (recursing into
pjit/remat/scan/cond sub-jaxprs) and tallies collective primitives, with a
best-effort grouping by ``jax.named_scope`` name stacks so the per-layer
structure is visible. Feeds the ``collective_count`` telemetry field, the
jaxpr-level regression tests (tests/test_halo_overlap.py) and the
``tools/halo_audit.py`` CLI.
"""

from __future__ import annotations

from collections import Counter

import jax

# collective primitives the graph runtime can emit (names as they appear
# in jaxprs across the jax versions this repo supports)
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "psum", "psum2", "all_gather", "all_to_all",
    "reduce_scatter", "pmax", "pmin", "pgather", "collective_permute",
})


def _iter_eqns(jaxpr):
    """Yield every eqn in ``jaxpr`` and all nested sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from _iter_eqns(sub)


def _sub_jaxprs(params):
    """Collect Jaxpr/ClosedJaxpr values from an eqn's params (fallback for
    jax versions without jax.core.jaxprs_in_params)."""
    out = []

    def visit(v):
        if hasattr(v, "eqns"):           # Jaxpr
            out.append(v)
        elif hasattr(v, "jaxpr"):        # ClosedJaxpr
            out.append(v.jaxpr)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    for v in params.values():
        visit(v)
    return out


def count_collectives(closed_jaxpr) -> Counter:
    """Counter of collective primitive name -> occurrence count over the
    whole program (nested jaxprs included). scan bodies count ONCE per
    trace — multiply by trip count yourself if you need dynamic totals."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    counts: Counter = Counter()
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            counts[name] += 1
    return counts


def collective_counts(fn, *args, **kwargs) -> Counter:
    """Trace ``fn(*args, **kwargs)`` (without executing it) and count its
    collectives."""
    return count_collectives(jax.make_jaxpr(fn)(*args, **kwargs))


def count_host_callbacks(closed_jaxpr) -> Counter:
    """Counter of host-callback/transfer primitives in a traced program.

    A program that should be fully device-resident (the DeviceMD chunk
    with its in-loop neighbor rebuild) must show an EMPTY counter: any
    ``pure_callback``/``io_callback``/infeed/outfeed would stall the
    accelerator on the host mid-loop. Substring matching on "callback"
    keeps this robust across jax versions' primitive renames."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    counts: Counter = Counter()
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if ("callback" in name or "infeed" in name or "outfeed" in name
                or name == "host_local_array_to_global_array"):
            counts[name] += 1
    return counts


def count_primitives(closed_jaxpr, names) -> Counter:
    """Occurrences of specific primitive names (nested jaxprs included) —
    e.g. ``{"while", "sort"}`` to assert a rebuild lowered INTO the MD
    loop rather than around it."""
    names = frozenset(names)
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    counts: Counter = Counter()
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name in names:
            counts[eqn.primitive.name] += 1
    return counts


def _eqn_axis_names(eqn) -> tuple:
    """Mesh axis names a collective eqn operates over, from its params.

    Collective primitives carry the axis under different param names across
    primitives and jax versions (``axis_name`` for ppermute/all_gather,
    ``axes`` for psum/pmax, sometimes ``axis_index_groups`` alongside);
    values may be a single name or a tuple. Returns ``("<unknown>",)`` when
    no axis metadata is present.
    """
    for key in ("axis_name", "axes", "named_axes"):
        val = eqn.params.get(key)
        if val is None:
            continue
        if isinstance(val, (tuple, list, frozenset, set)):
            named = tuple(v for v in val if isinstance(v, (str, int)))
            if named or not val:
                # an EMPTY axes tuple is a no-op psum (identity) the
                # partial evaluator sometimes leaves behind — attribute it
                # to no axis. A NON-empty tuple of unparseable axis objects
                # must NOT vanish: fall through to "<unknown>" so the
                # --mesh silence gate fails loudly instead of vacuously.
                return named
        elif isinstance(val, (str, int)):
            return (val,)
        break
    return ("<unknown>",)


def collectives_by_axis(closed_jaxpr) -> dict:
    """``{axis_name: Counter(primitive -> count)}`` over the whole program.

    The 2-D mesh invariant this feeds (``tools/halo_audit.py --mesh``): the
    ``"batch"`` axis must carry ZERO collectives — batched structures are
    block-diagonal, so all communication (halo ``ppermute``, readout
    ``psum``) belongs to the ``"spatial"`` axis. A collective naming both
    axes counts against both (it would already be a violation).
    """
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    by_axis: dict[str, Counter] = {}
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        for ax in _eqn_axis_names(eqn):
            by_axis.setdefault(str(ax), Counter())[name] += 1
    return by_axis


def axis_collective_count(closed_jaxpr, axis_name: str) -> int:
    """Total collectives attributed to one mesh axis (0 = the axis is
    communication-free, the batch-axis acceptance gate)."""
    counts = collectives_by_axis(closed_jaxpr).get(str(axis_name))
    return int(sum(counts.values())) if counts else 0


def ppermutes_by_scope(closed_jaxpr) -> Counter:
    """Counter of name-stack string -> ppermute count (best effort: name
    stacks are source metadata and may be absent on some jax builds, in
    which case everything lands under "<unknown>")."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    by_scope: Counter = Counter()
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name not in ("ppermute", "collective_permute"):
            continue
        try:
            scope = str(eqn.source_info.name_stack) or "<toplevel>"
        except Exception:  # noqa: BLE001 - metadata is optional
            scope = "<unknown>"
        by_scope[scope] += 1
    return by_scope
