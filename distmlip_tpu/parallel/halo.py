"""Halo exchange and the LocalGraph shard view.

The halo exchange is the TPU-native replacement of the reference's in-place
cross-GPU slice copies (reference dist.py:323-358): inside ``shard_map``,
each partition gathers its "to_q" rows into a fixed-capacity payload, rotates
it around the ring with ``jax.lax.ppermute`` (ICI neighbor traffic for slab
decompositions), and scatters the received payload into its "from" slots.
Padded recv indices point one past the array end, so XLA's
drop-out-of-bounds scatter discards them. ``jax.grad`` transposes the
ppermute automatically, which is exactly the reverse force flow the reference
gets from torch autograd through device copies (reference pes.py:121-124).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..telemetry import scope

if hasattr(lax, "axis_size"):  # jax >= 0.6
    _axis_size = lax.axis_size
else:  # 0.4.x: axis_frame(name) resolves to the (static) size
    def _axis_size(axis_name):
        frame = jax.core.axis_frame(axis_name)
        return getattr(frame, "size", frame)


def _exchange(feats, send_idx, send_mask, recv_idx, shifts, axis_name):
    """One round of halo exchange on a local feature array (N_cap, ...)."""
    if not shifts or axis_name is None:
        return feats
    n_dev = _axis_size(axis_name)
    for si, shift in enumerate(shifts):
        with scope(f"halo/shift{shift}"):
            idx = send_idx[si]
            mask = send_mask[si]
            payload = feats[idx]
            m = mask.astype(feats.dtype).reshape(
                mask.shape + (1,) * (feats.ndim - 1))
            payload = payload * m
            perm = [(p, (p + shift) % n_dev) for p in range(n_dev)]
            with scope("ppermute"):
                received = lax.ppermute(payload, axis_name, perm)
            feats = feats.at[recv_idx[si]].set(received, mode="drop")
    return feats


@dataclass
class LocalGraph:
    """Per-shard view of a PartitionedGraph (leading P axis squeezed away).

    Passed to model functions inside ``shard_map``; carries the local edge
    lists, masks, halo tables, and the collective axis name. Models call the
    methods below instead of touching collectives directly.
    """

    axis_name: str | None
    shifts: tuple
    n_cap: int
    e_cap: int
    b_cap: int
    species: Any
    node_mask: Any
    owned_mask: Any
    edge_src: Any
    edge_dst: Any       # CONTRACT: nondecreasing (models rely on
    edge_offset: Any    # indices_are_sorted=True segment sums); same for
    edge_mask: Any      # line_dst — established by build_partitioned_graph
    halo_send_idx: Any
    halo_send_mask: Any
    halo_recv_idx: Any
    lattice: Any
    # bond graph
    has_bond_graph: bool = False
    line_src: Any = None
    line_dst: Any = None
    line_mask: Any = None
    line_center: Any = None
    bond_map_edge: Any = None
    bond_map_bond: Any = None
    bond_map_mask: Any = None
    bond_halo_send_idx: Any = None
    bond_halo_send_mask: Any = None
    bond_halo_recv_idx: Any = None
    system: Any = None  # replicated per-system scalars (charge/spin/dataset)

    # ---- collectives ----
    def halo_exchange(self, feats):
        """Refresh halo (from-section) rows of a node feature array."""
        with scope("halo_exchange"):
            return _exchange(
                feats, self.halo_send_idx, self.halo_send_mask,
                self.halo_recv_idx, self.shifts, self.axis_name,
            )

    def bond_halo_exchange(self, feats):
        """Refresh halo rows of a bond-node feature array."""
        if not self.has_bond_graph:
            return feats
        with scope("bond_halo_exchange"):
            return _exchange(
                feats, self.bond_halo_send_idx, self.bond_halo_send_mask,
                self.bond_halo_recv_idx, self.shifts, self.axis_name,
            )

    def psum(self, x):
        if self.axis_name is None:
            return x
        return lax.psum(x, self.axis_name)

    # ---- geometry ----
    def edge_vectors(self, positions, lattice=None):
        """(E_cap, 3) displacement vectors dst - src + offsets @ lattice."""
        lat = self.lattice if lattice is None else lattice
        disp = positions[self.edge_dst] - positions[self.edge_src]
        return disp + self.edge_offset.astype(positions.dtype) @ lat

    # ---- bond-graph index remaps (reference dist.py:635-702 analogue) ----
    def edge_to_bond(self, edge_feats, bond_feats):
        """Seed owned bond-node rows from their atom-graph edge features."""
        with scope("edge_to_bond"):
            vals = edge_feats[self.bond_map_edge]
            m = self.bond_map_mask
            vals = vals * m.astype(vals.dtype).reshape(
                m.shape + (1,) * (vals.ndim - 1))
            idx = jnp.where(m, self.bond_map_bond, self.b_cap)
            return bond_feats.at[idx].set(vals, mode="drop")

    def bond_to_edge(self, bond_feats, edge_feats):
        """Write owned bond-node features back onto their edges."""
        with scope("bond_to_edge"):
            vals = bond_feats[self.bond_map_bond]
            m = self.bond_map_mask
            vals = vals * m.astype(vals.dtype).reshape(
                m.shape + (1,) * (vals.ndim - 1))
            idx = jnp.where(m, self.bond_map_edge, self.e_cap)
            return edge_feats.at[idx].set(vals, mode="drop")

    # ---- reductions ----
    def owned_sum(self, per_atom):
        """Sum a per-atom quantity over owned nodes, reduced across the mesh."""
        with scope("owned_sum"):
            m = self.owned_mask.astype(per_atom.dtype)
            local = jnp.sum(
                per_atom * m.reshape(m.shape + (1,) * (per_atom.ndim - 1)))
            return self.psum(local)


def local_graph_from_stacked(g, axis_name: str | None) -> tuple[LocalGraph, Any]:
    """Build a LocalGraph from shard-local (1, ...) slices of a PartitionedGraph.

    Returns (local_graph, positions_local) where positions keep their leading
    1-axis squeezed.
    """
    sq = lambda a: a[0] if a is not None and hasattr(a, "shape") and a.ndim >= 1 else a
    lg = LocalGraph(
        axis_name=axis_name,
        shifts=g.shifts,
        n_cap=g.n_cap,
        e_cap=g.e_cap,
        b_cap=g.b_cap,
        species=sq(g.species),
        node_mask=sq(g.node_mask),
        owned_mask=sq(g.owned_mask),
        edge_src=sq(g.edge_src),
        edge_dst=sq(g.edge_dst),
        edge_offset=sq(g.edge_offset),
        edge_mask=sq(g.edge_mask),
        halo_send_idx=g.halo_send_idx[:, 0],
        halo_send_mask=g.halo_send_mask[:, 0],
        halo_recv_idx=g.halo_recv_idx[:, 0],
        lattice=g.lattice,
        has_bond_graph=g.has_bond_graph,
        line_src=sq(g.line_src),
        line_dst=sq(g.line_dst),
        line_mask=sq(g.line_mask),
        line_center=sq(g.line_center),
        bond_map_edge=sq(g.bond_map_edge),
        bond_map_bond=sq(g.bond_map_bond),
        bond_map_mask=sq(g.bond_map_mask),
        bond_halo_send_idx=g.bond_halo_send_idx[:, 0],
        bond_halo_send_mask=g.bond_halo_send_mask[:, 0],
        bond_halo_recv_idx=g.bond_halo_recv_idx[:, 0],
        system=g.system,
    )
    return lg, sq(g.positions)
