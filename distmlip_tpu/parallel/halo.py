"""Halo exchange and the LocalGraph shard view.

The halo exchange is the TPU-native replacement of the reference's in-place
cross-GPU slice copies (reference dist.py:323-358): inside ``shard_map``,
each partition gathers its "to_q" rows into a fixed-capacity payload, rotates
it around the ring with ``jax.lax.ppermute`` (ICI neighbor traffic for slab
decompositions), and scatters the received payload into its "from" slots.
Padded recv indices point one past the array end, so XLA's
drop-out-of-bounds scatter discards them. ``jax.grad`` transposes the
ppermute automatically, which is exactly the reverse force flow the reference
gets from torch autograd through device copies (reference pes.py:121-124).

Two exchange implementations coexist behind ``halo_mode``:

- ``"coalesced"`` (default): ONE ``ppermute`` per ring shift per sync
  point, no matter how many feature arrays are refreshed together. All
  arrays' masked payloads are flattened and concatenated into a single
  flat buffer per shift (atom + bond features ride the same collective),
  and all shifts' received rows land in one scatter. This is the payload
  half of the overlap-aware pipeline: fewer, larger collectives expose the
  latency XLA's async-collective scheduler can hide behind interior edge
  compute (see ``LocalGraph.overlapped_edge_sum``).
- ``"legacy"``: the historical per-shift, per-array loop — one gather /
  ppermute / scatter round per (shift, array). Kept for A/B equivalence
  testing; results are identical (set-scatter of the same rows).

The two orders are interchangeable because send rows are always OWNED
locals and recv slots are always HALO locals — no scatter ever feeds a
later gather within one sync point.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..kernels.dispatch import (Gather, fused_edge_aggregate,
                                fused_segment_sum)
from ..telemetry import scope

HALO_MODES = ("coalesced", "legacy")


def validate_halo_mode(halo_mode: str) -> str:
    """Shared guard for every halo_mode entry point; returns the mode."""
    if halo_mode not in HALO_MODES:
        raise ValueError(
            f"halo_mode={halo_mode!r}: expected one of {HALO_MODES}")
    return halo_mode

if hasattr(lax, "axis_size"):  # jax >= 0.6
    _axis_size = lax.axis_size
else:  # 0.4.x: axis_frame(name) resolves to the (static) size
    def _axis_size(axis_name):
        frame = jax.core.axis_frame(axis_name)
        return getattr(frame, "size", frame)


def _exchange(feats, send_idx, send_mask, recv_idx, shifts, axis_name):
    """Legacy round: one gather->ppermute->scatter per shift (S collectives
    per array)."""
    if not shifts or axis_name is None:
        return feats
    n_dev = _axis_size(axis_name)
    for si, shift in enumerate(shifts):
        with scope(f"halo/shift{shift}"):
            idx = send_idx[si]
            mask = send_mask[si]
            payload = feats[idx]
            m = mask.astype(feats.dtype).reshape(
                mask.shape + (1,) * (feats.ndim - 1))
            payload = payload * m
            perm = [(p, (p + shift) % n_dev) for p in range(n_dev)]
            with scope("ppermute"):
                received = lax.ppermute(payload, axis_name, perm)
            feats = feats.at[recv_idx[si]].set(received, mode="drop")
    return feats


def _coalesced_round(groups, shifts, axis_name):
    """Coalesced round: ONE ppermute per ring shift for ALL groups.

    ``groups``: list of ``(feats, send_idx, send_mask, recv_idx)`` with
    per-shift tables shaped (S, H). Every group's masked payload is
    flattened to (S, H*F) and concatenated into one (S, sum H*F) buffer —
    mixed feature widths cost nothing (flat concat, no padding) and mixed
    dtypes are promoted to the widest (bf16 rides fp32 losslessly) and cast
    back on receive. Returns the updated feats list.

    Valid because send rows are owned locals and recv slots are halo
    locals: gathering every payload before any scatter reads exactly the
    rows the legacy sequential loop reads.
    """
    if not shifts or axis_name is None:
        return [g[0] for g in groups]
    n_dev = _axis_size(axis_name)
    S = len(shifts)
    dtype = jnp.result_type(*[g[0].dtype for g in groups])
    flats, shapes = [], []
    for feats, send_idx, send_mask, _ in groups:
        payload = feats[send_idx]                      # (S, H, *F)
        m = send_mask.astype(feats.dtype).reshape(
            send_mask.shape + (1,) * (feats.ndim - 1))
        payload = payload * m
        shapes.append(payload.shape)
        flats.append(payload.astype(dtype).reshape(S, -1))
    with scope("halo/coalesce"):
        buf = flats[0] if len(flats) == 1 else jnp.concatenate(flats, axis=1)
    received = []
    for si, shift in enumerate(shifts):
        perm = [(p, (p + shift) % n_dev) for p in range(n_dev)]
        with scope(f"halo/shift{shift}"), scope("ppermute"):
            received.append(lax.ppermute(buf[si], axis_name, perm))
    recv = received[0][None] if S == 1 else jnp.stack(received)  # (S, total)
    out, off = [], 0
    for (feats, _, _, recv_idx), shp in zip(groups, shapes):
        sz = 1
        for d in shp[1:]:
            sz *= int(d)
        seg = recv[:, off:off + sz].reshape(shp).astype(feats.dtype)
        off += sz
        # one scatter across all shifts: from-sections are disjoint per
        # source partition; padded slots point past the array end (dropped)
        rows = seg.reshape((-1,) + shp[2:])
        out.append(feats.at[recv_idx.reshape(-1)].set(rows, mode="drop"))
    return out


@dataclass
class LocalGraph:
    """Per-shard view of a PartitionedGraph (leading P axis squeezed away).

    Passed to model functions inside ``shard_map``; carries the local edge
    lists, masks, halo tables, and the collective axis name. Models call the
    methods below instead of touching collectives directly.

    Edge layout contract: ``edge_dst`` is nondecreasing within each of the
    interior ``[0, e_split)`` and frontier ``[e_split, e_cap)`` segments
    (``indices_are_sorted`` segment sums per segment — use
    ``aggregate_edges``/``overlapped_edge_sum``, never a raw full-array
    sorted segment sum when ``has_frontier_split``). Interior edges read
    only owned rows; frontier edges read halo src rows. Same contract for
    ``line_dst`` (unsplit, globally sorted).
    """

    axis_name: str | None
    shifts: tuple
    n_cap: int
    e_cap: int
    b_cap: int
    species: Any
    node_mask: Any
    owned_mask: Any
    edge_src: Any
    edge_dst: Any       # CONTRACT: nondecreasing within each edge segment
    edge_offset: Any    # (see class docstring); line_dst globally sorted —
    edge_mask: Any      # established by build_partitioned_graph
    halo_send_idx: Any
    halo_send_mask: Any
    halo_recv_idx: Any
    lattice: Any
    # bond graph
    has_bond_graph: bool = False
    line_src: Any = None
    line_dst: Any = None
    line_mask: Any = None
    line_center: Any = None
    bond_map_edge: Any = None
    bond_map_bond: Any = None
    bond_map_mask: Any = None
    bond_halo_send_idx: Any = None
    bond_halo_send_mask: Any = None
    bond_halo_recv_idx: Any = None
    system: Any = None  # replicated per-system scalars (charge/spin/dataset)
    # interior/frontier edge split (PartitionedGraph.e_split); < 0 or
    # == e_cap means unsplit
    e_split: int = -1
    halo_mode: str = "coalesced"
    # batched multi-structure packing (PartitionedGraph.batch_size /
    # struct_id); 0 = unbatched. Models never need these — the per-
    # structure readout lives in the batched runtime — but they ride the
    # LocalGraph so the runtime sees them inside the traced function.
    batch_size: int = 0
    struct_id: Any = None
    # Pallas kernel routing for the aggregation helpers below and the
    # models' own dispatch calls: None = env/backend default, False =
    # force the pure-XLA path, "interpret" = interpreter-mode kernels
    # (kernels/dispatch.resolve_kernel_mode)
    kernels: Any = None
    # whether fused-kernel custom VJPs propagate gradients into model
    # parameters (edge-MLP weights, SO(2) stacks). Training programs need
    # True; force/stress programs pass False so the kernel path emits no
    # weight-cotangent work or replicated-input psums (see
    # kernels/dispatch.fused_edge_aggregate)
    kernels_diff_params: bool = True

    @property
    def has_frontier_split(self) -> bool:
        return 0 <= self.e_split < self.e_cap

    def _node_tables(self):
        return (self.halo_send_idx, self.halo_send_mask, self.halo_recv_idx)

    def _bond_tables(self):
        return (self.bond_halo_send_idx, self.bond_halo_send_mask,
                self.bond_halo_recv_idx)

    # ---- collectives ----
    def halo_exchange(self, feats):
        """Refresh halo (from-section) rows of a node feature array."""
        with scope("halo_exchange"):
            if self.halo_mode == "legacy":
                return _exchange(feats, *self._node_tables(), self.shifts,
                                 self.axis_name)
            return _coalesced_round([(feats,) + self._node_tables()],
                                    self.shifts, self.axis_name)[0]

    def bond_halo_exchange(self, feats):
        """Refresh halo rows of a bond-node feature array."""
        if not self.has_bond_graph:
            return feats
        with scope("bond_halo_exchange"):
            if self.halo_mode == "legacy":
                return _exchange(feats, *self._bond_tables(), self.shifts,
                                 self.axis_name)
            return _coalesced_round([(feats,) + self._bond_tables()],
                                    self.shifts, self.axis_name)[0]

    def exchange_all(self, node_feats=(), bond_feats=()):
        """Refresh several feature arrays at one sync point.

        In ``"coalesced"`` mode every array rides the SAME ppermute (one
        collective per ring shift total — CHGNet's per-block atom+bond
        refresh pays 1 instead of 2); in ``"legacy"`` mode this is just the
        per-array loop. Returns ``(node_feats_out, bond_feats_out)`` tuples
        in input order. Bond arrays pass through untouched when the graph
        has no bond graph.
        """
        node_feats = tuple(node_feats)
        bond_feats = tuple(bond_feats)
        use_bond = self.has_bond_graph
        if self.axis_name is None or not self.shifts:
            return node_feats, bond_feats
        with scope("halo_exchange_all"):
            if self.halo_mode == "legacy":
                nodes = tuple(
                    _exchange(f, *self._node_tables(), self.shifts,
                              self.axis_name) for f in node_feats)
                bonds = tuple(
                    _exchange(f, *self._bond_tables(), self.shifts,
                              self.axis_name) if use_bond else f
                    for f in bond_feats)
                return nodes, bonds
            groups = [(f,) + self._node_tables() for f in node_feats]
            groups += [(f,) + self._bond_tables()
                       for f in bond_feats if use_bond]
            if not groups:
                return node_feats, bond_feats
            out = _coalesced_round(groups, self.shifts, self.axis_name)
            nodes = tuple(out[: len(node_feats)])
            if use_bond:
                bonds = tuple(out[len(node_feats):])
            else:
                bonds = bond_feats
            return nodes, bonds

    def psum(self, x):
        if self.axis_name is None:
            return x
        return lax.psum(x, self.axis_name)

    # ---- geometry ----
    def edge_vectors(self, positions, lattice=None):
        """(E_cap, 3) displacement vectors dst - src + offsets @ lattice."""
        lat = self.lattice if lattice is None else lattice
        disp = positions[self.edge_dst] - positions[self.edge_src]
        return disp + self.edge_offset.astype(positions.dtype) @ lat

    # ---- edge aggregation (interior/frontier aware) ----
    def aggregate_edges(self, data, mask=None):
        """Segment-sum per-edge rows onto their dst nodes ((n_cap, ...)).

        Honors the interior/frontier layout: each segment is dst-sorted,
        the concatenation is NOT — so the sorted fast path runs per
        segment. This is the drop-in replacement for the historical
        full-array ``masked_segment_sum(..., indices_are_sorted=True)``.
        Routes through the kernel dispatcher: on the Pallas path the
        masked scatter runs as the dst-tiled fused kernel.
        """
        if not self.has_frontier_split:
            return fused_segment_sum(data, self.edge_dst, self.n_cap, mask,
                                     indices_are_sorted=True,
                                     kernels=self.kernels)
        s = self.e_split
        out = fused_segment_sum(
            data[:s], self.edge_dst[:s], self.n_cap,
            None if mask is None else mask[:s], indices_are_sorted=True,
            kernels=self.kernels)
        return out + fused_segment_sum(
            data[s:], self.edge_dst[s:], self.n_cap,
            None if mask is None else mask[s:], indices_are_sorted=True,
            kernels=self.kernels)

    def aggregate_edge_messages(self, msg_fn, edge_inputs, mask=None):
        """Fused per-edge compute + dst aggregation ((n_cap, ...)).

        ``msg_fn(*rows) -> (E, ...)`` messages from per-edge inputs;
        ``edge_inputs`` may mix per-edge arrays with
        :class:`distmlip_tpu.kernels.Gather` markers (node-array rows
        gathered at per-edge indices). Honors the interior/frontier
        layout like :meth:`aggregate_edges`. On the Pallas path the
        gather, the message compute and the dst scatter fuse per dst
        tile and the ``(E, width)`` message tensor never materializes;
        the XLA path computes ``msg_fn`` on the full edge arrays and
        segment-sums with the sorted hint (the historical program).
        """
        if not self.has_frontier_split:
            return fused_edge_aggregate(
                msg_fn, edge_inputs, self.edge_dst, self.n_cap, mask,
                indices_are_sorted=True, kernels=self.kernels,
                diff_params=self.kernels_diff_params)
        out = None
        for sl in (slice(0, self.e_split), slice(self.e_split, None)):
            sliced = [Gather(i.node, i.idx[sl]) if isinstance(i, Gather)
                      else i[sl] for i in edge_inputs]
            part = fused_edge_aggregate(
                msg_fn, sliced, self.edge_dst[sl], self.n_cap,
                None if mask is None else mask[sl],
                indices_are_sorted=True, kernels=self.kernels,
                diff_params=self.kernels_diff_params)
            out = part if out is None else out + part
        return out

    def chunk_sorted(self, chunk: int) -> bool:
        """Whether every ``chunk``-row slice of ``edge_dst`` is
        nondecreasing — the per-chunk ``indices_are_sorted`` hint for the
        edge-chunked models (MACE/eSCN). True when the layout is unsplit or
        the split boundary lands on a chunk boundary; otherwise exactly one
        chunk straddles the interior->frontier reset and the hint must be
        dropped (correctness over the scatter fast path)."""
        if not self.has_frontier_split or chunk <= 0:
            return True
        return self.e_split % chunk == 0

    def overlapped_edge_sum(self, msg_fn, v_pre, v_post, edge_data=(),
                            mask=None):
        """Per-edge messages summed to dst with interior/frontier split
        scheduling.

        ``v_post = halo_exchange(v_pre)`` is the freshly exchanged node
        array. Interior edges gather src AND dst from ``v_pre`` (identical
        rows — both endpoints are owned — but data-independent of the
        in-flight ppermute), so XLA's async-collective scheduler can run
        their gathers, GEMMs and segment sum while the exchange is on the
        wire; frontier edges run on ``v_post`` after it lands.

        ``msg_fn(v_src, v_dst, *edge_slices) -> (rows, ...)`` is invoked
        once per segment; ``edge_data`` arrays are sliced alongside.
        """
        with scope("overlapped_edge_sum"):
            if not self.has_frontier_split:
                return fused_edge_aggregate(
                    msg_fn,
                    [Gather(v_post, self.edge_src),
                     Gather(v_post, self.edge_dst), *edge_data],
                    self.edge_dst, self.n_cap, mask,
                    indices_are_sorted=True, kernels=self.kernels,
                    diff_params=self.kernels_diff_params)
            s = self.e_split
            out = None
            for name, sl, v in (("interior", slice(0, s), v_pre),
                                ("frontier", slice(s, None), v_post)):
                with scope(f"edges/{name}"):
                    # dst rows are always owned: read them from v_pre in
                    # BOTH segments so only the frontier src gather waits
                    # on the collective
                    part = fused_edge_aggregate(
                        msg_fn,
                        [Gather(v, self.edge_src[sl]),
                         Gather(v_pre, self.edge_dst[sl]),
                         *[d[sl] for d in edge_data]],
                        self.edge_dst[sl], self.n_cap,
                        None if mask is None else mask[sl],
                        indices_are_sorted=True, kernels=self.kernels,
                        diff_params=self.kernels_diff_params)
                out = part if out is None else out + part
            return out

    # ---- bond-graph index remaps (reference dist.py:635-702 analogue) ----
    def edge_to_bond(self, edge_feats, bond_feats):
        """Seed owned bond-node rows from their atom-graph edge features.

        ``bond_map_bond`` is ascending by construction (arange of owned
        bonds per structure, block offsets ascending in the packed case)
        and the mask sentinel ``b_cap`` exceeds every real id, so the
        scatter rides the sorted fast path (scatter_hints contract).
        """
        with scope("edge_to_bond"):
            vals = edge_feats[self.bond_map_edge]
            m = self.bond_map_mask
            vals = vals * m.astype(vals.dtype).reshape(
                m.shape + (1,) * (vals.ndim - 1))
            idx = jnp.where(m, self.bond_map_bond, self.b_cap)
            return bond_feats.at[idx].set(vals, mode="drop",
                                          indices_are_sorted=True)

    def bond_to_edge(self, bond_feats, edge_feats):
        """Write owned bond-node features back onto their edges.

        ``bond_map_edge`` is bond-node-ordered, NOT edge-ordered — the
        scatter is legitimately unsorted (audited; sorting would need a
        second, edge-ordered copy of the map pair in the graph layout).
        """
        with scope("bond_to_edge"):
            vals = bond_feats[self.bond_map_bond]
            m = self.bond_map_mask
            vals = vals * m.astype(vals.dtype).reshape(
                m.shape + (1,) * (vals.ndim - 1))
            idx = jnp.where(m, self.bond_map_edge, self.e_cap)
            # contract: allow(scatter_hints)
            return edge_feats.at[idx].set(vals, mode="drop")

    # ---- reductions ----
    def structure_sum(self, per_atom):
        """Per-structure sums of a per-atom quantity on a packed graph.

        Axis-scoped batched readout: one masked ``segment_sum`` onto the
        shard's ``batch_size`` structure slots (owned rows only — halo and
        padded rows carry the ``batch_size`` sentinel and drop), then a
        ``psum`` over the SPATIAL axis so every slab of a spatially
        partitioned structure contributes. The batch axis is never
        touched: batch rows hold disjoint structures, so their readout is
        pure concatenation (shard_map out_specs), not communication.
        Returns ``(batch_size,)`` in ``per_atom``'s dtype.
        """
        if self.struct_id is None or self.batch_size <= 0:
            raise ValueError(
                "structure_sum requires a packed graph (struct_id + "
                "batch_size); build it with pack_structures()")
        with scope("structure_sum"):
            e = jnp.where(self.owned_mask, per_atom.reshape(-1), 0)
            out = jax.ops.segment_sum(
                e, self.struct_id, num_segments=self.batch_size,
                indices_are_sorted=True)
            return self.psum(out)

    def owned_sum(self, per_atom):
        """Sum a per-atom quantity over owned nodes, reduced across the mesh."""
        with scope("owned_sum"):
            m = self.owned_mask.astype(per_atom.dtype)
            local = jnp.sum(
                per_atom * m.reshape(m.shape + (1,) * (per_atom.ndim - 1)))
            return self.psum(local)


def local_graph_from_stacked(
    g, axis_name: str | None, halo_mode: str = "coalesced", kernels=None,
    kernels_diff_params: bool = True,
) -> tuple[LocalGraph, Any]:
    """Build a LocalGraph from shard-local (1, ...) slices of a PartitionedGraph.

    Returns (local_graph, positions_local) where positions keep their leading
    1-axis squeezed. ``halo_mode`` selects the exchange implementation
    (``"coalesced"`` | ``"legacy"``, see module docstring); ``kernels``
    is the Pallas-kernel routing flag the aggregation helpers dispatch on
    (None = env/backend default, False = pure XLA, "interpret" = the
    chip-free interpreter kernels); ``kernels_diff_params`` is whether
    kernel custom VJPs propagate into model weights (training True,
    force/stress programs False).
    """
    validate_halo_mode(halo_mode)
    sq = lambda a: a[0] if a is not None and hasattr(a, "shape") and a.ndim >= 1 else a
    lg = LocalGraph(
        axis_name=axis_name,
        shifts=g.shifts,
        n_cap=g.n_cap,
        e_cap=g.e_cap,
        b_cap=g.b_cap,
        e_split=g.e_split,
        halo_mode=halo_mode,
        kernels=kernels,
        kernels_diff_params=kernels_diff_params,
        species=sq(g.species),
        node_mask=sq(g.node_mask),
        owned_mask=sq(g.owned_mask),
        edge_src=sq(g.edge_src),
        edge_dst=sq(g.edge_dst),
        edge_offset=sq(g.edge_offset),
        edge_mask=sq(g.edge_mask),
        halo_send_idx=g.halo_send_idx[:, 0],
        halo_send_mask=g.halo_send_mask[:, 0],
        halo_recv_idx=g.halo_recv_idx[:, 0],
        lattice=g.lattice,
        has_bond_graph=g.has_bond_graph,
        line_src=sq(g.line_src),
        line_dst=sq(g.line_dst),
        line_mask=sq(g.line_mask),
        line_center=sq(g.line_center),
        bond_map_edge=sq(g.bond_map_edge),
        bond_map_bond=sq(g.bond_map_bond),
        bond_map_mask=sq(g.bond_map_mask),
        bond_halo_send_idx=g.bond_halo_send_idx[:, 0],
        bond_halo_send_mask=g.bond_halo_send_mask[:, 0],
        bond_halo_recv_idx=g.bond_halo_recv_idx[:, 0],
        system=g.system,
        batch_size=g.batch_size,
        struct_id=sq(g.struct_id),
    )
    return lg, sq(g.positions)
