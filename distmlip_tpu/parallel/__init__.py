from .mesh import (GRAPH_AXIS, ensure_latency_hiding_flags, graph_mesh,
                   latency_hiding_flags)
from .halo import HALO_MODES, LocalGraph, local_graph_from_stacked
from .runtime import (make_total_energy, make_potential_fn,
                      make_batched_potential_fn, make_site_fn,
                      graph_in_specs)
from .audit import collective_counts, count_collectives, ppermutes_by_scope

__all__ = [
    "GRAPH_AXIS",
    "graph_mesh",
    "latency_hiding_flags",
    "ensure_latency_hiding_flags",
    "HALO_MODES",
    "LocalGraph",
    "local_graph_from_stacked",
    "make_total_energy",
    "make_potential_fn",
    "make_batched_potential_fn",
    "make_site_fn",
    "graph_in_specs",
    "collective_counts",
    "count_collectives",
    "ppermutes_by_scope",
]
