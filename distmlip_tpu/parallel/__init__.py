from .mesh import (BATCH_AXIS, GRAPH_AXIS, SPATIAL_AXIS, device_mesh,
                   ensure_latency_hiding_flags, graph_mesh,
                   latency_hiding_flags, mesh_shape)
from .halo import HALO_MODES, LocalGraph, local_graph_from_stacked
from .runtime import (make_total_energy, make_potential_fn,
                      make_batched_potential_fn, make_packed_energy_fn,
                      make_site_fn, graph_in_specs, graph_row_axes)
from .audit import (collective_counts, collectives_by_axis,
                    count_collectives, ppermutes_by_scope)

__all__ = [
    "BATCH_AXIS",
    "SPATIAL_AXIS",
    "GRAPH_AXIS",
    "device_mesh",
    "mesh_shape",
    "graph_mesh",
    "latency_hiding_flags",
    "ensure_latency_hiding_flags",
    "HALO_MODES",
    "LocalGraph",
    "local_graph_from_stacked",
    "make_total_energy",
    "make_potential_fn",
    "make_batched_potential_fn",
    "make_packed_energy_fn",
    "make_site_fn",
    "graph_in_specs",
    "graph_row_axes",
    "collective_counts",
    "collectives_by_axis",
    "count_collectives",
    "ppermutes_by_scope",
]
