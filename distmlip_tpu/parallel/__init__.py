from .mesh import GRAPH_AXIS, graph_mesh
from .halo import LocalGraph, local_graph_from_stacked
from .runtime import (make_total_energy, make_potential_fn,
                      make_site_fn, graph_in_specs)

__all__ = [
    "GRAPH_AXIS",
    "graph_mesh",
    "LocalGraph",
    "local_graph_from_stacked",
    "make_total_energy",
    "make_potential_fn",
    "make_site_fn",
    "graph_in_specs",
]
