"""Device-mesh helpers: the named 2-D ``Mesh(("batch", "spatial"))``.

The parallel runtime addresses ONE named mesh with two axes:

- ``"spatial"`` — graph parallelism (slab s of a structure lives at spatial
  coordinate s; the halo exchange rides ``ppermute`` over this axis only).
- ``"batch"`` — data parallelism over packed structure batches. The batch
  axis NEVER carries a collective: batched structures are block-diagonal,
  so the only cross-device traffic a placement needs is the spatial halo
  ring inside each batch row (``tools/halo_audit.py --mesh B,S`` asserts
  this at the jaxpr level).

One executable family serves every placement on the same mesh: B
structures x 1 slab (pure batch-parallel), 1 structure x S slabs (the
historical 1-D ring, now addressed by axis name on the spatial sub-axis),
and B x S (each packed structure itself spatially partitioned).
``graph_mesh(P)`` remains as the 1-structure entry point and now returns a
``(1, P)`` 2-D mesh, so existing ``PartitionSpec(GRAPH_AXIS)`` programs run
unchanged. Multi-host meshes work as before: ``jax.devices()`` spans hosts
and slab adjacency maps onto ICI/DCN neighbor links.

This module also owns the XLA scheduler configuration for the
overlap-aware halo pipeline: the coalesced exchange (parallel/halo.py)
and the interior/frontier edge split (partition/graph.py) only pay off
when XLA (a) lowers ``ppermute`` to an async collective-permute pair and
(b) schedules independent compute between the start/done ops. Both are
driven by XLA flags that must be set BEFORE the backend initializes.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

BATCH_AXIS = "batch"
SPATIAL_AXIS = "spatial"
# historical name for the graph-parallel axis; now an alias of the spatial
# sub-axis of the 2-D mesh so existing PartitionSpec(GRAPH_AXIS) code keeps
# addressing the ring by name
GRAPH_AXIS = SPATIAL_AXIS

# Latency-hiding configuration for the TPU backend: async collective
# permutes (the halo ppermute becomes a start/done pair) + the
# latency-hiding scheduler that moves interior edge compute between them.
# TPU-only flags — the CPU backend (tests) rejects unknown xla_tpu_* flags,
# so they are applied conditionally by ensure_latency_hiding_flags().
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_async_collective_permute=true",
    "--xla_tpu_enable_latency_hiding_scheduler=true",
)


def latency_hiding_flags() -> tuple[str, ...]:
    """The XLA flags the overlap pipeline wants on TPU (documentation /
    tooling surface; see ensure_latency_hiding_flags for the setter)."""
    return LATENCY_HIDING_XLA_FLAGS


def _backend_initialized() -> bool:
    """True once an XLA backend exists (flag changes no longer take)."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:  # noqa: BLE001 - private API; assume live if unsure
        return True


def ensure_latency_hiding_flags(force: bool | None = None) -> bool:
    """Append the latency-hiding flags to ``XLA_FLAGS`` when they can still
    take effect. Returns True when the flags are (already) present.

    Applied only when a TPU platform is explicitly requested
    (``JAX_PLATFORMS`` mentions tpu) or ``DISTMLIP_LATENCY_HIDING=1``
    forces it, because other clients reject unknown ``xla_tpu_*`` flags —
    a CPU test run on a TPU-capable image must not poison its own
    ``XLA_FLAGS``. ``DISTMLIP_LATENCY_HIDING=0`` disables; the ``force``
    argument overrides both. Callers on the hot path (graph_mesh) invoke
    this best-effort: once the backend is live the environment is left
    untouched.
    """
    existing = os.environ.get("XLA_FLAGS", "")
    if all(f.split("=")[0] in existing for f in LATENCY_HIDING_XLA_FLAGS):
        return True
    env = os.environ.get("DISTMLIP_LATENCY_HIDING")
    if force is None:
        if env == "0":
            return False
        if env == "1":
            force = True
    if not force:
        platforms = os.environ.get("JAX_PLATFORMS", "").lower()
        if "tpu" not in platforms:
            return False
    if _backend_initialized():
        import warnings

        warnings.warn(
            "latency-hiding XLA flags requested but the XLA backend is "
            "already initialized — they cannot take effect this process. "
            "Import distmlip_tpu (or call ensure_latency_hiding_flags) "
            "before anything touches jax.devices().", stacklevel=2)
        return False
    missing = [f for f in LATENCY_HIDING_XLA_FLAGS
               if f.split("=")[0] not in existing]
    os.environ["XLA_FLAGS"] = (existing + " " + " ".join(missing)).strip()
    return True


def device_mesh(batch: int = 1, spatial: int = 1, devices=None) -> Mesh:
    """The named 2-D ``Mesh(("batch", "spatial"))`` of ``batch * spatial``
    devices.

    Device (b, s) holds spatial slab s of batch shard b. Spatial neighbors
    are adjacent in device order, so on a TPU slice the halo ``ppermute``
    rides ICI neighbor links within each batch row; batch rows never talk
    to each other (no batch-axis collectives by construction).
    """
    ensure_latency_hiding_flags()
    devices = list(devices if devices is not None else jax.devices())
    batch, spatial = int(batch), int(spatial)
    if batch < 1 or spatial < 1:
        raise ValueError(
            f"mesh axes must be >= 1, got batch={batch} spatial={spatial}")
    need = batch * spatial
    if need > len(devices):
        raise ValueError(
            f"Requested a {batch}x{spatial} mesh ({need} devices) but only "
            f"{len(devices)} devices are available.")
    return Mesh(np.array(devices[:need]).reshape(batch, spatial),
                (BATCH_AXIS, SPATIAL_AXIS))


def mesh_shape(mesh: Mesh) -> tuple[int, int]:
    """``(batch, spatial)`` sizes of a mesh. Meshes without an explicit
    batch axis (a user-built 1-D spatial mesh) report batch=1; a missing
    spatial axis reports spatial=1 (pure batch-parallel mesh)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(sizes.get(BATCH_AXIS, 1)), int(sizes.get(SPATIAL_AXIS, 1))


def mesh_row_axes(mesh: Mesh | None):
    """Mesh axes a graph's leading partition axis should shard over on
    ``mesh``: both named axes when the mesh carries a batch axis (even of
    size 1 — replicating rows over an unmentioned axis would add spurious
    gradient-transpose psums on it), else the spatial axis alone (a
    user-built 1-D spatial mesh)."""
    if mesh is None:
        return SPATIAL_AXIS
    if BATCH_AXIS in mesh.axis_names:
        return (BATCH_AXIS, SPATIAL_AXIS)
    return SPATIAL_AXIS


def graph_mesh(num_partitions: int | None = None, devices=None) -> Mesh:
    """A ``(1, P)`` mesh for pure graph parallelism (1 structure x P slabs).

    Historically this was the 1-D ``("gp",)`` mesh; it is now the batch=1
    slice of the named 2-D mesh, so single-structure programs and B x S
    placements share one mesh family (``PartitionSpec(GRAPH_AXIS)`` keeps
    addressing the spatial ring by name).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_partitions is None:
        num_partitions = len(devices)
    return device_mesh(1, num_partitions, devices)
