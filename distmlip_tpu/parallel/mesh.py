"""Device-mesh helpers for graph parallelism.

The framework runs graph-parallel over a 1-D mesh axis named ``"gp"``
(slab i lives on device i). Multi-host meshes work unchanged: ``jax.devices()``
spans hosts and slab adjacency maps onto ICI/DCN neighbor links.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

GRAPH_AXIS = "gp"


def graph_mesh(num_partitions: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh of ``num_partitions`` devices for graph parallelism."""
    devices = list(devices if devices is not None else jax.devices())
    if num_partitions is None:
        num_partitions = len(devices)
    if num_partitions > len(devices):
        raise ValueError(
            f"Requested {num_partitions} partitions but only {len(devices)} devices."
        )
    return Mesh(np.array(devices[:num_partitions]), (GRAPH_AXIS,))
