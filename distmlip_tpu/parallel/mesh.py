"""Device-mesh helpers for graph parallelism.

The framework runs graph-parallel over a 1-D mesh axis named ``"gp"``
(slab i lives on device i). Multi-host meshes work unchanged: ``jax.devices()``
spans hosts and slab adjacency maps onto ICI/DCN neighbor links.

This module also owns the XLA scheduler configuration for the
overlap-aware halo pipeline: the coalesced exchange (parallel/halo.py)
and the interior/frontier edge split (partition/graph.py) only pay off
when XLA (a) lowers ``ppermute`` to an async collective-permute pair and
(b) schedules independent compute between the start/done ops. Both are
driven by XLA flags that must be set BEFORE the backend initializes.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

GRAPH_AXIS = "gp"

# Latency-hiding configuration for the TPU backend: async collective
# permutes (the halo ppermute becomes a start/done pair) + the
# latency-hiding scheduler that moves interior edge compute between them.
# TPU-only flags — the CPU backend (tests) rejects unknown xla_tpu_* flags,
# so they are applied conditionally by ensure_latency_hiding_flags().
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_async_collective_permute=true",
    "--xla_tpu_enable_latency_hiding_scheduler=true",
)


def latency_hiding_flags() -> tuple[str, ...]:
    """The XLA flags the overlap pipeline wants on TPU (documentation /
    tooling surface; see ensure_latency_hiding_flags for the setter)."""
    return LATENCY_HIDING_XLA_FLAGS


def _backend_initialized() -> bool:
    """True once an XLA backend exists (flag changes no longer take)."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:  # noqa: BLE001 - private API; assume live if unsure
        return True


def ensure_latency_hiding_flags(force: bool | None = None) -> bool:
    """Append the latency-hiding flags to ``XLA_FLAGS`` when they can still
    take effect. Returns True when the flags are (already) present.

    Applied only when a TPU platform is explicitly requested
    (``JAX_PLATFORMS`` mentions tpu) or ``DISTMLIP_LATENCY_HIDING=1``
    forces it, because other clients reject unknown ``xla_tpu_*`` flags —
    a CPU test run on a TPU-capable image must not poison its own
    ``XLA_FLAGS``. ``DISTMLIP_LATENCY_HIDING=0`` disables; the ``force``
    argument overrides both. Callers on the hot path (graph_mesh) invoke
    this best-effort: once the backend is live the environment is left
    untouched.
    """
    existing = os.environ.get("XLA_FLAGS", "")
    if all(f.split("=")[0] in existing for f in LATENCY_HIDING_XLA_FLAGS):
        return True
    env = os.environ.get("DISTMLIP_LATENCY_HIDING")
    if force is None:
        if env == "0":
            return False
        if env == "1":
            force = True
    if not force:
        platforms = os.environ.get("JAX_PLATFORMS", "").lower()
        if "tpu" not in platforms:
            return False
    if _backend_initialized():
        import warnings

        warnings.warn(
            "latency-hiding XLA flags requested but the XLA backend is "
            "already initialized — they cannot take effect this process. "
            "Import distmlip_tpu (or call ensure_latency_hiding_flags) "
            "before anything touches jax.devices().", stacklevel=2)
        return False
    missing = [f for f in LATENCY_HIDING_XLA_FLAGS
               if f.split("=")[0] not in existing]
    os.environ["XLA_FLAGS"] = (existing + " " + " ".join(missing)).strip()
    return True


def graph_mesh(num_partitions: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh of ``num_partitions`` devices for graph parallelism."""
    ensure_latency_hiding_flags()
    devices = list(devices if devices is not None else jax.devices())
    if num_partitions is None:
        num_partitions = len(devices)
    if num_partitions > len(devices):
        raise ValueError(
            f"Requested {num_partitions} partitions but only {len(devices)} devices."
        )
    return Mesh(np.array(devices[:num_partitions]), (GRAPH_AXIS,))
