"""SLO-triggered flight recorder: capture the evidence when it matters.

When a burn-rate breach (or the first deadline miss / a replica wedge
suspicion) fires on hardware you can't reproduce locally, the thing you
actually want is a bounded snapshot of what the system was doing RIGHT
THEN. ``FlightRecorder.capture(reason)`` writes a timestamped incident
directory containing:

- ``incident.json`` — the reason, wall time, caller attributes, and the
  tracer/metrics bookkeeping counters;
- ``trace.json`` — the last ``last_k_traces`` distinct span trees from
  the tracer's ring buffer, exported as Perfetto-loadable
  ``trace_event`` JSON (open ``ui.perfetto.dev`` and drop the file in);
- ``metrics.prom`` / ``metrics.json`` — the full registry state in both
  exposition and snapshot form;
- ``profile/`` (optional, ``profile_s > 0``) — a BOUNDED
  ``jax.profiler`` device trace captured for ``profile_s`` seconds on a
  daemon thread, with host ``TraceAnnotation``\\ s enabled for the
  duration so the device timeline carries the serving span names.

Captures are rate-limited (``min_interval_s``) so a miss storm produces
one incident, not a disk full of them; suppressed captures are counted.
"""

from __future__ import annotations

import json
import os
import threading
import time


class FlightRecorder:
    """Bounded incident capture over a tracer + metrics registry."""

    def __init__(self, out_dir: str, tracer=None, metrics=None,
                 last_k_traces: int = 64, profile_s: float = 0.0,
                 min_interval_s: float = 60.0, clock=None):
        self.out_dir = str(out_dir)
        self.tracer = tracer
        self.metrics = metrics
        self.last_k_traces = int(last_k_traces)
        self.profile_s = float(profile_s)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._last_capture_t = None
        self._profiling = False
        self.captures = 0
        self.suppressed = 0
        self.incidents: list[str] = []

    # ------------------------------------------------------------------

    def _recent_trace_spans(self) -> list:
        """Spans of the last K distinct traces, walked newest-first over
        the tracer's ring buffer (a span tree is whatever of it the ring
        still holds — bounded by construction)."""
        spans = self.tracer.spans()
        keep: set = set()
        for s in reversed(spans):
            if s.trace_id not in keep:
                if len(keep) >= self.last_k_traces:
                    break
                keep.add(s.trace_id)
        return [s for s in spans if s.trace_id in keep]

    def capture(self, reason: str, attrs: dict | None = None) -> str | None:
        """Write one incident directory; returns its path, or None when
        rate-limited. Never raises into the serving path — any capture
        fault is recorded on the recorder and swallowed."""
        now = self._clock()
        with self._lock:
            if (self._last_capture_t is not None
                    and now - self._last_capture_t < self.min_interval_s):
                self.suppressed += 1
                return None
            self._last_capture_t = now
            self.captures += 1
            seq = self.captures
        try:
            return self._write_incident(reason, attrs, seq)
        except Exception as e:  # noqa: BLE001 - never fail the caller
            import warnings

            warnings.warn(f"flight recorder capture failed ({e}); "
                          f"incident dropped", stacklevel=2)
            return None

    def _write_incident(self, reason, attrs, seq) -> str:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        d = os.path.join(self.out_dir, f"incident-{stamp}-{seq:03d}")
        os.makedirs(d, exist_ok=True)
        meta = {
            "reason": reason,
            "t_wall": time.time(),
            "attrs": dict(attrs or {}),
            "capture_seq": seq,
        }
        if self.tracer is not None:
            meta["spans_finished"] = self.tracer.spans_finished
            meta["spans_dropped"] = self.tracer.spans_dropped
            from .export import write_trace

            write_trace(os.path.join(d, "trace.json"),
                        self._recent_trace_spans(),
                        t_wall0=self.tracer.t_wall0)
        if self.metrics is not None:
            with open(os.path.join(d, "metrics.prom"), "w") as f:
                f.write(self.metrics.render())
            self.metrics.dump_json(os.path.join(d, "metrics.json"))
        with open(os.path.join(d, "incident.json"), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        if self.profile_s > 0:
            self._start_profile(os.path.join(d, "profile"))
        self.incidents.append(d)
        return d

    def _start_profile(self, logdir: str) -> None:
        """Bounded jax.profiler capture on a daemon thread (at most one
        in flight — a second trigger during a capture is skipped; the
        profiler does not nest)."""
        with self._lock:
            if self._profiling:
                return
            self._profiling = True

        def _run():
            try:
                from ..telemetry.trace import device_trace

                with device_trace(logdir):
                    time.sleep(self.profile_s)
            except Exception:  # noqa: BLE001 - best-effort capture
                pass
            finally:
                with self._lock:
                    self._profiling = False

        threading.Thread(target=_run, name="distmlip-flight-profile",
                         daemon=True).start()

    def snapshot(self) -> dict:
        with self._lock:
            return {"captures": self.captures,
                    "suppressed": self.suppressed,
                    "incidents": list(self.incidents)}
