"""Observability: request-scoped tracing, live metrics, SLO flight
recorder.

Three planes over one set of instrumentation points (the existing
``StepRecord`` emission sites — the potential/model hot path is
untouched):

- **Records** (:mod:`distmlip_tpu.telemetry`) — the per-step JSONL
  artifact, analyzed offline. Unchanged, but records now carry
  ``trace_id``/``span_id`` so they correlate with the other planes.
- **Traces** (:mod:`.tracing` / :mod:`.export`) — one span tree per
  REQUEST across every hop (submit → admit → route → queue → plan →
  pack → dispatch → resolve, plus cache-hit/coalesce short-circuits and
  failover re-dispatch), with span links from each batch dispatch to its
  member requests. Exported as Perfetto-loadable ``trace_event`` JSON;
  ``tools/trace_view.py`` renders per-request critical paths.
- **Metrics** (:mod:`.metrics`) — typed Counter/Gauge/Histogram
  populated live (per-tenant request/latency, queue depth, batch
  occupancy, compiles, cache hits, replica liveness, HBM headroom,
  active-loop buffer/swaps), served as Prometheus text exposition by
  :class:`MetricsServer` and snapshot-dumpable into bench JSON.

- **Compiler/device** (:mod:`.profiling` / :mod:`.attribution` /
  :mod:`.roofline`) — compile telemetry at every compile point (fresh
  vs AOT-rehydrate, wall time, bucket key; ``distmlip_compile_seconds``
  + ``distmlip_compiles_total{kind=}``), scope-level device-time
  attribution from a profiler capture or the analytic cost model, and
  roofline rows (intensity / achieved vs peak / MFU) joined from the
  FLOP and memory planners. CLIs: ``tools/roofline.py`` and
  ``tools/perf_gate.py`` (baseline regression gate).

Plus the incident plane: :class:`~.slo.SLOMonitor` evaluates per-tenant
multi-window burn rates and, on breach (or first deadline miss / replica
wedge suspicion), the :class:`~.flight.FlightRecorder` captures traces +
metrics (+ an optional bounded ``jax.profiler`` capture) into a
timestamped incident directory.

Quick start::

    from distmlip_tpu import obs

    hub = obs.Observability.enable(slo=obs.SLOConfig(latency_s=0.5),
                                   flight_dir="incidents/")
    ...  # run fleet / engine traffic: spans + metrics flow automatically
    hub.tracer.write("trace.json")        # -> ui.perfetto.dev
    print(hub.metrics.render())           # Prometheus exposition
    obs.uninstall()

Everything here is host-side and stdlib-only; creating spans inside
jitted code is the DML003 lint violation (``contract_check --lint``).
"""

from __future__ import annotations

from . import attribution, profiling, roofline, runtime
from .attribution import ScopeBreakdown, attribute
from .export import (critical_path_summary, critical_paths,
                     format_critical_path, load_trace, load_trace_dir,
                     request_trace_summary, to_trace_events, write_trace)
from .flight import FlightRecorder
from .profiling import (CompileEvent, compile_counts, compile_events,
                        record_compile, reset_compile_log)
from .roofline import RooflineRow, format_roofline_table
from .metrics import (LATENCY_BUCKETS, MetricsRegistry, MetricsServer,
                      parse_exposition)
from .runtime import hub, install, uninstall
from .slo import SLOConfig, SLOMonitor
from .tracing import (REQUEST_ROOT_NAMES, TERMINAL_SPAN_NAME, RequestTrace,
                      Span, Tracer)


class Observability:
    """The hub: tracer + metrics + SLO monitor + flight recorder."""

    def __init__(self, tracer=None, metrics=None, slo=None, flight=None):
        self.tracer = tracer
        self.metrics = metrics
        self.slo = slo
        self.flight = flight

    @classmethod
    def enable(cls, *, tracing: bool = True, metrics: bool = True,
               slo=None, flight_dir: str | None = None,
               profile_s: float = 0.0, max_spans: int = 262144,
               last_k_traces: int = 64, min_interval_s: float = 60.0,
               clock=None, register: bool = True) -> "Observability":
        """Build a hub and (by default) install it process-globally.

        ``slo``: an :class:`SLOConfig` (one default policy), a
        ``{tenant: SLOConfig}`` mapping (first entry doubles as the
        default), or None for no SLO monitoring. ``flight_dir`` arms the
        flight recorder; SLO breaches auto-capture into it.
        """
        tr = Tracer(max_spans=max_spans, clock=clock) if tracing else None
        mx = MetricsRegistry() if metrics else None
        mon = None
        if slo is not None:
            if isinstance(slo, dict):
                default = next(iter(slo.values()))
                mon = SLOMonitor(default=default, per_tenant=slo,
                                 clock=clock)
            else:
                mon = SLOMonitor(default=slo, clock=clock)
        fr = None
        if flight_dir is not None:
            fr = FlightRecorder(flight_dir, tracer=tr, metrics=mx,
                                last_k_traces=last_k_traces,
                                profile_s=profile_s,
                                min_interval_s=min_interval_s,
                                clock=clock)
            if mon is not None:
                mon.on_breach = (
                    lambda tenant, info: fr.capture(
                        f"slo burn-rate breach: tenant {tenant!r}",
                        attrs=info))
        h = cls(tr, mx, mon, fr)
        if register:
            install(h)
        return h

    def close(self) -> None:
        """Uninstall (if this hub is the installed one)."""
        uninstall(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def snapshot(self) -> dict:
        out: dict = {}
        if self.tracer is not None:
            out["tracer"] = {
                "spans_finished": self.tracer.spans_finished,
                "spans_dropped": self.tracer.spans_dropped,
            }
        if self.metrics is not None:
            out["metrics"] = self.metrics.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.flight is not None:
            out["flight"] = self.flight.snapshot()
        return out


__all__ = [
    "Observability",
    "Tracer",
    "Span",
    "RequestTrace",
    "REQUEST_ROOT_NAMES",
    "TERMINAL_SPAN_NAME",
    "MetricsRegistry",
    "MetricsServer",
    "LATENCY_BUCKETS",
    "parse_exposition",
    "SLOConfig",
    "SLOMonitor",
    "FlightRecorder",
    "install",
    "uninstall",
    "hub",
    "runtime",
    "to_trace_events",
    "write_trace",
    "load_trace",
    "load_trace_dir",
    "request_trace_summary",
    "critical_paths",
    "critical_path_summary",
    "format_critical_path",
    "profiling",
    "attribution",
    "roofline",
    "CompileEvent",
    "record_compile",
    "compile_events",
    "compile_counts",
    "reset_compile_log",
    "ScopeBreakdown",
    "attribute",
    "RooflineRow",
    "format_roofline_table",
]
