"""Live metrics: typed Counter/Gauge/Histogram + Prometheus exposition.

The registry answers "what is tenant A's p99 *right now*" without
replaying a JSONL file: the serving instrumentation points (the same
places that emit ``StepRecord``\\ s) increment typed metrics, and the
current state is readable three ways — ``render()`` (Prometheus text
exposition, served by :class:`MetricsServer` on an optional stdlib
``http.server`` endpoint), ``snapshot()`` (a dict dumpable into the
bench/load-test JSON), and direct family reads in tests.

Hot-path cost: one dict lookup to find the family, one to find the
labeled child, one short ``threading.Lock`` hold per update (the lock is
per-family; counters and gauges hold it for a single float add). No jax,
no allocation after the first touch of a (family, labels) pair.

Histogram buckets are FIXED log-scale latency buckets (100 µs .. ~104 s,
x2 per rung) so percentile queries over the exposition are stable across
restarts and tenants — pass ``buckets=`` for non-latency quantities.

Label cardinality is BOUNDED: a registry-created family admits at most
``max_label_children`` distinct label-value sets (default 64); further
novel sets all route to one ``_other`` overflow child, and every routed
update increments ``distmlip_metrics_label_overflow_total{metric=...}``
— a tenant-id-per-request client degrades its own per-tenant resolution
instead of growing the registry (and every scrape) without bound.
"""

from __future__ import annotations

import bisect
import json
import threading

# fixed log-scale latency ladder: 100 µs doubling up to ~104 s. 21 rungs
# cover everything from a cache hit to a wedged-grant stall.
LATENCY_BUCKETS = tuple(1e-4 * 2 ** i for i in range(21))

_KINDS = ("counter", "gauge", "histogram")

# default per-family cap on distinct label-value sets; the overflow
# bucket label and the trip counter metric (exempt from its own cap)
DEFAULT_MAX_LABEL_CHILDREN = 64
OVERFLOW_LABEL = "_other"
_OVERFLOW_METRIC = "distmlip_metrics_label_overflow_total"


def _label_str(label_names, label_values) -> str:
    if not label_names:
        return ""
    inner = ",".join(f'{k}="{v}"'
                     for k, v in zip(label_names, label_values))
    return "{" + inner + "}"


class _Child:
    """One (family, label-values) time series."""

    __slots__ = ("family", "label_values", "value", "bucket_counts",
                 "sum", "count")

    def __init__(self, family, label_values):
        self.family = family
        self.label_values = label_values
        self.value = 0.0
        if family.kind == "histogram":
            self.bucket_counts = [0] * (len(family.buckets) + 1)  # +Inf
            self.sum = 0.0
            self.count = 0

    # --- counter / gauge ---

    def inc(self, n: float = 1.0) -> None:
        with self.family._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set(self, v: float) -> None:
        with self.family._lock:
            self.value = float(v)

    def get(self) -> float:
        with self.family._lock:
            return self.value

    # --- histogram ---

    def observe(self, v: float) -> None:
        fam = self.family
        i = bisect.bisect_left(fam.buckets, v)
        with fam._lock:
            self.bucket_counts[i] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the bucket the
        q-th observation falls in) — the live-p99 read."""
        fam = self.family
        with fam._lock:
            total = self.count
            counts = list(self.bucket_counts)
        if total == 0:
            return 0.0
        rank = max(1, int(q * total + 0.5))
        seen = 0
        for i, n in enumerate(counts):
            seen += n
            if seen >= rank:
                return (fam.buckets[i] if i < len(fam.buckets)
                        else float("inf"))
        return float("inf")


class MetricFamily:
    """A named metric with a fixed label schema; children per value set."""

    def __init__(self, name: str, help: str, kind: str, label_names=(),
                 buckets=None, max_children=None, registry=None):
        if kind not in _KINDS:
            raise ValueError(f"kind {kind!r} not in {_KINDS}")
        self.name = name
        self.help = help
        self.kind = kind
        self.label_names = tuple(label_names)
        self.buckets = (tuple(buckets) if buckets is not None
                        else LATENCY_BUCKETS) if kind == "histogram" \
            else ()
        # None = unbounded (directly-constructed families, tests); the
        # registry passes its cap. The trip counter itself is exempt —
        # its cardinality is bounded by the number of families anyway,
        # and routing it to _other would recurse.
        self._max_children = (None if name == _OVERFLOW_METRIC
                              else max_children)
        self._registry = registry
        self._lock = threading.Lock()
        self._children: dict[tuple, _Child] = {}
        self._default: _Child | None = None

    def labels(self, *values, **kv) -> _Child:
        if kv:
            values = tuple(str(kv[k]) for k in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        child = self._children.get(values)
        if child is not None:
            return child
        overflowed = False
        with self._lock:
            child = self._children.get(values)
            if child is None:
                if (self._max_children is not None and self.label_names
                        and len(self._children) >= self._max_children):
                    # cap tripped: route this (and every further novel)
                    # label set to the shared overflow child — each
                    # routed update counts one overflow below
                    overflowed = True
                    key = (OVERFLOW_LABEL,) * len(self.label_names)
                    child = self._children.get(key)
                    if child is None:
                        child = self._children[key] = _Child(self, key)
                else:
                    child = self._children[values] = _Child(self, values)
        if overflowed:
            # outside the family lock: the trip counter is ANOTHER
            # family, and nesting the two locks would order-invert
            # against a concurrent render()
            self._note_overflow()
        return child

    def _note_overflow(self) -> None:
        reg = self._registry
        if reg is None:
            return
        try:
            reg.counter(
                _OVERFLOW_METRIC,
                "Updates routed to the _other overflow child because a "
                "family hit its label-cardinality cap",
                labels=("metric",)).labels(metric=self.name).inc()
        except Exception:  # noqa: BLE001 - accounting must not raise
            pass

    def _unlabeled(self) -> _Child:
        if self._default is None:
            self._default = self.labels()
        return self._default

    # label-less convenience: the family itself acts as its single child
    def inc(self, n: float = 1.0) -> None:
        self._unlabeled().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._unlabeled().dec(n)

    def set(self, v: float) -> None:
        self._unlabeled().set(v)

    def get(self) -> float:
        return self._unlabeled().get()

    def observe(self, v: float) -> None:
        self._unlabeled().observe(v)

    def quantile(self, q: float) -> float:
        return self._unlabeled().quantile(q)

    # --- rendering ---

    def _render_into(self, out: list) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            items = sorted(self._children.items())
        for values, child in items:
            lbl = _label_str(self.label_names, values)
            if self.kind == "histogram":
                cum = 0
                for i, bound in enumerate(self.buckets):
                    cum += child.bucket_counts[i]
                    le = _label_str(self.label_names + ("le",),
                                    values + (f"{bound:g}",))
                    out.append(f"{self.name}_bucket{le} {cum}")
                cum += child.bucket_counts[-1]
                le = _label_str(self.label_names + ("le",),
                                values + ("+Inf",))
                out.append(f"{self.name}_bucket{le} {cum}")
                out.append(f"{self.name}_sum{lbl} {child.sum:g}")
                out.append(f"{self.name}_count{lbl} {child.count}")
            else:
                out.append(f"{self.name}{lbl} {child.value:g}")

    def _snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._children.items())
        samples = []
        for values, child in items:
            labels = dict(zip(self.label_names, values))
            if self.kind == "histogram":
                samples.append({
                    "labels": labels, "sum": child.sum,
                    "count": child.count,
                    "buckets": {f"{b:g}": c for b, c in
                                zip(self.buckets, child.bucket_counts)},
                    "overflow": child.bucket_counts[-1],
                })
            else:
                samples.append({"labels": labels, "value": child.value})
        return {"kind": self.kind, "help": self.help, "samples": samples}


class MetricsRegistry:
    """Get-or-create families by name; render / snapshot the whole set."""

    def __init__(self, max_label_children: int = DEFAULT_MAX_LABEL_CHILDREN):
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}
        self.max_label_children = max_label_children

    def _family(self, name, help, kind, labels, buckets=None):
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = MetricFamily(
                        name, help, kind, labels, buckets=buckets,
                        max_children=self.max_label_children,
                        registry=self)
                    self._families[name] = fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"requested {kind}")
        return fam

    def counter(self, name: str, help: str = "",
                labels=()) -> MetricFamily:
        return self._family(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "", labels=()) -> MetricFamily:
        return self._family(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "", labels=(),
                  buckets=None) -> MetricFamily:
        return self._family(name, help, "histogram", labels,
                            buckets=buckets)

    def get(self, name: str) -> MetricFamily | None:
        return self._families.get(name)

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        out: list[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            fam._render_into(out)
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        return {f.name: f._snapshot() for f in fams}

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)


def parse_exposition(text: str) -> dict[str, float]:
    """Parse Prometheus text exposition into ``{sample_line: value}``
    keyed by the full sample name incl. labels — the load-test scrape
    check compares these against the loadgen's own totals."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, value = line.rsplit(None, 1)
            out[key] = float(value)
        except ValueError:
            continue
    return out


class MetricsServer:
    """Optional stdlib HTTP endpoint serving ``GET /metrics``.

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    Runs a daemon thread; ``close()`` shuts the listener down. No
    third-party dependency — ``http.server.ThreadingHTTPServer`` only.
    """

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1", start: bool = True):
        self.registry = registry
        self.host = host
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None
        if start:
            self.start()

    def start(self) -> None:
        if self._httpd is not None:
            return
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = self.registry

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib naming
                if self.path.rstrip("/") in ("", "/metrics"):
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *args):  # silence per-scrape stderr
                pass

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="distmlip-metrics",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
