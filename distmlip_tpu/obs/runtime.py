"""Process-global observability hub + the cheap accessors instrumented
code calls.

The serving/fleet/active layers do NOT take tracer/metrics parameters —
instrumentation points ask this module for the installed hub at call
time, so:

- with nothing installed (the default) every instrumented site costs one
  module-global read and a ``None`` check — the hot path is untouched;
- one ``Observability.enable()`` (or ``install(hub)``) lights up every
  layer at once, including objects constructed before the call;
- tests install and uninstall deterministically (``uninstall()`` in a
  ``finally``); the CLI tools do the same.

Nothing here imports jax or any sibling subsystem — this module must be
importable from every instrumented layer without cycles.
"""

from __future__ import annotations

_HUB = None


def install(hub):
    """Install ``hub`` (an :class:`~distmlip_tpu.obs.Observability`) as
    the process-global observability surface; returns it."""
    global _HUB
    _HUB = hub
    return hub


def uninstall(hub=None) -> None:
    """Remove the global hub (or only ``hub``, if it is still the one
    installed — lets an owner tear down without clobbering a successor).
    """
    global _HUB
    if hub is None or _HUB is hub:
        _HUB = None


def hub():
    return _HUB


def tracer():
    """The installed Tracer, or None (the instrumented-site fast path)."""
    h = _HUB
    return None if h is None else h.tracer


def metrics():
    """The installed MetricsRegistry, or None."""
    h = _HUB
    return None if h is None else h.metrics


def slo():
    """The installed SLOMonitor, or None."""
    h = _HUB
    return None if h is None else h.slo


def flight():
    """The installed FlightRecorder, or None."""
    h = _HUB
    return None if h is None else h.flight


def current_ctx():
    """This thread's ambient (trace_id, span_id), or None."""
    h = _HUB
    if h is None or h.tracer is None:
        return None
    return h.tracer.current()


def current_trace_id():
    """This thread's ambient trace id, or None — producers fold it into
    ``jax.profiler.TraceAnnotation`` names so device timelines line up
    with host spans."""
    ctx = current_ctx()
    return None if ctx is None else ctx[0]
