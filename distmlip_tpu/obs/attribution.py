"""Scope-level device-time attribution (the compiler/device plane).

Maps where a step's device time actually went, bucketed into the
categories the partitioned potentials are built from:

- ``halo_exchange``        ring ppermute / collective-permute traffic
- ``interior_aggregation`` per-partition message aggregation (segment
                           sums, gathers, the dense edge MLP work)
- ``scatter``              force/feature scatter-adds back onto nodes
- ``pallas_kernel``        fused Pallas kernels (custom calls)
- ``gradient_transpose``   backward-pass transpose work (force/stress
                           autodiff)
- ``other``                everything else (elementwise glue, copies)

Two sources, one report shape (:class:`ScopeBreakdown`):

- **trace** — offline parse of a ``jax.profiler`` Perfetto/Chrome
  capture (``{"traceEvents": [...]}``): XLA op events (``ph == "X"``)
  are classified by op name + HLO metadata and their durations summed.
  This is the real measurement; needs a device capture.
- **cost_model** — trace-free fallback: walk the traced program with
  :func:`distmlip_tpu.analysis.ir.iter_sites`, weight each eqn
  analytically, classify it by primitive + ``named_scope`` stack, and
  apportion a MEASURED total step time by the resulting fractions. CPU
  CI exercises the same report path without a profiler capture.

Everything here is host-side; the jax import is deferred into the
cost-model path so trace parsing works without jax at all.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

CATEGORIES = (
    "halo_exchange",
    "interior_aggregation",
    "scatter",
    "pallas_kernel",
    "gradient_transpose",
    "other",
)

# classification rules, first match wins. Applied to the lowercased
# "name | scope" string of a trace event or eqn site — the HLO op name,
# its op_name metadata (which carries the named_scope stack the PR 7
# walker indexes), and the jaxpr scope all funnel through here so both
# sources bucket identically.
_RULES: tuple[tuple[str, re.Pattern], ...] = (
    ("halo_exchange", re.compile(
        r"ppermute|collective.?permute|halo|all.?to.?all|all.?gather")),
    ("pallas_kernel", re.compile(r"pallas|tpu.?custom.?call|mosaic")),
    ("gradient_transpose", re.compile(
        r"transpose\b|backward|vjp|grad|jvp_transpose")),
    ("scatter", re.compile(r"scatter")),
    ("interior_aggregation", re.compile(
        r"interior|aggregat|segment|unsorted_segment|edge_mlp|message"
        r"|gather|dot_general|dot\b|conv|einsum|reduce_sum|psum")),
)


def classify(name: str, scope: str = "") -> str:
    """Category for one op/eqn given its name and named_scope stack.

    The scope is checked FIRST: an author-placed ``named_scope`` (e.g.
    ``halo_exchange`` around the ppermute block) is stronger evidence
    than the op name (a ``dot_general`` inside the halo scope is halo
    cost, not interior work).
    """
    for text in (scope.lower(), name.lower()):
        if not text:
            continue
        for cat, pat in _RULES:
            if pat.search(text):
                return cat
    return "other"


@dataclass
class ScopeBreakdown:
    """Per-category / per-scope device-time breakdown for one program."""

    total_s: float
    by_category: dict = field(default_factory=dict)   # category -> seconds
    by_scope: dict = field(default_factory=dict)      # scope str -> seconds
    source: str = "cost_model"                        # "trace" | "cost_model"
    program: str = ""
    n_events: int = 0

    def fraction(self, category: str) -> float:
        if self.total_s <= 0:
            return 0.0
        return self.by_category.get(category, 0.0) / self.total_s

    def as_dict(self) -> dict:
        return {
            "program": self.program,
            "source": self.source,
            "total_s": self.total_s,
            "n_events": self.n_events,
            "by_category": dict(self.by_category),
            "by_scope": dict(self.by_scope),
        }

    def render(self, top_scopes: int = 8) -> str:
        head = self.program or "device time"
        lines = [f"{head}  [{self.source}]  total {self.total_s:.6f}s",
                 f"  {'category':<22} {'seconds':>12} {'frac':>7}"]
        for cat in CATEGORIES:
            s = self.by_category.get(cat, 0.0)
            if s <= 0 and cat != "other":
                continue
            lines.append(
                f"  {cat:<22} {s:>12.6f} {self.fraction(cat):>6.1%}")
        if self.by_scope:
            lines.append(f"  top scopes ({min(top_scopes, len(self.by_scope))}"
                         f" of {len(self.by_scope)}):")
            ranked = sorted(self.by_scope.items(), key=lambda kv: -kv[1])
            for scope, s in ranked[:top_scopes]:
                lines.append(f"    {scope:<40.40} {s:>12.6f}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# source 1: jax.profiler Perfetto/Chrome trace capture
# ---------------------------------------------------------------------------

# trace event names that are profiler bookkeeping, not device work
_TRACE_NOISE = re.compile(
    r"^(process_|thread_|trace_|args\b)|^\$|^Steps?$|^MemcpyD?2?[HD]?$",
    re.IGNORECASE)


def _iter_trace_events(trace):
    """Yield complete-duration events from a capture.

    ``trace`` is a path to a JSON file, a parsed ``{"traceEvents": [..]}``
    dict, or a bare list of events. Gzip'd ``.json.gz`` captures (what
    ``jax.profiler.trace`` writes) are handled for paths.
    """
    if isinstance(trace, str):
        if trace.endswith(".gz"):
            import gzip

            with gzip.open(trace, "rt") as f:
                trace = json.load(f)
        else:
            with open(trace) as f:
                trace = json.load(f)
    if isinstance(trace, dict):
        trace = trace.get("traceEvents", [])
    for ev in trace:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        if not name or _TRACE_NOISE.search(name):
            continue
        yield ev


def _event_scope(ev) -> str:
    """The named_scope stack for an XLA op event, from HLO metadata.

    XLA stamps each op's ``op_name`` as ``jit(fn)/scope_a/scope_b/op`` —
    the middle segments are exactly the ``jax.named_scope`` stack the
    jaxpr walker sees, so trace and cost-model attribution key on the
    same strings.
    """
    args = ev.get("args") or {}
    for key in ("long_name", "tf_op", "op_name", "name"):
        val = args.get(key)
        if isinstance(val, str) and val:
            return val
    return ""


def attribute_trace(trace, program: str = "",
                    device_only: bool = True) -> ScopeBreakdown:
    """Per-category breakdown from a profiler capture.

    ``device_only`` keeps events whose pid/tid row looks like a device
    track when that metadata exists; captures without track metadata
    (unit-test fixtures) are summed wholesale.
    """
    by_cat: dict[str, float] = {}
    by_scope: dict[str, float] = {}
    total = 0.0
    n = 0
    for ev in _iter_trace_events(trace):
        dur_s = float(ev.get("dur", 0.0)) * 1e-6
        if dur_s <= 0:
            continue
        scope = _event_scope(ev)
        cat = classify(str(ev.get("name", "")), scope)
        by_cat[cat] = by_cat.get(cat, 0.0) + dur_s
        key = scope or str(ev.get("name", ""))
        by_scope[key] = by_scope.get(key, 0.0) + dur_s
        total += dur_s
        n += 1
    return ScopeBreakdown(total_s=total, by_category=by_cat,
                          by_scope=by_scope, source="trace",
                          program=program, n_events=n)


# ---------------------------------------------------------------------------
# source 2: analytic cost model over the traced program
# ---------------------------------------------------------------------------

def _aval_elements(v) -> float:
    try:
        shape = v.aval.shape
    except Exception:  # noqa: BLE001 - abstract tokens etc.
        return 1.0
    n = 1.0
    for d in shape:
        n *= max(int(d), 1)
    return n


def _eqn_weight(eqn) -> float:
    """Analytic cost weight for one eqn — relative, not absolute.

    Output elements as the base (every produced element was computed or
    moved), with a contraction-depth multiplier for ``dot_general`` (the
    one primitive whose cost is not output-proportional) and a 2x for
    scatter (read-modify-write).
    """
    out = sum(_aval_elements(v) for v in eqn.outvars)
    name = eqn.primitive.name
    if name == "dot_general":
        try:
            ((lc, _), _) = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval.shape
            k = 1.0
            for ax in lc:
                k *= max(int(lhs[ax]), 1)
            return out * 2.0 * k
        except Exception:  # noqa: BLE001 - fall back to elements
            return out * 2.0
    if "scatter" in name:
        return out * 2.0
    if name in ("ppermute", "collective_permute", "all_gather",
                "all_to_all", "psum", "reduce_scatter"):
        # collectives cost bandwidth, not flops — weight by payload with
        # a latency-dominance multiplier so small halos don't vanish
        return out * 4.0
    return out


def attribute_cost_model(closed_jaxpr, total_s: float,
                         program: str = "") -> ScopeBreakdown:
    """Apportion a MEASURED step time by analytic eqn weights.

    Walks every eqn site (nested jaxprs included — loop bodies count
    once, same caveat as :func:`analysis.ir.iter_sites`), classifies by
    primitive + named_scope, and scales the weight fractions by
    ``total_s``. The split is an estimate; the total is real.
    """
    from ..analysis.ir import iter_sites

    w_cat: dict[str, float] = {}
    w_scope: dict[str, float] = {}
    w_total = 0.0
    n = 0
    for site in iter_sites(closed_jaxpr):
        w = _eqn_weight(site.eqn)
        if w <= 0:
            continue
        cat = classify(site.primitive, site.scope)
        w_cat[cat] = w_cat.get(cat, 0.0) + w
        key = site.scope or site.primitive
        w_scope[key] = w_scope.get(key, 0.0) + w
        w_total += w
        n += 1
    scale = (total_s / w_total) if w_total > 0 else 0.0
    return ScopeBreakdown(
        total_s=total_s,
        by_category={k: v * scale for k, v in w_cat.items()},
        by_scope={k: v * scale for k, v in w_scope.items()},
        source="cost_model", program=program, n_events=n)


def attribute(total_s: float, trace=None, jaxpr=None,
              program: str = "") -> ScopeBreakdown:
    """One entry point: trace when a capture exists, cost model else."""
    if trace is not None:
        bd = attribute_trace(trace, program=program)
        if bd.n_events:
            return bd
    if jaxpr is not None:
        return attribute_cost_model(jaxpr, total_s, program=program)
    return ScopeBreakdown(total_s=total_s, source="cost_model",
                          program=program)


__all__ = [
    "CATEGORIES",
    "ScopeBreakdown",
    "attribute",
    "attribute_cost_model",
    "attribute_trace",
    "classify",
]
