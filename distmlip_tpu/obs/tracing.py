"""Request-scoped distributed tracing for the serving stack.

One request = one trace. The router/engine open a ROOT span at submit
(``fleet.submit`` / ``engine.submit``), every hop the request takes adds
a child span — ``tenancy.admit``, ``router.route``, ``engine.queue``,
``cache.hit``, ``coalesce``, ``router.requeue`` — and the request's
resolution emits exactly one terminal ``future.resolve`` span and closes
the root. Batch-level work (``serve.batch`` with ``scheduler.plan_batch``
/ ``batched.pack`` / ``device.dispatch`` / ``device.compile`` children)
lives in its OWN trace carrying span LINKS back to every member
request's context, the Perfetto/OTel idiom for fan-in: per-request
critical paths are reconstructed by following the links
(:mod:`.export`).

Design constraints, in order:

- **Thread-safe across the scheduler/prefetch/health threads.** A span
  context is an immutable ``(trace_id, span_id)`` tuple; cross-thread
  propagation is EXPLICIT — the submitting thread stores a
  :class:`RequestTrace` handle on the request object, and the scheduler
  thread emits retroactive spans against it (``Tracer.emit`` with caller
  timestamps). Within one thread, ``Tracer.span()`` / ``Tracer.use()``
  chain parents automatically through a ``contextvars`` slot.
- **Lock-cheap.** Open spans are plain objects held by the caller; the
  tracer takes its lock only when a span FINISHES (one bounded-deque
  append per span, a handful of spans per request). Nothing here runs
  inside jitted code — creating host spans in a traced region is the
  DML003 lint violation (:mod:`distmlip_tpu.analysis.lint`).
- **One clock.** All span timestamps come from ``Tracer.now()``
  (``time.perf_counter`` by default, injectable) so retroactive and live
  spans land on one timeline; ``t_wall0`` anchors it to wall time for
  incident stamps.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import deque

# root span names that mark a trace as a REQUEST trace (vs batch-level
# traces like serve.batch); the completeness gate in export.py keys on
# these
REQUEST_ROOT_NAMES = ("fleet.submit", "engine.submit")
# the one terminal span every complete request trace must contain exactly
# once, whatever path the request took (dispatch, cache hit, coalesce,
# failover re-dispatch, shed, error)
TERMINAL_SPAN_NAME = "future.resolve"

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "distmlip_obs_span", default=None)


def _ctx_of(parent):
    """Normalize a Span / (trace_id, span_id) tuple / None to a ctx."""
    if parent is None:
        return None
    if isinstance(parent, Span):
        return (parent.trace_id, parent.span_id)
    return (parent[0], parent[1])


class Span:
    """One span: open until ``t_end`` is set by ``Tracer.end``."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t_start",
                 "t_end", "status", "attrs", "links")

    def __init__(self, trace_id, span_id, parent_id, name, t_start,
                 attrs=None, links=()):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t_start = float(t_start)
        self.t_end = None
        self.status = "open"
        self.attrs = dict(attrs) if attrs else None
        self.links = tuple(_ctx_of(l) for l in links)

    @property
    def ctx(self) -> tuple:
        return (self.trace_id, self.span_id)

    @property
    def duration_s(self) -> float:
        return (self.t_end - self.t_start) if self.t_end is not None else 0.0

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "t_start": self.t_start, "t_end": self.t_end,
            "status": self.status, "attrs": self.attrs or {},
            "links": [list(l) for l in self.links],
        }

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"status={self.status})")


class RequestTrace:
    """Per-request trace handle carried ACROSS THREADS on the request
    object (``_Request.trace`` / ``_Routed.trace``): the request's span
    context, the open root span when this layer OWNS the trace (None when
    an outer layer — the router above an engine — owns it and will close
    it), and the tracer-clock submit timestamp retroactive spans anchor
    on."""

    __slots__ = ("ctx", "root", "t_submit")

    def __init__(self, ctx, root, t_submit):
        self.ctx = ctx
        self.root = root
        self.t_submit = float(t_submit)

    @property
    def trace_id(self) -> str:
        return self.ctx[0]

    @property
    def span_id(self) -> str:
        return self.ctx[1]


class Tracer:
    """Bounded in-memory span collector.

    Completed spans land in a ``deque(maxlen=max_spans)`` — week-long
    runs trace at constant memory and the flight recorder snapshots the
    most recent window. ``spans_dropped`` counts evictions so a
    completeness gate can tell "incomplete trace" from "evicted trace".
    """

    def __init__(self, max_spans: int = 262144, clock=None):
        self._clock = clock if clock is not None else time.perf_counter
        self.max_spans = int(max_spans)
        self._spans: deque = deque(maxlen=self.max_spans)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # id base keeps span ids unique across tracers/processes sharing
        # one artifact (two load-test runs appending to one trace dir)
        self._base = f"{os.getpid() & 0xFFFF:04x}{id(self) & 0xFFF:03x}"
        self.spans_finished = 0
        self.t_wall0 = time.time() - self.now()   # wall anchor for exports

    # ------------------------------------------------------------------
    # core
    # ------------------------------------------------------------------

    def now(self) -> float:
        return self._clock()

    def current(self) -> tuple | None:
        """The ambient (trace_id, span_id) context of THIS thread."""
        return _CURRENT.get()

    def begin(self, name: str, parent=None, attrs=None, links=(),
              t_start=None, new_trace: bool = False) -> Span:
        """Open a span. Parent resolution: explicit ``parent`` wins, then
        the thread's ambient context, then a fresh trace (``new_trace``
        forces the fresh trace even when an ambient context exists)."""
        pctx = None if new_trace else (_ctx_of(parent) or _CURRENT.get())
        n = next(self._ids)
        span_id = f"{self._base}.{n:x}"
        trace_id = pctx[0] if pctx is not None else f"T{span_id}"
        return Span(trace_id, span_id, pctx[1] if pctx is not None else "",
                    name, self.now() if t_start is None else t_start,
                    attrs=attrs, links=links)

    def end(self, span: Span, status: str = "ok", t_end=None,
            attrs=None) -> Span:
        """Close a span and commit it to the buffer (idempotent)."""
        if span.t_end is not None:
            return span
        span.t_end = self.now() if t_end is None else float(t_end)
        span.status = status
        if attrs:
            span.attrs = {**(span.attrs or {}), **attrs}
        with self._lock:
            self._spans.append(span)
            self.spans_finished += 1
        return span

    def emit(self, name: str, parent=None, t_start=None, t_end=None,
             status: str = "ok", attrs=None, links=(),
             new_trace: bool = False) -> Span:
        """One-shot closed span with caller-supplied (retroactive)
        timestamps; ``t_start``/``t_end`` default to now (instant span)."""
        now = self.now()
        s = self.begin(name, parent=parent, attrs=attrs, links=links,
                       t_start=now if t_start is None else t_start,
                       new_trace=new_trace)
        return self.end(s, status=status,
                        t_end=now if t_end is None else t_end)

    @contextlib.contextmanager
    def span(self, name: str, parent=None, attrs=None, links=(),
             new_trace: bool = False):
        """Live span context manager; sets the ambient context so nested
        spans (and instrumented callees) chain under it."""
        s = self.begin(name, parent=parent, attrs=attrs, links=links,
                       new_trace=new_trace)
        token = _CURRENT.set(s.ctx)
        try:
            yield s
        except BaseException:
            self.end(s, status="error")
            raise
        else:
            self.end(s)
        finally:
            _CURRENT.reset(token)

    @contextlib.contextmanager
    def use(self, parent):
        """Set the ambient context WITHOUT opening a span (hand a stored
        request/batch context to code that reads ``current()``)."""
        token = _CURRENT.set(_ctx_of(parent))
        try:
            yield
        finally:
            _CURRENT.reset(token)

    # ------------------------------------------------------------------
    # request helpers (the one idiom engine/router instrumentation uses)
    # ------------------------------------------------------------------

    def start_request(self, name: str, attrs=None) -> RequestTrace:
        """Open a request ROOT span in a fresh trace and return the
        cross-thread handle. The caller that resolves the request must
        call :meth:`finish_request` exactly once."""
        root = self.begin(name, attrs=attrs, new_trace=True)
        return RequestTrace(root.ctx, root, root.t_start)

    def adopt_request(self, ctx=None) -> RequestTrace | None:
        """Join an OUTER layer's request trace (root=None: the outer
        layer closes it); ``ctx`` defaults to the ambient context.
        Returns None when there is nothing to join."""
        ctx = _ctx_of(ctx) if ctx is not None else _CURRENT.get()
        if ctx is None:
            return None
        return RequestTrace(ctx, None, self.now())

    def finish_request(self, trace: RequestTrace, status: str = "ok",
                       attrs=None) -> None:
        """Emit the terminal ``future.resolve`` span and close the root
        (no-op for adopted traces — the owner closes those)."""
        if trace is None or trace.root is None:
            return
        now = self.now()
        self.emit(TERMINAL_SPAN_NAME, parent=trace.ctx, t_start=now,
                  t_end=now, status=status, attrs=attrs)
        self.end(trace.root, status=status, t_end=now)

    # ------------------------------------------------------------------
    # introspection / export
    # ------------------------------------------------------------------

    @property
    def spans_dropped(self) -> int:
        with self._lock:
            return self.spans_finished - len(self._spans)

    def spans(self) -> list:
        """Snapshot of the completed-span buffer (oldest first)."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.spans_finished = 0

    def trace_events(self) -> dict:
        """Chrome/Perfetto ``trace_event`` JSON object (see export.py)."""
        from .export import to_trace_events

        return to_trace_events(self.spans(), t_wall0=self.t_wall0)

    def write(self, path: str) -> str:
        """Write the Perfetto-loadable trace JSON; returns ``path``."""
        from .export import write_trace

        return write_trace(path, self.spans(), t_wall0=self.t_wall0)
