"""Per-tenant latency SLOs with multi-window burn-rate evaluation.

The standard SRE alerting shape: an SLO of "``objective`` of requests
complete under ``latency_s``" leaves an error budget of ``1 -
objective``; the *burn rate* over a window is the observed bad-request
fraction divided by that budget (burn 1.0 = exactly spending the budget,
14.4 = spending a 30-day budget in 2 days). A breach fires only when
BOTH a fast window (catches sharp regressions quickly) and a slow window
(rejects blips) exceed their thresholds — the classic multi-window
multi-burn-rate rule, which is what keeps a single slow request from
paging.

On breach the monitor calls ``on_breach(tenant, info)`` — wired by
:class:`~distmlip_tpu.obs.Observability` to the flight recorder, so a
p99 regression on hardware you can't reproduce locally leaves behind a
trace + metrics incident instead of a mystery. Breaches are
cooldown-limited per tenant.

Everything is clock-injectable and lock-guarded (observations arrive
from router completion callbacks on many threads); per-tenant state is a
pruned deque bounded by the slow window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass


@dataclass
class SLOConfig:
    """One tenant's latency SLO + burn-rate alerting policy."""

    latency_s: float = 1.0        # a request over this is "bad"
    objective: float = 0.99       # target good fraction
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 14.4       # breach thresholds (burn-rate units)
    slow_burn: float = 6.0
    min_requests: int = 12        # no verdicts on tiny samples
    cooldown_s: float = 300.0     # min seconds between breach firings

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        if self.fast_window_s > self.slow_window_s:
            raise ValueError("fast_window_s must be <= slow_window_s")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective


class _TenantSLO:
    __slots__ = ("config", "events", "good", "bad", "breaches",
                 "last_breach_t")

    def __init__(self, config: SLOConfig):
        self.config = config
        self.events: deque = deque()    # (t, bad: bool)
        self.good = 0
        self.bad = 0
        self.breaches = 0
        self.last_breach_t = None


class SLOMonitor:
    """Observe per-request latencies; evaluate burn rates; fire breaches."""

    def __init__(self, default: SLOConfig | None = None,
                 per_tenant: dict | None = None, clock=None,
                 on_breach=None):
        self.default = default or SLOConfig()
        self.per_tenant = dict(per_tenant or {})
        self._clock = clock or time.monotonic
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantSLO] = {}

    def _state(self, tenant: str) -> _TenantSLO:
        st = self._tenants.get(tenant)
        if st is None:
            st = _TenantSLO(self.per_tenant.get(tenant, self.default))
            self._tenants[tenant] = st
        return st

    def observe(self, tenant: str, latency_s: float,
                ok: bool = True) -> None:
        """Record one completed request; evaluates (and possibly fires)
        only on BAD events — good traffic costs one deque append."""
        now = self._clock()
        fire = None
        with self._lock:
            st = self._state(tenant)
            bad = (not ok) or latency_s > st.config.latency_s
            st.events.append((now, bad))
            if bad:
                st.bad += 1
            else:
                st.good += 1
            self._prune(st, now)
            if bad:
                fire = self._evaluate_locked(st, tenant, now)
        if fire is not None and self.on_breach is not None:
            self.on_breach(tenant, fire)

    @staticmethod
    def _prune(st: _TenantSLO, now: float) -> None:
        horizon = now - st.config.slow_window_s
        ev = st.events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    def _window_burn(self, st: _TenantSLO, now: float,
                     window_s: float) -> tuple[float, int]:
        t0 = now - window_s
        n = bad = 0
        for t, b in reversed(st.events):
            if t < t0:
                break
            n += 1
            bad += int(b)
        if n == 0:
            return 0.0, 0
        return (bad / n) / st.config.error_budget, n

    def burn_rates(self, tenant: str) -> dict:
        """Current {fast, slow} burn rates (+ window sample counts)."""
        now = self._clock()
        with self._lock:
            st = self._state(tenant)
            self._prune(st, now)
            fast, n_fast = self._window_burn(st, now,
                                             st.config.fast_window_s)
            slow, n_slow = self._window_burn(st, now,
                                             st.config.slow_window_s)
        return {"fast": fast, "slow": slow,
                "fast_n": n_fast, "slow_n": n_slow}

    def _evaluate_locked(self, st: _TenantSLO, tenant: str,
                         now: float) -> dict | None:
        cfg = st.config
        fast, n_fast = self._window_burn(st, now, cfg.fast_window_s)
        slow, n_slow = self._window_burn(st, now, cfg.slow_window_s)
        if n_slow < cfg.min_requests:
            return None
        if fast < cfg.fast_burn or slow < cfg.slow_burn:
            return None
        if (st.last_breach_t is not None
                and now - st.last_breach_t < cfg.cooldown_s):
            return None
        st.breaches += 1
        st.last_breach_t = now
        return {
            "tenant": tenant,
            "fast_burn": round(fast, 3), "slow_burn": round(slow, 3),
            "fast_n": n_fast, "slow_n": n_slow,
            "latency_slo_s": cfg.latency_s,
            "objective": cfg.objective,
            "breach_count": st.breaches,
        }

    def snapshot(self) -> dict:
        with self._lock:
            tenants = list(self._tenants.items())
        out = {}
        for name, st in tenants:
            out[name] = {
                "good": st.good, "bad": st.bad, "breaches": st.breaches,
                **self.burn_rates(name),
            }
        return out
