"""Compile telemetry: one event per compile, fresh vs AOT-rehydrated.

The fourth observability plane's front door. Every compile point in the
stack — ``BatchedPotential`` bucket compiles, ``DistPotential`` runtime
builds, AOT rehydrates in ``fleet/aot.py``, train-step compiles in
``train/loop.py`` — calls :func:`record_compile` with the measured wall
time, the bucket key that triggered it, and the compile ``kind``:

- ``"fresh"`` — a real trace+lower+compile (XLA did the work now);
- ``"aot"``   — a ``jax.export`` rehydrate from the fleet AOT cache
  (deserialization cost only; the restart gate's whole point is that
  these are NOT compiles in the ``compile_count == 0`` sense).

Events land in a bounded process-global :class:`CompileLog` (cheap, lock
+ deque; always on) and — when an observability hub is installed — in
the metrics registry as ``distmlip_compile_seconds{site,kind}`` and
``distmlip_compiles_total{site,kind}``. With nothing installed a call
costs one deque append; the potential/model hot path never calls this
(compiles are rare by construction).

Nothing here imports jax — importable from every instrumented layer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from . import runtime as obsrt

__all__ = [
    "COMPILE_BUCKETS",
    "CompileEvent",
    "compile_counts",
    "compile_events",
    "record_compile",
    "reset_compile_log",
]

# histogram buckets for compile wall time: 1 ms .. ~17 min, log scale
# (bucket compiles run ~100ms..minutes; AOT rehydrates ~1-100 ms)
COMPILE_BUCKETS = tuple(1e-3 * 2**i for i in range(21))

KIND_FRESH = "fresh"
KIND_AOT = "aot"


@dataclass
class CompileEvent:
    """One compile (or AOT rehydrate) observed anywhere in the process."""

    site: str            # "batched_bucket" | "dist_build" | "aot_dispatch" | "train_step" | ...
    kind: str            # "fresh" | "aot"
    wall_s: float        # measured trace+lower+compile (or rehydrate) wall time
    bucket_key: str = ""
    executable_bytes: int = 0   # serialized executable size when known (AOT path)
    t_wall: float = field(default_factory=time.time)

    def as_dict(self) -> dict:
        return {
            "site": self.site,
            "kind": self.kind,
            "wall_s": round(self.wall_s, 6),
            "bucket_key": self.bucket_key,
            "executable_bytes": self.executable_bytes,
            "t_wall": self.t_wall,
        }


class CompileLog:
    """Bounded, thread-safe in-process event log (newest-last)."""

    def __init__(self, maxlen: int = 4096):
        self._events: deque[CompileEvent] = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def append(self, ev: CompileEvent) -> None:
        with self._lock:
            self._events.append(ev)

    def events(self) -> list[CompileEvent]:
        with self._lock:
            return list(self._events)

    def counts(self) -> dict[str, int]:
        """{kind: n} over the retained window."""
        out: dict[str, int] = {}
        with self._lock:
            for ev in self._events:
                out[ev.kind] = out.get(ev.kind, 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


_LOG = CompileLog()


def record_compile(site: str, kind: str, wall_s: float, bucket_key: str = "",
                   executable_bytes: int = 0) -> CompileEvent:
    """Record one compile event; feeds the global log + metrics registry.

    Never raises into the caller — a broken metrics backend must not
    fail a compile that already succeeded.
    """
    ev = CompileEvent(site=site, kind=kind, wall_s=float(wall_s),
                      bucket_key=str(bucket_key),
                      executable_bytes=int(executable_bytes))
    _LOG.append(ev)
    reg = obsrt.metrics()
    if reg is not None:
        try:
            reg.histogram(
                "distmlip_compile_seconds",
                "Wall time of compiles by site and kind (fresh|aot)",
                labels=("site", "kind"),
                buckets=COMPILE_BUCKETS).labels(
                    site=site, kind=kind).observe(ev.wall_s)
            reg.counter(
                "distmlip_compiles_total",
                "Compile events by site and kind (fresh|aot)",
                labels=("site", "kind")).labels(
                    site=site, kind=kind).inc()
        except Exception:  # noqa: BLE001 - metrics must not break compiles
            pass
    return ev


def compile_events() -> list[CompileEvent]:
    """Every retained event, oldest first."""
    return _LOG.events()


def compile_counts() -> dict[str, int]:
    """{kind: count} over the retained window — the fresh-vs-aot split."""
    return _LOG.counts()


def reset_compile_log() -> None:
    """Tests / fresh measurement windows."""
    _LOG.clear()
