"""Trace export + offline analysis: Perfetto JSON, completeness, critical
paths.

- :func:`to_trace_events` / :func:`write_trace` — Chrome/Perfetto
  ``trace_event`` JSON (complete "X" events, one lane per trace, span
  identity + links riding ``args``). Drop the file on ``ui.perfetto.dev``.
- :func:`load_trace` — round-trips an exported file back into the span
  dicts every function here consumes.
- :func:`request_trace_summary` — the ``trace_complete`` gate: every
  request trace (root named in ``REQUEST_ROOT_NAMES``) must be CLOSED and
  contain exactly one terminal ``future.resolve`` span, whatever path the
  request took (dispatch, cache hit, coalesce, failover re-dispatch,
  shed); a root that was rejected at the door closes without a terminal.
- :func:`critical_paths` / :func:`critical_path_summary` — per-request
  breakdown (queue vs pack vs compile vs device vs resolve), following
  the span links from batch-dispatch traces back to their member
  requests, plus an interval-union COVERAGE measure (what fraction of
  the request's wall time the spans explain — the 10%-accounting
  acceptance check) and the ``queue_dominant`` flag (median queue wait
  exceeding median device time: add capacity, not kernels).
"""

from __future__ import annotations

import json
import os

from .tracing import REQUEST_ROOT_NAMES, TERMINAL_SPAN_NAME, Span

# batch-level trace roots: their links point at member request contexts
BATCH_ROOT_NAMES = ("serve.batch", "serve.fallback")

# component classification for the critical-path table
_COMPONENT_OF = {
    "engine.queue": "queue",
    "router.queue": "queue",
    "router.route": "queue",
    "tenancy.admit": "queue",
    "router.requeue": "queue",
    "scheduler.plan_batch": "plan",
    "batched.pack": "pack",
    "device.compile": "compile",
    "device.dispatch": "device",
    "cache.hit": "cache",
    "coalesce": "coalesce",
    TERMINAL_SPAN_NAME: "resolve",
}
COMPONENTS = ("queue", "plan", "pack", "compile", "device", "cache",
              "coalesce", "resolve")


def _as_dict(span) -> dict:
    return span.to_dict() if isinstance(span, Span) else dict(span)


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace_event JSON
# ---------------------------------------------------------------------------


def to_trace_events(spans, t_wall0: float = 0.0) -> dict:
    """Chrome ``trace_event`` JSON object: one ``tid`` lane per trace,
    complete ("X") events in microseconds, span identity in ``args``."""
    spans = [_as_dict(s) for s in spans]
    tids: dict[str, int] = {}
    events = []
    for s in spans:
        tid = tids.setdefault(s["trace_id"], len(tids) + 1)
        if s["t_end"] is None:
            continue   # open spans have no duration to draw
        events.append({
            "name": s["name"],
            "cat": "distmlip",
            "ph": "X",
            "pid": 1,
            "tid": tid,
            "ts": round(1e6 * s["t_start"], 3),
            "dur": round(1e6 * (s["t_end"] - s["t_start"]), 3),
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_id": s["parent_id"],
                "status": s["status"],
                "links": [list(l) for l in s["links"]],
                **{k: v for k, v in (s.get("attrs") or {}).items()},
            },
        })
    # name each lane after its trace so Perfetto's track list is readable
    for trace_id, tid in tids.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": f"trace {trace_id}"},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"t_wall0": t_wall0, "producer": "distmlip_tpu.obs"},
    }


def write_trace(path: str, spans, t_wall0: float = 0.0) -> str:
    obj = to_trace_events(spans, t_wall0=t_wall0)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)
    return path


def load_trace(path: str) -> list[dict]:
    """Read an exported trace file back into span dicts (events without
    span identity — foreign trace files — are skipped)."""
    with open(path) as f:
        obj = json.load(f)
    events = obj.get("traceEvents", obj if isinstance(obj, list) else [])
    spans = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if "trace_id" not in args or "span_id" not in args:
            continue
        t0 = ev["ts"] / 1e6
        attrs = {k: v for k, v in args.items()
                 if k not in ("trace_id", "span_id", "parent_id",
                              "status", "links")}
        spans.append({
            "trace_id": args["trace_id"], "span_id": args["span_id"],
            "parent_id": args.get("parent_id", ""),
            "name": ev.get("name", ""),
            "t_start": t0, "t_end": t0 + ev.get("dur", 0.0) / 1e6,
            "status": args.get("status", "ok"),
            "attrs": attrs,
            "links": [tuple(l) for l in args.get("links", [])],
        })
    return spans


def load_trace_dir(path: str) -> list[dict]:
    """Load every ``*.json`` trace artifact under a directory (or a
    single file path) into one span list."""
    if os.path.isfile(path):
        return load_trace(path)
    spans: list[dict] = []
    for name in sorted(os.listdir(path)):
        if name.endswith(".json"):
            try:
                spans.extend(load_trace(os.path.join(path, name)))
            except (OSError, json.JSONDecodeError, KeyError):
                continue
    return spans


# ---------------------------------------------------------------------------
# completeness (the trace_complete gate)
# ---------------------------------------------------------------------------


def _group_by_trace(spans) -> dict[str, list[dict]]:
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        d = _as_dict(s)
        by_trace.setdefault(d["trace_id"], []).append(d)
    return by_trace


def _root_of(trace_spans) -> dict | None:
    for s in trace_spans:
        if not s["parent_id"]:
            return s
    return None


def request_trace_summary(spans) -> dict:
    """Span-tree conservation over every REQUEST trace.

    A request trace is complete when every span in it is closed and it
    contains exactly one ``future.resolve`` terminal — including the
    cache-hit and coalesce short-circuits and failover re-dispatch paths
    (span-COUNT conservation: N submissions in, N terminals out). A root
    with status ``rejected`` (quota/admission door) closes with zero
    terminals by contract.
    """
    requests = complete = 0
    incomplete: list[str] = []
    terminal_violations: list[str] = []
    n_terminals = 0
    for trace_id, ss in _group_by_trace(spans).items():
        root = _root_of(ss)
        if root is None or root["name"] not in REQUEST_ROOT_NAMES:
            continue
        requests += 1
        closed = all(s["t_end"] is not None for s in ss)
        terminals = sum(s["name"] == TERMINAL_SPAN_NAME for s in ss)
        n_terminals += terminals
        rejected = root["status"] == "rejected"
        ok_terminals = (terminals == 1) or (rejected and terminals == 0)
        if not ok_terminals:
            terminal_violations.append(trace_id)
        if closed and ok_terminals:
            complete += 1
        else:
            incomplete.append(trace_id)
    return {
        "requests": requests,
        "complete": complete,
        "terminals": n_terminals,
        "incomplete": incomplete[:16],
        "incomplete_count": len(incomplete),
        "terminal_violations": terminal_violations[:16],
        "terminal_violation_count": len(terminal_violations),
    }


# ---------------------------------------------------------------------------
# critical paths
# ---------------------------------------------------------------------------


def _union_len(intervals) -> float:
    total = 0.0
    end = None
    for a, b in sorted(intervals):
        if b <= a:
            continue
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def critical_paths(spans) -> list[dict]:
    """Per-request breakdown: seconds per component, total latency, and
    interval-union coverage (fraction of the request window explained by
    its own spans plus the batch-trace windows linked to it)."""
    spans = [_as_dict(s) for s in spans]
    by_trace = _group_by_trace(spans)
    # batch traces attribute their phase children to every linked request
    linked: dict[str, list[dict]] = {}   # request trace_id -> batch spans
    for ss in by_trace.values():
        root = _root_of(ss)
        if root is None or root["name"] not in BATCH_ROOT_NAMES:
            continue
        for link in root.get("links", ()):
            linked.setdefault(link[0], []).append(root)
            for s in ss:
                if s is not root:
                    linked.setdefault(link[0], []).append(s)
    out = []
    for trace_id, ss in by_trace.items():
        root = _root_of(ss)
        if root is None or root["name"] not in REQUEST_ROOT_NAMES:
            continue
        if root["t_end"] is None:
            continue
        w0, w1 = root["t_start"], root["t_end"]
        total = max(w1 - w0, 0.0)
        comps = dict.fromkeys(COMPONENTS, 0.0)
        intervals = []
        own = [s for s in ss if s is not root and s["t_end"] is not None]
        batch = [s for s in linked.get(trace_id, ())
                 if s["t_end"] is not None]
        for s in own + batch:
            comp = _COMPONENT_OF.get(s["name"])
            if comp is not None:
                comps[comp] += s["t_end"] - s["t_start"]
            # clip to the request window before counting coverage: a
            # batch span also serving other requests may start before
            # this request existed (it cannot — links point forward —
            # but clipping keeps the measure sound regardless)
            a, b = max(s["t_start"], w0), min(s["t_end"], w1)
            if b > a:
                intervals.append((a, b))
        covered = _union_len(intervals)
        out.append({
            "trace_id": trace_id,
            "root": root["name"],
            "status": root["status"],
            "total_s": total,
            "coverage": (covered / total) if total > 0 else 1.0,
            **{k: comps[k] for k in COMPONENTS},
        })
    return out


def _pct(sorted_xs, q):
    if not sorted_xs:
        return 0.0
    n = len(sorted_xs)
    return sorted_xs[min(n - 1, int(q * (n - 1) + 0.5))]


def critical_path_summary(spans) -> dict:
    """Percentiles per component + the queue_dominant flag.

    ``queue_dominant`` is true when the median queue wait exceeds the
    median device time (compile included): the fleet is capacity-bound —
    more replicas / bigger batches move the p99, faster kernels do not.
    This is the MACE case-study failure mode (arXiv:2504.10700) made
    visible per request instead of per run.
    """
    paths = critical_paths(spans)
    summary: dict = {"requests": len(paths)}
    if not paths:
        summary.update(components={}, queue_dominant=False,
                       coverage_p50=0.0)
        return summary
    comps = {}
    for key in (*COMPONENTS, "total_s", "coverage"):
        xs = sorted(p[key] for p in paths)
        comps[key] = {"p50": _pct(xs, 0.50), "p90": _pct(xs, 0.90),
                      "p99": _pct(xs, 0.99), "max": xs[-1]}
    device_median = comps["device"]["p50"] + comps["compile"]["p50"]
    summary["components"] = {k: comps[k] for k in COMPONENTS}
    summary["total"] = comps["total_s"]
    summary["coverage_p50"] = comps["coverage"]["p50"]
    summary["queue_dominant"] = bool(
        comps["queue"]["p50"] > 0.0
        and comps["queue"]["p50"] > device_median)
    return summary


def format_critical_path(summary: dict) -> str:
    """Render the per-request critical-path percentile table."""
    lines = [f"trace critical path ({summary.get('requests', 0)} "
             f"request(s)):"]
    comps = summary.get("components") or {}
    rows = [(k, comps[k]) for k in COMPONENTS
            if k in comps and comps[k]["max"] > 0.0]
    if "total" in summary:
        rows.append(("total", summary["total"]))
    if rows:
        lines.append("  component       p50_ms     p90_ms     p99_ms"
                     "     max_ms")
        for name, s in rows:
            lines.append(
                f"  {name:<12} {1e3 * s['p50']:9.2f} {1e3 * s['p90']:10.2f}"
                f" {1e3 * s['p99']:10.2f} {1e3 * s['max']:10.2f}")
    if "coverage_p50" in summary:
        lines.append(f"  span coverage p50={summary['coverage_p50']:.2f} "
                     f"queue_dominant={summary.get('queue_dominant')}")
    return "\n".join(lines)
