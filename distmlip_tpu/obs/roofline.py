"""Roofline accounting: arithmetic intensity + achieved vs peak FLOP/s.

Joins the two analytic planes the repo already maintains —
:func:`distmlip_tpu.utils.flops.model_flop_estimate` (FLOPs per step)
and :func:`distmlip_tpu.analysis.memory.analyze_memory` (bytes) — into
per-program :class:`RooflineRow` entries:

- **intensity** = flops / bytes_touched (FLOP per HBM byte). Bytes
  touched is the MINIMUM traffic ``arg + const + out`` of the traced
  program (every input is read at least once, every output written
  once); intermediate spills push the true number higher, so the
  intensity here is an UPPER bound and sits on the optimistic side of
  the ridge.
- **achieved** = flops / (time_s * n_devices) when a measured step time
  exists (bench JSONL, telemetry records); 0.0 otherwise.
- **mfu** = achieved / peak, with peak from
  :func:`~distmlip_tpu.utils.flops.peak_flops_per_device` (0.0 on CPU
  runs — rows still render, utilization just reads n/a).

Consumed by ``tools/roofline.py`` (CLI over the 28 contract-check
programs) and ``telemetry_report`` (roofline section when records carry
the needed fields). Host-side only; no jax imports at module scope.
"""

from __future__ import annotations

from dataclasses import dataclass


# primitives that do arithmetic (~1 FLOP per output element). Data
# movement (reshape/slice/gather/broadcast/convert/...) counts zero;
# dot_general is handled exactly below.
_FLOP_PRIMS = frozenset({
    "add", "add_any", "sub", "mul", "div", "rem", "neg", "abs", "sign",
    "max", "min", "pow", "integer_pow", "exp", "expm1", "log", "log1p",
    "sqrt", "rsqrt", "cbrt", "sin", "cos", "tan", "asin", "acos", "atan",
    "atan2", "sinh", "cosh", "tanh", "erf", "erfc", "logistic", "square",
    "reciprocal", "floor", "ceil", "round", "clamp", "nextafter",
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "cumsum",
    "psum", "select_n", "eq", "ne", "lt", "le", "gt", "ge", "and", "or",
    "not", "xor", "is_finite",
})


def _shape_elems(shape) -> float:
    n = 1.0
    for d in shape:
        n *= max(int(d), 1)
    return n


def jaxpr_flop_estimate(closed_jaxpr) -> float:
    """FLOPs of one execution of the traced program, from the jaxpr.

    Exact for ``dot_general`` (2*M*N*K over the batched output), ~1 FLOP
    per output element for elementwise/reduce arithmetic, 2 per scatter
    update (read-modify-write), zero for pure data movement. Loop/branch
    bodies count ONCE per trace (same caveat as ``iter_sites``) — a
    ``device_md`` chunk's per-chunk cost is this times its trip count.

    This is the PADDED cost — what the device executes, masked lanes
    included — which is the right numerator for roofline/MFU accounting
    (the analytic :func:`utils.flops.model_flop_estimate` prices live
    atoms/edges instead; the gap between the two is padding waste).
    """
    from ..analysis.ir import iter_sites

    flops = 0.0
    for site in iter_sites(closed_jaxpr):
        eqn = site.eqn
        name = eqn.primitive.name
        try:
            out = sum(_shape_elems(v.aval.shape) for v in eqn.outvars)
        except Exception:  # noqa: BLE001 - abstract tokens
            out = 1.0
        if name == "dot_general":
            try:
                ((lc, _), _) = eqn.params["dimension_numbers"]
                lhs = eqn.invars[0].aval.shape
                k = 1.0
                for ax in lc:
                    k *= max(int(lhs[ax]), 1)
                flops += 2.0 * out * k
            except Exception:  # noqa: BLE001 - fall back
                flops += 2.0 * out
        elif name.startswith("conv"):
            flops += 2.0 * out
        elif "scatter" in name:
            try:
                upd = _shape_elems(eqn.invars[-1].aval.shape)
            except Exception:  # noqa: BLE001
                upd = out
            flops += 2.0 * upd
        elif name in _FLOP_PRIMS:
            flops += out
    return flops


def bytes_touched(plan) -> int:
    """Minimum HBM traffic of one step from a :class:`MemoryPlan`."""
    return int(getattr(plan, "arg_bytes", 0)
               + getattr(plan, "const_bytes", 0)
               + getattr(plan, "out_bytes", 0))


@dataclass
class RooflineRow:
    """One program's position on the roofline."""

    program: str
    flops: float = 0.0            # analytic FLOPs per step
    bytes: float = 0.0            # minimum HBM bytes per step
    time_s: float = 0.0           # measured step device time (0 = none)
    peak_flops: float = 0.0       # per-device peak x n_devices (0 = unknown)
    n_devices: int = 1
    source: str = "cost_model"    # "measured" when time_s came from a run

    @property
    def intensity(self) -> float:
        return self.flops / self.bytes if self.bytes > 0 else 0.0

    @property
    def achieved_flops(self) -> float:
        """Aggregate achieved FLOP/s across the devices that ran it."""
        return self.flops / self.time_s if self.time_s > 0 else 0.0

    @property
    def mfu(self) -> float:
        total_peak = self.peak_flops * max(self.n_devices, 1)
        if total_peak <= 0 or self.time_s <= 0:
            return 0.0
        return self.achieved_flops / total_peak

    @property
    def ridge_bound(self) -> str:
        """Which roof limits this program at ``peak_flops`` — "compute"
        when its intensity clears the ridge point assuming the canonical
        ~1 TB/s-class HBM per peak-PFLOP ratio is unknown; "" when peak
        is unknown (no basis to place the ridge)."""
        if self.peak_flops <= 0 or self.intensity <= 0:
            return ""
        # ridge = peak_flops / hbm_bw; without a per-chip BW table use
        # the conservative 100 FLOP/byte watershed typical of TPU gens
        return "compute" if self.intensity >= 100.0 else "memory"

    def as_dict(self) -> dict:
        return {
            "program": self.program,
            "flops": self.flops,
            "bytes": self.bytes,
            "intensity": round(self.intensity, 3),
            "time_s": self.time_s,
            "achieved_flops": self.achieved_flops,
            "peak_flops": self.peak_flops,
            "n_devices": self.n_devices,
            "mfu": round(self.mfu, 6),
            "ridge_bound": self.ridge_bound,
            "source": self.source,
        }


def _fmt_si(x: float) -> str:
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(x) >= div:
            return f"{x / div:.2f}{suf}"
    return f"{x:.1f}"


def format_roofline_table(rows, title: str = "roofline") -> str:
    """Fixed-width table over :class:`RooflineRow` entries."""
    lines = [title,
             f"  {'program':<38} {'flops':>9} {'bytes':>9} {'F/B':>8} "
             f"{'time_s':>9} {'achieved':>9} {'mfu':>7} {'bound':>7}"]
    for r in rows:
        mfu = f"{r.mfu:.1%}" if r.mfu > 0 else "n/a"
        ach = _fmt_si(r.achieved_flops) if r.time_s > 0 else "n/a"
        t = f"{r.time_s:.5f}" if r.time_s > 0 else "n/a"
        lines.append(
            f"  {r.program:<38.38} {_fmt_si(r.flops):>9} "
            f"{_fmt_si(r.bytes):>9} {r.intensity:>8.2f} {t:>9} "
            f"{ach:>9} {mfu:>7} {r.ridge_bound or 'n/a':>7}")
    return "\n".join(lines)


def rows_from_records(records) -> list:
    """Roofline rows recoverable from telemetry StepRecords.

    Groups records by ``(kind, bucket_key)``; a group yields a row only
    when some record carries a FLOP estimate (``extra["flops_per_step"]``
    — bench/CLI-stamped; plain serving records don't have one). Bytes
    come from ``est_peak_bytes`` as a traffic PROXY (it is a live-set
    peak, not traffic — rows from records are for trending only, the
    jaxpr-accurate numbers come from ``tools/roofline.py``). Mixed
    rounds where only some records carry the fields degrade to fewer
    rows, never to a KeyError.
    """
    groups: dict[tuple, list] = {}
    for r in records:
        key = (getattr(r, "kind", ""), getattr(r, "bucket_key", ""))
        groups.setdefault(key, []).append(r)
    rows = []
    for (kind, bucket), recs in sorted(groups.items()):
        flops = 0.0
        nbytes = 0.0
        times = []
        n_dev = 1
        for r in recs:
            extra = getattr(r, "extra", None) or {}
            try:
                f = float(extra.get("flops_per_step", 0.0) or 0.0)
            except (TypeError, ValueError):
                f = 0.0
            flops = max(flops, f)
            nbytes = max(nbytes, float(getattr(r, "est_peak_bytes", 0) or 0))
            t = (getattr(r, "timings", None) or {}).get("device_s", 0.0)
            if t and not getattr(r, "compiled", False):
                times.append(float(t))  # warm steps only — compiles skew
            n_dev = max(n_dev, int(getattr(r, "num_partitions", 0) or 0) or 1)
        if flops <= 0:
            continue
        times.sort()
        t_med = times[len(times) // 2] if times else 0.0
        from ..utils.flops import peak_flops_per_device

        name = kind + (f"[{bucket}]" if bucket else "")
        rows.append(RooflineRow(
            program=name, flops=flops, bytes=nbytes, time_s=t_med,
            peak_flops=peak_flops_per_device(), n_devices=n_dev,
            source="measured" if t_med > 0 else "cost_model"))
    return rows


__all__ = [
    "RooflineRow",
    "bytes_touched",
    "format_roofline_table",
    "jaxpr_flop_estimate",
    "rows_from_records",
]
