"""Lattice / periodic-boundary geometry.

Host-side (numpy, float64) helpers used by neighbor search and partitioning,
plus device-side (jax) variants used inside jitted model code (strain
application for stress, edge-vector computation).

Reference semantics being matched (behavior, not code):
  - fractional wrapping only along periodic axes, original shift retained for
    image-offset correction (reference fpis.c:490-517);
  - cartesian->wrapped-fractional helper (reference dist.py:128-156).

Conventions:
  - ``lattice`` is a (3, 3) array whose **rows** are the lattice vectors, so
    ``cart = frac @ lattice``.
  - image ``offsets`` are integer (3,) vectors such that the neighbor position
    in the *input* (unwrapped) frame is ``cart[j] + offsets @ lattice``.
"""

from __future__ import annotations

import numpy as np


def cart_to_frac(cart: np.ndarray, lattice: np.ndarray) -> np.ndarray:
    """Cartesian -> fractional: solve frac @ lattice = cart."""
    return np.linalg.solve(lattice.T, np.asarray(cart, dtype=np.float64).T).T


def frac_to_cart(frac: np.ndarray, lattice: np.ndarray) -> np.ndarray:
    return np.asarray(frac, dtype=np.float64) @ np.asarray(lattice, dtype=np.float64)


def wrap_frac(frac: np.ndarray, pbc: np.ndarray):
    """Wrap fractional coords into [0, 1) along periodic axes.

    Returns (wrapped_frac, shift) where ``shift`` is the integer number of
    lattice translations removed: ``wrapped = frac - shift`` with ``shift = 0``
    on non-periodic axes.
    """
    frac = np.asarray(frac, dtype=np.float64)
    pbc_mask = np.asarray(pbc, dtype=bool)
    shift = np.where(pbc_mask[None, :], np.floor(frac), 0.0)
    wrapped = frac - shift
    # Guard against frac values like -1e-16 -> wrapped == 1.0 exactly.
    on_edge = pbc_mask[None, :] & (wrapped >= 1.0)
    shift = shift + np.where(on_edge, 1.0, 0.0)
    wrapped = frac - shift
    return wrapped, shift.astype(np.int64)


def wrap_positions(cart: np.ndarray, lattice: np.ndarray, pbc) -> tuple[np.ndarray, np.ndarray]:
    """Wrap cartesian positions into the cell; returns (wrapped_cart, shift)."""
    frac = cart_to_frac(cart, lattice)
    wrapped, shift = wrap_frac(frac, pbc)
    return frac_to_cart(wrapped, lattice), shift


def plane_spacings(lattice: np.ndarray) -> np.ndarray:
    """Distance between adjacent lattice planes along each axis.

    ``d_i = 1 / |row_i(inv(lattice))|`` — used to size the periodic-image
    search window (reference fpis.c:507-517 uses the reciprocal lattice for
    the same purpose).
    """
    inv = np.linalg.inv(np.asarray(lattice, dtype=np.float64))
    return 1.0 / np.linalg.norm(inv, axis=0)


def cell_volume(lattice: np.ndarray) -> float:
    return float(abs(np.linalg.det(np.asarray(lattice, dtype=np.float64))))


def make_supercell(
    frac: np.ndarray, lattice: np.ndarray, reps: tuple[int, int, int]
) -> tuple[np.ndarray, np.ndarray]:
    """Tile a unit cell ``reps`` times along each axis.

    Returns (frac_coords_of_supercell, supercell_lattice). Species tiling is
    the caller's job (``np.tile(species, np.prod(reps))`` — image-major order
    matching the returned coordinates).
    """
    frac = np.asarray(frac, dtype=np.float64)
    nx, ny, nz = reps
    shifts = np.stack(
        np.meshgrid(np.arange(nx), np.arange(ny), np.arange(nz), indexing="ij"),
        axis=-1,
    ).reshape(-1, 3)
    new_frac = (frac[None, :, :] + shifts[:, None, :]).reshape(-1, 3)
    new_frac /= np.array([nx, ny, nz], dtype=np.float64)
    new_lattice = np.asarray(lattice, dtype=np.float64) * np.array(reps, dtype=np.float64)[:, None]
    return new_frac, new_lattice


# ---------------------------------------------------------------------------
# Device-side (jax) helpers — safe to call inside jit.
# ---------------------------------------------------------------------------

def edge_vectors(positions, lattice, src, dst, offsets):
    """Edge displacement vectors r_dst - r_src + offsets @ lattice (jax).

    ``positions`` (N,3), ``lattice`` (3,3) rows=vectors, ``src``/``dst`` (E,),
    ``offsets`` (E,3) float or int. Differentiable wrt positions and lattice.
    """
    import jax.numpy as jnp

    disp = positions[dst] - positions[src]
    return disp + jnp.asarray(offsets, dtype=positions.dtype) @ lattice


def apply_strain(positions, lattice, strain):
    """Apply a symmetric strain: x -> x @ (I + strain).

    Used for stress: stress = (1/V) dE/dstrain at strain=0 (reference
    pes.py:140-145 computes the same through torch autograd).
    """
    import jax.numpy as jnp

    defm = jnp.eye(3, dtype=positions.dtype) + 0.5 * (strain + strain.T)
    return positions @ defm, lattice @ defm
