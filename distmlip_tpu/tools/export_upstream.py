"""Export an upstream torch checkpoint into a portable npz for this
framework's ``from_torch`` ingestion.

Run this IN AN ENVIRONMENT WITH THE UPSTREAM PACKAGE INSTALLED (mace-torch /
matgl); this image does not ship them. The reference's ``from_existing``
wraps a live upstream module (mace/models.py:252-263); the TPU-native flow
is instead: export once here, then load the npz anywhere:

    # in a mace-torch environment
    python -m distmlip_tpu.tools.export_upstream mace /path/to/model.pt out.npz

    # in this framework (model= validates checkpoint constants vs the config)
    sd = dict(np.load("out.npz"))
    params, report = from_torch("mace", sd, model.init(key), model=model)

The export includes every state-dict tensor AND buffer (mace's
symmetric-contraction U matrices ride along as buffers, which is what makes
the exact product-basis change in models/convert.py possible), plus a CG
sign calibration: e3nn's wigner_3j and this framework's
real_clebsch_gordan agree up to a per-(l1,l2,l3) sign, which is resolved
here — where e3nn is importable — and recorded as ``__cg_sign__.{l1}.{l2}.{l3}``
entries that the mace mapping folds into the radial-MLP output blocks.
"""

from __future__ import annotations

import sys

import numpy as np


def _cg_signs(l_max: int = 3) -> dict:
    """Per-(l1,l2,l3) sign s with real_clebsch_gordan = s*sqrt(2l3+1)*w3j."""
    try:
        from e3nn import o3
    except ImportError:
        print("WARNING: e3nn not importable; CG sign calibration skipped "
              "(conversion assumes matching sign conventions)")
        return {}
    from ..ops.so3 import real_clebsch_gordan

    out = {}
    for l1 in range(l_max + 1):
        for l2 in range(l_max + 1):
            for l3 in range(abs(l1 - l2), min(l1 + l2, l_max) + 1):
                if (l1 + l2 + l3) % 2:
                    continue
                w3j = o3.wigner_3j(l1, l2, l3).numpy()
                ours = real_clebsch_gordan(l1, l2, l3)
                scaled = np.sqrt(2 * l3 + 1) * w3j
                dot = float(np.sum(ours * scaled))
                norm = float(np.sqrt(np.sum(ours**2) * np.sum(scaled**2)))
                align = dot / max(norm, 1e-12)
                if abs(abs(align) - 1.0) > 1e-4:
                    # a ±1 calibration cannot represent this; exporting one
                    # anyway would produce a silently wrong potential
                    raise RuntimeError(
                        f"CG ({l1},{l2},{l3}) bases differ beyond a sign "
                        f"(|cos|={abs(align):.6f}); conversion needs a full "
                        f"per-path basis alignment — please report this "
                        f"combination"
                    )
                out[f"__cg_sign__.{l1}.{l2}.{l3}"] = np.array(
                    1.0 if align >= 0 else -1.0
                )
    return out


def export_mace(model_path: str, out_path: str) -> None:
    import torch

    model = torch.load(model_path, map_location="cpu", weights_only=False)
    if hasattr(model, "models"):  # mace calculators wrap a list
        model = model.models[0]
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    sd.update(_cg_signs(int(getattr(model, "max_ell", 3))))
    np.savez_compressed(out_path, **sd)
    print(f"exported {len(sd)} tensors -> {out_path}")


def export_state_dict(model_path: str, out_path: str) -> None:
    """matgl (chgnet/tensornet) and fairchem (escn/UMA) exporter.

    Loads the checkpoint and dumps every state-dict tensor; the per-arch
    MAPPINGS handle the prefixes as-is ("model." for matgl Potential dumps,
    "backbone." for whole-model UMA dumps). Plain state-dict checkpoints
    (fairchem's format) load without the upstream package; pickled Module
    checkpoints need it importable for unpickling.
    """
    import torch

    obj = torch.load(model_path, map_location="cpu", weights_only=False)
    if isinstance(obj, dict):
        # fairchem-style: {"state_dict": ...} or a raw state dict
        sd = obj.get("state_dict", obj)
        sd = {k: v for k, v in sd.items() if hasattr(v, "detach")}
        # fairchem wraps in DDP-ish prefixes: strip a leading "module."
        sd = {(k[len("module."):] if k.startswith("module.") else k): v
              for k, v in sd.items()}
    else:
        # matgl Potential wrappers export whole: the mappings accept the
        # "model." prefix, and data_mean/std/element_refs ride along
        sd = obj.state_dict()
    # bf16 (and other non-numpy) dtypes upcast to fp32 for the npz
    numpy_ok = (torch.float32, torch.float64, torch.int32, torch.int64,
                torch.bool, torch.int8, torch.uint8, torch.int16)
    out = {k: (v.detach().cpu().numpy() if v.dtype in numpy_ok
               else v.detach().cpu().float().numpy())
           for k, v in sd.items()}
    np.savez_compressed(out_path, **out)
    print(f"exported {len(out)} tensors -> {out_path}")


_EXPORTERS = {
    "mace": export_mace,
    "chgnet": export_state_dict,
    "tensornet": export_state_dict,
    "escn": export_state_dict,
}


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 3 or argv[0] not in _EXPORTERS:
        print(__doc__)
        print("usage: python -m distmlip_tpu.tools.export_upstream "
              f"{{{'|'.join(sorted(_EXPORTERS))}}} <model.pt> <out.npz>")
        return 2
    _EXPORTERS[argv[0]](argv[1], argv[2])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
