"""One-command upstream-parity verification (VERDICT r4 item 6).

    python -m distmlip_tpu.tools.verify_upstream <family> <ckpt> \
        [--set key=val ...] [--out report.json]

family: mace | chgnet | tensornet | escn. <ckpt> is an upstream torch
checkpoint (or an npz already produced by tools/export_upstream).

What it does, end to end:
  1. export   — dump every state-dict tensor to npz (export_upstream);
  2. infer    — derive the model config from tensor SHAPES (anything not
                shape-derivable falls back to the upstream default and is
                printed; override with --set key=val);
  3. convert  — from_torch with strict=True + constant validation;
  4. ours     — evaluate E/F on a deterministic fixture crystal through
                DistPotential at P=1 and P=2 (internal dist consistency);
  5. upstream — evaluate the SAME fixture with the live upstream package
                (mace-torch / matgl / fairchem + ase) when importable and
                compare; otherwise print SKIP.

Run it wherever the upstream package IS installed to close the loop the
zero-egress build image cannot: the reference's ``from_existing``
workflow (implementations/matgl/models/chgnet.py:551-560,
implementations/uma/escn_md.py:559-569) verified numerically, one
command, PASS/FAIL per family. Exit codes: 0 full PASS, 1 FAIL,
3 converted + self-consistent but upstream not importable (SKIP).

Thresholds: |dE|/atom < 1e-4 eV and max|dF| < 1e-3 eV/A vs upstream
(float32 eval; the in-repo float64 golden oracles pin 1e-9 — this check
is about REAL checkpoints, where the error budget is dominated by fp32
forward noise).
"""

from __future__ import annotations

import json
import sys
import tempfile

import numpy as np

PASS_DE = 1e-4   # eV/atom vs upstream
PASS_DF = 1e-3   # eV/A max component vs upstream
SELF_DE = 1e-5   # eV/atom P=2 vs P=1 (internal)

# ONE task constant for both sides of the eSCN/UMA parity check: the local
# eval's dataset-conditioning index and the upstream FAIRChemCalculator's
# task_name must select the same csd/dataset embedding, or a multi-dataset
# checkpoint reports a spurious FAIL (ADVICE r5).
UMA_PARITY_TASK = "omat"


def _log(stage, msg):
    print(f"[{stage}] {msg}", flush=True)


# ---------------------------------------------------------------------------
# fixture: deterministic crystal valid for any of the four families
# ---------------------------------------------------------------------------


def make_fixture(cutoff: float, atomic_numbers, seed: int = 0):
    """Perturbed fcc supercell, elongated so P=2 slabs satisfy
    box_x / 2 > 2 * (cutoff + skin)."""
    from .. import geometry

    rng = np.random.default_rng(seed)
    a = 4.1
    import math

    nx = max(3, math.ceil(2 * 2 * (cutoff + 0.6) / a))
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * a, (nx, 2, 2))
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, 0.06, (len(frac), 3))
    zs = np.asarray(atomic_numbers)
    numbers = zs[rng.integers(0, len(zs), len(cart))]
    return numbers.astype(np.int64), cart, lattice


# ---------------------------------------------------------------------------
# config inference from state-dict shapes (loud about what it assumes)
# ---------------------------------------------------------------------------


def _parse_value(v):
    """Parse a --set value: bool words, int, float, comma-tuple of ints,
    else the raw string. NEVER cast via type(existing) — bool('false') is
    True and tuple('13,14') is character soup. A malformed comma tuple
    raises ValueError with a usable message; main() turns it into the
    structured rc=2 usage error (never an uncaught traceback)."""
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    if "," in v:
        try:
            return tuple(int(x) for x in v.split(","))
        except ValueError:
            raise ValueError(
                f"comma value {v!r} must be a tuple of ints (e.g. 2,2,1)"
            ) from None
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            continue
    return v


def _apply_overrides(kw, overrides, assumed):
    for k, v in overrides.items():
        if k in assumed:
            assumed.remove(k)
        kw[k] = _parse_value(v)
    return kw


def _log_assumed(assumed, notes):
    for k in assumed:
        _log("infer", f"ASSUMED {k}{notes.get(k, '')} — override with "
                      f"--set {k}=val")


def infer_mace(sd, overrides):
    from ..models import MACE, MACEConfig

    zs = np.asarray(sd["atomic_numbers"]).astype(int)
    S = len(zs)
    C = np.asarray(sd["node_embedding.linear.weight"]).size // S
    num_bessel = np.asarray(
        sd["radial_embedding.bessel_fn.bessel_weights"]).size
    layer_keys = sorted(
        k for k in sd
        if k.startswith("interactions.0.conv_tp_weights.layer")
        and k.endswith(".weight"))
    radial_mlp = int(np.asarray(sd[layer_keys[0]]).shape[1])
    n_inter = int(np.asarray(sd["num_interactions"]))
    # path count read from the LAST interaction, whose richer l_h set
    # discriminates l_max candidates the scalar-input first layer cannot
    last_keys = sorted(
        k for k in sd
        if k.startswith(f"interactions.{n_inter - 1}.conv_tp_weights.layer")
        and k.endswith(".weight"))
    n_paths_c = int(np.asarray(sd[last_keys[-1]]).shape[1])
    # correlation = number of U_matrix_{nu} orders present
    corr = len([k for k in sd if k.startswith(
        "products.0.symmetric_contractions.contractions.0.U_matrix_")])
    u1 = np.asarray(
        sd["products.0.symmetric_contractions.contractions.0.U_matrix_1"])
    a_lmax = int(round(np.sqrt(u1.shape[1]))) - 1
    n_contr = len({k.split(".")[4] for k in sd if k.startswith(
        "products.0.symmetric_contractions.contractions.")})
    hidden_lmax = n_contr - 1
    H = (np.asarray(sd["readouts.0.linear.weight"]).size // C
         if "readouts.0.linear.weight" in sd else 1)
    kw = dict(
        num_species=S, channels=C,
        a_lmax=a_lmax, hidden_lmax=hidden_lmax, correlation=corr,
        num_interactions=int(np.asarray(sd["num_interactions"])),
        num_bessel=num_bessel, radial_mlp=radial_mlp,
        radial_layers=len(layer_keys) - 1,
        cutoff=float(np.asarray(sd["r_max"])),
        cutoff_p=int(np.asarray(sd["radial_embedding.cutoff_fn.p"])),
        avg_num_neighbors=float(np.asarray(
            sd["interactions.0.avg_num_neighbors"]))
        if "interactions.0.avg_num_neighbors" in sd else 14.0,
        num_heads=H, zbl="pair_repulsion_fn.a_exp" in sd,
        atomic_numbers=tuple(zs.tolist()),
    )
    assumed = (["avg_num_neighbors"]
               if "interactions.0.avg_num_neighbors" not in sd else [])
    if "l_max" in overrides:
        kw["l_max"] = int(overrides["l_max"])
    else:
        # l_max is not a tensor shape: recover it by matching the
        # message-path count the radial MLP's output width encodes
        matches = []
        for cand in range(0, 5):
            try:
                model = MACE(MACEConfig(l_max=cand, **kw))
            except Exception:
                continue
            if len(model.msg_paths[n_inter - 1]) * C == n_paths_c:
                matches.append(cand)
        if not matches:
            raise ValueError(
                f"could not infer l_max: no candidate yields "
                f"{n_paths_c // C} message paths — pass --set l_max=N")
        # beyond the saturation point extra harmonics feed no CG path, so
        # the candidates are numerically identical — smallest is canonical
        if len(matches) > 1:
            _log("infer", f"l_max candidates {matches} are "
                          f"path-equivalent; using {matches[0]}")
        kw["l_max"] = matches[0]
    kw = _apply_overrides(
        kw, {k: v for k, v in overrides.items() if k != "l_max"}, assumed)
    return MACEConfig(**kw), assumed, zs, {}


def infer_chgnet(sd, overrides):
    from ..models import CHGNetConfig

    p = "model." if any(k.startswith("model.") for k in sd) else ""
    emb = np.asarray(sd[p + "atom_embedding.weight"])
    S, units = emb.shape
    num_rbf = np.asarray(sd[p + "bond_expansion.frequencies"]).size
    # fourier basis stores max_f + 1 frequencies (constant + max_f waves)
    nf = np.asarray(sd[p + "angle_expansion.frequencies"]).size - 1
    n_blocks = len({k[len(p):].split(".")[1] for k in sd
                    if k.startswith(p + "atom_graph_layers.")})
    kw = dict(num_species=S, units=units, num_rbf=num_rbf, num_angle=nf,
              num_blocks=n_blocks, cutoff=6.0, bond_cutoff=3.0)
    assumed = ["cutoff", "bond_cutoff"]  # matgl hyperparams, not tensors
    kw = _apply_overrides(kw, overrides, assumed)
    return CHGNetConfig(**kw), assumed, np.arange(1, S + 1), {}


def infer_tensornet(sd, overrides):
    from ..models import TensorNetConfig

    p = "model." if any(k.startswith("model.") for k in sd) else ""
    emb = np.asarray(sd[p + "tensor_embedding.emb.weight"])
    S, units = emb.shape[0], emb.shape[1]
    num_rbf = np.asarray(sd[p + "tensor_embedding.distance_proj1.weight"]
                         ).shape[1]
    n_layers = len({k[len(p):].split(".")[1] for k in sd
                    if k.startswith(p + "layers.")})
    kw = dict(num_species=S, units=units, num_rbf=num_rbf,
              num_layers=n_layers, cutoff=5.0)
    assumed = ["cutoff"]
    kw = _apply_overrides(kw, overrides, assumed)
    return TensorNetConfig(**kw), assumed, np.arange(1, S + 1), {}


def infer_escn(sd, overrides):
    from ..models import ESCNMDConfig

    p = "backbone." if any(k.startswith("backbone.") for k in sd) else ""
    emb = np.asarray(sd[p + "sphere_embedding.weight"])
    Z, C = emb.shape
    CE = np.asarray(sd[p + "source_embedding.weight"]).shape[1]
    offsets = np.asarray(sd[p + "distance_expansion.offset"]).ravel()
    n_blocks = len({int(k[len(p):].split(".")[1]) for k in sd
                    if k.startswith(p + "blocks.")})
    # lmax from norm affine (lmax+1, C); mmax from the so2_m_conv count
    lmax = np.asarray(sd[p + "blocks.0.norm_1.affine_weight"]).shape[0] - 1
    mmax = len({k for k in sd if
                k.startswith(p + "blocks.0.so2_conv_1.so2_m_conv.")
                and k.endswith(".fc.weight")})
    H = np.asarray(sd[p + "blocks.0.so2_conv_2.fc_m0.weight"]).shape[-1] \
        // (lmax + 1)
    nq = np.asarray(sd[p + "csd_embedding.charge_embedding.weight"]).shape[0]
    ns = np.asarray(sd[p + "csd_embedding.spin_embedding.weight"]).shape[0]
    nd = np.asarray(
        sd[p + "csd_embedding.dataset_embedding.weight"]).shape[0]
    kw = dict(max_num_elements=Z, sphere_channels=C, lmax=lmax, mmax=mmax,
              num_layers=n_blocks, hidden_channels=H, edge_channels=CE,
              num_distance_basis=offsets.size,
              num_charges=nq, charge_min=-(nq // 2), num_spins=ns,
              num_datasets=nd,
              cutoff=float(offsets[-1]), avg_degree=14.0)
    assumed = ["avg_degree", "basis_width_scalar", "charge_min"]
    notes = {"basis_width_scalar": " (=2.0, lineage default)",
             "charge_min": f" (=-{nq // 2}, centered range)"}
    kw = _apply_overrides(kw, overrides, assumed)
    return ESCNMDConfig(**kw), assumed, np.arange(1, Z), notes


# ---------------------------------------------------------------------------
# our side: convert + evaluate through the public DistPotential surface
# ---------------------------------------------------------------------------


def _model_for(family, cfg):
    from .. import models

    cls = {"mace": models.MACE, "chgnet": models.CHGNet,
           "tensornet": models.TensorNet, "escn": models.ESCNMD}[family]
    return cls(cfg)


def eval_ours(family, cfg, sd, numbers, cart, lattice, info):
    import jax

    from ..calculators import Atoms, DistPotential
    from ..models.convert import from_torch

    model = _model_for(family, cfg)
    params = model.init(jax.random.PRNGKey(0))
    params, report = from_torch(family, sd, params, model=model)
    _log("convert", f"mapped={report['mapped']} "
                    f"unused={len(report['unused_torch'])}")
    smap = np.full(int(numbers.max()) + 1, -1, np.int32)
    zs = sorted(set(numbers.tolist()))
    # species index: mace carries its own Z table; fairchem eSCN/UMA
    # embeddings are indexed by RAW atomic number (identity); the matgl
    # families use Z-ordered element_types (index z-1)
    if family == "mace" and cfg.atomic_numbers is not None:
        for i, z in enumerate(cfg.atomic_numbers):
            if z < len(smap):
                smap[z] = i
    elif family == "escn":
        for z in zs:
            smap[z] = min(z, cfg.max_num_elements - 1)
    else:
        for z in zs:
            smap[z] = min(z - 1, cfg.num_species - 1)
    atoms = Atoms(numbers=numbers, positions=cart, cell=lattice)
    atoms.info = dict(info)
    out = {}
    for P in (1, 2):
        pot = DistPotential(model, params, num_partitions=P,
                            species_map=smap)
        r = pot.calculate(atoms)
        out[P] = (float(r["energy"]), np.asarray(r["forces"]))
    de_self = abs(out[2][0] - out[1][0]) / len(numbers)
    _log("ours", f"P=1 E={out[1][0]:.6f} eV; P=2 dE/atom={de_self:.2e}")
    if de_self > SELF_DE:
        raise AssertionError(
            f"internal P=2 vs P=1 disagreement {de_self:.2e} eV/atom")
    return out[1]


# ---------------------------------------------------------------------------
# upstream side (requires the upstream package + ase; SKIPs when absent)
# ---------------------------------------------------------------------------


def eval_upstream(family, ckpt, numbers, cart, lattice, info):
    if ckpt.endswith(".npz"):
        # the npz export carries tensors only — upstream needs its own
        # checkpoint format to rebuild the live model
        _log("upstream", "SKIP (npz input; pass the original upstream "
                         "checkpoint to run the numeric comparison)")
        return None
    try:
        import ase

        atoms = ase.Atoms(numbers=numbers, positions=cart, cell=lattice,
                          pbc=True)
        if family == "mace":
            from mace.calculators import MACECalculator

            atoms.calc = MACECalculator(model_paths=ckpt, device="cpu",
                                        default_dtype="float64")
        elif family in ("chgnet", "tensornet"):
            import matgl
            from matgl.ext.ase import PESCalculator

            try:
                pot = matgl.load_model(ckpt)
            except Exception:  # a torch.save'd Potential
                import torch

                pot = torch.load(ckpt, map_location="cpu",
                                 weights_only=False)
            atoms.calc = PESCalculator(pot)
        else:  # escn / UMA
            from fairchem.core import FAIRChemCalculator
            from fairchem.core.units.mlip_unit import load_predict_unit

            atoms.info.update(info)
            atoms.calc = FAIRChemCalculator(load_predict_unit(ckpt),
                                            task_name=UMA_PARITY_TASK)
        return float(atoms.get_potential_energy()), atoms.get_forces()
    except ImportError as e:
        _log("upstream", f"SKIP ({e})")
        return None
    except Exception as e:  # noqa: BLE001 - report, don't traceback
        _log("upstream", f"SKIP (upstream evaluation failed: "
                         f"{type(e).__name__}: {e})")
        return None


# ---------------------------------------------------------------------------


_INFER = {"mace": infer_mace, "chgnet": infer_chgnet,
          "tensornet": infer_tensornet, "escn": infer_escn}


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    overrides, out_json = {}, None
    try:
        while "--set" in argv:
            i = argv.index("--set")
            k, v = argv[i + 1].split("=", 1)
            overrides[k] = v
            del argv[i:i + 2]
        if "--out" in argv:
            i = argv.index("--out")
            out_json = argv[i + 1]
            del argv[i:i + 2]
    except (IndexError, ValueError):
        print(__doc__)
        print("ERROR: --set expects key=val and --out expects a path")
        return 2
    # validate every --set value NOW, before any expensive export/infer
    # work, so a malformed value (e.g. --set grid=2,2.5) is a structured
    # usage error instead of an uncaught traceback mid-run
    for k, v in overrides.items():
        try:
            _parse_value(v)
        except ValueError as e:
            print(__doc__)
            print(f"ERROR: --set {k}={v}: {e}")
            return 2
    if len(argv) != 2 or argv[0] not in _INFER:
        print(__doc__)
        return 2
    family, ckpt = argv
    _log("verify_upstream", f"family={family} checkpoint={ckpt}")

    # 1. export (npz input is passed through)
    if ckpt.endswith(".npz"):
        sd = dict(np.load(ckpt))
    else:
        from .export_upstream import main as export_main

        with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
            npz = f.name
        try:
            if export_main([family, ckpt, npz]) != 0:
                return 1
            sd = dict(np.load(npz))
        finally:
            import os

            try:
                os.unlink(npz)
            except OSError:
                pass
    _log("export", f"{len(sd)} tensors")

    # 2. infer config
    cfg, assumed, zs, notes = _INFER[family](sd, overrides)
    _log("infer", f"{cfg}")
    _log_assumed(assumed, notes)

    # 3-4. convert + our eval (eSCN conditions on the SAME task as the
    # upstream eval — see UMA_PARITY_TASK; a single-dataset checkpoint has
    # only index 0, where the task routing is moot)
    if family == "escn":
        from ..calculators.calculator import UMA_TASK_DATASETS

        ds = min(UMA_TASK_DATASETS[UMA_PARITY_TASK],
                 getattr(cfg, "num_datasets", 1) - 1)
        info = {"charge": 0, "spin": 0, "dataset": ds}
    else:
        info = {}
    numbers, cart, lattice = make_fixture(cfg.cutoff, zs)
    e_ours, f_ours = eval_ours(family, cfg, sd, numbers, cart, lattice, info)

    # 5. upstream eval + compare
    up = eval_upstream(family, ckpt, numbers, cart, lattice, info)
    result = {"family": family, "checkpoint": ckpt, "n_atoms": len(numbers),
              "energy_ours": e_ours, "assumed": assumed}
    if up is None:
        _log("RESULT", "CONVERT-OK-UPSTREAM-SKIPPED (run this command in "
                       "an environment with the upstream package to close "
                       "the loop)")
        result["status"] = "upstream_skipped"
        rc = 3
    else:
        e_up, f_up = up
        de = abs(e_ours - e_up) / len(numbers)
        df = float(np.abs(f_ours - np.asarray(f_up)).max())
        result.update(energy_upstream=e_up, de_per_atom=de, df_max=df)
        ok = de < PASS_DE and df < PASS_DF
        _log("compare", f"dE/atom={de:.3e} eV (<{PASS_DE}) "
                        f"dF_max={df:.3e} eV/A (<{PASS_DF})")
        _log("RESULT", "PASS" if ok else "FAIL")
        result["status"] = "pass" if ok else "fail"
        rc = 0 if ok else 1
    if out_json:
        with open(out_json, "w") as f:
            json.dump(result, f, indent=1)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
