"""Async serving engine: continuous micro-batching over the batched
multi-structure potential.

The serving layer the ROADMAP north star ("serves heavy traffic") sits
on: callers ``submit()`` single structures with priority/deadline and get
Futures; a background scheduler assembles bucket-aware micro-batches
(scheduler.plan_batch fills toward the BucketPolicy capacity ladder) and
executes them through one shared ``BatchedPotential``, with admission
control, a ``DistPotential`` fallback lane for oversized structures and
per-request error isolation.

Quick start::

    from distmlip_tpu.calculators import BatchedPotential
    from distmlip_tpu.serve import ServeEngine

    engine = ServeEngine(BatchedPotential(model, params), max_batch=8)
    future = engine.submit(atoms, priority=0, deadline=1.0)
    result = future.result()     # same dict calculate() returns
    engine.close()               # drains in-flight work first

Load testing: ``tools/load_test.py`` (CLI) over ``loadgen.run_closed_loop``
/ ``run_open_loop``.
"""

from .engine import (ADMISSION_MODES, EngineClosed, ServeEngine,
                     ServeRejected, ServeStats)
from .loadgen import LoadReport, run_closed_loop, run_open_loop
from .scheduler import BatchPlan, plan_batch

__all__ = [
    "ServeEngine",
    "ServeStats",
    "ServeRejected",
    "EngineClosed",
    "ADMISSION_MODES",
    "BatchPlan",
    "plan_batch",
    "LoadReport",
    "run_closed_loop",
    "run_open_loop",
]
