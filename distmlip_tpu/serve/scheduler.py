"""Micro-batch assembly planning for the serving engine.

Pure host-side logic (no threads, no jax): given the sizes of the queued
requests in dispatch order, pick the subset that forms the next
micro-batch. The planner is bucket-aware — it fills toward the
``BucketPolicy`` capacity ladder (partition/capacity.py) so the packed
graph lands on a well-occupied rung: every admission either stays inside
the current rung (strictly raising occupancy) or climbs to a rung where
occupancy is at least as good. Because every emitted total quantizes onto
the same geometric ladder the single-structure stream uses, scheduler-
driven traffic inherits the ladder's compile bound (``max_rungs``); the
adversarial streams in tests/test_capacity_adversarial.py assert this.

Separated from the engine so the assembly policy is unit-testable against
adversarial request streams without spinning up threads.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..partition import BucketPolicy


@dataclass
class BatchPlan:
    """Outcome of one assembly pass over the queue head.

    ``take`` holds queue indices (into the order the planner saw) chosen
    for this micro-batch; indices not taken stay queued in their original
    order. ``skipped`` are indices the planner examined but left behind
    because admitting them would have degraded rung occupancy.
    """

    take: list[int] = field(default_factory=list)
    skipped: list[int] = field(default_factory=list)
    total_atoms: int = 0
    node_cap: int = 0
    est_bytes: int | None = None   # planner's estimate for the chosen batch
    # the HEAD request ALONE is over the bytes budget on its own
    # MEASURED rung: the plan is head-only and must NOT be dispatched —
    # the engine fails the request instead (this closes the
    # pre-calibration admission race: a request admitted before the
    # bytes model existed can become an over-budget head later). A head
    # over budget on an EXTRAPOLATED estimate is also head-only but NOT
    # flagged: it dispatches as a solo probe whose compile calibrates
    # the rung with the truth.
    over_budget: bool = False

    @property
    def occupancy(self) -> float:
        return self.total_atoms / self.node_cap if self.node_cap else 0.0

    def span_attrs(self) -> dict:
        """Attributes for the ``scheduler.plan_batch`` span
        (distmlip_tpu.obs): how this assembly decision went, visible per
        batch in the trace timeline instead of only in aggregate."""
        return {
            "take": len(self.take),
            "skipped": len(self.skipped),
            "total_atoms": self.total_atoms,
            "node_cap": self.node_cap,
            "occupancy": round(self.occupancy, 3),
            "over_budget": self.over_budget,
        }


def plan_batch(
    sizes,
    policy: BucketPolicy | None = None,
    max_batch: int = 8,
    window: int = 64,
    bytes_budget: int | None = None,
) -> BatchPlan:
    """Greedy bucket-aware micro-batch selection.

    ``sizes``: per-request atom counts in dispatch (priority/deadline)
    order. The head request is always taken — the max-wait timer already
    decided a batch must go out, so the oldest/most-urgent request is
    never starved by the occupancy rule (a head request too big for the
    BYTES budget never reaches the planner: engine admission rejects it
    at submit). Subsequent requests (scanned up to ``window`` deep) are
    admitted while the batch stays under ``max_batch`` slots and the
    admission keeps rung occupancy nondecreasing:

    - same node-capacity rung: always admit (occupancy strictly rises);
    - next rung: admit if ``new_total/new_cap >= total/cap`` (climbing
      does not dilute the rung);
    - a rung-degrading candidate is skipped ONLY when the batch is at a
      power-of-two slot count — the packed ``batch_size`` dimension rounds
      to the next power of two, so stopping there wastes no batch slots.
      Off a power-of-two boundary, the candidate is admitted anyway:
      finishing the slot bucket beats the node-rung padding it costs
      (batch-slot occupancy is the serving throughput lever; node padding
      only costs masked lanes).

    Skipped requests keep their queue position and seed (or join) the next
    batch, so a huge request mixed into a small-request stream waits at
    most until it reaches the queue head — then it is the seed and gets
    its own appropriately-sized rung.

    ``bytes_budget`` (memory-aware autobatching): the per-device HBM
    budget in bytes. Every admission is additionally checked against the
    policy's calibrated bytes model
    (``BucketPolicy.estimate_batch_bytes``) — a candidate whose admission
    would push the batch estimate past the budget is skipped, whatever
    the slot/occupancy rules say, so the planner NEVER assembles a
    multi-request batch estimated over budget. A HEAD whose solo
    estimate already exceeds the budget yields a head-only plan flagged
    ``over_budget=True`` — the caller must fail that request, not
    dispatch it (engine admission normally rejects such requests at
    submit, but a request admitted BEFORE the model calibrated can
    become an over-budget head later). Until the model has any
    calibration the check is a no-op — the first batch through a fresh
    engine calibrates it.
    """
    policy = policy or BucketPolicy()
    plan = BatchPlan()
    if not len(sizes):
        return plan
    est = getattr(policy, "estimate_batch_bytes", None)
    if bytes_budget is None:
        est = None
    total = int(sizes[0])
    cap = policy.get("nodes", total)
    plan.take.append(0)
    if est is not None:
        e0 = est(total)
        if e0 is not None and e0 > bytes_budget:
            plan.total_atoms, plan.node_cap = total, cap
            plan.est_bytes = e0
            # head-only either way, but only a MEASURED rung justifies
            # failing the request: an extrapolated guess ships as a solo
            # probe — its compile calibrates the rung with the truth
            # (rejecting on guesses would livelock the lane: see
            # BucketPolicy.has_calibrated_rung)
            exact = getattr(policy, "has_calibrated_rung", None)
            plan.over_budget = bool(exact and exact(total))
            return plan
    for i in range(1, min(len(sizes), window)):
        n = len(plan.take)
        if n >= max_batch:
            break
        new_total = total + int(sizes[i])
        new_cap = policy.get("nodes", new_total)
        if est is not None:
            e = est(new_total)
            if e is not None and e > bytes_budget:
                # admitting this request would blow the HBM budget — the
                # slot/occupancy rules never override the bytes gate
                plan.skipped.append(i)
                continue
        rung_ok = new_cap == cap or new_total * cap >= total * new_cap
        at_slot_boundary = n & (n - 1) == 0   # 1, 2, 4, 8, ...
        if rung_ok or not at_slot_boundary:
            plan.take.append(i)
            total, cap = new_total, new_cap
        else:
            plan.skipped.append(i)
    plan.total_atoms = total
    plan.node_cap = cap
    if est is not None:
        plan.est_bytes = est(total)
    return plan
