"""Load generation against a ServeEngine: closed- and open-loop drivers.

Two canonical serving-benchmark regimes (the distinction matters — closed
loops hide queueing delay because offered load backs off with latency,
open loops expose it):

- **closed loop** (``run_closed_loop``): ``concurrency`` workers each keep
  exactly one request outstanding — submit, wait, repeat. Measures
  best-case service latency and saturation throughput at a fixed
  multiprogramming level.
- **open loop** (``run_open_loop``): requests arrive on an independent
  schedule (Poisson by default) regardless of completions, the way real
  user traffic does; queue-wait shows up in the latency tail.

Both return a ``LoadReport`` with p50/p95/p99 latency, structures/sec and
the engine's stats snapshot — ``tools/load_test.py`` is the CLI wrapper
that feeds these numbers into the bench JSONL trajectory.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..telemetry.record import percentile


@dataclass
class LoadReport:
    """Outcome of one load-generation run."""

    mode: str = "closed"
    n_requests: int = 0
    n_ok: int = 0
    n_failed: int = 0
    n_rejected: int = 0
    wall_s: float = 0.0
    latencies_s: list[float] = field(default_factory=list)
    engine_stats: dict = field(default_factory=dict)

    @property
    def structures_per_sec(self) -> float:
        return self.n_ok / self.wall_s if self.wall_s > 0 else 0.0

    def latency_percentiles(self) -> dict:
        xs = sorted(self.latencies_s)
        return {"p50_s": percentile(xs, 0.50),
                "p95_s": percentile(xs, 0.95),
                "p99_s": percentile(xs, 0.99),
                "max_s": xs[-1] if xs else 0.0}

    def summary(self) -> dict:
        p = self.latency_percentiles()
        return {
            "mode": self.mode,
            "requests": self.n_requests,
            "ok": self.n_ok,
            "failed": self.n_failed,
            "rejected": self.n_rejected,
            "wall_s": round(self.wall_s, 4),
            "structures_per_sec": round(self.structures_per_sec, 2),
            "latency_p50_ms": round(1e3 * p["p50_s"], 2),
            "latency_p95_ms": round(1e3 * p["p95_s"], 2),
            "latency_p99_ms": round(1e3 * p["p99_s"], 2),
        }


def run_closed_loop(engine, structures, n_requests: int,
                    concurrency: int = 4, priority_fn=None) -> LoadReport:
    """``concurrency`` workers round-robin over ``structures``, each with
    one request outstanding, until ``n_requests`` have been issued."""
    from .engine import ServeRejected

    rep = LoadReport(mode="closed", n_requests=int(n_requests))
    counter = {"next": 0}
    lock = threading.Lock()
    lat_lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = counter["next"]
                if i >= n_requests:
                    return
                counter["next"] = i + 1
            atoms = structures[i % len(structures)]
            prio = priority_fn(i) if priority_fn else 0
            t0 = time.perf_counter()
            try:
                fut = engine.submit(atoms, priority=prio)
                fut.result()
            except ServeRejected:
                with lat_lock:
                    rep.n_rejected += 1
                continue
            except Exception:  # noqa: BLE001 - per-request failure counted
                with lat_lock:
                    rep.n_failed += 1
                continue
            with lat_lock:
                rep.n_ok += 1
                rep.latencies_s.append(time.perf_counter() - t0)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, int(concurrency)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    engine.drain()
    rep.wall_s = time.perf_counter() - t_start
    rep.engine_stats = engine.stats.snapshot()
    return rep


def run_open_loop(engine, structures, n_requests: int, rate_hz: float,
                  rng=None, poisson: bool = True) -> LoadReport:
    """Submit on an arrival schedule independent of completions: mean rate
    ``rate_hz``, exponential inter-arrivals when ``poisson`` (else a fixed
    period). ``rate_hz <= 0`` means burst mode: submit everything at once
    (maximum queueing pressure — the B∈{1,8} bench phase uses this)."""
    import numpy as np

    from .engine import ServeRejected

    rng = rng or np.random.default_rng(0)
    rep = LoadReport(mode="open", n_requests=int(n_requests))
    lat_lock = threading.Lock()
    submit_times: list[float] = []
    futures = []

    def on_done(t_sub):
        # completion timestamp must be captured WHEN the future resolves
        # (scheduler thread), not when the driver later harvests results
        def cb(fut):
            t_done = time.perf_counter()
            if fut.exception() is None:
                with lat_lock:
                    rep.latencies_s.append(t_done - t_sub)
        return cb

    t_start = time.perf_counter()
    for i in range(n_requests):
        if rate_hz > 0 and i > 0:
            gap = (rng.exponential(1.0 / rate_hz) if poisson
                   else 1.0 / rate_hz)
            # arrival schedule is absolute, so a slow submit path does not
            # silently stretch the offered rate
            target = submit_times[-1] + gap
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
        t_sub = time.perf_counter()
        try:
            fut = engine.submit(structures[i % len(structures)])
            fut.add_done_callback(on_done(t_sub))
            futures.append(fut)
        except ServeRejected:
            rep.n_rejected += 1
        submit_times.append(t_sub)
    for fut in futures:
        try:
            fut.result()
        except Exception:  # noqa: BLE001 - per-request failure counted
            rep.n_failed += 1
            continue
        rep.n_ok += 1
    rep.wall_s = time.perf_counter() - t_start
    rep.engine_stats = engine.stats.snapshot()
    return rep
