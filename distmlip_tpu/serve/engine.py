"""In-process async inference engine: continuous micro-batching over the
batched multi-structure potential.

``ServeEngine`` is the serving layer the ROADMAP's "heavy traffic" north
star needs on top of PR 3's block-diagonal packing: callers ``submit()``
single structures and get ``concurrent.futures.Future``s back; a
background scheduler thread continuously assembles micro-batches —
bucket-aware (scheduler.plan_batch fills toward the BucketPolicy capacity
ladder), priority/deadline-ordered, with a max-wait timer so a lone
request is never starved — and executes them through ONE shared
``BatchedPotential``. Oversized structures route to a ``DistPotential``
fallback lane instead of blowing up the packed program's shape buckets.

Robustness contract (tests/test_serve.py):

- bounded queue with admission control: ``admission="reject"`` raises
  ``ServeRejected`` when the queue is full, ``"block"`` parks the caller
  until the scheduler frees a slot;
- memory-aware admission: when the shared potential carries an HBM budget
  (``BatchedPotential.hbm_budget_bytes``) and its calibrated bytes model
  estimates that a submitted structure ALONE would exceed it, the request
  is rejected at submit in BOTH admission modes (parking a request that
  can never fit would hang the submitter forever); batch assembly fills
  toward the same budget (``plan_batch(bytes_budget=...)``), so no
  dispatched batch is ever estimated over budget;
- per-request error isolation: a poison structure (non-finite positions,
  or anything that makes the batch raise) fails its OWN Future; the rest
  of the batch returns results and the engine thread survives;
- ``drain()`` flushes everything in flight deterministically and returns
  with the queue empty and every Future resolved; ``close()`` drains by
  default, then joins the scheduler thread; ``extract_pending()`` is the
  fleet router's handoff hook — it reclaims the queued requests (Futures
  UNRESOLVED) for re-dispatch on another replica instead of failing them;
- the scheduler thread can never die: every execution path is wrapped so
  an unexpected failure resolves the affected Futures exceptionally and
  the loop continues.

Telemetry: each dispatched batch emits a ``StepRecord`` (kind
``serve_batch`` / ``serve_fallback``) carrying per-request queue-wait and
latency lists, queue depth, batch occupancy and cumulative reject /
deadline-miss counters — rendered by ``telemetry_report``'s "serving"
section.

Observability (:mod:`distmlip_tpu.obs`): with a hub installed, every
request grows a span tree — ``engine.submit`` root (standalone) or the
router's ambient context (fleet), a retroactive ``engine.queue`` span at
dispatch, a batch-level ``serve.batch`` trace (plan/pack/compile/device
children) LINKED to every member request, and exactly one terminal
``future.resolve`` per request, whatever path it took (dispatch, shed,
over-budget fail, poison isolation, non-draining close). The layer that
OPENED the root closes it: a router-adopted request's terminal is the
router's to emit. Metrics (queue depth, batch occupancy, service
histogram, compiles, rejects/sheds) ride the same points. With no hub
installed each site costs one global read — the disabled hot path is
unchanged.
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from ..obs import runtime as obsrt
from ..telemetry import StepRecord
from .scheduler import plan_batch

ADMISSION_MODES = ("reject", "block")


class ServeRejected(RuntimeError):
    """The request was NOT enqueued: queue full under admission="reject",
    or the structure's estimated HBM footprint alone exceeds the batched
    lane's budget (rejected in both admission modes — it can never fit)."""


class EngineClosed(RuntimeError):
    """submit() after close(), or a pending request flushed by a
    non-draining close."""


@dataclass(order=True)
class _Request:
    """One queued request. Heap order: priority, then earliest deadline,
    then submission order (FIFO within a class)."""

    priority: int
    deadline_abs: float      # absolute clock time; +inf = no deadline
    seq: int
    atoms: object = field(compare=False)
    properties: tuple | None = field(compare=False, default=None)
    future: Future = field(compare=False, default_factory=Future)
    t_submit: float = field(compare=False, default=0.0)
    n_atoms: int = field(compare=False, default=0)
    # observability handle (obs.tracing.RequestTrace): the request's span
    # context, carried across the submitter -> scheduler thread hop. When
    # its .root is set the ENGINE owns the trace (standalone submit) and
    # emits the terminal future.resolve; under a FleetRouter the root
    # lives router-side and this holds only the adopted context.
    trace: object = field(compare=False, default=None, repr=False)


@dataclass
class ServeStats:
    """Cumulative engine counters (thread-safe reads: plain ints swapped
    under the engine lock)."""

    submitted: int = 0
    completed: int = 0
    failed: int = 0
    cancelled: int = 0
    rejected: int = 0
    deadline_misses: int = 0
    shed_count: int = 0          # deadline-shed at assembly (never ran)
    batches: int = 0
    fallback_requests: int = 0
    scheduler_errors: int = 0    # isolated loop faults (engine survived)
    # bucket_key -> [batches, sum(batch_occupancy), sum(batch_size)]
    buckets: dict = field(default_factory=dict)

    def note_batch(self, bucket_key: str, occupancy: float, size: int):
        b = self.buckets.setdefault(bucket_key, [0, 0.0, 0])
        b[0] += 1
        b[1] += occupancy
        b[2] += size

    def dominant_bucket(self) -> tuple[str, float] | None:
        """(bucket_key, mean batch-slot occupancy) of the bucket that served
        the most batches — the load test's acceptance metric."""
        if not self.buckets:
            return None
        key = max(self.buckets, key=lambda k: self.buckets[k][0])
        n, occ_sum, _ = self.buckets[key]
        return key, occ_sum / max(n, 1)

    def snapshot(self) -> dict:
        d = {k: v for k, v in vars(self).items() if k != "buckets"}
        d["buckets"] = {k: {"batches": v[0],
                            "mean_batch_occupancy": v[1] / max(v[0], 1),
                            "requests": v[2]}
                        for k, v in self.buckets.items()}
        return d


def _finite_positions(atoms) -> bool:
    pos = np.asarray(atoms.positions)
    return bool(np.isfinite(pos).all())


_NULL_CTX = contextlib.nullcontext()


class ServeEngine:
    """Continuous micro-batching scheduler over a shared BatchedPotential.

    Parameters
    ----------
    potential : BatchedPotential — the shared batched executor. Its Verlet
        cache and compile cache are only touched from the scheduler thread
        (and BatchedPotential.calculate is itself lock-guarded, so a caller
        sharing the potential outside the engine stays safe).
    fallback : optional DistPotential for structures larger than
        ``max_batch_atoms`` — the single-structure (possibly
        halo-partitioned) lane. When the shared ``BatchedPotential`` runs
        on a 2-D mesh and no explicit fallback is given, the engine builds
        the lane AUTOMATICALLY on the SPATIAL sub-axis of that same mesh
        (a ``DistPotential`` over one batch row's spatial devices): small
        requests pack onto the batch axis, oversized ones spatially
        partition across the spatial axis — one mesh, two routes, uniform
        ``last_stats`` telemetry either way. Without a mesh or explicit
        fallback, oversized requests fail their Future with ValueError.
    max_batch : micro-batch slot budget (power of two keeps the packed
        ``batch_size`` bucket stable).
    max_wait_s : max time a request waits for co-batching before the
        scheduler dispatches an underfilled batch (the lone-request
        starvation bound). Measured on ``clock``.
    max_queue : admission bound on queued (not yet dispatched) requests.
    admission : "reject" (raise ServeRejected when full) or "block" (park
        the submitter until space frees).
    max_batch_atoms : per-structure size ceiling for the batched lane;
        larger structures route to ``fallback``. None disables routing.
    window : how deep past the queue head assembly may scan.
    shed_deadlines : deadline-aware LOAD SHEDDING (off by default — the
        historical contract delivers late results and only counts the
        miss). When on, a queued request whose deadline has already
        passed at assembly time — or which PROVABLY cannot be met even
        if dispatched in the very next batch, judged against the
        engine's EWMA batch service time — fails fast with
        ``ServeRejected`` instead of occupying batch slots, so a
        backed-up queue sheds the work nobody will use and the live
        deadlines keep making it. Shed requests count in
        ``stats.shed_count`` (and the ``shed_count`` StepRecord field),
        never in ``deadline_misses``. The service EWMA is measured in
        real seconds; with an injected test clock, seed
        ``_service_ewma`` directly.
    clock : monotonic-seconds callable; tests inject a fake clock so the
        max-wait timer is deterministic (no real sleeps).
    start : spawn the scheduler thread immediately. ``start=False`` lets
        tests stage a queue and then start the engine for deterministic
        assembly.
    """

    def __init__(
        self,
        potential,
        fallback=None,
        max_batch: int = 8,
        max_wait_s: float = 0.02,
        max_queue: int = 256,
        admission: str = "reject",
        max_batch_atoms: int | None = None,
        window: int = 64,
        shed_deadlines: bool = False,
        telemetry=None,
        clock=None,
        start: bool = True,
    ):
        if admission not in ADMISSION_MODES:
            raise ValueError(
                f"admission {admission!r} not in {ADMISSION_MODES}")
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self.potential = potential
        self.fallback = fallback
        self._spatial_lane = None         # lazily built mesh spatial lane
        self._spatial_lane_error = None
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.admission = admission
        self.max_batch_atoms = (int(max_batch_atoms)
                                if max_batch_atoms is not None else None)
        self.window = int(window)
        self.shed_deadlines = bool(shed_deadlines)
        # EWMA of per-batch service seconds — the fastest a freshly
        # queued request could possibly complete (None until the first
        # dispatch lands; the predictive shed rule stays off until then)
        self._service_ewma: float | None = None
        self._real_clock = clock is None
        self._clock = clock if clock is not None else time.monotonic
        self.telemetry = telemetry
        if telemetry is not None and hasattr(potential, "attach_telemetry"):
            potential.attach_telemetry(telemetry)
        self.stats = ServeStats()
        self._cv = threading.Condition()
        self._pending: list[_Request] = []   # heap
        self._seq = itertools.count()
        self._inflight = 0
        self._draining = 0
        self._closed = False     # submit() gate
        self._closing = False    # scheduler exit signal
        # last time the scheduler completed a dispatch round (or had an
        # empty queue) — the wedge-detection signal health_snapshot serves
        self._last_progress = self._clock()
        self._step = 0
        self._last_plan_attrs: dict | None = None   # obs plan-span attrs
        self._thread: threading.Thread | None = None
        if start:
            self.start()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        if self._closed:
            raise EngineClosed("engine already closed")
        self._thread = threading.Thread(
            target=self._loop, name="distmlip-serve", daemon=True)
        self._thread.start()

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._pending)

    @property
    def compile_count(self) -> int:
        return getattr(self.potential, "compile_count", 0)

    def kick(self) -> None:
        """Wake the scheduler immediately (tests use this after advancing a
        fake clock past the max-wait deadline)."""
        with self._cv:
            self._cv.notify_all()

    def extract_pending(self) -> list:
        """Reclaim every NOT-YET-DISPATCHED request for re-dispatch
        elsewhere (the fleet router's drain-and-handoff path).

        Atomically pops the whole queue and returns the ``_Request``
        objects in dispatch (priority/deadline/FIFO) order — each carries
        ``atoms``, ``properties``, ``priority``, ``deadline_abs``,
        ``t_submit`` and its UNRESOLVED ``future``. The engine stops
        accepting new submits (as if closed); in-flight batches still
        complete and resolve their own Futures. Unlike
        ``close(drain=False)``, nothing returned here is failed with
        ``EngineClosed`` — the caller owns re-dispatching (or failing)
        the reclaimed requests, so no submitted Future is ever lost to a
        replica handoff."""
        with self._cv:
            self._closed = True     # no new submits race the handoff
            reqs = []
            while self._pending:
                reqs.append(heapq.heappop(self._pending))
            # blocked admission waiters observe _closed and raise
            self._cv.notify_all()
        return reqs

    @property
    def scheduler_alive(self) -> bool:
        """The scheduler thread exists and is still serving (a dead
        thread strands Futures and blocks drain forever)."""
        t = self._thread
        return t is not None and t.is_alive()

    def health_snapshot(self) -> dict:
        """One consistent health sample for a replica monitor: queue
        depth, in-flight batches, liveness, and how long ago the
        scheduler last made dispatch progress (on the engine clock). A
        wedged engine shows ``queue_depth > 0`` (or in-flight work) with
        an ever-growing ``last_progress_age_s`` while
        ``scheduler_alive`` stays True — the BENCH_r03 signature, visible
        without touching the device."""
        with self._cv:
            return {
                "queue_depth": len(self._pending),
                "inflight": self._inflight,
                "scheduler_alive": self.scheduler_alive,
                "last_progress_age_s": self._clock() - self._last_progress,
                "completed": self.stats.completed,
                "failed": self.stats.failed,
            }

    def drain(self, timeout: float | None = None) -> bool:
        """Flush: dispatch everything queued (bypassing max-wait) and wait
        until the queue is empty and no batch is in flight — i.e. every
        submitted Future is resolved. Returns False on (real-time)
        timeout."""
        with self._cv:
            if self._thread is None:
                # no scheduler to flush the queue: report the truth instead
                # of blocking forever
                return not self._pending
            self._draining += 1
            self._cv.notify_all()
            try:
                return self._cv.wait_for(
                    lambda: not self._pending and self._inflight == 0,
                    timeout=timeout)
            finally:
                self._draining -= 1
                self._cv.notify_all()

    def close(self, drain: bool = True, timeout: float | None = None) -> None:
        """Stop accepting work and shut the scheduler down.

        ``drain=True`` (default) flushes queued work first so every
        accepted Future resolves deterministically; ``drain=False`` fails
        still-queued requests with ``EngineClosed`` (in-flight batches
        still complete). Idempotent."""
        with self._cv:
            if self._closed and self._thread is None:
                return
            self._closed = True      # no new submits
            if self._thread is None:
                # never started: there is no scheduler to flush the queue,
                # so a "graceful" close can only fail what's pending
                drain = False
            if not drain:
                while self._pending:
                    req = heapq.heappop(self._pending)
                    if req.future.set_running_or_notify_cancel():
                        self._trace_terminal(req, "error")
                        req.future.set_exception(EngineClosed(
                            "engine closed before this request was "
                            "dispatched"))
                        self.stats.failed += 1
            self._closing = True
            self._cv.notify_all()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout)
        # the auto-built spatial lane is engine-owned (unlike an explicit
        # user fallback): release its background-rebuild worker and cached
        # graphs deterministically rather than waiting on GC
        lane, self._spatial_lane = self._spatial_lane, None
        if lane is not None:
            lane.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def submit(self, atoms, properties=None, priority: int = 0,
               deadline: float | None = None) -> Future:
        """Enqueue one structure; returns a Future resolving to the same
        result dict ``calculate`` produces (optionally trimmed to
        ``properties``).

        ``priority``: lower values dispatch first (default 0; negative =
        urgent). ``deadline``: seconds from now (on the engine clock); used
        for earliest-deadline-first ordering within a priority class and
        for deadline-miss accounting — late results are still delivered.
        """
        now = self._clock()
        req = _Request(
            priority=int(priority),
            deadline_abs=(now + float(deadline) if deadline is not None
                          else float("inf")),
            seq=next(self._seq),
            atoms=atoms,
            properties=tuple(properties) if properties is not None else None,
            t_submit=now,
            n_atoms=len(atoms),
        )
        mx = obsrt.metrics()
        with self._cv:
            if self._closed:
                raise EngineClosed("submit() on a closed engine")
            try:
                self._check_hbm_admission(atoms)
            except ServeRejected:
                if mx is not None:
                    mx.counter("distmlip_serve_rejected_total",
                               "admission-rejected requests").inc()
                raise
            if len(self._pending) >= self.max_queue:
                if self.admission == "reject":
                    self.stats.rejected += 1
                    if mx is not None:
                        mx.counter("distmlip_serve_rejected_total",
                                   "admission-rejected requests").inc()
                    raise ServeRejected(
                        f"queue full ({self.max_queue} pending); retry later "
                        f"or construct with admission='block'")
                self._cv.wait_for(
                    lambda: len(self._pending) < self.max_queue
                    or self._closed)
                if self._closed:
                    raise EngineClosed("engine closed while blocked on "
                                       "admission")
            self.stats.submitted += 1
            tr = obsrt.tracer()
            if tr is not None:
                # adopt an ambient (router-owned) request trace, or open
                # a root of our own for standalone submissions
                req.trace = tr.adopt_request()
                if req.trace is None:
                    req.trace = tr.start_request(
                        "engine.submit",
                        attrs={"n_atoms": req.n_atoms,
                               "priority": req.priority})
            heapq.heappush(self._pending, req)
            if mx is not None:
                mx.counter("distmlip_serve_submitted_total",
                           "accepted engine submissions").inc()
                mx.gauge("distmlip_serve_queue_depth",
                         "requests queued, not yet dispatched").set(
                             len(self._pending))
            self._cv.notify_all()
        return req.future

    def _hbm_budget(self) -> int | None:
        """The batched lane's per-device HBM budget (None: no budget)."""
        return getattr(self.potential, "hbm_budget_bytes", None)

    def _check_hbm_admission(self, atoms) -> None:
        """Reject a structure whose MEASURED solo footprint (its own
        calibrated rung) exceeds the batched lane's HBM budget — it
        cannot fit any batch, so parking it (admission="block") would
        hang the submitter forever. An over-budget EXTRAPOLATED estimate
        admits: the planner ships it as a solo probe whose compile
        calibrates the rung (rejecting on guesses could livelock the
        lane after one over-budget calibration elsewhere). Routed
        oversized structures (> max_batch_atoms) are exempt: they ride
        the fallback lane, which this budget does not govern."""
        budget = self._hbm_budget()
        if budget is None:
            return
        n = len(atoms)
        if self.max_batch_atoms is not None and n > self.max_batch_atoms:
            return
        caps = getattr(self.potential, "caps", None)
        exact = getattr(caps, "has_calibrated_rung", None)
        if exact is None or not exact(n):
            return
        est_fn = getattr(self.potential, "estimate_batch_bytes", None)
        est = est_fn(n) if est_fn is not None else None
        if est is not None and est > budget:
            self.stats.rejected += 1
            raise ServeRejected(
                f"structure of {n} atoms is estimated at "
                f"{est / 2**20:.1f} MiB peak — over the batched lane's "
                f"{budget / 2**20:.1f} MiB HBM budget; partition it "
                f"spatially (DistPotential / the engine's oversized "
                f"lane via max_batch_atoms) instead")

    # ------------------------------------------------------------------
    # scheduler loop
    # ------------------------------------------------------------------

    def _wait_timeout(self, oldest_age: float) -> float:
        """How long the scheduler may sleep before re-checking the max-wait
        deadline. On the real clock this is the exact remaining budget; on
        an injected (fake) clock fall back to a short poll so tests stay
        deterministic without mapping fake seconds to real ones."""
        if self._real_clock:
            return max(min(self.max_wait_s - oldest_age, 0.05), 0.001)
        return 0.005

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closing:
                    self._last_progress = self._clock()  # idle = healthy
                    self._cv.wait(timeout=0.05)
                if not self._pending and self._closing:
                    return
                now = self._clock()
                oldest = min(r.t_submit for r in self._pending)
                ready = (len(self._pending) >= self.max_batch
                         or self._draining > 0 or self._closing
                         or now - oldest >= self.max_wait_s)
                if not ready:
                    self._cv.wait(timeout=self._wait_timeout(now - oldest))
                    continue
                tr = obsrt.tracer()
                t_plan0 = tr.now() if tr is not None else 0.0
                batch, oversized, overbudget, shed = \
                    self._assemble_locked(now)
                plan_win = ((t_plan0, tr.now())
                            if tr is not None else None)
                self._inflight += 1
                self._cv.notify_all()   # admission slots freed
            try:
                self._run_dispatch(batch, oversized, overbudget, shed, now,
                                   plan_win)
            except BaseException:  # noqa: BLE001 - the loop must survive
                self.stats.scheduler_errors += 1
                import traceback
                import warnings

                warnings.warn("serve scheduler dispatch fault (isolated):\n"
                              + traceback.format_exc(), stacklevel=1)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._last_progress = self._clock()
                    self._cv.notify_all()

    def _provably_late(self, req: _Request, now: float) -> bool:
        """Deadline shedding predicate: the deadline already passed, or —
        given the EWMA batch service time — the request would miss even
        if dispatched in the very next batch (the most optimistic drain
        the queue can offer)."""
        if req.deadline_abs == float("inf"):
            return False
        if req.deadline_abs <= now:
            return True
        ewma = self._service_ewma
        return ewma is not None and req.deadline_abs < now + ewma

    def _note_service(self, service_s: float) -> None:
        """Fold one dispatch's service time into the shedding EWMA."""
        prev = self._service_ewma
        self._service_ewma = (service_s if prev is None
                              else 0.7 * prev + 0.3 * service_s)

    def _assemble_locked(self, now: float):
        """Pop the next micro-batch (plus any oversized requests seen
        while scanning, a head whose solo HBM estimate is over budget,
        and — with ``shed_deadlines`` — requests whose deadline provably
        cannot be met; all failed by the dispatcher, never run). Called
        under the lock; returns ``(batch, oversized, overbudget,
        shed)``."""
        window: list[_Request] = []
        limit = max(self.window, self.max_batch)
        while self._pending and len(window) < limit:
            window.append(heapq.heappop(self._pending))
        oversized, normal, shed = [], [], []
        for r in window:
            if self.shed_deadlines and self._provably_late(r, now):
                shed.append(r)
            elif (self.max_batch_atoms is not None
                    and r.n_atoms > self.max_batch_atoms):
                oversized.append(r)
            else:
                normal.append(r)
        batch: list[_Request] = []
        overbudget: list[_Request] = []
        self._last_plan_attrs = None
        if normal:
            plan = plan_batch([r.n_atoms for r in normal],
                              policy=getattr(self.potential, "caps", None),
                              max_batch=self.max_batch, window=limit,
                              bytes_budget=self._hbm_budget())
            self._last_plan_attrs = plan.span_attrs()
            chosen = set(plan.take)
            for i, r in enumerate(normal):
                if i in chosen:
                    # a head flagged over_budget was admitted BEFORE the
                    # bytes model calibrated (the admission race); it can
                    # never fit a batch — fail it instead of dispatching
                    # an over-budget program
                    (overbudget if plan.over_budget else batch).append(r)
                else:
                    # not picked this round (occupancy rule / slot budget):
                    # keep its queue position for the next batch
                    heapq.heappush(self._pending, r)
        return batch, oversized, overbudget, shed

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _run_dispatch(self, batch, oversized, overbudget, shed,
                      t_dispatch, plan_win=None) -> None:
        mx = obsrt.metrics()
        for req in shed:
            # outside the lock (done-callbacks run here). Shed requests
            # were healthy at admission and expired in the queue: they
            # count in shed_count, not in failed/deadline_misses
            for r in self._start_requests([req]):
                self.stats.shed_count += 1
                if mx is not None:
                    mx.counter("distmlip_serve_shed_total",
                               "deadline-shed requests").inc()
                why = ("has already passed" if r.deadline_abs <= t_dispatch
                       else "provably cannot be met at the current queue "
                            "drain rate")
                self._trace_terminal(r, "shed")
                r.future.set_exception(ServeRejected(
                    f"deadline shed: the request's deadline {why} (queue "
                    f"wait {t_dispatch - r.t_submit:.3f}s); retry with a "
                    f"looser deadline or more capacity"))
        for req in overbudget:
            # outside the lock: failing a Future runs its done-callbacks.
            # Accounting: this request WAS accepted (it predates the bytes
            # model), so it counts as a failure via _fail — NOT as a
            # submit-time reject (which would double-count it)
            for r in self._start_requests([req]):
                self._fail(r, ServeRejected(
                    f"structure of {r.n_atoms} atoms is estimated over the "
                    f"batched lane's HBM budget (admitted before the bytes "
                    f"model calibrated); partition it spatially instead"))
        for req in oversized:
            self._run_fallback(req, t_dispatch)
        if batch:
            self._run_batch(batch, t_dispatch, plan_win)

    def _start_requests(self, requests) -> list[_Request]:
        """Transition Futures to running; drop the ones a caller already
        cancelled."""
        live = []
        for r in requests:
            if r.future.set_running_or_notify_cancel():
                live.append(r)
            else:
                self.stats.cancelled += 1
                self._trace_terminal(r, "cancelled")
        return live

    def _trace_terminal(self, req: _Request, status: str) -> None:
        """Close an ENGINE-OWNED request trace with its one terminal
        ``future.resolve`` span (no-op for router-owned traces — the
        router closes those when the caller-visible Future resolves)."""
        if req.trace is None:
            return
        tr = obsrt.tracer()
        if tr is not None:
            tr.finish_request(req.trace, status=status)

    def _resolve(self, req: _Request, result: dict, t_done: float) -> None:
        if req.deadline_abs < t_done:
            self.stats.deadline_misses += 1
            fl = obsrt.flight()
            if fl is not None:
                # first deadline miss = incident (rate-limited inside):
                # the flight recorder captures traces + metrics while the
                # regression is still on the wire
                fl.capture("serve deadline miss", attrs={
                    "queue_wait_s": round(t_done - req.t_submit, 6),
                    "n_atoms": req.n_atoms,
                    "deadline_misses": self.stats.deadline_misses})
            mx = obsrt.metrics()
            if mx is not None:
                mx.counter("distmlip_serve_deadline_miss_total",
                           "requests resolved past their deadline").inc()
        if req.properties is not None:
            keep = set(req.properties) | {"energy"}
            result = {k: v for k, v in result.items() if k in keep}
        self.stats.completed += 1
        mx = obsrt.metrics()
        if mx is not None:
            mx.counter("distmlip_serve_completed_total",
                       "requests resolved with a result").inc()
        self._trace_terminal(req, "ok")
        req.future.set_result(result)

    def _fail(self, req: _Request, exc: BaseException) -> None:
        self.stats.failed += 1
        mx = obsrt.metrics()
        if mx is not None:
            mx.counter("distmlip_serve_failed_total",
                       "requests resolved with an explicit error").inc()
        self._trace_terminal(req, "error")
        req.future.set_exception(exc)

    def _oversized_lane(self):
        """The potential serving oversized structures: the explicit
        ``fallback`` if configured, else a lazily built ``DistPotential``
        over the SPATIAL sub-axis of the shared BatchedPotential's mesh
        (one batch row's spatial devices — same chips, spatial route).
        Returns None when neither is available."""
        if self.fallback is not None:
            return self.fallback
        mesh = getattr(self.potential, "mesh", None)
        if mesh is None:
            return None
        if self._spatial_lane is None:
            try:
                from ..calculators.calculator import DistPotential
                from ..parallel import mesh_shape

                pot = self.potential
                _bp, sp = mesh_shape(mesh)
                # the lane mirrors the shared potential's configuration
                # (magmoms, skin cache, threading, telemetry) so the two
                # routes differ only in placement
                self._spatial_lane = DistPotential(
                    pot.model, pot.params,
                    num_partitions=sp,
                    devices=list(np.asarray(mesh.devices).reshape(-1)[:sp]),
                    species_map=getattr(pot, "species_map", None),
                    compute_stress=getattr(pot, "compute_stress", True),
                    compute_magmom=getattr(pot, "compute_magmom", False),
                    skin=getattr(pot, "skin", 0.0),
                    num_threads=getattr(pot, "num_threads", None),
                    kernels=getattr(pot, "kernels", None),
                    telemetry=getattr(pot, "telemetry", None))
                self._spatial_lane_error = None
            except Exception as e:  # noqa: BLE001 - retried next request
                # remember the cause for the failure message but do NOT
                # latch it: a transient build failure (OOM while a batch is
                # resident, backend hiccup) must not disable the lane for
                # the engine's lifetime
                self._spatial_lane_error = e
                return None
        return self._spatial_lane

    def _run_fallback(self, req: _Request, t_dispatch: float) -> None:
        live = self._start_requests([req])
        if not live:
            return
        req = live[0]
        tr = obsrt.tracer()
        t_dev0 = 0.0
        if tr is not None and req.trace is not None:
            # queue wait + device dispatch ride the request's OWN trace
            # (no separate batch trace: the oversized lane is B=1)
            t_dev0 = tr.now()
            tr.emit("engine.queue", parent=req.trace.ctx,
                    t_start=req.trace.t_submit, t_end=t_dev0,
                    attrs={"n_atoms": req.n_atoms, "lane": "oversized"})
        t0 = time.perf_counter()
        try:
            lane = self._oversized_lane()
            if lane is None:
                raise ValueError(
                    f"structure with {req.n_atoms} atoms exceeds "
                    f"max_batch_atoms={self.max_batch_atoms} and no "
                    f"fallback DistPotential (or batched-potential mesh "
                    f"spatial axis) is configured"
                ) from self._spatial_lane_error
            if not _finite_positions(req.atoms):
                raise ValueError("non-finite positions")
            # snapshot last_stats in the same critical section as the
            # call (same rule as _run_batch): a direct caller sharing an
            # explicit fallback potential must not overwrite the stats
            # between this request executing and the engine reading them
            lock = getattr(lane, "_lock", None)
            with lock if lock is not None else _NULL_CTX:
                result = lane.calculate(req.atoms)
                pot_stats = dict(getattr(lane, "last_stats", None) or {})
        except Exception as e:  # noqa: BLE001 - isolate to this request
            self._fail(req, e)
            return
        t_done = self._clock()
        self.stats.fallback_requests += 1
        if tr is not None and req.trace is not None:
            tr.emit("device.dispatch", parent=req.trace.ctx,
                    t_start=t_dev0, t_end=tr.now(),
                    attrs={"lane": "oversized"})
        # deliberately NOT folded into the shedding EWMA: one slow
        # oversized request on the spatial lane would inflate the
        # batched lane's drain estimate and shed healthy deadlines
        self._resolve(req, result, t_done)
        # unified stats emission: the spatial/fallback lane reports the
        # same last_stats surface the batched lane does, so fallback
        # batches no longer bypass graph/occupancy telemetry
        self._emit_record("serve_fallback", [req], t_dispatch, t_done,
                          service_s=time.perf_counter() - t0,
                          pot_stats=pot_stats,
                          trace_ctx=(req.trace.ctx if req.trace is not None
                                     else None))

    def _run_batch(self, batch: list[_Request], t_dispatch: float,
                   plan_win=None) -> None:
        batch = self._start_requests(batch)
        if not batch:
            return
        # cheap poison screen: non-finite positions would feed NaN through
        # the neighbor build; fail those Futures here and keep the rest
        good = []
        for r in batch:
            if _finite_positions(r.atoms):
                good.append(r)
            else:
                self._fail(r, ValueError(
                    "non-finite positions (NaN/inf) in submitted structure"))
        if not good:
            return
        # --- tracing: close each member's queue wait, open the batch
        # trace with span LINKS back to every member request ---
        tr = obsrt.tracer()
        batch_span = None
        if tr is not None:
            t_q = tr.now()
            links = []
            for r in good:
                if r.trace is not None:
                    tr.emit("engine.queue", parent=r.trace.ctx,
                            t_start=r.trace.t_submit, t_end=t_q,
                            attrs={"n_atoms": r.n_atoms})
                    links.append(r.trace.ctx)
            batch_span = tr.begin(
                "serve.batch", new_trace=True, links=links,
                t_start=plan_win[0] if plan_win is not None else t_q,
                attrs={"batch_size": len(good)})
            if plan_win is not None:
                tr.emit("scheduler.plan_batch", parent=batch_span,
                        t_start=plan_win[0], t_end=plan_win[1],
                        attrs=self._last_plan_attrs)
        t0 = time.perf_counter()
        cc_before = self.compile_count
        pot_stats: dict = {}
        pot_timings: dict = {}
        t_calc_end = 0.0
        try:
            # snapshot last_stats in the same critical section as the call:
            # a direct caller sharing the potential (or this lane's own
            # singles retry below) must not overwrite the stats between the
            # batch executing and the engine reading its occupancy
            lock = getattr(self.potential, "_lock", None)
            with lock if lock is not None else _NULL_CTX:
                # ambient batch context: the potential's own record
                # stamps these ids and its TraceAnnotation carries the
                # trace id, lining device timelines up with host spans
                with (tr.use(batch_span) if tr is not None
                      else contextlib.nullcontext()):
                    results = self.potential.calculate(
                        [r.atoms for r in good])
                pot_stats = dict(
                    getattr(self.potential, "last_stats", None) or {})
                pot_timings = dict(
                    getattr(self.potential, "last_timings", None) or {})
            if tr is not None:
                t_calc_end = tr.now()
        except Exception:  # noqa: BLE001 - isolate per request below
            # a batch-level fault (one request's graph build blowing up the
            # pack) is isolated by re-running each request alone: the
            # poison fails its own Future, the rest still get results
            results = None
        if results is None:
            for r in good:
                t_r0 = tr.now() if tr is not None else 0.0
                try:
                    r_result = self.potential.calculate([r.atoms])[0]
                except Exception as e:  # noqa: BLE001
                    exc: BaseException | None = e
                else:
                    exc = None
                if tr is not None and r.trace is not None:
                    tr.emit("device.dispatch", parent=r.trace.ctx,
                            t_start=t_r0, t_end=tr.now(),
                            status="ok" if exc is None else "error",
                            attrs={"retry": True})
                if exc is not None:
                    self._fail(r, exc)
                else:
                    self._resolve(r, r_result, self._clock())
            t_done = self._clock()
        else:
            t_done = self._clock()
            for r, res in zip(good, results):
                self._resolve(r, res, t_done)
        # diffed AFTER any singles retries: a retry's fresh B=1 bucket
        # is a real compile and must keep the compiles counter in step
        # with the compile_count gauge
        compiled = self.compile_count > cc_before
        if tr is not None and batch_span is not None:
            if results is not None and pot_timings.get("total_s"):
                # reconstruct the pack/device phase windows from the
                # potential's own perf_counter phase timings, anchored at
                # the end of the calculate call (same tracer clock)
                t_c0 = t_calc_end - pot_timings["total_s"]
                pack_s = (pot_timings.get("neighbor_s", 0.0)
                          + pot_timings.get("partition_s", 0.0)
                          + pot_timings.get("rebuild_s", 0.0))
                tr.emit("batched.pack", parent=batch_span,
                        t_start=t_c0, t_end=t_c0 + pack_s,
                        attrs={"bucket_key":
                               pot_stats.get("bucket_key", "")})
                tr.emit("device.compile" if compiled
                        else "device.dispatch", parent=batch_span,
                        t_start=t_c0 + pack_s,
                        t_end=t_c0 + pack_s
                        + pot_timings.get("device_s", 0.0),
                        attrs={"compiled": compiled})
            tr.end(batch_span,
                   status="ok" if results is not None else "error",
                   attrs={"bucket_key": pot_stats.get("bucket_key", "")})
        service = time.perf_counter() - t0
        self._note_service(service)
        self.stats.batches += 1
        if results is not None:
            occupancy = (len(good) / pot_stats["batch_slots"]
                         if pot_stats.get("batch_slots") else 1.0)
            self.stats.note_batch(pot_stats.get("bucket_key", ""), occupancy,
                                  len(good))
        else:
            # the planned batch never ran as one packed program — the
            # requests executed as B=1 singles, so attributing the intended
            # batch's occupancy/bucket would corrupt the per-bucket stats
            pot_stats = {}
            occupancy = 0.0
        mx = obsrt.metrics()
        if mx is not None:
            mx.counter("distmlip_serve_batches_total",
                       "dispatched micro-batches").inc()
            mx.histogram("distmlip_serve_service_seconds",
                         "batch service time").observe(service)
            mx.gauge("distmlip_serve_batch_occupancy",
                     "real structures / padded batch slots of the last "
                     "batch").set(occupancy)
            mx.gauge("distmlip_serve_queue_depth",
                     "requests queued, not yet dispatched").set(
                         self.queue_depth)
            mx.gauge("distmlip_serve_compile_count",
                     "executables compiled by the shared potential").set(
                         self.compile_count)
            if compiled:
                mx.counter("distmlip_serve_compiles_total",
                           "batches that triggered an XLA compile").inc()
            if pot_stats.get("hbm_headroom_frac"):
                mx.gauge("distmlip_hbm_headroom_frac",
                         "1 - est_peak_bytes / bytes_limit of the last "
                         "batch").set(pot_stats["hbm_headroom_frac"])
        self._emit_record("serve_batch", good, t_dispatch, t_done,
                          service_s=service, pot_stats=pot_stats,
                          batch_occupancy=occupancy,
                          trace_ctx=(batch_span.ctx
                                     if batch_span is not None else None))

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------

    def _emit_record(self, kind: str, requests, t_dispatch, t_done,
                     service_s: float, pot_stats: dict | None = None,
                     batch_occupancy: float = 1.0,
                     trace_ctx: tuple | None = None) -> None:
        self._step += 1
        tel = self.telemetry
        if tel is None or not tel.wants_records():
            return
        rec = StepRecord(
            trace_id=trace_ctx[0] if trace_ctx is not None else "",
            span_id=trace_ctx[1] if trace_ctx is not None else "",
            step=self._step, kind=kind,
            timings={"service_s": service_s,
                     "total_s": max(t_done - t_dispatch, service_s)},
            batch_size=len(requests),
            batch_occupancy=batch_occupancy,
            queue_depth=self.queue_depth,
            queue_wait_s=[round(t_dispatch - r.t_submit, 6)
                          for r in requests],
            request_latency_s=[round(t_done - r.t_submit, 6)
                               for r in requests],
            reject_count=self.stats.rejected,
            deadline_miss_count=self.stats.deadline_misses,
            shed_count=self.stats.shed_count,
            structures_per_sec=(len(requests) / service_s
                                if service_s > 0 else 0.0),
        )
        for k in ("bucket_key", "node_occupancy", "edge_occupancy",
                  "padding_waste_frac", "n_atoms", "rebuild_count",
                  "rebuild_on_device", "rebuild_overflow_count",
                  "num_partitions", "n_cap", "e_cap",
                  "mesh_shape", "spatial_parts", "batch_parts",
                  "halo_send_per_part", "kernel_mode", "kernel_coverage",
                  "est_peak_bytes", "hbm_headroom_frac", "aot_rehydrated"):
            if pot_stats and k in pot_stats:
                setattr(rec, k, pot_stats[k])
        tel.emit(rec)
