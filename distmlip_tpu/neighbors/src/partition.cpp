// Native partitioner (C++/OpenMP) — fast path mirroring
// distmlip_tpu/partition/partitioner.py. Implementation lands after the
// numpy oracle is locked in by the test suite.
