// Native spatial graph partitioner (C++/OpenMP).
//
// TPU-host fast path mirroring distmlip_tpu/partition/partitioner.py (the
// numpy oracle) exactly — same slab rule inputs (walls computed host-side in
// Python), same [pure | to_* | from_*] section layout with ascending global
// ids, same owner-computes edge assignment, same directed line-graph
// construction and ordering. Behavioral ancestor: the reference's
// subgraph_creation_utils.c (see SURVEY.md §2.1 N2); this is a new
// implementation against the numpy spec, not a port.
//
// Memory notes: global->local maps use two slots per node (owner partition +
// halo target partition) instead of P x N arrays, so 1M-atom systems stay
// cheap at any partition count.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct PartResult {
  int64_t P = 0;
  int err = 0;               // 0 ok; -2 multi-destination border node
  int64_t err_node = -1;
  bool has_bond = false;
  std::vector<std::vector<int64_t>> global_ids, node_markers;
  std::vector<std::vector<int64_t>> edge_ids, src_local, dst_local;
  std::vector<std::vector<int64_t>> bond_markers, bond_global_edge;
  std::vector<std::vector<int64_t>> line_src, line_dst, line_center;
  std::vector<std::vector<int64_t>> bm_edge, bm_bond;
};

}  // namespace

extern "C" {

void* dm_partition_build(
    int64_t n, int64_t ne, const int64_t* src, const int64_t* dst,
    const double* frac_axis,   // (n,) wrapped fractional coord along slab axis
    const double* walls,       // (P-1,) ascending
    int64_t P, const uint8_t* bond_mask, int use_bond_graph, int nthreads) {
#ifdef _OPENMP
  if (nthreads > 0) omp_set_num_threads(nthreads);
#endif
  auto* R = new PartResult();
  R->P = P;

  // --- node -> slab ---
  std::vector<int64_t> part(n);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    part[i] = std::upper_bound(walls, walls + (P - 1), frac_axis[i]) - walls;
  }

  // --- border classification (nodes_to_partition) ---
  std::vector<int64_t> ntp(n, -1);
  int err = 0;
  int64_t err_node = -1;
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < ne; ++e) {
    int64_t s = src[e], d = dst[e];
    int64_t ps = part[s], pd = part[d];
    if (ps == pd) continue;
    int64_t expected = -1;
    if (!__atomic_compare_exchange_n(&ntp[s], &expected, pd, false,
                                     __ATOMIC_SEQ_CST, __ATOMIC_SEQ_CST)) {
      if (expected != pd) {
#pragma omp critical
        {
          err = -2;
          err_node = s;
        }
      }
    }
  }
  if (err != 0) {
    R->err = err;
    R->err_node = err_node;
    return R;
  }

  // --- node sections: [pure | to_0..to_{P-1} | from_0..from_{P-1}] ---
  // counts[p][section]; section: 0 = pure, 1+q = to_q, 1+P+q = from_q
  const int64_t S = 1 + 2 * P;
  std::vector<std::vector<int64_t>> counts((size_t)P, std::vector<int64_t>(S, 0));
  for (int64_t i = 0; i < n; ++i) {
    int64_t p = part[i];
    if (ntp[i] < 0) {
      counts[p][0]++;
    } else {
      counts[p][1 + ntp[i]]++;
      counts[ntp[i]][1 + P + p]++;
    }
  }
  R->global_ids.resize(P);
  R->node_markers.resize(P);
  std::vector<std::vector<int64_t>> sec_off((size_t)P, std::vector<int64_t>(S + 1, 0));
  for (int64_t p = 0; p < P; ++p) {
    for (int64_t s = 0; s < S; ++s) sec_off[p][s + 1] = sec_off[p][s] + counts[p][s];
    R->node_markers[p].assign(sec_off[p].begin(), sec_off[p].end());
    R->global_ids[p].resize(sec_off[p][S]);
  }
  // fill ascending-global-id within each section; record local ids:
  // two slots per node: local id in owner partition, local id in halo target
  std::vector<int64_t> loc_owner(n), loc_halo(n, -1);
  {
    std::vector<std::vector<int64_t>> cur = sec_off;  // running cursors
    for (int64_t i = 0; i < n; ++i) {
      int64_t p = part[i];
      int64_t s = (ntp[i] < 0) ? 0 : 1 + ntp[i];
      int64_t li = cur[p][s]++;
      R->global_ids[p][li] = i;
      loc_owner[i] = li;
      if (ntp[i] >= 0) {
        int64_t q = ntp[i];
        int64_t lh = cur[q][1 + P + p]++;
        R->global_ids[q][lh] = i;
        loc_halo[i] = lh;
      }
    }
  }
  auto g2l = [&](int64_t p, int64_t node) -> int64_t {
    if (part[node] == p) return loc_owner[node];
    if (ntp[node] == p) return loc_halo[node];
    return -1;
  };

  // --- owner-computes edge assignment ---
  R->edge_ids.resize(P);
  R->src_local.resize(P);
  R->dst_local.resize(P);
  std::vector<int64_t> ecount(P, 0);
  for (int64_t e = 0; e < ne; ++e) ecount[part[dst[e]]]++;
  for (int64_t p = 0; p < P; ++p) {
    R->edge_ids[p].reserve(ecount[p]);
    R->src_local[p].resize(ecount[p]);
    R->dst_local[p].resize(ecount[p]);
  }
  std::vector<int64_t> edge_local(ne);
  for (int64_t e = 0; e < ne; ++e) {
    int64_t p = part[dst[e]];
    edge_local[e] = (int64_t)R->edge_ids[p].size();
    R->edge_ids[p].push_back(e);
  }
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < ne; ++e) {
    int64_t p = part[dst[e]];
    int64_t li = edge_local[e];
    R->src_local[p][li] = g2l(p, src[e]);
    R->dst_local[p][li] = g2l(p, dst[e]);
  }

  if (!use_bond_graph) return R;
  R->has_bond = true;

  // --- bond-graph nodes: within-bond edges W, sectioned like nodes ---
  std::vector<int64_t> W;
  W.reserve(ne / 4 + 1);
  for (int64_t e = 0; e < ne; ++e)
    if (bond_mask[e]) W.push_back(e);
  const int64_t nw = (int64_t)W.size();

  R->bond_markers.resize(P);
  R->bond_global_edge.resize(P);
  R->bm_edge.resize(P);
  R->bm_bond.resize(P);
  R->line_src.resize(P);
  R->line_dst.resize(P);
  R->line_center.resize(P);

  for (int64_t p = 0; p < P; ++p) {
    std::vector<int64_t> bc(S, 0);
    for (int64_t wi = 0; wi < nw; ++wi) {
      int64_t d = dst[W[wi]];
      if (part[d] == p) {
        bc[(ntp[d] < 0) ? 0 : 1 + ntp[d]]++;
      } else if (ntp[d] == p) {
        bc[1 + P + part[d]]++;
      }
    }
    std::vector<int64_t> off(S + 1, 0);
    for (int64_t s = 0; s < S; ++s) off[s + 1] = off[s] + bc[s];
    R->bond_markers[p].assign(off.begin(), off.end());
    R->bond_global_edge[p].resize(off[S]);
    std::vector<int64_t> cur = off;
    const int64_t owned_b = R->bond_markers[p][1 + P];
    R->bm_edge[p].resize(owned_b);
    R->bm_bond[p].resize(owned_b);
    for (int64_t wi = 0; wi < nw; ++wi) {
      int64_t e = W[wi];
      int64_t d = dst[e];
      if (part[d] == p) {
        int64_t s = (ntp[d] < 0) ? 0 : 1 + ntp[d];
        R->bond_global_edge[p][cur[s]++] = e;
      } else if (ntp[d] == p) {
        R->bond_global_edge[p][cur[1 + P + part[d]]++] = e;
      }
    }
    for (int64_t li = 0; li < owned_b; ++li) {
      R->bm_edge[p][li] = edge_local[R->bond_global_edge[p][li]];
      R->bm_bond[p][li] = li;
    }

    // --- line graph: a=(s->d), b=(d->k) with b locally computed, k != s ---
    const int64_t nb = (int64_t)R->bond_global_edge[p].size();
    // locally-computed bond nodes (local id < owned_b) grouped by global
    // src node, stable in local-id order
    std::vector<std::pair<int64_t, int64_t>> nil_by_src((size_t)owned_b);
    for (int64_t li = 0; li < owned_b; ++li)
      nil_by_src[li] = {src[R->bond_global_edge[p][li]], li};
    std::stable_sort(
        nil_by_src.begin(), nil_by_src.end(),
        [](const std::pair<int64_t, int64_t>& a,
           const std::pair<int64_t, int64_t>& b) { return a.first < b.first; });
    auto lower = [&](int64_t key) {
      return std::lower_bound(
          nil_by_src.begin(), nil_by_src.end(), key,
          [](const std::pair<int64_t, int64_t>& pr, int64_t k) {
            return pr.first < k;
          });
    };
    auto upper = [&](int64_t key) {
      return std::upper_bound(
          nil_by_src.begin(), nil_by_src.end(), key,
          [](int64_t k, const std::pair<int64_t, int64_t>& pr) {
            return k < pr.first;
          });
    };
    std::vector<int64_t> lcount(nb, 0);
#pragma omp parallel for schedule(dynamic, 256)
    for (int64_t a = 0; a < nb; ++a) {
      int64_t e_a = R->bond_global_edge[p][a];
      int64_t gs = src[e_a], gd = dst[e_a];
      int64_t c = 0;
      for (auto it = lower(gd); it != upper(gd); ++it) {
        if (dst[R->bond_global_edge[p][it->second]] != gs) ++c;
      }
      lcount[a] = c;
    }
    std::vector<int64_t> loff(nb + 1, 0);
    for (int64_t a = 0; a < nb; ++a) loff[a + 1] = loff[a] + lcount[a];
    R->line_src[p].resize(loff[nb]);
    R->line_dst[p].resize(loff[nb]);
    R->line_center[p].resize(loff[nb]);
#pragma omp parallel for schedule(dynamic, 256)
    for (int64_t a = 0; a < nb; ++a) {
      int64_t e_a = R->bond_global_edge[p][a];
      int64_t gs = src[e_a], gd = dst[e_a];
      int64_t w = loff[a];
      for (auto it = lower(gd); it != upper(gd); ++it) {
        int64_t b = it->second;
        int64_t e_b = R->bond_global_edge[p][b];
        if (dst[e_b] == gs) continue;
        R->line_src[p][w] = a;
        R->line_dst[p][w] = b;
        R->line_center[p][w] = g2l(p, src[e_b]);
        ++w;
      }
    }
  }
  return R;
}

int dm_partition_err(void* h, int64_t* err_node) {
  auto* R = static_cast<PartResult*>(h);
  *err_node = R->err_node;
  return R->err;
}

// sizes for partition p: [n_nodes, n_edges, n_bonds, n_lines, n_bm]
void dm_partition_sizes(void* h, int64_t p, int64_t* out) {
  auto* R = static_cast<PartResult*>(h);
  out[0] = (int64_t)R->global_ids[p].size();
  out[1] = (int64_t)R->edge_ids[p].size();
  out[2] = R->has_bond ? (int64_t)R->bond_global_edge[p].size() : 0;
  out[3] = R->has_bond ? (int64_t)R->line_src[p].size() : 0;
  out[4] = R->has_bond ? (int64_t)R->bm_edge[p].size() : 0;
}

void dm_partition_copy(void* h, int64_t p, int64_t* global_ids,
                       int64_t* node_markers, int64_t* edge_ids,
                       int64_t* src_local, int64_t* dst_local,
                       int64_t* bond_markers, int64_t* bond_global_edge,
                       int64_t* line_src, int64_t* line_dst,
                       int64_t* line_center, int64_t* bm_edge,
                       int64_t* bm_bond) {
  auto* R = static_cast<PartResult*>(h);
  auto cp = [](int64_t* out, const std::vector<int64_t>& v) {
    if (out && !v.empty()) std::memcpy(out, v.data(), v.size() * sizeof(int64_t));
  };
  cp(global_ids, R->global_ids[p]);
  cp(node_markers, R->node_markers[p]);
  cp(edge_ids, R->edge_ids[p]);
  cp(src_local, R->src_local[p]);
  cp(dst_local, R->dst_local[p]);
  if (R->has_bond) {
    cp(bond_markers, R->bond_markers[p]);
    cp(bond_global_edge, R->bond_global_edge[p]);
    cp(line_src, R->line_src[p]);
    cp(line_dst, R->line_dst[p]);
    cp(line_center, R->line_center[p]);
    cp(bm_edge, R->bm_edge[p]);
    cp(bm_bond, R->bm_bond[p]);
  }
}

void dm_partition_free(void* h) { delete static_cast<PartResult*>(h); }

}  // extern "C"
