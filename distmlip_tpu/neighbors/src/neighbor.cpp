// Native periodic neighbor search (linked-cell, OpenMP).
//
// TPU-host equivalent of the reference's FPIS layer (behavioral spec at
// reference fpis.c:418-856; this is a new implementation, not a port):
//   * dual cutoff in one pass (atom cutoff r, bond cutoff bond_r <= r)
//   * image offsets relative to the unwrapped input coordinates
//   * self pairs (d < 1e-8) excluded; periodic self-images kept
//   * two-pass count -> prefix-sum -> fill parallelism (race-free)
//
// Exposed through a C ABI consumed via ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>
#include <algorithm>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr double kNumericalTol = 1e-8;

struct Mat3 {
  double m[9];  // row-major; rows are lattice vectors
};

static Mat3 invert3(const Mat3& a) {
  const double* p = a.m;
  double det = p[0] * (p[4] * p[8] - p[5] * p[7]) -
               p[1] * (p[3] * p[8] - p[5] * p[6]) +
               p[2] * (p[3] * p[7] - p[4] * p[6]);
  double id = 1.0 / det;
  Mat3 r;
  r.m[0] = (p[4] * p[8] - p[5] * p[7]) * id;
  r.m[1] = (p[2] * p[7] - p[1] * p[8]) * id;
  r.m[2] = (p[1] * p[5] - p[2] * p[4]) * id;
  r.m[3] = (p[5] * p[6] - p[3] * p[8]) * id;
  r.m[4] = (p[0] * p[8] - p[2] * p[6]) * id;
  r.m[5] = (p[2] * p[3] - p[0] * p[5]) * id;
  r.m[6] = (p[3] * p[7] - p[4] * p[6]) * id;
  r.m[7] = (p[1] * p[6] - p[0] * p[7]) * id;
  r.m[8] = (p[0] * p[4] - p[1] * p[3]) * id;
  return r;
}

// frac = cart @ inv(lattice)
static inline void cart_to_frac(const double* cart, const Mat3& inv, double* frac) {
  for (int k = 0; k < 3; ++k)
    frac[k] = cart[0] * inv.m[0 + k] + cart[1] * inv.m[3 + k] + cart[2] * inv.m[6 + k];
}

static inline void frac_to_cart(const double* frac, const Mat3& lat, double* cart) {
  for (int k = 0; k < 3; ++k)
    cart[k] = frac[0] * lat.m[0 + k] + frac[1] * lat.m[3 + k] + frac[2] * lat.m[6 + k];
}

struct NeighborResult {
  std::vector<int64_t> src, dst;
  std::vector<int32_t> offsets;    // 3*E
  std::vector<double> distances;   // E
  std::vector<uint8_t> bond_mask;  // E
  std::vector<double> wrapped;     // 3*N
  std::vector<int64_t> shift;      // 3*N
};

struct ExpandedPoint {
  double x, y, z;
  int64_t atom;
  int32_t ix, iy, iz;  // image offset
};

}  // namespace

extern "C" {

void* dm_neighbor_build(int64_t n, const double* cart, const double* lattice_in,
                        const int64_t* pbc, double r, double bond_r, double tol,
                        int nthreads) {
  if (n <= 0 || r <= 0) return nullptr;
#ifdef _OPENMP
  if (nthreads > 0) omp_set_num_threads(nthreads);
#endif
  Mat3 lat;
  std::memcpy(lat.m, lattice_in, sizeof(lat.m));
  Mat3 inv = invert3(lat);

  auto* res = new NeighborResult();
  res->wrapped.resize(3 * n);
  res->shift.resize(3 * n);
  std::vector<double> frac(3 * n);

  // wrap into [0,1) along periodic axes, remember the removed translations
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    double f[3];
    cart_to_frac(cart + 3 * i, inv, f);
    for (int k = 0; k < 3; ++k) {
      int64_t s = 0;
      if (pbc[k]) {
        s = (int64_t)std::floor(f[k]);
        double w = f[k] - (double)s;
        if (w >= 1.0) { s += 1; w = f[k] - (double)s; }
        f[k] = w;
      }
      frac[3 * i + k] = f[k];
      res->shift[3 * i + k] = s;
    }
    frac_to_cart(f, lat, &res->wrapped[3 * i]);
  }

  // plane spacings -> image counts per axis; non-periodic axes are never
  // wrapped, so atoms may sit at any fractional coordinate there — no
  // margin culling on those axes
  double dspace[3], margin[3];
  int64_t nimg[3];
  for (int k = 0; k < 3; ++k) {
    double nk = std::sqrt(inv.m[0 + k] * inv.m[0 + k] + inv.m[3 + k] * inv.m[3 + k] +
                          inv.m[6 + k] * inv.m[6 + k]);
    dspace[k] = 1.0 / nk;
    margin[k] = pbc[k] ? r / dspace[k] + 1e-12 : 1e300;
    nimg[k] = pbc[k] ? (int64_t)std::floor(r / dspace[k]) + 1 : 0;
  }

  // --- expand periodic images within a margin of r around the cell (2-pass) ---
  int64_t n_off = (2 * nimg[0] + 1) * (2 * nimg[1] + 1) * (2 * nimg[2] + 1);
  std::vector<ExpandedPoint> pts;
  {
    std::vector<int64_t> counts(n_off, 0);
#pragma omp parallel for schedule(static)
    for (int64_t o = 0; o < n_off; ++o) {
      int64_t t = o;
      int64_t oz = t % (2 * nimg[2] + 1) - nimg[2]; t /= (2 * nimg[2] + 1);
      int64_t oy = t % (2 * nimg[1] + 1) - nimg[1]; t /= (2 * nimg[1] + 1);
      int64_t ox = t - nimg[0];
      int64_t c = 0;
      for (int64_t i = 0; i < n; ++i) {
        double fx = frac[3 * i + 0] + ox, fy = frac[3 * i + 1] + oy, fz = frac[3 * i + 2] + oz;
        if (fx >= -margin[0] && fx <= 1 + margin[0] && fy >= -margin[1] &&
            fy <= 1 + margin[1] && fz >= -margin[2] && fz <= 1 + margin[2])
          ++c;
      }
      counts[o] = c;
    }
    std::vector<int64_t> offs(n_off + 1, 0);
    for (int64_t o = 0; o < n_off; ++o) offs[o + 1] = offs[o] + counts[o];
    pts.resize(offs[n_off]);
#pragma omp parallel for schedule(static)
    for (int64_t o = 0; o < n_off; ++o) {
      int64_t t = o;
      int64_t oz = t % (2 * nimg[2] + 1) - nimg[2]; t /= (2 * nimg[2] + 1);
      int64_t oy = t % (2 * nimg[1] + 1) - nimg[1]; t /= (2 * nimg[1] + 1);
      int64_t ox = t - nimg[0];
      int64_t w = offs[o];
      for (int64_t i = 0; i < n; ++i) {
        double f[3] = {frac[3 * i + 0] + ox, frac[3 * i + 1] + oy, frac[3 * i + 2] + oz};
        if (f[0] < -margin[0] || f[0] > 1 + margin[0] || f[1] < -margin[1] ||
            f[1] > 1 + margin[1] || f[2] < -margin[2] || f[2] > 1 + margin[2])
          continue;
        double c[3];
        frac_to_cart(f, lat, c);
        pts[w++] = ExpandedPoint{c[0], c[1], c[2], i, (int32_t)ox, (int32_t)oy, (int32_t)oz};
      }
    }
  }
  const int64_t npts = (int64_t)pts.size();

  // --- linked cells over expanded points (counting sort) ---
  double edge = std::max(r, 0.1);
  double lo[3] = {1e300, 1e300, 1e300}, hi[3] = {-1e300, -1e300, -1e300};
  for (const auto& p : pts) {
    lo[0] = std::min(lo[0], p.x); hi[0] = std::max(hi[0], p.x);
    lo[1] = std::min(lo[1], p.y); hi[1] = std::max(hi[1], p.y);
    lo[2] = std::min(lo[2], p.z); hi[2] = std::max(hi[2], p.z);
  }
  for (int k = 0; k < 3; ++k) lo[k] -= 1e-9;
  int64_t nc[3];
  for (int k = 0; k < 3; ++k)
    nc[k] = std::max<int64_t>(1, (int64_t)std::floor((hi[k] - lo[k]) / edge) + 1);
  const int64_t ncell = nc[0] * nc[1] * nc[2];
  auto cell_of = [&](double x, double y, double z) -> int64_t {
    int64_t cx = (int64_t)((x - lo[0]) / edge);
    int64_t cy = (int64_t)((y - lo[1]) / edge);
    int64_t cz = (int64_t)((z - lo[2]) / edge);
    cx = std::min(std::max<int64_t>(cx, 0), nc[0] - 1);
    cy = std::min(std::max<int64_t>(cy, 0), nc[1] - 1);
    cz = std::min(std::max<int64_t>(cz, 0), nc[2] - 1);
    return (cx * nc[1] + cy) * nc[2] + cz;
  };
  std::vector<int64_t> cell_start(ncell + 1, 0), pt_cell(npts), pt_order(npts);
  for (int64_t p = 0; p < npts; ++p) {
    pt_cell[p] = cell_of(pts[p].x, pts[p].y, pts[p].z);
    cell_start[pt_cell[p] + 1]++;
  }
  for (int64_t c = 0; c < ncell; ++c) cell_start[c + 1] += cell_start[c];
  {
    std::vector<int64_t> cur(cell_start.begin(), cell_start.end() - 1);
    for (int64_t p = 0; p < npts; ++p) pt_order[cur[pt_cell[p]]++] = p;
  }

  // --- per-center 27-cell scan, 2-pass count/fill ---
  const double r_tol = r + tol;
  const double b_tol = bond_r > 0 ? bond_r + tol : -1.0;
  std::vector<int64_t> ecount(n, 0);
  auto scan = [&](int64_t i, bool fill, int64_t base) -> int64_t {
    const double* w = &res->wrapped[3 * i];
    int64_t cx = (int64_t)((w[0] - lo[0]) / edge);
    int64_t cy = (int64_t)((w[1] - lo[1]) / edge);
    int64_t cz = (int64_t)((w[2] - lo[2]) / edge);
    int64_t cnt = 0;
    for (int64_t dx = -1; dx <= 1; ++dx)
      for (int64_t dy = -1; dy <= 1; ++dy)
        for (int64_t dz = -1; dz <= 1; ++dz) {
          int64_t x = cx + dx, y = cy + dy, z = cz + dz;
          if (x < 0 || x >= nc[0] || y < 0 || y >= nc[1] || z < 0 || z >= nc[2]) continue;
          int64_t c = (x * nc[1] + y) * nc[2] + z;
          for (int64_t s = cell_start[c]; s < cell_start[c + 1]; ++s) {
            const ExpandedPoint& p = pts[pt_order[s]];
            double ddx = p.x - w[0], ddy = p.y - w[1], ddz = p.z - w[2];
            double d = std::sqrt(ddx * ddx + ddy * ddy + ddz * ddz);
            if (d >= r_tol || d <= kNumericalTol) continue;
            if (fill) {
              int64_t e = base + cnt;
              res->src[e] = i;
              res->dst[e] = p.atom;
              res->offsets[3 * e + 0] =
                  p.ix + (int32_t)(res->shift[3 * i + 0] - res->shift[3 * p.atom + 0]);
              res->offsets[3 * e + 1] =
                  p.iy + (int32_t)(res->shift[3 * i + 1] - res->shift[3 * p.atom + 1]);
              res->offsets[3 * e + 2] =
                  p.iz + (int32_t)(res->shift[3 * i + 2] - res->shift[3 * p.atom + 2]);
              res->distances[e] = d;
              res->bond_mask[e] = (b_tol > 0 && d < b_tol) ? 1 : 0;
            }
            ++cnt;
          }
        }
    return cnt;
  };

#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t i = 0; i < n; ++i) ecount[i] = scan(i, false, 0);
  std::vector<int64_t> estart(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) estart[i + 1] = estart[i] + ecount[i];
  const int64_t ne = estart[n];
  res->src.resize(ne);
  res->dst.resize(ne);
  res->offsets.resize(3 * ne);
  res->distances.resize(ne);
  res->bond_mask.resize(ne);
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t i = 0; i < n; ++i) scan(i, true, estart[i]);

  return res;
}

int64_t dm_neighbor_num_edges(void* h) {
  return h ? (int64_t)static_cast<NeighborResult*>(h)->src.size() : -1;
}

void dm_neighbor_copy(void* h, int64_t* src, int64_t* dst, int32_t* offsets,
                      double* distances, uint8_t* bond_mask, double* wrapped,
                      int64_t* shift) {
  auto* r = static_cast<NeighborResult*>(h);
  std::memcpy(src, r->src.data(), r->src.size() * sizeof(int64_t));
  std::memcpy(dst, r->dst.data(), r->dst.size() * sizeof(int64_t));
  std::memcpy(offsets, r->offsets.data(), r->offsets.size() * sizeof(int32_t));
  std::memcpy(distances, r->distances.data(), r->distances.size() * sizeof(double));
  std::memcpy(bond_mask, r->bond_mask.data(), r->bond_mask.size() * sizeof(uint8_t));
  std::memcpy(wrapped, r->wrapped.data(), r->wrapped.size() * sizeof(double));
  std::memcpy(shift, r->shift.data(), r->shift.size() * sizeof(int64_t));
}

void dm_neighbor_free(void* h) { delete static_cast<NeighborResult*>(h); }

}  // extern "C"
