from .python_ref import NeighborList, neighbor_list_brute, neighbor_list_numpy
from .native import neighbor_list

__all__ = [
    "NeighborList",
    "neighbor_list",
    "neighbor_list_brute",
    "neighbor_list_numpy",
]
