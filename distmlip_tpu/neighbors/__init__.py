from .python_ref import NeighborList, neighbor_list_brute, neighbor_list_numpy
from .native import neighbor_list
from .device import (CellListStatic, PackedStatic, build_cell_list_spec,
                     build_packed_spec, cell_list_neighbors,
                     device_neighbor_list, device_packed_neighbor_list,
                     device_rebuild_enabled, packed_neighbors)

__all__ = [
    "NeighborList",
    "neighbor_list",
    "neighbor_list_brute",
    "neighbor_list_numpy",
    "CellListStatic",
    "PackedStatic",
    "build_cell_list_spec",
    "build_packed_spec",
    "cell_list_neighbors",
    "device_neighbor_list",
    "device_packed_neighbor_list",
    "device_rebuild_enabled",
    "packed_neighbors",
]
