"""ctypes bindings for the native (C++/OpenMP) neighbor search + partitioner.

The shared library is built on demand from ``src/`` with ``make`` (g++,
-O3 -march=native -fopenmp). If the build or load fails, callers fall back
to the numpy implementations — same results, slower host path.

No pybind11 in this image, so the ABI is a plain C handle API consumed via
ctypes (see src/neighbor.cpp).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from .python_ref import NeighborList, neighbor_list_numpy

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
# DISTMLIP_TPU_NATIVE_LIB points the loader at an alternate build — the
# sanitizer lane (make asan / make tsan in src/, see the Makefile) loads
# _native_asan.so/_native_tsan.so through this
_LIB_PATH = os.environ.get(
    "DISTMLIP_TPU_NATIVE_LIB",
    os.path.join(os.path.dirname(__file__), "_native.so"))
_lock = threading.Lock()
_lib = None
_load_failed = False


def _build_and_load():
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            srcs = [os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR) if f.endswith(".cpp")]
            if "DISTMLIP_TPU_NATIVE_LIB" not in os.environ and (
                not os.path.exists(_LIB_PATH) or any(
                    os.path.getmtime(s) > os.path.getmtime(_LIB_PATH)
                    for s in srcs)
            ):
                subprocess.run(
                    ["make", "-s", "-C", _SRC_DIR],
                    check=True,
                    capture_output=True,
                    text=True,
                )
            lib = ctypes.CDLL(_LIB_PATH)
            lib.dm_neighbor_build.restype = ctypes.c_void_p
            lib.dm_neighbor_build.argtypes = [
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_double,
                ctypes.c_double,
                ctypes.c_double,
                ctypes.c_int,
            ]
            lib.dm_neighbor_num_edges.restype = ctypes.c_int64
            lib.dm_neighbor_num_edges.argtypes = [ctypes.c_void_p]
            lib.dm_neighbor_copy.restype = None
            lib.dm_neighbor_copy.argtypes = [ctypes.c_void_p] + [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.dm_neighbor_free.restype = None
            lib.dm_neighbor_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _load_failed = True
            _lib = None
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


def resolve_num_threads() -> int:
    """Single source of truth for the host-thread knob (0 = all cores)."""
    return int(os.environ.get("DISTMLIP_TPU_NUM_THREADS",
                              os.environ.get("DISTMLIP_NUM_THREADS", 0)))


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def neighbor_list(
    cart, lattice, pbc, r: float, bond_r: float = 0.0, tol: float = 1e-8,
    num_threads: int | None = None,
) -> NeighborList:
    """Periodic neighbor search — native fast path with numpy fallback.

    Threads resolve as: explicit arg > DISTMLIP_TPU_NUM_THREADS >
    DISTMLIP_NUM_THREADS > 0 (= OpenMP default, all cores). The env-var knob
    mirrors the reference (pes.py:65-66).
    """
    lib = _build_and_load()
    if lib is None or np.asarray(cart).shape[0] == 0:
        return neighbor_list_numpy(cart, lattice, pbc, r, bond_r, tol)
    if num_threads is None:
        num_threads = resolve_num_threads()
    cart = np.ascontiguousarray(cart, dtype=np.float64)
    lattice = np.ascontiguousarray(lattice, dtype=np.float64)
    pbc_arr = np.ascontiguousarray(np.asarray(pbc, dtype=np.int64))
    n = cart.shape[0]
    handle = lib.dm_neighbor_build(
        n, _ptr(cart, ctypes.c_double), _ptr(lattice, ctypes.c_double),
        _ptr(pbc_arr, ctypes.c_int64), float(r), float(bond_r), float(tol),
        int(num_threads),
    )
    if not handle:
        raise RuntimeError("native neighbor search failed (empty system or r<=0)")
    try:
        ne = lib.dm_neighbor_num_edges(handle)
        src = np.empty(ne, dtype=np.int64)
        dst = np.empty(ne, dtype=np.int64)
        offsets = np.empty((ne, 3), dtype=np.int32)
        distances = np.empty(ne, dtype=np.float64)
        bond_mask = np.empty(ne, dtype=np.uint8)
        wrapped = np.empty((n, 3), dtype=np.float64)
        shift = np.empty((n, 3), dtype=np.int64)
        lib.dm_neighbor_copy(
            handle, _ptr(src, ctypes.c_int64), _ptr(dst, ctypes.c_int64),
            _ptr(offsets, ctypes.c_int32), _ptr(distances, ctypes.c_double),
            _ptr(bond_mask, ctypes.c_uint8), _ptr(wrapped, ctypes.c_double),
            _ptr(shift, ctypes.c_int64),
        )
    finally:
        lib.dm_neighbor_free(handle)
    return NeighborList(src, dst, offsets, distances, bond_mask.astype(bool), wrapped, shift)


# ---------------------------------------------------------------------------
# Native partitioner bindings (partition.cpp)
# ---------------------------------------------------------------------------

def _partition_symbols(lib):
    if getattr(lib, "_partition_ready", False):
        return lib
    lib.dm_partition_build.restype = ctypes.c_void_p
    lib.dm_partition_build.argtypes = [
        ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
    ]
    lib.dm_partition_err.restype = ctypes.c_int
    lib.dm_partition_err.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.dm_partition_sizes.restype = None
    lib.dm_partition_sizes.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.POINTER(ctypes.c_int64)]
    lib.dm_partition_copy.restype = None
    lib.dm_partition_copy.argtypes = [ctypes.c_void_p, ctypes.c_int64] + [
        ctypes.POINTER(ctypes.c_int64)
    ] * 12
    lib.dm_partition_free.restype = None
    lib.dm_partition_free.argtypes = [ctypes.c_void_p]
    lib._partition_ready = True
    return lib


def native_partition(src, dst, frac_axis, walls, num_partitions, bond_mask,
                     use_bond_graph, num_threads=None):
    """Run the native partitioner; returns per-partition dict arrays.

    Returns None if the native library is unavailable. Raises RuntimeError
    with the offending node on a multi-destination border node (same
    condition the numpy oracle raises PartitionError for).
    """
    lib = _build_and_load()
    if lib is None:
        return None
    _partition_symbols(lib)
    if num_threads is None:
        num_threads = resolve_num_threads()
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    frac_axis = np.ascontiguousarray(frac_axis, dtype=np.float64)
    walls = np.ascontiguousarray(walls, dtype=np.float64)
    bm = np.ascontiguousarray(
        bond_mask if bond_mask is not None else np.zeros(len(src), bool),
        dtype=np.uint8,
    )
    n, ne, P = len(frac_axis), len(src), int(num_partitions)
    h = lib.dm_partition_build(
        n, ne, _ptr(src, ctypes.c_int64), _ptr(dst, ctypes.c_int64),
        _ptr(frac_axis, ctypes.c_double), _ptr(walls, ctypes.c_double),
        P, _ptr(bm, ctypes.c_uint8), int(bool(use_bond_graph)), int(num_threads),
    )
    try:
        err_node = ctypes.c_int64(-1)
        err = lib.dm_partition_err(h, ctypes.byref(err_node))
        if err != 0:
            raise RuntimeError(
                f"native partitioner: node {err_node.value} reaches multiple "
                f"partitions (code {err}); reduce num_partitions"
            )
        out = []
        null = ctypes.POINTER(ctypes.c_int64)()
        for p in range(P):
            sizes = np.zeros(5, dtype=np.int64)
            lib.dm_partition_sizes(h, p, _ptr(sizes, ctypes.c_int64))
            nn, nee, nb, nl, nm = map(int, sizes)
            d = {
                "global_ids": np.empty(nn, np.int64),
                "node_markers": np.empty(2 * P + 2, np.int64),
                "edge_ids": np.empty(nee, np.int64),
                "src_local": np.empty(nee, np.int64),
                "dst_local": np.empty(nee, np.int64),
            }
            if use_bond_graph:
                d.update(
                    bond_markers=np.empty(2 * P + 2, np.int64),
                    bond_global_edge=np.empty(nb, np.int64),
                    line_src=np.empty(nl, np.int64),
                    line_dst=np.empty(nl, np.int64),
                    line_center=np.empty(nl, np.int64),
                    bm_edge=np.empty(nm, np.int64),
                    bm_bond=np.empty(nm, np.int64),
                )
            args = [
                _ptr(d["global_ids"], ctypes.c_int64),
                _ptr(d["node_markers"], ctypes.c_int64),
                _ptr(d["edge_ids"], ctypes.c_int64),
                _ptr(d["src_local"], ctypes.c_int64),
                _ptr(d["dst_local"], ctypes.c_int64),
            ]
            if use_bond_graph:
                args += [
                    _ptr(d["bond_markers"], ctypes.c_int64),
                    _ptr(d["bond_global_edge"], ctypes.c_int64),
                    _ptr(d["line_src"], ctypes.c_int64),
                    _ptr(d["line_dst"], ctypes.c_int64),
                    _ptr(d["line_center"], ctypes.c_int64),
                    _ptr(d["bm_edge"], ctypes.c_int64),
                    _ptr(d["bm_bond"], ctypes.c_int64),
                ]
            else:
                args += [null] * 7
            lib.dm_partition_copy(h, p, *args)
            out.append(d)
        return out
    finally:
        lib.dm_partition_free(h)
