"""Pure-numpy periodic neighbor search.

Two implementations:

- ``neighbor_list_brute``: O(N^2 x images) ground truth used by the test
  suite to validate both the vectorized numpy path and the native C++ path.
- ``neighbor_list_numpy``: vectorized linked-cell search, the fallback when
  the native library is unavailable.

Semantics match the reference's FPIS layer (behavioral spec, not a port —
reference fpis.c:418-856):
  - dual cutoff: one pass emits all edges within ``r`` and flags the subset
    within ``bond_r`` (fpis.c:435-438);
  - image offsets are reported relative to the *unwrapped* input coordinates
    (fpis.c:838-840): neighbor position = cart[dst] + offsets @ lattice;
  - self pairs (distance < 1e-8) are excluded; an atom CAN neighbor its own
    periodic image (cell smaller than cutoff);
  - an edge (i, j) means j is within ``r + tol`` of i; both directions are
    emitted as separate directed edges.
"""

from __future__ import annotations

import numpy as np

from .. import geometry

NUMERICAL_TOL = 1e-8


class NeighborList:
    """Result of a neighbor search.

    Attributes
    ----------
    src, dst : (E,) int64 — directed edges (center, neighbor).
    offsets : (E, 3) int32 — periodic image of ``dst`` relative to the
        unwrapped input coordinates.
    distances : (E,) float64.
    bond_mask : (E,) bool — edges also within the secondary cutoff
        ``bond_r`` (the three-body / line-graph cutoff).
    wrapped_cart : (N, 3) float64 — input positions wrapped into the cell.
    shift : (N, 3) int64 — lattice translations removed by wrapping.
    """

    def __init__(self, src, dst, offsets, distances, bond_mask, wrapped_cart, shift):
        self.src = src
        self.dst = dst
        self.offsets = offsets
        self.distances = distances
        self.bond_mask = bond_mask
        self.wrapped_cart = wrapped_cart
        self.shift = shift

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def sorted_copy(self) -> "NeighborList":
        """Canonical ordering (src, dst, offsets) for comparisons."""
        key = np.lexsort(
            (self.offsets[:, 2], self.offsets[:, 1], self.offsets[:, 0], self.dst, self.src)
        )
        return NeighborList(
            self.src[key], self.dst[key], self.offsets[key], self.distances[key],
            self.bond_mask[key], self.wrapped_cart, self.shift,
        )


def _image_ranges(lattice: np.ndarray, pbc, r: float) -> np.ndarray:
    d = geometry.plane_spacings(lattice)
    pbc_mask = np.asarray(pbc, dtype=bool)
    n = np.where(pbc_mask, np.floor(r / d).astype(np.int64) + 1, 0)
    return n


def neighbor_list_brute(cart, lattice, pbc, r, bond_r=0.0, tol=1e-8) -> NeighborList:
    """O(N^2) reference implementation. Use only for tests / tiny systems."""
    cart = np.asarray(cart, dtype=np.float64)
    lattice = np.asarray(lattice, dtype=np.float64)
    n = cart.shape[0]
    wrapped, shift = geometry.wrap_positions(cart, lattice, pbc)
    pbc_mask = np.asarray(pbc, dtype=bool)
    nimg = _image_ranges(lattice, pbc, r) + np.where(pbc_mask, 1, 0)  # margin on pbc axes
    ax = [np.arange(-k, k + 1) for k in nimg]
    imgs = np.stack(np.meshgrid(*ax, indexing="ij"), axis=-1).reshape(-1, 3)
    img_cart = imgs @ lattice  # (M, 3)

    src_l, dst_l, off_l, dist_l = [], [], [], []
    for i in range(n):
        # candidates: wrapped[j] + img - wrapped[i]
        diff = wrapped[None, :, :] + img_cart[:, None, :] - wrapped[i]  # (M, N, 3)
        dists = np.linalg.norm(diff, axis=-1)
        keep = (dists < r + tol) & (dists > NUMERICAL_TOL)
        mi, ji = np.nonzero(keep)
        src_l.append(np.full(ji.shape, i, dtype=np.int64))
        dst_l.append(ji.astype(np.int64))
        off_l.append(imgs[mi] + shift[i][None, :] - shift[ji])
        dist_l.append(dists[mi, ji])
    src = np.concatenate(src_l)
    dst = np.concatenate(dst_l)
    offsets = np.concatenate(off_l).astype(np.int32)
    distances = np.concatenate(dist_l)
    bond_mask = distances < bond_r + tol if bond_r > 0 else np.zeros_like(distances, bool)
    return NeighborList(src, dst, offsets, distances, bond_mask, wrapped, shift).sorted_copy()


def neighbor_list_numpy(cart, lattice, pbc, r, bond_r=0.0, tol=1e-8) -> NeighborList:
    """Vectorized linked-cell periodic neighbor search (numpy fallback)."""
    cart = np.asarray(cart, dtype=np.float64)
    lattice = np.asarray(lattice, dtype=np.float64)
    n = cart.shape[0]
    if n == 0:
        z = np.zeros(0, dtype=np.int64)
        return NeighborList(z, z, np.zeros((0, 3), np.int32), np.zeros(0), np.zeros(0, bool),
                            cart.copy(), np.zeros((0, 3), np.int64))
    wrapped, shift = geometry.wrap_positions(cart, lattice, pbc)
    frac = geometry.cart_to_frac(wrapped, lattice)

    # --- expand periodic images covering a margin of r around the cell ---
    # non-periodic axes are never wrapped, so atoms may legally sit at any
    # fractional coordinate there: no margin culling on those axes
    d = geometry.plane_spacings(lattice)
    pbc_mask = np.asarray(pbc, dtype=bool)
    margins = np.where(pbc_mask, r / d + 1e-12, np.inf)
    nimg = _image_ranges(lattice, pbc, r)
    ax = [np.arange(-k, k + 1) for k in nimg]
    imgs = np.stack(np.meshgrid(*ax, indexing="ij"), axis=-1).reshape(-1, 3)  # (M,3)
    efrac = frac[None, :, :] + imgs[:, None, :].astype(np.float64)  # (M,N,3)
    inside = np.all(
        (efrac >= -margins[None, None, :]) & (efrac <= 1.0 + margins[None, None, :]), axis=-1
    )
    m_idx, a_idx = np.nonzero(inside)
    pts = efrac[m_idx, a_idx] @ lattice  # (K,3) expanded cartesian
    pt_atom = a_idx.astype(np.int64)
    pt_img = imgs[m_idx]  # (K,3)

    # --- linked cells over the expanded points ---
    edge = max(r, 0.1)
    lo = pts.min(axis=0) - 1e-9
    cell_idx = np.floor((pts - lo) / edge).astype(np.int64)
    ncell = cell_idx.max(axis=0) + 1
    flat = (cell_idx[:, 0] * ncell[1] + cell_idx[:, 1]) * ncell[2] + cell_idx[:, 2]
    order = np.argsort(flat, kind="stable")
    flat_sorted = flat[order]
    # cell start offsets via searchsorted
    ncell_flat = int(ncell[0] * ncell[1] * ncell[2])
    starts = np.searchsorted(flat_sorted, np.arange(ncell_flat + 1))

    # cells of the centers (wrapped atoms are a subset of expanded points with img=0)
    c_cell = np.floor((wrapped - lo) / edge).astype(np.int64)

    src_l, dst_l, off_l, dist_l = [], [], [], []
    # group centers by cell to batch candidate gathers
    c_flat = (c_cell[:, 0] * ncell[1] + c_cell[:, 1]) * ncell[2] + c_cell[:, 2]
    uniq, inv = np.unique(c_flat, return_inverse=True)
    nbr_sh = np.stack(
        np.meshgrid([-1, 0, 1], [-1, 0, 1], [-1, 0, 1], indexing="ij"), axis=-1
    ).reshape(-1, 3)
    for u_i, cf in enumerate(uniq):
        centers = np.nonzero(inv == u_i)[0]
        cc = c_cell[centers[0]]
        cand = []
        for sh in nbr_sh:
            cx = cc + sh
            if np.any(cx < 0) or np.any(cx >= ncell):
                continue
            f = (cx[0] * ncell[1] + cx[1]) * ncell[2] + cx[2]
            s, e = starts[f], starts[f + 1]
            if e > s:
                cand.append(order[s:e])
        if not cand:
            continue
        cand = np.concatenate(cand)
        diff = pts[cand][None, :, :] - wrapped[centers][:, None, :]  # (C, K, 3)
        dists = np.linalg.norm(diff, axis=-1)
        keep = (dists < r + tol) & (dists > NUMERICAL_TOL)
        ci, ki = np.nonzero(keep)
        src_l.append(centers[ci])
        dst_l.append(pt_atom[cand[ki]])
        off_l.append(pt_img[cand[ki]] + shift[centers[ci]] - shift[pt_atom[cand[ki]]])
        dist_l.append(dists[ci, ki])

    src = np.concatenate(src_l) if src_l else np.zeros(0, np.int64)
    dst = np.concatenate(dst_l) if dst_l else np.zeros(0, np.int64)
    offsets = (np.concatenate(off_l) if off_l else np.zeros((0, 3))).astype(np.int32)
    distances = np.concatenate(dist_l) if dist_l else np.zeros(0)
    bond_mask = distances < bond_r + tol if bond_r > 0 else np.zeros_like(distances, bool)
    return NeighborList(src, dst, offsets, distances, bond_mask, wrapped, shift).sorted_copy()
