"""Device-resident neighbor search: on-device cell lists under static caps.

The host FPIS pipeline (``neighbors/native.py`` -> ``partition``) is exact
but synchronous: every Verlet-skin invalidation stops the device, syncs
positions to the host, rebuilds the graph in C/NumPy and re-uploads the
packed arrays. This module removes that last host-bound segment of the MD/
relax hot path (TorchSim's observation, arXiv:2508.06628; same conclusion
for inference kernels in arXiv:2504.16068): the neighbor graph is rebuilt
ENTIRELY on the accelerator, under fixed, sticky capacities, so the rebuild
can live inside a jitted ``lax.while_loop`` and a trajectory never leaves
the chip.

Two kernels share the emission/compaction contract:

- ``cell_list_neighbors`` — single-structure linked-cell search. Atoms are
  binned into a static cell grid (an on-device ``argsort`` + ``searchsorted``
  builds the (ncell, cell_cap) table); candidate pairs come from a static
  stencil of neighboring cells with periodic wrap counts supplying the
  image offsets. The stencil generalizes the classic 27-cell case: when the
  box is smaller than the cutoff the per-axis reach grows past one wrap, so
  multi-image pairs (an atom neighboring its own periodic images) are
  enumerated exactly — parity with ``neighbor_list_numpy`` is pair-set
  EXACT, not approximate (tests/test_device_neighbors.py).
- ``packed_neighbors`` — block-diagonal multi-structure search for graphs
  built by ``partition.pack_structures``. The batched regime is many SMALL
  structures, so each block runs a dense all-pairs x images check (vmapped
  over the batch, trivially sized) and image offsets are baked to Cartesian
  with each structure's own cell, matching the packed layout.

Emission contract (identical to the host builders, so the arrays can be
swapped into an existing ``PartitionedGraph`` without re-tracing):

- edges are enumerated CENTER-major, and the center plays the ``dst`` role
  (owner-computes: messages aggregate onto dst), so the compacted
  ``edge_dst`` is globally nondecreasing — ``indices_are_sorted=True``
  segment sums stay on the fast path;
- compaction is a cumsum counting sort (order-preserving) into the fixed
  ``e_cap`` slots; a count past ``e_cap`` (or a cell past ``cell_cap``)
  raises the OVERFLOW flag instead of silently dropping pairs — callers
  fall back to the host rebuild with grown caps;
- offsets are integer periodic-image vectors relative to the UNWRAPPED
  input frame (``neighbor position = positions[src] + off @ lattice`` seen
  from the dst row), exactly the ``python_ref`` convention.

Capacities are static trace constants: same caps => same shapes => zero
recompiles across rebuilds. ``DISTMLIP_DEVICE_REBUILD=0`` disables every
device-rebuild consumer at once (forcing the host FPIS path).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import numpy as np

from .. import geometry
from .python_ref import NUMERICAL_TOL, _image_ranges


def device_rebuild_enabled() -> bool:
    """Process-wide kill switch: DISTMLIP_DEVICE_REBUILD=0 forces the host
    FPIS rebuild everywhere (DeviceMD, DistPotential, BatchedPotential)."""
    return os.environ.get("DISTMLIP_DEVICE_REBUILD", "1") != "0"


# ---------------------------------------------------------------------------
# Single-structure cell list
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CellListStatic:
    """Hashable static half of a cell-list spec (jit static argument).

    Every field feeds a traced shape or a trace-time constant; two specs
    with equal statics (and equal-shaped arrays) share one executable.
    """

    grid: tuple          # (g0, g1, g2) cells per axis
    n_stencil: int       # stencil offsets (shape of the arrays' stencil)
    cell_cap: int        # max atoms per cell before overflow
    n_atoms: int         # real atoms (rows [0, n_atoms) of the padded array)
    n_cap: int           # padded node rows
    e_cap: int           # padded edge slots
    pbc: tuple           # (bool, bool, bool)
    r: float             # build cutoff (cutoff + skin)

    @property
    def ncell(self) -> int:
        return int(self.grid[0] * self.grid[1] * self.grid[2])


def estimate_cell_capacity(occupancy: int, floor: int = 4,
                           slack: float = 1.5) -> int:
    """Sticky-style cell capacity from an observed max occupancy: slack
    headroom so atoms migrating between cells mid-trajectory don't
    immediately overflow, floored so near-empty builds keep room."""
    return max(int(math.ceil(occupancy * slack)) + 1, int(floor))


def grow_caps_after_overflow(caps, edges_needed: int, e_cap: int,
                             cell_cap: int, cell_cap_floor: int) -> int:
    """Shared overflow-growth policy for every device-rebuild consumer.

    The kernel reports the TRUE edge need even past ``e_cap``, so an edge
    bust grows the sticky edge bucket directly; otherwise the bust was the
    cell table (whose edge count is undercounted, so the two cases are
    mutually exclusive as observed) and the cell capacity doubles. Returns
    the (possibly grown) cell-cap floor; ``caps`` is grown in place.
    """
    if edges_needed > e_cap:
        caps.get("edges", int(edges_needed))
        return int(cell_cap_floor)
    return max(int(cell_cap_floor), 2 * int(cell_cap))


def build_cell_list_spec(
    lattice,
    pbc,
    r: float,
    n_atoms: int,
    n_cap: int,
    e_cap: int,
    positions=None,
    cell_cap: int | None = None,
    min_cell_cap: int = 4,
    dtype=np.float32,
):
    """Host-side spec construction: grid dims, stencil, capacities.

    Grid: ``g_a = max(1, floor(d_a / r))`` cells along each PERIODIC axis
    (``d_a`` = plane spacing, skew-safe), one cell along non-periodic axes
    (atoms are unbounded there — the distance filter does the work). The
    stencil reach per periodic axis is ``floor(r / w_a) + 1`` cells
    (``w_a = d_a / g_a``): two points whose extended cells differ by D
    along axis a are at least ``(D - 1) * w_a`` apart, so the reach covers
    every pair within ``r`` — including multi-wrap (multi-image) pairs when
    the box is smaller than the cutoff.

    ``cell_cap`` defaults to the observed max occupancy of ``positions``
    (plus slack) — pass the previous spec's grown value after an overflow.
    Returns ``(static, arrays)`` for the jitted kernel; ``arrays`` holds the
    lattice, its inverse, and the stencil as plain numpy (device_put'd on
    first use).
    """
    lattice = np.asarray(lattice, dtype=np.float64)
    pbc_mask = np.asarray(pbc, dtype=bool)
    d = geometry.plane_spacings(lattice)
    grid = np.where(pbc_mask, np.maximum(
        1, np.floor(d / max(r, 1e-6)).astype(np.int64)), 1)
    w = d / grid
    reach = np.where(pbc_mask,
                     np.floor((r + NUMERICAL_TOL) / w).astype(np.int64) + 1,
                     0)
    ax = [np.arange(-k, k + 1) for k in reach]
    stencil = np.stack(
        np.meshgrid(*ax, indexing="ij"), axis=-1).reshape(-1, 3)
    if cell_cap is None:
        occ = 0
        if positions is not None and n_atoms > 0:
            wrapped, _ = geometry.wrap_positions(
                np.asarray(positions, dtype=np.float64)[:n_atoms],
                lattice, pbc_mask)
            frac = geometry.cart_to_frac(wrapped, lattice)
            c = np.clip((frac * grid).astype(np.int64), 0, grid - 1)
            flat = (c[:, 0] * grid[1] + c[:, 1]) * grid[2] + c[:, 2]
            occ = int(np.bincount(flat).max())
        else:
            occ = n_atoms
        cell_cap = estimate_cell_capacity(occ, floor=min_cell_cap)
    static = CellListStatic(
        grid=tuple(int(g) for g in grid),
        n_stencil=int(len(stencil)),
        cell_cap=int(cell_cap),
        n_atoms=int(n_atoms),
        n_cap=int(n_cap),
        e_cap=int(e_cap),
        pbc=tuple(bool(b) for b in pbc_mask),
        r=float(r),
    )
    arrays = {
        "lattice": lattice.astype(dtype),
        "inv_lattice": np.linalg.inv(lattice).astype(dtype),
        "stencil": stencil.astype(np.int32),
    }
    return static, arrays


def _wrap_device(positions, inv_lattice, pbc_mask):
    """(frac, shift, wrapped_frac) with wrapping only on periodic axes —
    the in-jit analogue of ``geometry.wrap_positions``."""
    import jax.numpy as jnp

    frac = positions @ inv_lattice
    shift = jnp.where(pbc_mask, jnp.floor(frac), 0.0)
    return frac, shift.astype(jnp.int32), frac - shift


def _compact_edges(src, dst, off, valid, e_cap: int):
    """Order-preserving cumsum compaction of flat candidate arrays into
    ``e_cap`` slots. Returns (src, dst, off, n_edges, overflow_edges);
    entries past ``e_cap`` are dropped and flagged, never silently lost
    within the count."""
    import jax.numpy as jnp

    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    n_edges = jnp.sum(valid.astype(jnp.int32))
    slot = jnp.where(valid & (pos < e_cap), pos, e_cap)
    src_o = jnp.zeros((e_cap,), jnp.int32).at[slot].set(
        src.astype(jnp.int32), mode="drop")
    dst_o = jnp.zeros((e_cap,), jnp.int32).at[slot].set(
        dst.astype(jnp.int32), mode="drop")
    off_o = jnp.zeros((e_cap, 3), off.dtype).at[slot].set(off, mode="drop")
    return src_o, dst_o, off_o, n_edges, n_edges > e_cap


def cell_list_neighbors(static: CellListStatic, arrays, positions):
    """Traceable single-structure neighbor search (call inside jit/scan/
    while_loop; use :func:`device_neighbor_list` from host code).

    ``positions``: (n_cap, 3) UNWRAPPED input-frame coordinates (padded
    rows ignored). Returns ``(src, dst, off, n_edges, overflow)`` with
    (e_cap,)-shaped edge arrays: ``dst`` is the center atom and is
    nondecreasing over the real prefix; ``off`` is the int32 image offset
    of ``src`` relative to the input frame; ``overflow`` flags a cell or
    edge capacity bust (results must then be discarded by the caller).
    """
    import jax.numpy as jnp

    st = static
    dtype = positions.dtype
    g = jnp.asarray(st.grid, dtype=jnp.int32)
    gf = jnp.asarray(st.grid, dtype=dtype)
    pbc_mask = jnp.asarray(st.pbc)
    lat = jnp.asarray(arrays["lattice"], dtype=dtype)
    inv = jnp.asarray(arrays["inv_lattice"], dtype=dtype)
    stencil = jnp.asarray(arrays["stencil"], dtype=jnp.int32)
    ncell, cap = st.ncell, st.cell_cap
    valid_atom = jnp.arange(st.n_cap) < st.n_atoms

    _, shift, w = _wrap_device(positions, inv, pbc_mask)
    c = jnp.clip(jnp.floor(w * gf).astype(jnp.int32), 0, g - 1)
    flat = (c[:, 0] * g[1] + c[:, 1]) * g[2] + c[:, 2]
    ids = jnp.where(valid_atom, flat, ncell)

    # --- bin via on-device sort: (ncell, cap) table of atom indices ---
    order = jnp.argsort(ids)
    sorted_ids = ids[order]
    starts = jnp.searchsorted(sorted_ids, jnp.arange(ncell + 1))
    rank = jnp.arange(st.n_cap, dtype=jnp.int32) - starts[sorted_ids].astype(
        jnp.int32)
    in_cell = sorted_ids < ncell
    overflow_cells = jnp.any(in_cell & (rank >= cap))
    slot = jnp.where(in_cell & (rank < cap),
                     sorted_ids.astype(jnp.int32) * cap + rank,
                     ncell * cap)
    table = jnp.full((ncell * cap,), st.n_cap, jnp.int32).at[slot].set(
        order.astype(jnp.int32), mode="drop").reshape(ncell, cap)

    # --- stencil enumeration: extended cells -> (neighbor cell, wrap) ---
    tc = c[:, None, :] + stencil[None, :, :]              # (n_cap, S, 3)
    wrap = jnp.floor_divide(tc, g)                        # image count
    cin = tc - wrap * g
    ok_st = jnp.all(pbc_mask | (wrap == 0), axis=-1)      # (n_cap, S)
    flat_t = (cin[..., 0] * g[1] + cin[..., 1]) * g[2] + cin[..., 2]
    cand = table[flat_t]                                  # (n_cap, S, cap)
    valid_j = cand < st.n_cap
    jc = jnp.minimum(cand, st.n_cap - 1)

    # --- distance filter against the center's wrapped position ---
    wpos = w @ lat                                        # (n_cap, 3)
    img_cart = wrap.astype(dtype) @ lat                   # (n_cap, S, 3)
    diff = wpos[jc] + img_cart[:, :, None, :] - wpos[:, None, None, :]
    d2 = jnp.sum(diff * diff, axis=-1)                    # (n_cap, S, cap)
    r2 = jnp.asarray((st.r + NUMERICAL_TOL) ** 2, dtype=dtype)
    tiny = jnp.asarray(NUMERICAL_TOL ** 2, dtype=dtype)
    valid = (valid_j & ok_st[:, :, None] & valid_atom[:, None, None]
             & (d2 < r2) & (d2 > tiny))

    # --- emit: center = dst (sorted by construction), neighbor = src ---
    # the ref edge (center j, neighbor c at image -wrap) has
    # off = -wrap + shift[src] - shift[dst] in the unwrapped input frame
    off = (-wrap[:, :, None, :] + shift[jc]
           - shift[:, None, None, :]).astype(jnp.int32)   # (n_cap,S,cap,3)
    dst = jnp.broadcast_to(
        jnp.arange(st.n_cap, dtype=jnp.int32)[:, None, None], valid.shape)
    src, dst, off, n_edges, overflow_edges = _compact_edges(
        cand.reshape(-1), dst.reshape(-1), off.reshape(-1, 3),
        valid.reshape(-1), st.e_cap)
    return src, dst, off, n_edges, overflow_cells | overflow_edges


_cell_list_jitted = None


def device_neighbor_list(static: CellListStatic, arrays, positions):
    """Jitted host entry for the single-structure kernel (tests, the
    rebuilds/sec microbench, DistPotential's refresh). One executable per
    distinct ``static`` + positions shape."""
    global _cell_list_jitted
    if _cell_list_jitted is None:
        import jax

        _cell_list_jitted = jax.jit(cell_list_neighbors, static_argnums=0)
    return _cell_list_jitted(static, _as_device_arrays(arrays), positions)


def _as_device_arrays(arrays):
    """Spec arrays as device arrays. jnp.asarray is a no-op for arrays
    already on device, so callers that convert once at spec-install time
    (the hot paths) pay nothing here on subsequent calls."""
    import jax.numpy as jnp

    return {k: jnp.asarray(v) for k, v in arrays.items()}


# ---------------------------------------------------------------------------
# Packed (block-diagonal) batch
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PackedStatic:
    """Static half of a packed-batch spec (jit static argument)."""

    n_struct: int        # real structures
    n_max: int           # max atoms over structures
    m_max: int           # max periodic images over structures
    n_cap: int           # packed node rows
    e_cap: int           # packed edge slots
    r: float             # build cutoff (cutoff + skin)


def build_packed_spec(
    cells,
    pbcs,
    n_atoms,
    node_offsets,
    r: float,
    n_cap: int,
    e_cap: int,
    dtype=np.float32,
):
    """Spec for refreshing a block-diagonally packed graph on device.

    Per-structure cells/pbc/image sets are padded to the batch maxima; the
    kernel is a dense all-pairs x images check per block (the packed regime
    is many SMALL structures — TorchSim batching, arXiv:2508.06628), so no
    cell table or cell capacity is involved. Returns ``(static, arrays)``.
    """
    B = len(n_atoms)
    n_max = int(max(int(n) for n in n_atoms))
    imgs_list = []
    for cell, pbc in zip(cells, pbcs):
        n = _image_ranges(np.asarray(cell, dtype=np.float64), pbc, r)
        ax = [np.arange(-k, k + 1) for k in n]
        imgs_list.append(np.stack(
            np.meshgrid(*ax, indexing="ij"), axis=-1).reshape(-1, 3))
    m_max = max(len(m) for m in imgs_list)
    imgs = np.zeros((B, m_max, 3), dtype=np.int32)
    img_mask = np.zeros((B, m_max), dtype=bool)
    for b, m in enumerate(imgs_list):
        imgs[b, : len(m)] = m
        img_mask[b, : len(m)] = True
    gather_idx = np.zeros((B, n_max), dtype=np.int32)
    atom_mask = np.zeros((B, n_max), dtype=bool)
    for b, n in enumerate(n_atoms):
        n = int(n)
        gather_idx[b, :n] = np.arange(n) + int(node_offsets[b])
        atom_mask[b, :n] = True
    cells_np = np.stack([np.asarray(c, dtype=np.float64) for c in cells])
    static = PackedStatic(
        n_struct=B, n_max=n_max, m_max=m_max,
        n_cap=int(n_cap), e_cap=int(e_cap), r=float(r),
    )
    arrays = {
        "gather_idx": gather_idx,
        "atom_mask": atom_mask,
        "cells": cells_np.astype(dtype),
        "inv_cells": np.stack(
            [np.linalg.inv(c) for c in cells_np]).astype(dtype),
        "pbc": np.stack([np.asarray(p, dtype=bool) for p in pbcs]),
        "imgs": imgs,
        "img_mask": img_mask,
    }
    return static, arrays


def packed_neighbors(static: PackedStatic, arrays, positions):
    """Traceable packed-batch neighbor search over a (n_cap, 3) packed
    position array (input frame). Returns ``(src, dst, off_cart, n_edges,
    overflow)``: packed-row edge indices, CARTESIAN offsets (each block
    baked with its own cell, matching ``pack_structures``), nondecreasing
    ``dst`` (blocks are enumerated in packing order, centers within)."""
    import jax.numpy as jnp

    st = static
    dtype = positions.dtype
    gi = jnp.asarray(arrays["gather_idx"])
    am = jnp.asarray(arrays["atom_mask"])
    cells = jnp.asarray(arrays["cells"], dtype=dtype)
    invs = jnp.asarray(arrays["inv_cells"], dtype=dtype)
    pbc = jnp.asarray(arrays["pbc"])
    imgs = jnp.asarray(arrays["imgs"])
    img_mask = jnp.asarray(arrays["img_mask"])

    p = positions[gi]                                     # (B, n_max, 3)
    frac = jnp.einsum("bki,bij->bkj", p, invs)
    shift = jnp.where(pbc[:, None, :], jnp.floor(frac), 0.0)
    w = frac - shift
    shift = shift.astype(jnp.int32)
    wc = jnp.einsum("bki,bij->bkj", w, cells)             # wrapped cartesian
    imgc = jnp.einsum("bmi,bij->bmj", imgs.astype(dtype), cells)

    # diff[b, k(center), j(neighbor), m] = wc[b,j] + imgc[b,m] - wc[b,k]
    diff = (wc[:, None, :, None, :] + imgc[:, None, None, :, :]
            - wc[:, :, None, None, :])
    d2 = jnp.sum(diff * diff, axis=-1)                    # (B, k, j, m)
    r2 = jnp.asarray((st.r + NUMERICAL_TOL) ** 2, dtype=dtype)
    tiny = jnp.asarray(NUMERICAL_TOL ** 2, dtype=dtype)
    valid = (am[:, :, None, None] & am[:, None, :, None]
             & img_mask[:, None, None, :] & (d2 < r2) & (d2 > tiny))

    off_int = (-imgs[:, None, None, :, :]
               + shift[:, None, :, None, :]
               - shift[:, :, None, None, :])              # (B, k, j, m, 3)
    off_cart = jnp.einsum("bkjmi,bin->bkjmn", off_int.astype(dtype), cells)
    src = jnp.broadcast_to(gi[:, None, :, None], valid.shape)
    dst = jnp.broadcast_to(gi[:, :, None, None], valid.shape)
    return _compact_edges(
        src.reshape(-1), dst.reshape(-1), off_cart.reshape(-1, 3),
        valid.reshape(-1), st.e_cap)


_packed_jitted = None


def device_packed_neighbor_list(static: PackedStatic, arrays, positions):
    """Jitted host entry for the packed kernel."""
    global _packed_jitted
    if _packed_jitted is None:
        import jax

        _packed_jitted = jax.jit(packed_neighbors, static_argnums=0)
    return _packed_jitted(static, _as_device_arrays(arrays), positions)
