"""Kernel dispatch: Pallas on TPU, pure XLA everywhere else.

Every fused-kernel call site in the codebase goes through this module,
never through :mod:`segment`/:mod:`so3` directly. The dispatcher owns

- **routing**: trace-time selection of the Pallas kernel vs the
  pure-XLA ops (``ops/segment.py`` semantics). Pallas runs on TPU
  backends, under ``DISTMLIP_KERNELS=interpret`` (interpreter-mode
  kernels — the chip-free test lane), or inside a
  :func:`force_kernel_mode` context; the ``DISTMLIP_KERNELS=0`` kill
  switch and per-object ``kernels=False`` force XLA. The decision is
  static per trace — both paths ship from ONE code path with no model
  forks.
- **autodiff**: ``pallas_call`` has no transpose rule, so each fused op
  carries a custom VJP. ``fused_segment_sum``'s backward is the sorted
  gather ``g[segment_ids] * mask``; ``fused_edge_aggregate``'s backward
  re-runs the per-edge compute in bounded chunks (a ``lax.scan``) so the
  backward pass ALSO never materializes the ``(E, width)`` message
  cotangent; ``fused_so2_conv``'s backward is the VJP of the XLA
  reference (its operand is already chunk-bounded by the model's edge
  scan). The transposed node-gathers emit unsorted scatter-adds — the
  audited grad-program exemption of the ``scatter_hints`` contract pass.
- **telemetry**: a trace-time counter (:func:`counting`) records how
  many aggregation call sites routed to Pallas vs XLA; the runtime's
  cached contract-audit trace snapshots it into ``StepRecord``'s
  ``kernel_mode``/``kernel_coverage`` fields.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.segment import masked_segment_sum
from .segment import pallas_edge_aggregate, pallas_segment_sum
from .so3 import packed_m_layout, so2_conv_pallas, so2_conv_reference

# node arrays larger than this are pre-gathered by XLA instead of riding
# VMEM into the kernel for the in-kernel gather
DEFAULT_VMEM_BUDGET = int(os.environ.get("DISTMLIP_KERNELS_VMEM",
                                         2 * 1024 * 1024))
# backward-pass edge chunk (bounds the message-cotangent working set)
DEFAULT_BWD_CHUNK = int(os.environ.get("DISTMLIP_KERNELS_BWD_CHUNK", "32768"))

_MODES = ("pallas", "interpret", "xla")
_local = threading.local()


@dataclass
class KernelCounter:
    """Trace-time tally of dispatch decisions (edge aggregations only)."""

    pallas: int = 0
    xla: int = 0

    @property
    def total(self) -> int:
        return self.pallas + self.xla

    @property
    def coverage(self) -> float:
        return self.pallas / self.total if self.total else 0.0

    @property
    def mode(self) -> str:
        if self.total == 0:
            return ""
        return "pallas" if self.pallas > 0 else "xla"


@dataclass
class Gather:
    """A deferred node-row gather input to :func:`fused_edge_aggregate`.

    ``node`` is an (N, ...) array, ``idx`` the (E,) per-edge row indices.
    On the Pallas path small node arrays ride VMEM whole and the gather
    happens INSIDE the kernel; oversized ones (and the XLA fallback)
    pre-gather with a plain XLA gather.
    """

    node: Any
    idx: Any
    # populated by dispatch: node flattened trailing shape restored in rows
    trailing: tuple = field(default_factory=tuple)


def force_kernel_mode(mode: str | None):
    """Context manager pinning the dispatch decision for the current
    thread: ``"pallas" | "interpret" | "xla" | None`` (None restores the
    env/backend default). Used by the contract checker's ``--kernels``
    flag and the parity tests."""

    @contextmanager
    def ctx():
        if mode is not None and mode not in _MODES:
            raise ValueError(f"mode={mode!r}: expected one of {_MODES}")
        old = getattr(_local, "forced", None)
        _local.forced = mode
        try:
            yield
        finally:
            _local.forced = old

    return ctx()


@contextmanager
def counting():
    """Collect this thread's dispatch decisions into a fresh counter
    (nested uses shadow the outer counter)."""
    old = getattr(_local, "counter", None)
    c = KernelCounter()
    _local.counter = c
    try:
        yield c
    finally:
        _local.counter = old


def _count(used_pallas: bool) -> None:
    c = getattr(_local, "counter", None)
    if c is not None:
        if used_pallas:
            c.pallas += 1
        else:
            c.xla += 1


def resolve_kernel_mode(kernels=None) -> str:
    """Static (trace-time) routing decision.

    Priority: :func:`force_kernel_mode` context > per-object ``kernels``
    (``False`` -> xla, ``"interpret"``/``"pallas"``/``"xla"`` verbatim)
    > ``DISTMLIP_KERNELS`` env (``0``/``off`` kill switch, ``interpret``,
    ``1``/``on``) > backend default (pallas iff the default backend is
    TPU). ``kernels=None``/``True`` both mean "backend default" — True
    cannot force a compiled Pallas kernel onto a CPU host.
    """
    forced = getattr(_local, "forced", None)
    if forced is not None:
        return forced
    if kernels is False:
        return "xla"
    if isinstance(kernels, str):
        if kernels not in _MODES:
            raise ValueError(f"kernels={kernels!r}: expected bool, None or "
                             f"one of {_MODES}")
        return kernels
    env = os.environ.get("DISTMLIP_KERNELS", "auto").strip().lower()
    if env in ("0", "off", "false", "xla"):
        return "xla"
    if env == "interpret":
        return "interpret"
    if env in ("1", "on", "force", "pallas"):
        return "pallas"
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - no backend yet: fall back to XLA
        backend = "cpu"
    return "pallas" if backend == "tpu" else "xla"


def _mask_mul(rows, mask):
    if mask is None:
        return rows
    m = mask.astype(rows.dtype)
    return rows * m.reshape(m.shape + (1,) * (rows.ndim - m.ndim))


def _int_zero(x):
    """float0 cotangent for an integer/bool primal (custom_vjp contract)."""
    import numpy as np

    return np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)


# ---------------------------------------------------------------------------
# fused segment sum
# ---------------------------------------------------------------------------

def fused_segment_sum(data, segment_ids, num_segments: int, mask=None,
                      indices_are_sorted: bool = False, kernels=None):
    """Dispatching drop-in for ``masked_segment_sum``.

    Routes to the dst-tiled Pallas kernel when the layout contract holds
    (``indices_are_sorted=True`` — the dst-tile slicing depends on it)
    and the mode resolves to Pallas; identical masking/padding semantics
    on both paths, custom VJP on the kernel path.
    """
    mode = resolve_kernel_mode(kernels)
    # float (inexact) masks would need a real mask cotangent (the bwd
    # returns float0) — all repo masks are boolean; float masks take the
    # XLA path where plain AD handles them
    float_mask = (mask is not None
                  and jnp.issubdtype(jnp.result_type(mask), jnp.inexact))
    use = (mode != "xla" and indices_are_sorted and not float_mask
           and data.shape[0] > 0 and num_segments > 0)
    _count(use)
    if not use:
        return masked_segment_sum(data, segment_ids, num_segments, mask,
                                  indices_are_sorted=indices_are_sorted)
    interpret = mode == "interpret"
    # every traced operand is an EXPLICIT custom_vjp arg (ids/mask may be
    # tracers of an enclosing scan/checkpoint body — closing over them
    # would leak out of that trace when the backward replays); integer
    # and bool primals get float0 cotangents. Under remat the replayed
    # forward of this call can be fully dead (the bwd needs only ids/mask
    # residuals); XLA DCEs the pure replay, no bytes ship:
    # contract: allow(dead_compute)
    return _segment_sum_vjp(num_segments, interpret,
                            jnp.result_type(data))(data, segment_ids, mask)


def _segment_sum_vjp(num_segments: int, interpret: bool, dtype):
    # shape/dtype are trace-time statics: they ride the factory closure,
    # NOT the custom_vjp residuals (residuals must be valid JAX types —
    # they become scan carries when the call sits inside a scanned body)
    @jax.custom_vjp
    def f(d, ids, m):
        return pallas_segment_sum(d, ids, num_segments, mask=m,
                                  interpret=interpret)

    def fwd(d, ids, m):
        return f(d, ids, m), (ids, m)

    def bwd(res, g):
        ids, m = res
        # transpose of a masked segment sum: the sorted per-edge gather
        gd = jnp.take(g, ids, axis=0)
        m_ct = None if m is None else _int_zero(m)
        return (_mask_mul(gd, m).astype(dtype), _int_zero(ids), m_ct)

    f.defvjp(fwd, bwd)
    return f


# ---------------------------------------------------------------------------
# fused gather -> edge compute -> scatter
# ---------------------------------------------------------------------------

def _jaxpr_call(jaxpr, n_rows: int):
    """``fun(*rows, *consts)`` re-evaluating a traced edge_fn jaxpr with
    its hoisted consts as explicit trailing arguments."""

    def fun(*args):
        rows, cs = args[:n_rows], args[n_rows:]
        out = jax.core.eval_jaxpr(jaxpr, list(cs), *rows)
        if len(out) != 1:
            raise ValueError("edge_fn must return a single array")
        return out[0]

    return fun


def _hoist(edge_fn, row_avals):
    """Trace ``edge_fn`` at the given row shapes and hoist its closure
    captures (weights, tables). Returns ``(jaxpr, raw_consts)`` — the
    RAW captured objects, so two traces of the same function can be
    matched by identity (the jaxpr's shapes are baked, and the kernel
    and the chunked backward evaluate at different row counts)."""
    closed = jax.make_jaxpr(edge_fn)(*row_avals)
    return closed.jaxpr, list(closed.consts)


def _match_consts(raw_fwd, raw_bwd):
    """Position of each backward-trace const in the forward trace's const
    list. Tracing one function at two leading-axis sizes walks the same
    code path, so the captured objects are the same — anything else means
    a shape-dependent branch inside edge_fn, where silently dropping a
    cotangent would corrupt training grads: fail loudly instead."""
    id2fwd = {id(c): i for i, c in enumerate(raw_fwd)}
    perm = [id2fwd.get(id(c)) for c in raw_bwd]
    if None in perm or len(set(perm)) != len(raw_fwd):
        raise ValueError(
            "fused_edge_aggregate: edge_fn's closure captures differ "
            "between the kernel-block and backward-chunk traces (shape-"
            "dependent capture set); pass kernels=False for this call "
            "site or restructure edge_fn")
    return perm


def _rows_of(item):
    """Materialize one input's per-edge rows (XLA path / backward).

    Half-precision node arrays gather through an fp32 view: the gather's
    TRANSPOSE is a scatter-add of per-edge cotangents into the node rows,
    and routing it through fp32 accumulates those contributions at full
    precision with one rounding at the end (the dtype_discipline
    contract) — the forward rows are bit-identical (upcast/downcast of
    the same values) and the convert fuses into the gather."""
    if isinstance(item, Gather):
        node = jnp.asarray(item.node)
        if str(node.dtype) in ("bfloat16", "float16"):
            return jnp.take(node.astype(jnp.float32), item.idx,
                            axis=0).astype(node.dtype)
        return jnp.take(node, item.idx, axis=0)
    return jnp.asarray(item)


def fused_edge_aggregate(edge_fn, inputs, segment_ids, num_segments: int,
                         mask=None, indices_are_sorted: bool = True,
                         kernels=None, diff_params: bool = True,
                         vmem_budget: int | None = None,
                         bwd_chunk: int | None = None):
    """Fused gather + per-edge compute + dst-sorted segment sum.

    ``inputs``: per-edge arrays ``(E, ...)`` and/or :class:`Gather`
    markers. ``edge_fn(*rows) -> (E,) + out_shape`` messages; the result
    is ``sum_{e: dst[e]=n} mask[e] * edge_fn(...)[e]`` with the exact
    ``masked_segment_sum`` padding semantics. On the Pallas path the
    message tensor only ever exists one ``(BLK, width)`` block at a time
    in VMEM — forward AND backward (chunked custom VJP).

    ``diff_params``: whether gradients flow into ``edge_fn``'s hoisted
    float closure captures (edge-MLP weights). Training programs need
    True (the default). Force/stress programs differentiate positions
    only — they pass False, which stop-gradients the captures so the
    custom VJP neither computes the (dead) weight cotangents nor emits
    the replicated-input psums shard_map's transpose would otherwise
    add for them (a custom_vjp marks every primal perturbed; without
    this knob the kernel path would ship weight-gradient bytes over the
    mesh on every force call that plain XLA AD never ships).
    """
    inputs = list(inputs)
    mode = resolve_kernel_mode(kernels)
    e = int(segment_ids.shape[0])
    # float (inexact) masks would need a mask cotangent the chunked
    # backward doesn't produce — every mask in this repo is boolean; a
    # float mask routes to the XLA path where plain AD handles it
    float_mask = (mask is not None
                  and jnp.issubdtype(jnp.result_type(mask), jnp.inexact))
    use = (mode != "xla" and indices_are_sorted and e > 0
           and num_segments > 0 and not float_mask)
    _count(use)
    if not use:
        msg = edge_fn(*[_rows_of(i) for i in inputs])
        return masked_segment_sum(msg, segment_ids, num_segments, mask,
                                  indices_are_sorted=indices_are_sorted)

    interpret = mode == "interpret"
    budget = DEFAULT_VMEM_BUDGET if vmem_budget is None else int(vmem_budget)
    chunk = DEFAULT_BWD_CHUNK if bwd_chunk is None else int(bwd_chunk)

    # oversized node arrays: pre-gather with XLA (the kernel's in-kernel
    # gather wants the node array VMEM-resident)
    prep = []
    for item in inputs:
        if isinstance(item, Gather):
            node = jnp.asarray(item.node)
            if node.size * node.dtype.itemsize > budget:
                prep.append(_rows_of(item))
            else:
                prep.append(Gather(node, item.idx, node.shape[1:]))
        else:
            prep.append(jnp.asarray(item))

    # per-edge row avals at an arbitrary leading size (the jaxpr shapes
    # are baked, so the kernel traces at its block size and the backward
    # at its chunk size)
    def avals_at(n):
        return [
            jax.ShapeDtypeStruct((n,) + tuple(p.trailing), p.node.dtype)
            if isinstance(p, Gather)
            else jax.ShapeDtypeStruct((n,) + p.shape[1:], p.dtype)
            for p in prep
        ]

    out_aval = jax.eval_shape(edge_fn, *avals_at(e))
    out_shape, out_dtype = out_aval.shape[1:], out_aval.dtype

    # hoist edge_fn's closure captures (edge-MLP weights, coupling tables)
    # into explicit arrays: a Pallas kernel cannot capture array constants,
    # and parameter captures must stay DIFFERENTIABLE (training grads flow
    # through the per-edge compute). conv_fn(*rows, *consts) is edge_fn
    # with its captures as trailing args; float consts become primal args
    # of the custom VJP, integer tables stay constant. (jax.closure_convert
    # hoists only TRACER captures — concrete weight arrays would stay baked
    # in and trip pallas_call's no-captured-constants check — so the
    # hoisting is done on an explicit jaxpr trace at the kernel's block
    # granularity.)
    from .segment import _pick_tiles

    tn, eb = _pick_tiles(e, num_segments, None, None)
    jaxpr_blk, raw_consts = _hoist(edge_fn, avals_at(eb))
    consts = [jnp.asarray(c) for c in raw_consts]
    if not diff_params:
        # force-only program: cut the capture gradients here, INSIDE the
        # shard-local function, so no weight-cotangent psum ever reaches
        # the shard_map boundary
        consts = [jax.lax.stop_gradient(c) for c in consts]
    conv_fn = _jaxpr_call(jaxpr_blk, len(prep))
    diff_cpos = [i for i, c in enumerate(consts)
                 if jnp.issubdtype(c.dtype, jnp.inexact)]
    n_in = len(prep)

    def merged_consts(dconsts):
        out = list(consts)
        for i, d in zip(diff_cpos, dconsts):
            out[i] = d
        return out

    # EVERY traced operand is an explicit custom_vjp primal — node/edge
    # arrays, gather index columns, segment ids, the mask and the hoisted
    # float consts. Closing over any of them would leak tracers out of an
    # enclosing scan/remat body when the backward replays under
    # higher-order AD (training differentiates THROUGH the force vjp).
    idxs = [p.idx for p in prep if isinstance(p, Gather)]
    n_idx = len(idxs)
    has_mask = mask is not None

    def split(args):
        arrs = args[:n_in]
        idxs_ = list(args[n_in:n_in + n_idx])
        ids_ = args[n_in + n_idx]
        m_ = args[n_in + n_idx + 1] if has_mask else None
        dconsts = args[n_in + n_idx + 1 + int(has_mask):]
        return arrs, idxs_, ids_, m_, dconsts

    @jax.custom_vjp
    def f(*args):
        arrs, idxs_, ids_, m_, dconsts = split(args)
        items = []
        gi = 0
        for p, a in zip(prep, arrs):
            if isinstance(p, Gather):
                items.append(("gather", a, idxs_[gi]))
                gi += 1
            else:
                items.append(a)
        return pallas_edge_aggregate(
            conv_fn, items, ids_, num_segments, m_,
            out_shape=out_shape, out_dtype=out_dtype,
            consts=merged_consts(dconsts), tile_n=tn, edge_blk=eb,
            interpret=interpret)

    def f_fwd(*args):
        return f(*args), args

    def f_bwd(args, g):
        arrs, idxs_, ids_, m_, dconsts = split(args)

        def make_rowwise(chunk_n):
            # re-trace at the backward's chunk granularity; the captures
            # are matched BY IDENTITY to the forward trace so the float
            # ones route through the custom-VJP args (grads flow)
            jaxpr_bwd, raw_bwd = _hoist(edge_fn, avals_at(chunk_n))
            perm = _match_consts(raw_consts, raw_bwd)
            bwd_fn = _jaxpr_call(jaxpr_bwd, n_in)

            def rowwise(rows, dconsts_):
                merged = merged_consts(list(dconsts_))
                return bwd_fn(*rows, *[merged[p] for p in perm])

            return rowwise

        in_cts, const_cts = _edge_aggregate_bwd(
            make_rowwise, prep, arrs, dconsts, idxs_,
            ids_, m_, g, chunk, diff_params)
        out = in_cts + tuple(_int_zero(i) for i in idxs_)
        out = out + (_int_zero(ids_),)
        if has_mask:
            out = out + (_int_zero(m_),)  # masks are bool/int (gated above)
        return out + const_cts

    f.defvjp(f_fwd, f_bwd)
    diff = ([p.node if isinstance(p, Gather) else p for p in prep]
            + idxs + [segment_ids] + ([mask] if has_mask else [])
            + [consts[i] for i in diff_cpos])
    # custom_vjp must return a cotangent for EVERY primal; when the
    # enclosing transpose needs only some, the rest (including their
    # scatter-adds) are dead and XLA DCEs them:
    # contract: allow(dead_compute)
    return f(*diff)


def _edge_aggregate_bwd(make_rowwise, prep, arrs, dconsts, idxs,
                        segment_ids, mask, g, chunk,
                        diff_params: bool = True):
    """Chunked backward: per edge chunk, re-run the per-edge compute under
    ``jax.vjp`` against the gathered message cotangent ``g[dst] * mask``
    and accumulate input cotangents — plain inputs stack per-chunk rows,
    gathered node arrays scatter-add (the audited unsorted grad-program
    scatter), hoisted float consts (edge-MLP weights) sum across chunks.
    Working set is O(chunk * width), not O(E * width). With
    ``diff_params=False`` the const cotangents are symbolic zeros (the
    caller stop-gradients the captures; computing real cotangents here
    would be pure dead work). Returns ``(input_cts, const_cts)``."""
    e = int(segment_ids.shape[0])
    chunk = max(1, min(chunk, e))
    k = -(-e // chunk)
    e_pad = k * chunk
    pad = e_pad - e

    def pad_rows(x, fill=0):
        if pad == 0:
            return x
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths, constant_values=fill)

    ids_p = jnp.concatenate(
        [segment_ids, jnp.broadcast_to(segment_ids[-1], (pad,))]
    ) if pad else segment_ids
    m = jnp.ones((e,), dtype=g.dtype) if mask is None else mask.astype(g.dtype)
    m_p = pad_rows(m)

    # per-edge xs streams: plain rows come from the primal arrays, gather
    # inputs stream their idx column (node arrays stay closed over)
    xs = [ids_p, m_p]
    gi = 0
    for p, a in zip(prep, arrs):
        if isinstance(p, Gather):
            xs.append(pad_rows(idxs[gi].astype(jnp.int32)))
            gi += 1
        else:
            xs.append(pad_rows(a))
    rowwise = make_rowwise(chunk)

    def chunk_fn(carry, xs_c):
        node_cts, const_cts = carry
        ids_c, m_c, *per_edge = xs_c
        rows = []
        for p, a, col in zip(prep, arrs, per_edge):
            if isinstance(p, Gather):
                # f32-view gather for half node arrays: under SECOND-order
                # AD (the force loss differentiates through this backward)
                # the take's transpose scatter-adds per-edge cotangents
                # into the node rows — same fp32-accumulation contract as
                # _rows_of; forward rows are bit-identical
                if str(a.dtype) in ("bfloat16", "float16"):
                    rows.append(jnp.take(a.astype(jnp.float32), col,
                                         axis=0).astype(a.dtype))
                else:
                    rows.append(jnp.take(a, col, axis=0))
            else:
                rows.append(col)
        # same f32-view rule for the message-cotangent gather: its
        # second-order transpose segment-sums per-edge rows back into the
        # (num_segments, width) cotangent — fp32 accumulation, one round
        if str(g.dtype) in ("bfloat16", "float16"):
            gm = jnp.take(g.astype(jnp.float32), ids_c,
                          axis=0).astype(g.dtype)
        else:
            gm = jnp.take(g, ids_c, axis=0)
        gm = gm * m_c.reshape(m_c.shape + (1,) * (gm.ndim - 1))
        if diff_params:
            msg, vjp_fn = jax.vjp(rowwise, tuple(rows), tuple(dconsts))
            row_cts, dc_cts = vjp_fn(gm.astype(msg.dtype))
        else:
            msg, vjp_fn = jax.vjp(
                lambda rs: rowwise(rs, tuple(dconsts)), tuple(rows))
            (row_cts,) = vjp_fn(gm.astype(msg.dtype))
            dc_cts = tuple(jnp.zeros(c.shape, c.dtype) for c in dconsts)
        new_node_cts = list(node_cts)
        plain_out = []
        gi = 0
        for p, col, ct in zip(prep, per_edge, row_cts):
            if isinstance(p, Gather):
                # contract: allow(scatter_hints) — grad-path transpose of
                # an unsorted gather (src order is not dst order). The
                # accumulator carries fp32 (node_cts0 below): half inputs
                # would otherwise round per edge AND per chunk.
                new_node_cts[gi] = new_node_cts[gi].at[col].add(
                    ct.astype(new_node_cts[gi].dtype))
                gi += 1
            else:
                plain_out.append(ct)
        new_const_cts = (tuple(c0 + c for c0, c in zip(const_cts, dc_cts))
                         if diff_params else const_cts)
        return (tuple(new_node_cts), new_const_cts), tuple(plain_out)

    # half-precision node arrays accumulate their cotangents in an fp32
    # carry (rounded back to the storage dtype once, after the scan) —
    # the dtype_discipline fp32-accumulation contract
    node_cts0 = tuple(
        jnp.zeros(a.shape, jnp.float32 if str(a.dtype) in
                  ("bfloat16", "float16") else a.dtype)
        for p, a in zip(prep, arrs) if isinstance(p, Gather))
    const_cts0 = tuple(jnp.zeros(c.shape, c.dtype) for c in dconsts)

    if k == 1:
        (node_cts, const_cts), plain = chunk_fn(
            (node_cts0, const_cts0), tuple(xs))
        plain = [c[:e] for c in plain]
    else:
        xs_c = tuple(x.reshape((k, chunk) + x.shape[1:]) for x in xs)
        (node_cts, const_cts), plain_stacked = jax.lax.scan(
            chunk_fn, (node_cts0, const_cts0), xs_c)
        plain = [c.reshape((e_pad,) + c.shape[2:])[:e]
                 for c in plain_stacked]

    out = []
    gi = pi = 0
    for p, a in zip(prep, arrs):
        if isinstance(p, Gather):
            out.append(node_cts[gi].astype(a.dtype))
            gi += 1
        else:
            out.append(plain[pi])
            pi += 1
    return tuple(out), tuple(const_cts)


# ---------------------------------------------------------------------------
# fused SO(2) convolution (eSCN channel mixing)
# ---------------------------------------------------------------------------

def fused_so2_conv(h, weights, m_idx: dict, channels: int, kernels=None,
                   diff_params: bool = True):
    """SO(2) convolution over all |m| blocks, dispatched.

    ``h``: (E, S, C) coefficients in the model's (e3nn) layout;
    ``weights``: ``[W0, W1r, W1i, ...]`` mixed (d, d) matrices per m;
    ``m_idx``: the model's per-|m| (plus, minus) index sets. Returns the
    convolved coefficients in the SAME layout. On the Pallas path every
    per-(l, m) GEMM runs in one VMEM-resident kernel; backward is the
    VJP of the XLA reference (the operand is already chunk-bounded by
    the model's edge scan). ``diff_params=False`` stop-gradients the
    weight stack (force/stress programs — same rationale as
    :func:`fused_edge_aggregate`); training keeps the default True.
    """
    perm, inv, segments = packed_m_layout(m_idx)

    def ref(h_, *ws):
        return so2_conv_reference(h_[:, perm, :], list(ws), segments,
                                  channels)[:, inv, :]

    mode = resolve_kernel_mode(kernels)
    use = mode != "xla" and h.shape[0] > 0
    _count(use)
    if not use:
        return ref(h, *weights)
    interpret = mode == "interpret"
    if not diff_params:
        weights = [jax.lax.stop_gradient(w) for w in weights]

    @jax.custom_vjp
    def f(h_, *ws):
        return so2_conv_pallas(h_[:, perm, :], list(ws), segments, channels,
                               interpret=interpret)[:, inv, :]

    def f_fwd(h_, *ws):
        return f(h_, *ws), (h_,) + ws

    def f_bwd(res, g):
        h_, ws = res[0], res[1:]
        if diff_params:
            _, vjp_fn = jax.vjp(ref, h_, *ws)
            return vjp_fn(g)
        _, vjp_fn = jax.vjp(lambda hh: ref(hh, *ws), h_)
        (gh,) = vjp_fn(g)
        return (gh,) + tuple(jnp.zeros(w.shape, w.dtype) for w in ws)

    f.defvjp(f_fwd, f_bwd)
    return f(h, *weights)
