"""Pallas TPU kernels for the message-passing hot path.

The inner loop of every model is gather -> edge compute (elementwise
weighting, radial envelopes, small edge-MLP/tensor-product GEMMs) ->
dst-sorted segment sum. XLA compiles these as separate HLOs with
materialized ``(E, width)`` intermediates in HBM; the kernels here fuse
the pipeline per tile of DESTINATION nodes instead, exploiting the
repo-wide padding contract (globally nondecreasing ``edge_dst``,
``indices_are_sorted=True`` — ops/segment.py): each dst tile owns a
CONTIGUOUS slice of the edge array, computable with one on-device
``searchsorted`` over the tile boundaries.

Layout:

- :mod:`segment` — the fused gather+scatter segment kernels
  (``pallas_segment_sum``, ``pallas_edge_aggregate``) and the XLA
  reference implementations they are tested against.
- :mod:`so3` — the fused SO(2)/channel-mixing kernel for the MACE/eSCN
  equivariant inner loop (per-|m| complex-pair GEMMs batched into one
  VMEM-resident kernel).
- :mod:`dispatch` — the routing layer every call site goes through:
  Pallas on TPU, pure-XLA everywhere else (or under the
  ``DISTMLIP_KERNELS=0`` kill switch / per-object ``kernels=False``),
  with custom VJPs so ``value_and_grad`` force/stress programs work
  identically on both paths.
"""

from .dispatch import (  # noqa: F401
    Gather,
    KernelCounter,
    counting,
    force_kernel_mode,
    fused_edge_aggregate,
    fused_segment_sum,
    fused_so2_conv,
    resolve_kernel_mode,
)
from .segment import pallas_edge_aggregate, pallas_segment_sum  # noqa: F401
from .so3 import so2_conv_reference  # noqa: F401
