"""Dst-tiled Pallas segment kernels (fused gather -> edge compute -> scatter).

The padding contract (ops/segment.py, partition/graph.py) keeps every edge
array dst-sorted: ``segment_ids`` is globally nondecreasing within a layout
segment, padded rows repeat the last real id, and a validity mask screens
padding. That contract is exactly what makes a DESTINATION-TILED kernel
possible: the edges landing in dst rows ``[t*TILE_N, (t+1)*TILE_N)`` form a
CONTIGUOUS slice of the edge array whose bounds come from one on-device
``searchsorted`` over the tile boundaries (:func:`dst_tile_offsets`).

Each grid step then owns one dst tile: it streams that tile's edge slice
from HBM in fixed-size blocks (async DMA into VMEM scratch), optionally
gathers per-edge rows from VMEM-resident node arrays, applies a
caller-supplied per-edge compute, and accumulates into the tile's
``(TILE_N, W)`` VMEM accumulator with a one-hot MXU matmul — the classic
TPU segment-sum idiom. The ``(E, width)`` message tensor never exists:
messages live one ``(BLK, width)`` block at a time in VMEM.

Everything here is the raw kernel layer: no routing, no autodiff. Call
sites go through :mod:`distmlip_tpu.kernels.dispatch`, which adds the
XLA fallback and the custom VJPs.

Shapes are NOT required to be multiples of the tile sizes — inputs are
guard-padded with ZERO-filled rows (:func:`_prepare_edges`) so in-kernel
block slices never hit ``dynamic_slice``'s end-clamp, and outputs are
sliced back. The guard rows' content is never read as real data: tile
offsets come from the UNPADDED ids, and the in-kernel ``pos < tile_end``
test screens every guard row before it can reach the accumulator — do
not drop that test in favor of trusting the pad values.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# default tile of destination rows per grid step and edges per streamed
# block. Both are compile-time constants of one pallas_call; the dispatch
# layer may shrink them for tiny problems so guard padding stays bounded.
TILE_N = 128
EDGE_BLK = 256


def dst_tile_offsets(segment_ids, num_segments: int, tile_n: int):
    """(num_tiles + 1,) int32 edge offsets of each dst tile's slice.

    ``segment_ids`` must be nondecreasing (the dst-sorted contract);
    ``offsets[t]`` is the first edge whose dst lands at or past row
    ``t * tile_n``, so tile ``t`` owns edges ``[offsets[t], offsets[t+1])``.
    Runs on device inside the surrounding jit (one ``searchsorted`` over
    ``num_tiles + 1`` boundaries — noise next to the aggregation itself).
    """
    num_tiles = -(-num_segments // tile_n)
    bounds = jnp.arange(num_tiles + 1, dtype=segment_ids.dtype) * tile_n
    return jnp.searchsorted(segment_ids, bounds, side="left").astype(jnp.int32)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _pad_rows(x, rows: int, fill=0):
    if rows == 0:
        return x
    widths = [(0, rows)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths, constant_values=fill)


def _flatten_width(x):
    """(E, ...) -> (E, W) with W >= 1 (scalars get a singleton lane)."""
    if x.ndim == 1:
        return x[:, None]
    return x.reshape(x.shape[0], -1)


def _pick_tiles(n_edges: int, num_segments: int, tile_n: int | None,
                edge_blk: int | None):
    """Clamp the static tile sizes to the problem so guard padding on tiny
    graphs (tests, 1-atom structures) stays proportionate."""
    tn = tile_n if tile_n else min(TILE_N, max(8, _round_up(num_segments, 8)))
    eb = edge_blk if edge_blk else min(EDGE_BLK, max(8, _round_up(n_edges, 8)))
    return int(tn), int(eb)


def _prepare_edges(arrays, n_edges: int, edge_blk: int):
    """Guard-pad every (E, ...) array to ``round_up(E, blk) + blk`` rows so
    in-kernel block slices never hit ``dynamic_slice``'s end-clamp (which
    would silently re-read earlier rows)."""
    e_pad = _round_up(max(n_edges, 1), edge_blk) + edge_blk
    return [_pad_rows(a, e_pad - n_edges) for a in arrays], e_pad


def _block_copy(src_ref, dst_ref, sem, start, rows: int):
    """DMA ``rows`` rows of ``src_ref`` starting at ``start`` into VMEM."""
    cp = pltpu.make_async_copy(src_ref.at[pl.ds(start, rows)], dst_ref, sem)
    cp.start()
    cp.wait()


def _onehot_accumulate(acc, msg, local_dst, valid, tile_n: int):
    """acc += onehot(local_dst)^T @ (msg * valid): the per-block dst
    scatter as ONE MXU matmul against a (BLK, TILE_N) one-hot."""
    blk = msg.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (blk, tile_n), 1)
    onehot = jnp.where((local_dst[:, None] == cols) & valid[:, None], 1.0, 0.0
                       ).astype(jnp.float32)
    return acc + jax.lax.dot_general(
        onehot, msg.astype(jnp.float32),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _gather_rows(node_ref, idx, width: int):
    """(BLK,) indexed rows of a VMEM-resident (N, W) node ref.

    Row-looped dynamic slices — the node array is VMEM-resident (the
    dispatch layer only routes arrays under its VMEM budget here; larger
    arrays are pre-gathered by XLA), so each read is an on-chip dynamic
    slice, not an HBM round trip.
    """
    blk = idx.shape[0]

    zero = jnp.zeros((), dtype=idx.dtype)  # match idx dtype under x64 tracing

    def body(j, acc):
        row = jax.lax.dynamic_slice(node_ref[:], (idx[j], zero), (1, width))
        return jax.lax.dynamic_update_slice(acc, row,
                                            (j.astype(idx.dtype), zero))

    init = jnp.zeros((blk, width), dtype=node_ref.dtype)
    return jax.lax.fori_loop(0, blk, body, init)


# ---------------------------------------------------------------------------
# fused segment sum (data already per-edge)
# ---------------------------------------------------------------------------

def pallas_segment_sum(data, segment_ids, num_segments: int, mask=None, *,
                       tile_n: int | None = None, edge_blk: int | None = None,
                       interpret: bool = False):
    """Masked dst-tiled segment sum of dst-sorted ``data``.

    Drop-in for ``ops.segment.masked_segment_sum(..., indices_are_sorted=
    True)`` on sorted layouts: same masking semantics (padded rows repeat
    the last real id and are screened by ``mask``), fp32 accumulation in
    VMEM, result cast back to ``data.dtype``. ``data`` may carry any
    trailing shape; it is streamed as ``(E, prod(trailing))``.
    """
    e = data.shape[0]
    out_shape = (num_segments,) + data.shape[1:]
    if e == 0 or num_segments == 0:
        return jnp.zeros(out_shape, dtype=data.dtype)
    flat = _flatten_width(data)
    w = flat.shape[1]
    tn, eb = _pick_tiles(e, num_segments, tile_n, edge_blk)
    ntile = -(-num_segments // tn)
    offs = dst_tile_offsets(segment_ids, num_segments, tn)

    m = (jnp.ones((e,), jnp.int32) if mask is None
         else mask.astype(jnp.int32))
    (flat_p, ids_p, m_p), _ = _prepare_edges(
        [flat, segment_ids.astype(jnp.int32), m], e, eb)

    kernel = functools.partial(_segment_sum_kernel, tile_n=tn, edge_blk=eb,
                               width=w)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ntile,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),   # ids
            pl.BlockSpec(memory_space=pltpu.ANY),   # mask
            pl.BlockSpec(memory_space=pltpu.ANY),   # data
        ],
        out_specs=pl.BlockSpec((tn, w), lambda i, offs: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((eb,), jnp.int32),
            pltpu.VMEM((eb,), jnp.int32),
            pltpu.VMEM((eb, w), flat.dtype),
            pltpu.VMEM((tn, w), jnp.float32),
            pltpu.SemaphoreType.DMA((3,)),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((ntile * tn, w), data.dtype),
        interpret=interpret,
    )(offs, ids_p, m_p, flat_p)
    return out[:num_segments].reshape(out_shape)


def _segment_sum_kernel(offs_ref, ids_ref, mask_ref, data_ref, out_ref,
                        ids_s, mask_s, data_s, acc_s, sems, *,
                        tile_n: int, edge_blk: int, width: int):
    i = pl.program_id(0)
    e0 = offs_ref[i]
    e1 = offs_ref[i + 1]
    acc_s[:] = jnp.zeros_like(acc_s)
    nblk = pl.cdiv(e1 - e0, edge_blk)

    def body(b, _):
        s = e0 + b * edge_blk
        _block_copy(ids_ref, ids_s, sems.at[0], s, edge_blk)
        _block_copy(mask_ref, mask_s, sems.at[1], s, edge_blk)
        _block_copy(data_ref, data_s, sems.at[2], s, edge_blk)
        pos = s + jax.lax.broadcasted_iota(jnp.int32, (edge_blk, 1), 0)[:, 0]
        valid = (pos < e1) & (mask_s[:] != 0)
        local = ids_s[:] - i * tile_n
        acc_s[:] = _onehot_accumulate(acc_s[:], data_s[:], local, valid,
                                      tile_n)
        return _

    jax.lax.fori_loop(0, nblk, body, None)
    out_ref[:] = acc_s[:].astype(out_ref.dtype)


# ---------------------------------------------------------------------------
# fused gather -> edge compute -> scatter
# ---------------------------------------------------------------------------

def pallas_edge_aggregate(edge_fn, inputs, segment_ids, num_segments: int,
                          mask=None, *, out_shape, out_dtype, consts=(),
                          tile_n: int | None = None,
                          edge_blk: int | None = None,
                          interpret: bool = False):
    """Fused gather + per-edge compute + dst-tiled scatter.

    ``inputs`` is a sequence of either per-edge arrays ``(E, ...)``
    (streamed from HBM block by block) or ``("gather", node_array, idx)``
    triples — ``node_array`` rides VMEM whole and its ``idx`` rows are
    gathered INSIDE the kernel per block. ``edge_fn(*blocks)`` receives one
    ``(BLK, ...)`` block per input (original trailing shapes restored) and
    returns ``(BLK,) + out_shape`` messages, which are masked and
    accumulated onto their dst rows without ever materializing the
    ``(E,) + out_shape`` message tensor. ``consts`` are whole-array
    kernel inputs (edge-MLP weights, coupling tables — hoisted closure
    captures, a Pallas kernel cannot close over arrays) appended to the
    ``edge_fn`` call after the per-edge blocks; they ride VMEM whole.

    The caller guarantees ``segment_ids`` is nondecreasing (the dst-sorted
    layout contract) — exactly the precondition of the
    ``indices_are_sorted=True`` fast path this kernel replaces.
    """
    e = segment_ids.shape[0]
    full_out = (num_segments,) + tuple(out_shape)
    if e == 0 or num_segments == 0:
        return jnp.zeros(full_out, dtype=out_dtype)
    tn, eb = _pick_tiles(e, num_segments, tile_n, edge_blk)
    ntile = -(-num_segments // tn)
    offs = dst_tile_offsets(segment_ids, num_segments, tn)
    w_out = 1
    for d in out_shape:
        w_out *= int(d)

    # split inputs into streamed per-edge arrays and gathered node arrays;
    # every input contributes exactly ONE streamed array (its data, or the
    # gather's idx column), so input position == streamed-array position
    edge_arrays = []                    # flattened (E, Wi), one per input
    node_arrays, node_widths = [], []
    kinds = []                          # ("edge", trailing)|("gather", k, tr)
    for item in inputs:
        if isinstance(item, tuple) and len(item) == 3 and item[0] == "gather":
            _, node, idx = item
            node2 = _flatten_width(node)
            kinds.append(("gather", len(node_arrays), node.shape[1:]))
            node_arrays.append(node2)
            node_widths.append(node2.shape[1])
            edge_arrays.append(idx.astype(jnp.int32)[:, None])
        else:
            arr = jnp.asarray(item)
            kinds.append(("edge", None, arr.shape[1:]))
            edge_arrays.append(_flatten_width(arr))

    m = (jnp.ones((e,), jnp.int32) if mask is None
         else mask.astype(jnp.int32))
    padded, _ = _prepare_edges(
        [segment_ids.astype(jnp.int32), m] + edge_arrays, e, eb)
    ids_p, m_p = padded[0], padded[1]
    edge_p = padded[2:]

    # whole-array consts: 0/1-d arrays ride as (1, n) (TPU wants >= 2-d
    # tiles); the kernel restores the original shapes before edge_fn
    const_shapes = tuple(jnp.shape(c) for c in consts)
    const_in = [jnp.asarray(c).reshape(
        (1, max(1, int(jnp.size(c)))) if jnp.ndim(c) < 2 else jnp.shape(c))
        for c in consts]

    kernel = functools.partial(
        _edge_aggregate_kernel, edge_fn=edge_fn, kinds=kinds,
        node_widths=node_widths, const_shapes=const_shapes, tile_n=tn,
        edge_blk=eb, w_out=w_out, out_shape=tuple(out_shape))
    n_stream = 2 + len(edge_p)  # ids + mask + per-edge arrays
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ntile,),
        in_specs=(
            [pl.BlockSpec(memory_space=pltpu.ANY)] * n_stream
            + [pl.BlockSpec(memory_space=pltpu.VMEM)]
            * (len(node_arrays) + len(const_in))
        ),
        out_specs=pl.BlockSpec((tn, w_out), lambda i, offs: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((eb,), jnp.int32),
            pltpu.VMEM((eb,), jnp.int32),
        ] + [
            pltpu.VMEM((eb, a.shape[1]), a.dtype) for a in edge_p
        ] + [
            pltpu.VMEM((tn, w_out), jnp.float32),
            pltpu.SemaphoreType.DMA((n_stream,)),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((ntile * tn, w_out), out_dtype),
        interpret=interpret,
    )(offs, ids_p, m_p, *edge_p, *node_arrays, *const_in)
    return out[:num_segments].reshape(full_out)


def _edge_aggregate_kernel(offs_ref, ids_ref, mask_ref, *refs, edge_fn,
                           kinds, node_widths, const_shapes, tile_n: int,
                           edge_blk: int, w_out: int, out_shape):
    n_edge = len(kinds)
    n_node = len(node_widths)
    n_const = len(const_shapes)
    edge_refs = refs[:n_edge]
    node_refs = refs[n_edge:n_edge + n_node]
    const_refs = refs[n_edge + n_node:n_edge + n_node + n_const]
    out_ref = refs[n_edge + n_node + n_const]
    ids_s = refs[n_edge + n_node + n_const + 1]
    mask_s = refs[n_edge + n_node + n_const + 2]
    edge_s = refs[n_edge + n_node + n_const + 3:
                  n_edge + n_node + n_const + 3 + n_edge]
    acc_s = refs[-2]
    sems = refs[-1]
    const_vals = [r[:].reshape(shp) for r, shp in
                  zip(const_refs, const_shapes)]

    i = pl.program_id(0)
    e0 = offs_ref[i]
    e1 = offs_ref[i + 1]
    acc_s[:] = jnp.zeros_like(acc_s)
    nblk = pl.cdiv(e1 - e0, edge_blk)

    def body(b, _):
        s = e0 + b * edge_blk
        _block_copy(ids_ref, ids_s, sems.at[0], s, edge_blk)
        _block_copy(mask_ref, mask_s, sems.at[1], s, edge_blk)
        for k, (eref, sref) in enumerate(zip(edge_refs, edge_s)):
            _block_copy(eref, sref, sems.at[2 + k], s, edge_blk)
        args = []
        for p, (tag, node_k, trailing) in enumerate(kinds):
            if tag == "gather":
                idx = edge_s[p][:][:, 0]
                rows = _gather_rows(node_refs[node_k], idx,
                                    node_widths[node_k])
                args.append(rows.reshape((edge_blk,) + tuple(trailing)))
            else:
                args.append(edge_s[p][:].reshape(
                    (edge_blk,) + tuple(trailing)))
        msg = edge_fn(*args, *const_vals).reshape(edge_blk, w_out)
        pos = s + jax.lax.broadcasted_iota(jnp.int32, (edge_blk, 1), 0)[:, 0]
        valid = (pos < e1) & (mask_s[:] != 0)
        local = ids_s[:] - i * tile_n
        acc_s[:] = _onehot_accumulate(acc_s[:], msg, local, valid, tile_n)
        return _

    jax.lax.fori_loop(0, nblk, body, None)
    out_ref[:] = acc_s[:].astype(out_ref.dtype)
