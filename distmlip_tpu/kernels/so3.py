"""Fused SO(2)/channel-mixing kernel for the equivariant inner loop.

eSCN's SO(2) convolution (models/escn.py) is, per edge, a stack of small
per-|m| GEMMs over the (+m, -m) complex coefficient pairs:

    m = 0:  y0 = f0 @ W0
    m > 0:  y+ = f+ @ Wr - f- @ Wi,   y- = f+ @ Wi + f- @ Wr

with ``f`` the (nl * C)-flattened coefficient block for that |m|. XLA
evaluates each as its own HLO with the per-edge operand round-tripping
HBM between them. The kernel here batches ALL per-(l, m) GEMMs into one
VMEM-resident pallas_call over edge blocks: one load of the (BLK, S, C)
coefficient block, 2 * l_max + 1 MXU matmuls against the VMEM-resident
weight stack, one store. (MACE's per-path channel mixing rides the
generic :func:`distmlip_tpu.kernels.segment.pallas_edge_aggregate`
instead — its contraction is already fused into the density-projection
edge compute.)

Coefficients arrive in the PACKED per-m layout (``packed_m_layout``):
``[m=0 block | m=1 plus | m=1 minus | m=2 plus | ...]`` so every per-m
operand is a static slice — the (cheap, static) permutation from the
e3nn layout is applied by the dispatch layer, not the kernel.

``so2_conv_reference`` is the same math in plain XLA: the fallback path,
the custom-VJP backward, and the parity oracle for the tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

EDGE_BLK = 256


def packed_m_layout(m_idx: dict) -> tuple:
    """(perm, inv, segments): the packed per-m coefficient order.

    ``m_idx[m] = (plus_indices, minus_indices)`` in the source layout
    (models/escn.py ``self.m_idx``). ``perm`` gathers source -> packed,
    ``inv`` gathers packed -> source, ``segments`` lists
    ``(m, start, nl)`` static slice bounds of each packed block (for
    ``m > 0`` the minus block sits at ``start + nl``).
    """
    order = []
    segments = []
    for m in sorted(m_idx):
        plus, minus = m_idx[m]
        segments.append((m, len(order), len(plus)))
        order.extend(int(i) for i in plus)
        if m > 0:
            order.extend(int(i) for i in minus)
    perm = np.asarray(order, dtype=np.int32)
    inv = np.argsort(perm).astype(np.int32)
    return perm, inv, tuple(segments)


def so2_conv_reference(h_packed, weights, segments, channels: int):
    """Pure-XLA SO(2) convolution on packed-layout coefficients.

    ``weights`` is ``[W0, W1r, W1i, W2r, W2i, ...]`` (one (d, d) matrix
    per m=0 block, a real/imag pair per m > 0, ``d = nl * C``). Returns
    the packed-layout output; identical math to the kernel.
    """
    e = h_packed.shape[0]
    c = channels
    out = []
    wi = 0
    for m, start, nl in segments:
        d = nl * c
        if m == 0:
            f = h_packed[:, start:start + nl, :].reshape(e, d)
            out.append((f @ weights[wi]).reshape(e, nl, c))
            wi += 1
        else:
            fp = h_packed[:, start:start + nl, :].reshape(e, d)
            fm = h_packed[:, start + nl:start + 2 * nl, :].reshape(e, d)
            wr, wim = weights[wi], weights[wi + 1]
            wi += 2
            out.append((fp @ wr - fm @ wim).reshape(e, nl, c))
            out.append((fp @ wim + fm @ wr).reshape(e, nl, c))
    return jnp.concatenate(out, axis=1)


def so2_conv_pallas(h_packed, weights, segments, channels: int, *,
                    edge_blk: int | None = None, interpret: bool = False):
    """One VMEM-resident pallas_call evaluating every per-m GEMM.

    ``h_packed``: (E, S, C) packed-layout coefficients; ``weights`` as in
    :func:`so2_conv_reference` (they ride VMEM whole — SO(2) stacks are
    O(l_max * (l_max * C)^2) bytes, far under the VMEM budget for every
    model config this repo ships).
    """
    e, s, c = h_packed.shape
    blk = min(edge_blk or EDGE_BLK, max(8, e))
    e_pad = -(-e // blk) * blk
    h_in = (jnp.pad(h_packed, ((0, e_pad - e), (0, 0), (0, 0)))
            if e_pad != e else h_packed)

    kernel = functools.partial(_so2_kernel, segments=segments, channels=c,
                               n_weights=len(weights))
    out = pl.pallas_call(
        kernel,
        grid=(e_pad // blk,),
        in_specs=(
            [pl.BlockSpec((blk, s, c), lambda i: (i, 0, 0))]
            + [pl.BlockSpec(w.shape, lambda i: (0,) * w.ndim)
               for w in weights]
        ),
        out_specs=pl.BlockSpec((blk, s, c), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((e_pad, s, c), h_packed.dtype),
        interpret=interpret,
    )(h_in, *weights)
    return out[:e]


def _so2_kernel(h_ref, *refs, segments, channels: int, n_weights: int):
    w_refs = refs[:n_weights]
    out_ref = refs[n_weights]
    c = channels
    blk = h_ref.shape[0]
    h = h_ref[:]
    wi = 0
    for m, start, nl in segments:
        d = nl * c
        if m == 0:
            f = h[:, start:start + nl, :].reshape(blk, d)
            y = jnp.dot(f, w_refs[wi][:],
                        preferred_element_type=jnp.float32)
            out_ref[:, start:start + nl, :] = y.reshape(blk, nl, c).astype(
                out_ref.dtype)
            wi += 1
        else:
            fp = h[:, start:start + nl, :].reshape(blk, d)
            fm = h[:, start + nl:start + 2 * nl, :].reshape(blk, d)
            wr = w_refs[wi][:]
            wim = w_refs[wi + 1][:]
            wi += 2
            yp = (jnp.dot(fp, wr, preferred_element_type=jnp.float32)
                  - jnp.dot(fm, wim, preferred_element_type=jnp.float32))
            ym = (jnp.dot(fp, wim, preferred_element_type=jnp.float32)
                  + jnp.dot(fm, wr, preferred_element_type=jnp.float32))
            out_ref[:, start:start + nl, :] = yp.reshape(blk, nl, c).astype(
                out_ref.dtype)
            out_ref[:, start + nl:start + 2 * nl, :] = ym.reshape(
                blk, nl, c).astype(out_ref.dtype)
