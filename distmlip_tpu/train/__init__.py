"""Distributed training subsystem over the 2-D mesh.

The historical ``distmlip_tpu/train.py`` module grew into this package;
its entire surface (``make_loss_fn`` / ``make_train_step`` /
``make_batched_train_step`` / ``make_eval_fn`` / ``stack_graphs`` /
``stack_targets`` / ``save_train_state`` / ``load_train_state``) remains
importable from ``distmlip_tpu.train`` unchanged (now defined in
:mod:`.legacy`). The subsystem proper:

- :mod:`.data` — labeled-structure datasets, deterministic seeded
  shuffling, bucket-aware block-diagonal packing at frozen worst-case
  capacities, target packing into the padded local layout, and a
  double-buffered host-side prefetch loader with a 3-integer resumable
  cursor;
- :mod:`.step` — ``TrainState`` (fp32 master weights, optimizer state,
  EMA, dynamic loss scale, rng), the packed multi-structure loss, and the
  accumulated mixed-precision step: ``lax.scan`` over micro-batches,
  global-norm clipping, nonfinite-skip loss-scale dynamics, and ZeRO-1
  optimizer-state sharding over the mesh's batch axis (psum grads via the
  shard_map transpose, one all_gather of updated params);
- :mod:`.loop` — ``Trainer``: epoch/step loop, periodic EMA eval,
  best-model tracking, per-step :class:`~distmlip_tpu.telemetry.TrainRecord`
  telemetry, and static-HBM-planner micro-batch auto-sizing
  (``micro_batch_size="auto"`` / up-front over-budget rejection);
- :mod:`.checkpoint` — async atomic resumable checkpoints carrying the
  full TrainState + loader cursor, making mid-epoch resume bitwise.

Quick start::

    from distmlip_tpu.train import Sample, TrainConfig, Trainer

    data = [Sample(atoms, energy, forces) for ...]
    trainer = Trainer(model.energy_fn, params, optax.adam(1e-3), data,
                      cutoff=model.cfg.cutoff, micro_batch_size=4,
                      config=TrainConfig(accum_steps=2, precision="bf16"),
                      val_samples=held_out, checkpoint_dir="ckpts")
    trainer.fit(epochs=10)
"""

from .checkpoint import TrainCheckpointer, latest_checkpoint
from .data import (PackedBatchLoader, Sample, TrainBatch, epoch_permutation,
                   labelled_dataset, pack_targets, structure_needs)
from .packing import (CostCensus, assign_tiers, default_cost, model_cost_fn,
                      plan_epoch, plan_epoch_naive, predicted_plan_waste,
                      tier_caps)
from .legacy import (load_train_state, make_batched_train_step, make_eval_fn,
                     make_loss_fn, make_train_step, save_train_state,
                     stack_graphs, stack_targets)
from .loop import Trainer, estimate_step_peak_bytes
from .step import (TrainConfig, TrainState, init_train_state,
                   make_accum_train_step, make_eval_step,
                   make_packed_loss_fn, resolve_zero1)

__all__ = [
    # legacy surface (the historical train.py module)
    "make_loss_fn",
    "make_train_step",
    "make_batched_train_step",
    "make_eval_fn",
    "stack_graphs",
    "stack_targets",
    "save_train_state",
    "load_train_state",
    # data pipeline
    "Sample",
    "labelled_dataset",
    "PackedBatchLoader",
    "TrainBatch",
    "pack_targets",
    "epoch_permutation",
    "structure_needs",
    # cost-model packing (train/packing.py)
    "CostCensus",
    "assign_tiers",
    "default_cost",
    "model_cost_fn",
    "plan_epoch",
    "plan_epoch_naive",
    "predicted_plan_waste",
    "tier_caps",
    # step
    "TrainConfig",
    "TrainState",
    "init_train_state",
    "make_accum_train_step",
    "make_packed_loss_fn",
    "make_eval_step",
    "resolve_zero1",
    # loop + checkpointing
    "Trainer",
    "estimate_step_peak_bytes",
    "TrainCheckpointer",
    "latest_checkpoint",
]
