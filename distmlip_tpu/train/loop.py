"""Trainer: the epoch/step loop tying the subsystem together.

``Trainer`` owns the loader (deterministic, prefetching), the jitted
accumulated step, periodic held-out eval (on the EMA weights), resumable
async checkpoints, best-model tracking, and per-step telemetry
(:class:`~distmlip_tpu.telemetry.TrainRecord` riding the shared sinks).

Memory-aware micro-batch sizing: before ANY compile, the candidate step
program is abstractly traced and run through the static HBM planner
(``analysis.memory.analyze_memory`` — the PR 9 machinery), with the
donated ``TrainState`` buffers marked reusable. ``micro_batch_size="auto"``
walks power-of-two candidates downward and picks the largest whose
estimated per-device peak fits ``hbm_budget_frac`` of the budget; an
explicit micro-batch size is still CHECKED and rejected up front when its
estimate exceeds the budget — the OOM surfaces as a ValueError naming the
estimate, not as a dead chip 40 minutes into a run.
"""

from __future__ import annotations

import math
import time

import jax
import numpy as np

from ..analysis.memory import analyze_memory
from ..telemetry import TrainRecord
from ..utils.memory import device_bytes_limit
from .checkpoint import TrainCheckpointer
from .data import PackedBatchLoader
from .step import (TrainConfig, init_train_state, make_accum_train_step,
                   make_eval_step)


def estimate_step_peak_bytes(step_fn, state, batch) -> int:
    """Static per-device peak estimate of one train-step dispatch: trace
    abstractly (no compile, no chip), mark the donated state's buffers
    reusable, run the buffer-liveness planner."""
    jaxpr = jax.make_jaxpr(step_fn)(state, batch.graphs, batch.targets)
    n_args = len(jaxpr.jaxpr.invars)
    donated = np.zeros(n_args, dtype=bool)
    donated[:len(jax.tree.leaves(state))] = True
    return analyze_memory(jaxpr, donated=donated).peak_bytes


class Trainer:
    """End-to-end training over a labeled dataset of structures.

    Parameters
    ----------
    model_energy_fn, params, optimizer:
        the model's per-shard energy function, its initial parameters
        (master fp32 copies are made), and an optax optimizer — any
        transformation off-mesh; when ZeRO-1 shards the state it must be
        ELEMENTWISE (adam/sgd family; see
        :func:`distmlip_tpu.train.step.resolve_zero1` — global-norm
        clipping belongs in ``TrainConfig.clip_norm``, not the chain).
    samples:
        ``list[train.data.Sample]`` training set.
    cutoff:
        neighbor cutoff for the packed graphs (model cutoff).
    micro_batch_size:
        structures per micro-batch, or ``"auto"`` (fit the HBM budget).
    config:
        :class:`TrainConfig` — loss weights, precision, accumulation,
        clipping, loss-scale dynamics, ZeRO-1 policy.
    mesh:
        2-D device mesh for (batch x spatial) placement of every pack;
        None = single device.
    val_samples / eval_every:
        held-out set and eval cadence in optimizer steps (0 = once per
        epoch). Eval runs on the EMA weights when EMA is enabled.
    checkpoint_dir / checkpoint_every:
        resumable async checkpoints (0 = once per epoch); best-model
        tracking keys on the eval loss.
    hbm_budget_bytes / hbm_budget_frac:
        per-device budget for the static planner gate (default: the
        backend-reported limit; no limit and no explicit budget =>
        the gate is skipped, e.g. CPU test runs).
    """

    def __init__(self, model_energy_fn, params, optimizer, samples,
                 cutoff: float, *, micro_batch_size="auto",
                 config: TrainConfig = TrainConfig(), mesh=None,
                 val_samples=None, eval_every: int = 0,
                 checkpoint_dir: str | None = None,
                 checkpoint_every: int = 0, keep_checkpoints: int = 3,
                 hbm_budget_bytes: int | None = None,
                 hbm_budget_frac: float = 0.8, telemetry=None,
                 seed: int = 0, kernels=None, loader_kwargs: dict | None = None):
        self.config = config
        self.mesh = mesh
        self.telemetry = telemetry
        self.eval_every = int(eval_every)
        self.checkpoint_every = int(checkpoint_every)
        self.history: list[dict] = []
        self.best_val: float | None = None
        lk = dict(loader_kwargs or {})
        lk.setdefault("seed", seed)
        lk.setdefault("accum_steps", config.accum_steps)

        self.state = init_train_state(optimizer, params, mesh, config,
                                      seed=seed)
        self.step_fn = make_accum_train_step(model_energy_fn, optimizer,
                                             mesh, config, kernels=kernels)
        self.eval_fn = make_eval_step(model_energy_fn, mesh, config,
                                      kernels=kernels)

        budget = hbm_budget_bytes
        if budget is None:
            budget = device_bytes_limit()
        self.hbm_budget_bytes = budget
        self.est_peak_bytes = 0
        self.tier_peak_bytes: dict[int, int] = {}
        self.loader = self._size_loader(samples, cutoff, micro_batch_size,
                                        budget, hbm_budget_frac, lk)

        self._val_batch = (self.loader.eval_batch(val_samples)
                          if val_samples else None)
        self.checkpointer = (TrainCheckpointer(checkpoint_dir,
                                               keep=keep_checkpoints)
                             if checkpoint_dir else None)

    # ---- memory-aware micro-batch sizing ----

    def _probe_loader(self, samples, cutoff, B, lk, needs):
        lk = dict(lk)
        # a caller may hand a precomputed dataset census through
        # loader_kwargs (e.g. bench.py's naive-vs-cost-model A/B shares
        # one census across two Trainers); an in-sizing-loop census from
        # a previous candidate wins — both are the same dataset property
        needs = needs if needs is not None else lk.pop(
            "precomputed_needs", None)
        lk.pop("precomputed_needs", None)
        return PackedBatchLoader(samples, cutoff, micro_batch_size=B,
                                 precomputed_needs=needs, **lk)

    def _size_loader(self, samples, cutoff, micro_batch_size, budget,
                     frac, lk) -> PackedBatchLoader:
        accum = int(lk.get("accum_steps", 1))
        max_b = max(len(samples) // max(accum, 1), 1)
        # needs are a property of the DATASET, not the batch size —
        # compute once, share across candidate loaders
        probe = None
        needs = None
        if micro_batch_size == "auto":
            b = 1 << int(math.floor(math.log2(max_b)))
            candidates = []
            while b >= 1:
                candidates.append(b)
                b //= 2
        else:
            b = int(micro_batch_size)
            if b > max_b:
                raise ValueError(
                    f"micro_batch_size={b} needs {b * accum} structures "
                    f"per optimizer step but the dataset has "
                    f"{len(samples)}")
            candidates = [b]
        last_est = None
        for b in candidates:
            probe = self._probe_loader(samples, cutoff, b, lk, needs)
            needs = probe.needs
            if budget is None:
                # no limit to gate against (CPU entry point, no explicit
                # budget): take the first candidate, record the estimate
                self.est_peak_bytes = self._estimate(probe)
                return probe
            last_est = self._estimate(probe)
            if last_est <= frac * budget:
                self.est_peak_bytes = last_est
                return probe
            probe.close()
        raise ValueError(
            f"no micro-batch size from {candidates} fits the HBM budget: "
            f"smallest candidate estimates {last_est / 2**20:.1f} MiB "
            f"per device vs budget {frac * budget / 2**20:.1f} MiB "
            f"({frac:.0%} of {budget / 2**30:.2f} GiB) — shrink the "
            f"model/accumulation window or raise hbm_budget_frac")

    def _estimate(self, loader) -> int:
        # price EVERY frozen capacity tier up front (cost-model packing
        # compiles one executable per tier; each must fit the budget, and
        # the gate compares against the most expensive one). The naive
        # loader reports a single tier {0: 0}.
        self.tier_peak_bytes = {}
        for tier, step in sorted(loader.tier_first_steps().items()):
            batch = loader._build(0, step)
            self.tier_peak_bytes[tier] = estimate_step_peak_bytes(
                self.step_fn, self.state, batch)
        return max(self.tier_peak_bytes.values())

    @property
    def compile_count(self) -> int:
        """Train-step executables compiled so far (jit cache entries) —
        pinned <= ``loader.num_tiers`` for the whole run (every tier's
        shapes are frozen; -1 when the jit internals are unavailable)."""
        try:
            return int(self.step_fn._cache_size())
        except Exception:  # noqa: BLE001 - introspection-only surface
            return -1

    # ---- the loop ----

    @property
    def steps_per_epoch(self) -> int:
        return self.loader.steps_per_epoch

    def train_step(self) -> dict:
        """One optimizer step: next batch -> jitted step -> telemetry.
        Returns the host metrics dict (floats)."""
        t0 = time.perf_counter()
        batch = self.loader.next_batch()
        t_data = time.perf_counter() - t0
        cc0 = self.compile_count
        self.state, metrics = self.step_fn(self.state, batch.graphs,
                                           batch.targets)
        m = {k: float(v) for k, v in metrics.items()}  # blocks on device
        dt = time.perf_counter() - t0
        # compile telemetry: a grown jit cache means THIS dispatch traced
        # and compiled a new per-tier executable (wall includes the first
        # execution — indistinguishable at this layer)
        compile_s, compile_kind = 0.0, ""
        if cc0 >= 0 and self.compile_count > cc0:
            from ..obs import profiling as _profiling

            compile_s = dt - t_data
            compile_kind = _profiling.KIND_FRESH
            _profiling.record_compile(
                site="train_step", kind=compile_kind, wall_s=compile_s,
                bucket_key=batch.meta.get(
                    "bucket_key", f"tier={batch.meta.get('tier', 0)}"))
        epoch = int(batch.meta.get("epoch", 0))
        step_no = int(m.pop("step"))
        # cadence keys on the APPLIED-step transition: a nonfinite-skipped
        # step leaves step_no unchanged, and re-firing eval/checkpoint on
        # every retry of the same applied step would hammer exactly the
        # run that is already struggling
        advanced = not m["skipped"]
        tier = int(batch.meta.get("tier", 0))
        m.update(epoch=epoch, examples_per_sec=(
            batch.meta.get("n_structures", 0) / max(dt, 1e-9)),
            tier=tier,
            padding_waste_frac=batch.meta.get("padding_waste_frac", 0.0),
            edge_balance=batch.meta.get("edge_balance", 1.0))

        if self._val_batch is not None and self._due(step_no, batch,
                                                     self.eval_every,
                                                     advanced):
            val = self.evaluate()
            m["val_loss"] = val["loss"]
            if self.checkpointer is not None:
                if self.checkpointer.save_best(self.state, val["loss"],
                                               self.loader.state()):
                    self.best_val = val["loss"]
        if self.checkpointer is not None and self._due(
                step_no, batch, self.checkpoint_every, advanced):
            self.checkpointer.save(self.state, self.loader.state(),
                                   step=step_no)

        if self.telemetry is not None:
            # per-tier executables are priced separately; report the one
            # THIS step dispatched (falling back to the run max) and
            # derive headroom from the SAME estimate so the record stays
            # self-consistent (record.py: 1 - est_peak_bytes / limit)
            tier_est = self.tier_peak_bytes.get(tier, self.est_peak_bytes)
            rec = TrainRecord(
                step=step_no, epoch=epoch,
                timings={"data_s": t_data, "device_s": dt - t_data,
                         "total_s": dt},
                loss=m["loss"], loss_energy=m["energy"],
                loss_force=m["force"], loss_stress=m["stress"],
                val_loss=m.get("val_loss", float("nan")),
                grad_norm=m["grad_norm"], loss_scale=m["loss_scale"],
                skipped=bool(m["skipped"]),
                accum_steps=self.config.accum_steps,
                micro_batch_size=self.loader.micro_batch_size,
                examples_per_sec=m["examples_per_sec"],
                batch_size=batch.meta.get("n_structures", 0),
                n_atoms=batch.meta.get("n_atoms", 0),
                bucket_key=batch.meta.get("bucket_key", ""),
                tier=tier,
                padding_waste_frac=m["padding_waste_frac"],
                edge_balance=m["edge_balance"],
                est_peak_bytes=tier_est,
                hbm_headroom_frac=(
                    1.0 - tier_est / self.hbm_budget_bytes
                    if self.hbm_budget_bytes and tier_est
                    else 0.0),
                compile_s=compile_s,
                compile_kind=compile_kind,
                compiled=bool(compile_kind),
            )
            if self.mesh is not None:
                from ..parallel.mesh import mesh_shape

                bp, sp = mesh_shape(self.mesh)
                rec.mesh_shape = [bp, sp]
                rec.batch_parts, rec.spatial_parts = bp, sp
            self.telemetry.emit(rec)
        self.history.append(m)
        return m

    def _due(self, step_no: int, batch, every: int,
             advanced: bool) -> bool:
        if every > 0:
            # fire once per applied-step TRANSITION (skipped steps repeat
            # the same step_no and must not re-fire)
            return advanced and step_no > 0 and step_no % every == 0
        # per-epoch cadence: fire on the last batch of each epoch (the
        # batch cursor advances even on skipped steps, so this fires once
        # per epoch position)
        return batch.meta.get("step", -1) == self.loader.steps_per_epoch - 1

    def fit(self, epochs: int = 1, steps: int | None = None) -> list[dict]:
        """Run ``steps`` optimizer steps (default: ``epochs`` full passes).
        Returns the per-step metrics history (cumulative across calls)."""
        total = (int(steps) if steps is not None
                 else int(epochs) * self.steps_per_epoch)
        for _ in range(total):
            self.train_step()
        if self.checkpointer is not None:
            self.checkpointer.wait()
        return self.history

    def evaluate(self) -> dict:
        """Held-out loss components on the EMA weights (master weights
        when EMA is off)."""
        if self._val_batch is None:
            raise ValueError("Trainer was built without val_samples")
        params = (self.state.ema_params if self.config.ema_decay > 0.0
                  else self.state.params)
        comps = self.eval_fn(params, self._val_batch.graphs,
                             self._val_batch.targets)
        return {k: float(v) for k, v in comps.items()}

    # ---- checkpoint plumbing ----

    def save_checkpoint(self) -> str:
        if self.checkpointer is None:
            raise ValueError("Trainer was built without checkpoint_dir")
        path = self.checkpointer.save(self.state, self.loader.state())
        self.checkpointer.wait()
        return path

    def restore(self, path: str | None = None) -> int:
        """Resume from ``path`` (default: newest checkpoint): restores the
        full TrainState AND the loader cursor — training continues
        bitwise as if never interrupted. Returns the restored step."""
        if self.checkpointer is None:
            raise ValueError("Trainer was built without checkpoint_dir")
        state, loader_state = self.checkpointer.restore(self.state, path)
        self.state = state
        self.loader.set_state(loader_state)
        return int(state.step)

    def close(self) -> None:
        self.loader.close()
        if self.checkpointer is not None:
            self.checkpointer.wait()
