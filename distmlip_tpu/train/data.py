"""Training data pipeline: labeled structures -> packed, prefetched batches.

The block-diagonal packer (PR 3) is exactly the right substrate for
variable-size molecular training data (cf. arXiv 2504.10700 on data
distribution for MACE training): every micro-batch packs ``B`` structures
into ONE padded super-graph, so a whole micro-batch moves through the
device as one program. This module adds the training-specific layers on
top:

- **deterministic seeded shuffling** — the epoch order is a pure function
  of ``(seed, epoch)`` (:func:`epoch_permutation`), so a resumed run
  replays the EXACT stream an uninterrupted run would have seen (the
  bitwise-resume contract in tests/test_train_subsystem.py);
- **shape-stable bucketing** — the training set is enumerable up front
  (unlike a serving stream), so the loader precomputes the worst-case
  micro-batch capacities once (:func:`partition.fixed_caps_for_batches`)
  and packs EVERY batch of every epoch at those frozen shapes: one step
  executable per accumulation window for the whole run, under the same
  logarithmic ladder quantization serving uses;
- **cost-model packing** (``packing="cost_model"``) — on long-tail size
  distributions ONE frozen worst case pays the 99th-percentile padding on
  every step, so the loader can instead census per-structure cost from
  the analytic FLOP model (edges are the unit of work), cluster the cost
  histogram into 2–3 frozen capacity TIERS (train/packing.py), and
  bin-pack each epoch so total edges balance across micro-batches and
  mesh batch rows. Compile count stays pinned at <= the tier count; the
  cursor grows a (derived) tier coordinate and resume stays bitwise —
  the epoch plan is a pure function of ``(seed, epoch)``;
- **target packing** — energies/forces/stresses land in the padded local
  layout of the graph they train against (owned-row force masks via
  ``atom_slots``; strain-gradient stress slots via ``structure_slots``);
- **host-side prefetch** — a double-buffered background builder thread
  overlaps neighbor lists + packing of batch k+1 with the device step on
  batch k. No wallclock enters the jitted program; the loader hands the
  step plain arrays.

The loader's cursor (``state()``/``set_state()``) is three integers —
(seed, epoch, step) — which is what makes mid-epoch checkpoint resume
bitwise (train/checkpoint.py persists it next to the model state).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, NamedTuple

import numpy as np

from ..neighbors import neighbor_list
from ..partition import (BucketPolicy, bucket_key, fixed_caps_for_batches,
                         pack_structures)
from ..partition.partitioner import build_plan
from .packing import CostCensus, assign_tiers, plan_epoch, tier_caps


class Sample(NamedTuple):
    """One labeled structure: geometry + regression targets."""

    atoms: Any                 # calculators.Atoms (positions/cell/pbc/numbers)
    energy: float              # total energy (eV)
    forces: np.ndarray         # (n, 3) eV/Å
    stress: np.ndarray | None = None  # (3, 3) eV/Å^3, optional


def labelled_dataset(structures, energies, forces, stresses=None):
    """Zip parallel lists into a ``list[Sample]`` dataset."""
    if stresses is None:
        stresses = [None] * len(structures)
    if not (len(structures) == len(energies) == len(forces)
            == len(stresses)):
        raise ValueError(
            f"dataset lists disagree: {len(structures)} structures, "
            f"{len(energies)} energies, {len(forces)} forces, "
            f"{len(stresses)} stresses")
    return [Sample(a, float(e), np.asarray(f), s)
            for a, e, f, s in zip(structures, energies, forces, stresses)]


def epoch_permutation(n: int, seed: int, epoch: int) -> np.ndarray:
    """The deterministic visit order of epoch ``epoch``: a pure function
    of (seed, epoch) — no hidden generator state — so any consumer
    (loader, resume, tests) recomputes the identical permutation."""
    return np.random.default_rng([int(seed), int(epoch)]).permutation(n)


def structure_needs(atoms_list, cutoff: float, bond_cutoff: float = 0.0,
                    use_bond_graph: bool = False,
                    num_threads=None) -> list[dict]:
    """Per-structure capacity needs (single-partition plan counts) — the
    dataset census the frozen-cap AND cost-model packers both build from.
    Module-level so tools (pack_audit) can census a dataset without
    constructing a loader."""
    needs = []
    b_r = bond_cutoff if use_bond_graph else 0.0
    for a in atoms_list:
        nl = neighbor_list(a.positions, a.cell, a.pbc, cutoff,
                           bond_r=b_r, num_threads=num_threads)
        plan = build_plan(nl, a.cell, a.pbc, 1, cutoff, b_r,
                          use_bond_graph)
        need = {"nodes": len(a.positions),
                "edges": len(plan.src_local[0])}
        if use_bond_graph:
            need.update(
                bonds=int(plan.bond_markers[0][-1]),
                lines=len(plan.line_src[0]),
                bond_map=len(plan.bond_mapping_edge[0]))
        needs.append(need)
    return needs


@dataclass
class TrainBatch:
    """One optimizer step's worth of data: ``accum_steps`` stacked packed
    micro-batches. ``graphs``/``targets`` pytree leaves carry a leading
    accumulation axis A — ``lax.scan`` food for the accumulated step."""

    graphs: Any                # stacked PartitionedGraph pytree (A, ...)
    targets: Any               # stacked target dict (A, ...)
    meta: dict = field(default_factory=dict)


def pack_targets(graph, host, samples, dtype=np.float32) -> dict:
    """Pack per-structure targets into ``graph``'s padded local layout.

    Returns the target pytree the packed loss (train/step.py) consumes:

    - ``energy`` (B_total,): per-slot total energies (0 on empty slots);
    - ``forces`` (P, N_cap, 3): owned-row force targets, packed exactly
      like positions (halo/padded rows 0);
    - ``atom_slot`` (P, N_cap) int32: each row's flat energy slot, with
      the B_total sentinel on halo/padded rows — the loss derives its
      owned-row force mask AND the per-structure 1/(3n) normalization
      from this one array;
    - ``n_atoms`` (B_total,): real atoms per slot (1 on empty slots so
      divisions stay finite; the mask zeroes their contribution);
    - ``struct_mask`` (B_total,): 1.0 on slots holding a real structure;
    - ``stress`` (B_total, 3, 3) + ``inv_volume`` (B_total,): present
      only when EVERY sample carries a stress target (the runtime's
      strain gradient divides by volume per structure).
    """
    B_total = max(graph.batch_parts, 1) * graph.batch_size
    slots = host.structure_slots
    energy = np.zeros(B_total, dtype=dtype)
    n_atoms = np.ones(B_total, dtype=dtype)
    struct_mask = np.zeros(B_total, dtype=dtype)
    for i, s in enumerate(samples):
        energy[slots[i]] = s.energy
        n_atoms[slots[i]] = max(len(s.forces), 1)
        struct_mask[slots[i]] = 1.0
    targets = {
        "energy": energy,
        "forces": host.scatter_per_atom([s.forces for s in samples],
                                        dtype=dtype),
        "atom_slot": host.atom_slots(),
        "n_atoms": n_atoms,
        "struct_mask": struct_mask,
    }
    if all(s.stress is not None for s in samples):
        stress = np.zeros((B_total, 3, 3), dtype=dtype)
        inv_vol = np.zeros(B_total, dtype=dtype)
        for i, s in enumerate(samples):
            stress[slots[i]] = s.stress
            inv_vol[slots[i]] = 1.0 / max(float(host.volumes[i]), 1e-12)
        targets["stress"] = stress
        targets["inv_volume"] = inv_vol
    return targets


def _stack_host(trees):
    """Stack a list of identically-shaped pytrees along a new leading
    axis, on the HOST (numpy — the loader thread never touches a device;
    jit moves the result once, when the step consumes it)."""
    import jax

    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                        *trees)


class PackedBatchLoader:
    """Deterministic, resumable, prefetching loader of packed train batches.

    Each :meth:`next_batch` returns one :class:`TrainBatch`: ``accum_steps``
    micro-batches of ``micro_batch_size`` structures, each packed
    block-diagonally (``pack_structures``) at FROZEN worst-case capacities
    so every batch of the run shares one executable, stacked along a
    leading scan axis. Epoch order is :func:`epoch_permutation`; tail
    structures that don't fill a full accumulation window are dropped
    (shape stability — grad-accumulation parity needs equal-B windows).

    ``batch_parts``/``spatial_parts`` select the 2-D mesh placement of
    every pack. With ``spatial_parts == 1`` (the data-parallel training
    regime) shapes are frozen via :func:`fixed_caps_for_batches`; spatial
    slab packing falls back to the shared geometric ladder (slab halos
    make the worst-case pre-computation structure-dependent), which keeps
    compiles logarithmic rather than exactly one.

    ``packing`` selects the micro-batch assembly policy:

    - ``"naive"`` (default, the PR 10 behavior): contiguous permutation
      slices packed at ONE frozen worst-case capacity set;
    - ``"cost_model"``: the train/packing.py pipeline — per-structure
      cost census (``cost_fn``; default edge count, or
      :func:`~distmlip_tpu.train.packing.model_cost_fn` for the analytic
      FLOP model), up to ``num_tiers`` frozen capacity tiers clustered
      from the cost histogram, and seed-stable edge-balanced bin-packing
      per epoch. Every accumulation window stays within one tier, so the
      run compiles at most ``num_tiers`` step executables.

    The cursor is ``state() -> {"seed", "epoch", "step"[, "tier"]}`` (the
    tier coordinate is DERIVED from the plan — recorded for validation
    and observability, not an independent degree of freedom);
    ``set_state`` repositions the stream EXACTLY (the prefetcher restarts
    from the new cursor). ``close()`` stops the background builder.
    """

    def __init__(self, samples, cutoff: float, micro_batch_size: int,
                 accum_steps: int = 1, bond_cutoff: float = 0.0,
                 use_bond_graph: bool = False, caps=None, species_fn=None,
                 seed: int = 0, shuffle: bool = True, batch_parts: int = 1,
                 spatial_parts: int = 1, system: dict | None = None,
                 num_threads: int | None = None, prefetch: int = 2,
                 dtype=np.float32, precomputed_needs=None,
                 packing: str = "naive", num_tiers: int = 2,
                 cost_fn=None):
        if not samples:
            raise ValueError("PackedBatchLoader needs at least one sample")
        B, A = int(micro_batch_size), int(accum_steps)
        if B < 1 or A < 1:
            raise ValueError(
                f"micro_batch_size/accum_steps must be >= 1, got {B}/{A}")
        if len(samples) < B * A:
            raise ValueError(
                f"dataset has {len(samples)} structures but one optimizer "
                f"step consumes micro_batch_size * accum_steps = {B * A}")
        self.samples = list(samples)
        self.cutoff = float(cutoff)
        self.bond_cutoff = float(bond_cutoff)
        self.use_bond_graph = bool(use_bond_graph)
        self.micro_batch_size = B
        self.accum_steps = A
        self.species_fn = species_fn
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.batch_parts = int(batch_parts)
        self.spatial_parts = int(spatial_parts)
        self.system = system
        self.num_threads = num_threads
        self.dtype = dtype
        self._epoch = 0
        self._step = 0
        if packing not in ("naive", "cost_model"):
            raise ValueError(
                f"packing must be 'naive' or 'cost_model', got {packing!r}")
        self.packing = packing
        ladder = caps or BucketPolicy()
        # per-structure capacity needs: computed once (or handed in by a
        # caller probing several micro-batch sizes over one dataset —
        # Trainer's memory-aware auto-sizing) and frozen into the caps
        self.needs = precomputed_needs
        self.census = None
        self.tier_of = None
        self.tier_caps = {}
        # the prefetch thread (building ahead) and the consumer (cursor/
        # state queries) both read this cache; plans are deterministic so
        # duplicate computation is benign, but eviction needs the lock
        self._plan_cache: dict[int, list] = {}
        self._plan_lock = threading.Lock()
        if packing == "cost_model":
            if self.spatial_parts != 1:
                raise ValueError(
                    "packing='cost_model' needs spatial_parts == 1 (slab "
                    "halos make frozen per-tier capacities structure-"
                    "dependent; use the geometric ladder for spatial "
                    "training)")
            if self.needs is None:
                self.needs = self.structure_needs()
            self.census = CostCensus.from_needs(self.needs, cost_fn)
            # every tier must fill at least one whole accumulation window
            self.tier_of, self.tier_thresholds = assign_tiers(
                self.census.costs, num_tiers, min_members=B * A)
            self.tier_caps = tier_caps(self.needs, self.tier_of, B,
                                       self.batch_parts, policy=ladder,
                                       accum_steps=A,
                                       costs=self.census.costs)
            # eval packs (arbitrary held-out subsets, outside the plan's
            # round guarantee) keep the dataset-wide worst-case caps the
            # naive loader uses — eval compiles its own program anyway
            self.caps = fixed_caps_for_batches(
                self.needs, -(-B // self.batch_parts), policy=ladder)
        elif self.spatial_parts == 1:
            if self.needs is None:
                self.needs = self.structure_needs()
            self.caps = fixed_caps_for_batches(
                self.needs,
                -(-B // self.batch_parts),  # per batch shard
                policy=ladder)
        else:
            self.caps = ladder
        self._depth = max(int(prefetch), 0)
        self._prefetcher = None

    # ---- capacity planning ----

    def structure_needs(self) -> list[dict]:
        """Per-structure capacity needs (single-partition plan counts) —
        computed ONCE at loader construction to freeze the run's shapes."""
        return structure_needs([s.atoms for s in self.samples], self.cutoff,
                               self.bond_cutoff, self.use_bond_graph,
                               self.num_threads)

    # ---- the per-epoch packing plan (cost-model path) ----

    def epoch_plan(self, epoch: int) -> list:
        """The epoch's deterministic packing plan (cost-model packing
        only) — a pure function of ``(seed, epoch)``, cached for the
        couple of epochs the prefetcher may straddle."""
        if self.packing != "cost_model":
            raise ValueError("epoch_plan is only defined under "
                             "packing='cost_model'")
        with self._plan_lock:
            plan = self._plan_cache.get(epoch)
        if plan is None:
            plan = plan_epoch(
                self.census.costs, self.tier_of, seed=self.seed,
                epoch=epoch, micro_batch_size=self.micro_batch_size,
                accum_steps=self.accum_steps,
                batch_parts=self.batch_parts, shuffle=self.shuffle)
            with self._plan_lock:
                self._plan_cache[epoch] = plan
                while len(self._plan_cache) > 4:
                    del self._plan_cache[min(self._plan_cache)]
        return plan

    @property
    def num_tiers(self) -> int:
        """Distinct frozen capacity tiers (1 under naive packing) — the
        whole run's train-step compile count is bounded by this."""
        return len(self.tier_caps) if self.packing == "cost_model" else 1

    def tier_first_steps(self, epoch: int = 0) -> dict:
        """{tier: first step index of ``epoch`` running that tier} — the
        Trainer prices each tier's executable through the HBM planner by
        building exactly these steps."""
        if self.packing != "cost_model":
            return {0: 0}
        firsts: dict[int, int] = {}
        for i, step in enumerate(self.epoch_plan(epoch)):
            firsts.setdefault(step.tier, i)
        return firsts

    def step_tier(self, epoch: int, step: int) -> int:
        """Tier of the (epoch, step) macro-batch (0 under naive packing)."""
        if self.packing != "cost_model":
            return 0
        plan = self.epoch_plan(epoch)
        if step >= len(plan):  # cursor parked on an epoch boundary
            return self.epoch_plan(epoch + 1)[0].tier
        return plan[step].tier

    # ---- cursor ----

    @property
    def steps_per_epoch(self) -> int:
        if self.packing == "cost_model":
            # per-tier window counts are a function of STATIC tier
            # membership, so this is epoch-independent like the naive path
            B_A = self.micro_batch_size * self.accum_steps
            return sum(int(np.sum(self.tier_of == t)) // B_A
                       for t in self.tier_caps)
        return len(self.samples) // (self.micro_batch_size
                                     * self.accum_steps)

    def state(self) -> dict:
        """The resumable cursor: batches CONSUMED so far (not built —
        prefetched-but-undelivered batches are rebuilt on resume). Under
        cost-model packing the cursor grows a ``tier`` coordinate — the
        tier of the NEXT step, derived from the plan — so a resume can
        validate that it rebuilt the same tiering the checkpoint saw."""
        cur = {"seed": self.seed, "epoch": self._epoch, "step": self._step}
        if self.packing == "cost_model":
            cur["tier"] = self.step_tier(self._epoch, self._step)
        return cur

    def set_state(self, state: dict) -> None:
        self.close()
        self.seed = int(state["seed"])
        self._epoch = int(state["epoch"])
        self._step = int(state["step"])
        with self._plan_lock:
            self._plan_cache.clear()
        if self.packing == "cost_model" and "tier" in state:
            want = int(state["tier"])
            have = self.step_tier(self._epoch, self._step)
            if want != have:
                raise ValueError(
                    f"loader cursor tier mismatch: checkpoint says the "
                    f"next step runs tier {want}, this loader's plan says "
                    f"tier {have} — the dataset, seed, micro-batch size "
                    f"or tier configuration changed since the checkpoint "
                    f"was written (resume would not be bitwise)")

    # ---- batch building ----

    def _order(self, epoch: int) -> np.ndarray:
        if self.shuffle:
            return epoch_permutation(len(self.samples), self.seed, epoch)
        return np.arange(len(self.samples))

    def _micro_indices(self, epoch: int, step: int) -> tuple[int, list]:
        """(tier, [A index-lists]) of the (epoch, step) macro-batch under
        the active packing policy."""
        B, A = self.micro_batch_size, self.accum_steps
        if self.packing == "cost_model":
            macro = self.epoch_plan(epoch)[step]
            return macro.tier, [list(m) for m in macro.micro]
        order = self._order(epoch)
        start = step * B * A
        return 0, [list(order[start + a_i * B:start + (a_i + 1) * B])
                   for a_i in range(A)]

    def _build(self, epoch: int, step: int) -> TrainBatch:
        """Build the (epoch, step) macro-batch — a pure function of the
        cursor, which is the whole resume story."""
        tier, micros = self._micro_indices(epoch, step)
        caps = (self.tier_caps[tier] if self.packing == "cost_model"
                else self.caps)
        graphs, targets = [], []
        n_atoms_total = 0
        wastes, balances, edge_totals = [], [], []
        for idx in micros:
            batch_samples = [self.samples[i] for i in idx]
            graph, host = pack_structures(
                [s.atoms for s in batch_samples], self.cutoff,
                bond_cutoff=self.bond_cutoff,
                use_bond_graph=self.use_bond_graph, caps=caps,
                species_fn=self.species_fn, dtype=self.dtype,
                system=self.system, num_threads=self.num_threads,
                spatial_parts=self.spatial_parts,
                batch_parts=self.batch_parts)
            graphs.append(graph)
            targets.append(pack_targets(graph, host, batch_samples,
                                        dtype=self.dtype))
            n_atoms_total += int(sum(len(s.forces) for s in batch_samples))
            stats = host.stats or {}
            wastes.append(float(stats.get("padding_waste_frac", 0.0)))
            rows = stats.get("n_edges_per_part") or []
            edge_totals.append(float(sum(rows)))
            if rows and max(rows) > 0:
                balances.append(sum(rows) / len(rows) / max(rows))
        # edge balance: rows within each micro-batch AND micro-batches
        # within the window — 1.0 means no device/scan-slot ever waits on
        # a heavier sibling
        balance = min(balances) if balances else 1.0
        if edge_totals and max(edge_totals) > 0:
            balance = min(balance, sum(edge_totals) / len(edge_totals)
                          / max(edge_totals))
        B, A = self.micro_batch_size, self.accum_steps
        return TrainBatch(
            graphs=_stack_host(graphs),
            targets=_stack_host(targets),
            meta={"epoch": epoch, "step": step, "tier": tier,
                  "bucket_key": bucket_key(graphs[0]),
                  "n_structures": B * A, "n_atoms": n_atoms_total,
                  "padding_waste_frac": (sum(wastes) / len(wastes)
                                         if wastes else 0.0),
                  "edge_balance": balance})

    def _advance(self, epoch: int, step: int) -> tuple[int, int]:
        step += 1
        if step >= self.steps_per_epoch:
            return epoch + 1, 0
        return epoch, step

    def next_batch(self) -> TrainBatch:
        """The next macro-batch in cursor order (prefetched when a depth
        was configured); advances the consumed cursor."""
        if self._depth > 0:
            if self._prefetcher is None:
                self._prefetcher = _Prefetcher(
                    self._build, self._advance,
                    (self._epoch, self._step), self._depth)
            batch, nxt = self._prefetcher.get()
        else:
            batch = self._build(self._epoch, self._step)
            nxt = self._advance(self._epoch, self._step)
        self._epoch, self._step = nxt
        return batch

    def eval_batch(self, samples) -> TrainBatch:
        """A single stacked batch (A=1) over ``samples`` — the held-out
        eval surface, packed at the SAME frozen caps as the train stream
        when it fits (no extra executable for eval)."""
        graph, host = pack_structures(
            [s.atoms for s in samples], self.cutoff,
            bond_cutoff=self.bond_cutoff,
            use_bond_graph=self.use_bond_graph, caps=self.caps,
            species_fn=self.species_fn, dtype=self.dtype,
            system=self.system, num_threads=self.num_threads,
            spatial_parts=self.spatial_parts, batch_parts=self.batch_parts)
        targets = pack_targets(graph, host, samples, dtype=self.dtype)
        return TrainBatch(
            graphs=_stack_host([graph]), targets=_stack_host([targets]),
            meta={"bucket_key": bucket_key(graph),
                  "n_structures": len(samples)})

    def close(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.stop()
            self._prefetcher = None

    def __del__(self):  # pragma: no cover - GC ordering
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class _Prefetcher:
    """Double-buffered background batch builder.

    Builds batches from its own cursor into a bounded queue; the consumer
    pops ``(batch, next_cursor)`` pairs in order. A builder exception is
    delivered to the consumer at the matching ``get()`` (not swallowed,
    not fatal to the thread's queue discipline)."""

    def __init__(self, build_fn, advance_fn, cursor, depth: int):
        self._build = build_fn
        self._advance = advance_fn
        self._cursor = cursor
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="distmlip-train-prefetch", daemon=True)
        self._thread.start()

    def _run(self):
        cursor = self._cursor
        while not self._stop.is_set():
            try:
                item = (self._build(*cursor), self._advance(*cursor), None)
            except BaseException as e:  # noqa: BLE001 - delivered at get()
                item = (None, self._advance(*cursor), e)
            cursor = item[1]
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def get(self):
        while True:
            try:
                batch, nxt, err = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    raise RuntimeError(
                        "train prefetch thread died without delivering")
        if err is not None:
            raise err
        return batch, nxt

    def stop(self):
        self._stop.set()
        # unblock a producer stuck on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
