"""Legacy single-program training surface (the historical ``train.py``).

The reference is inference-only (training stays in upstream libraries,
reference README.md:53); here training is first-class: the loss
differentiates through the same sharded potential (halo exchanges included),
so gradients w.r.t. parameters aggregate across partitions with a psum —
graph parallelism doubles as data parallelism over space.

This module is the recipe-sized surface: one jitted step per call, stacked
same-bucket graphs, npz checkpoint of (params, opt_state, step). The full
subsystem — packed-batch data pipeline, gradient accumulation, mixed
precision, ZeRO-1 sharded optimizer state, resumable async checkpoints —
lives in the sibling modules (:mod:`distmlip_tpu.train.data` /
``step`` / ``loop`` / ``checkpoint``); everything here stays supported and
re-exported from :mod:`distmlip_tpu.train`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..parallel.runtime import make_total_energy


def make_loss_fn(model_energy_fn, mesh, w_energy=1.0, w_force=1.0, w_stress=0.0):
    """Loss: (params, graph, positions, targets) -> scalar.

    targets: dict with 'energy' (),
             'forces' (P, N_cap, 3) in the graph's local layout,
             optional 'stress' (3, 3).
    Forces are compared on owned rows only (halo rows belong to a peer).
    """
    total_energy = make_total_energy(model_energy_fn, mesh)

    def loss_fn(params, graph, positions, targets):
        strain = jnp.zeros((3, 3), dtype=positions.dtype)
        if w_force > 0.0 or w_stress > 0.0:
            energy, (g_pos, g_strain) = jax.value_and_grad(
                total_energy, argnums=(2, 3)
            )(params, graph, positions, strain)
            forces = -g_pos
        else:
            energy = total_energy(params, graph, positions, strain)
            forces = None
        n_atoms = jnp.maximum(graph.n_total_nodes.astype(energy.dtype), 1.0)
        loss = w_energy * ((energy - targets["energy"]) / n_atoms) ** 2
        if w_force > 0.0:
            mask = graph.owned_mask[..., None]
            diff = jnp.where(mask, forces - targets["forces"], 0.0)
            loss = loss + w_force * jnp.sum(diff**2) / (3.0 * n_atoms)
        if w_stress > 0.0:
            vol = jnp.abs(jnp.linalg.det(graph.lattice.astype(energy.dtype)))
            stress = g_strain / vol
            loss = loss + w_stress * jnp.mean((stress - targets["stress"]) ** 2)
        return loss

    return loss_fn


def make_train_step(model_energy_fn, mesh, optimizer, w_energy=1.0, w_force=1.0,
                    w_stress=0.0):
    """Jitted SGD/optax step over the sharded loss.

    Returns step(params, opt_state, graph, positions, targets) ->
    (params, opt_state, loss).
    """
    loss_fn = make_loss_fn(model_energy_fn, mesh, w_energy, w_force, w_stress)

    @jax.jit
    def step(params, opt_state, graph, positions, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, graph, positions, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


# ---------------------------------------------------------------------------
# Multi-structure batching (stacked graphs under one capacity bucket)
# ---------------------------------------------------------------------------


def stack_graphs(graphs):
    """Stack same-capacity PartitionedGraphs into one batched pytree.

    All graphs must share capacities (build them with one CapacityPolicy —
    the sticky buckets make equal shapes the common case) and the same
    partition count. The batch axis is leading; use with
    ``make_batched_train_step`` / ``make_eval_fn``, which vmap the whole
    sharded program over it (the same one-program batching the stacked
    ensembles use, calculators/ensemble.py).
    """
    import numpy as np

    # compare the FULL leaf-shape signature (node, edge, bond, halo
    # capacities all matter, not just positions) so mismatches surface as
    # this actionable message, not a raw tree-structure error from stack
    sigs = {tuple(np.shape(x) for x in jax.tree.leaves(g)) for g in graphs}
    if len(sigs) != 1:
        raise ValueError(
            "graphs have mixed array shapes (different capacity buckets); "
            "build them with a shared CapacityPolicy so they land in one "
            f"bucket: {sorted(sigs)[:2]} ...")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *graphs)


def stack_targets(targets):
    """Stack per-structure target dicts along a leading batch axis."""
    return jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                        *targets)


def make_batched_train_step(model_energy_fn, mesh, optimizer, w_energy=1.0,
                            w_force=1.0, w_stress=0.0):
    """Train step over a BATCH of structures: the per-structure loss is
    vmapped over the stacked graphs and averaged, so one jitted program
    moves the whole minibatch per step.

    Returns step(params, opt_state, graphs, positions, targets) ->
    (params, opt_state, loss) with graphs/positions/targets stacked by
    ``stack_graphs`` / ``stack_targets``.
    """
    loss_fn = make_loss_fn(model_energy_fn, mesh, w_energy, w_force, w_stress)

    def batch_loss(params, graphs, positions, targets):
        per = jax.vmap(loss_fn, in_axes=(None, 0, 0, 0))(
            params, graphs, positions, targets)
        return jnp.mean(per)

    @jax.jit
    def step(params, opt_state, graphs, positions, targets):
        loss, grads = jax.value_and_grad(batch_loss)(
            params, graphs, positions, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


def make_eval_fn(model_energy_fn, mesh, w_energy=1.0, w_force=1.0,
                 w_stress=0.0):
    """Held-out evaluation: (params, graphs, positions, targets) -> mean
    loss over a stacked validation batch (no gradient, same loss weights)."""
    loss_fn = make_loss_fn(model_energy_fn, mesh, w_energy, w_force, w_stress)

    @jax.jit
    def evaluate(params, graphs, positions, targets):
        per = jax.vmap(loss_fn, in_axes=(None, 0, 0, 0))(
            params, graphs, positions, targets)
        return jnp.mean(per)

    return evaluate


# ---------------------------------------------------------------------------
# Checkpoint/resume for training runs (params + optimizer state + step)
# ---------------------------------------------------------------------------


def save_train_state(path: str, params, opt_state, step: int) -> None:
    """One npz with the full resumable state (utils/checkpoint format)."""
    from ..utils.checkpoint import save_params

    save_params(path, {"params": params, "opt_state": opt_state,
                       "step": jnp.asarray(step)})


def load_train_state(path: str, params_like, opt_state_like):
    """Restore (params, opt_state, step) saved by save_train_state."""
    from ..utils.checkpoint import load_params

    state = load_params(path, like={"params": params_like,
                                    "opt_state": opt_state_like,
                                    "step": jnp.asarray(0)})
    return state["params"], state["opt_state"], int(state["step"])
