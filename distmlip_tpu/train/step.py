"""The accumulated mixed-precision train step over packed batches.

One optimizer step = ONE jitted program per accumulation window:

- **packed loss** (:func:`make_packed_loss_fn`) — energy/force/stress
  matching against a block-diagonally packed micro-batch through the
  params-differentiable packed energy program
  (``parallel.make_packed_energy_fn``): inner ``value_and_grad`` over
  positions/strain for forces/stress, outer grad over params for the
  update — the same double-differentiation the legacy recipe uses, now
  over B structures at once, normalized per structure (energy per atom²,
  forces per 3n, mean over real slots);
- **mixed precision** — ``precision="bf16"`` pairs with a model built
  with ``cfg.dtype="bfloat16"`` (every model in the zoo supports it):
  the MODEL casts params to bf16 per forward through its own curated
  ``keep_fp32`` list (species references, readout heads, norms stay
  fp32), grad-side gathers accumulate fp32 (``ops.nn.gather_rows``),
  and the step's master weights / grads / optimizer stay fp32
  throughout — the ``dtype_discipline`` contract (fp32 master weights,
  no half-precision scatter accumulation) is pinned by
  ``tools/contract_check.py`` on the traced train program. On the step
  side the knob selects the loss-scale default (2^15);
- **dynamic loss scaling** — the loss is scaled before the backward,
  grads unscaled after accumulation; a nonfinite global grad norm skips
  the update (params, opt state, EMA, step count all unchanged) and
  halves the scale; ``growth_interval`` consecutive finite steps double
  it (capped). bf16 rarely overflows, fp16-style runs and exploding
  losses are absorbed the same way;
- **gradient accumulation** — ``lax.scan`` over the batch's leading
  accumulation axis: grads and loss components sum in fp32 carries, so
  accumulation N with micro-batch B matches the N*B big-batch step to
  fp32 roundoff (asserted in tests);
- **ZeRO-1 optimizer-state sharding** — with a mesh whose batch axis has
  extent Bm > 1, master params and grads ravel to a (Bm, K) layout whose
  rows shard over the batch axis: every batch row updates ITS shard of
  the optimizer state (adam moments never replicate), then one tiled
  ``all_gather`` rebuilds the full parameter vector. Grad reduction
  itself is the shard_map transpose's psum — the checker budget is
  exactly {psum: grads, all_gather: 1} on the batch axis
  (tools/contract_check.py pins it);
- **EMA** — an exponential moving average of the master weights rides
  the state (applied steps only), the standard eval/serving weight set
  for MLIP training.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import BATCH_AXIS, mesh_shape
from ..parallel.runtime import _NO_CHECK, make_packed_energy_fn, shard_map


@dataclass(frozen=True)
class TrainConfig:
    """Knobs of the accumulated step (static: baked into the executable)."""

    w_energy: float = 1.0
    w_force: float = 1.0
    w_stress: float = 0.0
    precision: str = "fp32"          # "fp32" | "bf16" compute (master fp32)
    accum_steps: int = 1             # micro-batches per optimizer step
    clip_norm: float = 0.0           # global-norm clip; 0 disables
    ema_decay: float = 0.999         # EMA of master weights; 0 disables
    zero1: Any = "auto"              # True | False | "auto" (mesh batch > 1)
    loss_scale: float | None = None  # None: 2**15 for bf16, 1.0 for fp32
    scale_growth_interval: int = 2000
    scale_factor: float = 2.0
    max_loss_scale: float = 2.0 ** 24
    min_loss_scale: float = 2.0 ** -14

    def __post_init__(self):
        if self.precision not in ("fp32", "bf16"):
            raise ValueError(
                f"precision must be 'fp32' or 'bf16', got "
                f"{self.precision!r}")
        if self.accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got "
                             f"{self.accum_steps}")

    @property
    def initial_loss_scale(self) -> float:
        if self.loss_scale is not None:
            return float(self.loss_scale)
        return 2.0 ** 15 if self.precision == "bf16" else 1.0


class TrainState(NamedTuple):
    """The full resumable optimizer-step state (a pytree; checkpointed
    whole by train/checkpoint.py, donated whole by the jitted step)."""

    params: Any          # fp32 master weights
    opt_state: Any       # optax state; ZeRO-1: (Bm, K) leaves batch-sharded
    step: Any            # () int32 — APPLIED optimizer steps
    ema_params: Any      # EMA of master weights (== params when disabled)
    loss_scale: Any      # () float32 dynamic loss scale
    good_steps: Any      # () int32 finite steps since last scale change
    rng: Any             # jax PRNG key (reserved for stochastic models)


def resolve_zero1(config: TrainConfig, mesh) -> bool:
    """ZeRO-1 is on when requested, or by default whenever the mesh has a
    batch axis of extent > 1 (sharding over a 1-row axis is a no-op that
    still pays the program plumbing).

    CONSTRAINT: the sharded update runs the optax transformation on each
    row's (Bm, K)-raveled shard independently, which reproduces the
    unsharded step exactly ONLY for elementwise transformations (sgd,
    adam/adamw, rmsprop, schedules — the moment/update math never mixes
    parameters). Transformations that couple across the whole pytree
    (optax.clip_by_global_norm in a chain, lamb's trust ratio, adafactor's
    factored moments) would silently compute their statistics per shard —
    pass ``zero1=False`` for those (global-norm clipping is already a
    step-level knob, ``TrainConfig.clip_norm``, applied BEFORE the
    optimizer on the full gradient).
    """
    has_batch = mesh is not None and BATCH_AXIS in mesh.axis_names
    if config.zero1 != "auto":
        if config.zero1 and not has_batch:
            raise ValueError(
                "zero1=True needs a mesh with a named batch axis to shard "
                "over; pass mesh=device_mesh(B, S) (or leave zero1='auto')")
        return bool(config.zero1)
    return has_batch and mesh_shape(mesh)[0] > 1


def global_norm(tree) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32)))
              for x in jax.tree.leaves(tree)]
    return jnp.sqrt(sum(leaves)) if leaves else jnp.float32(0.0)


def make_packed_loss_fn(model_energy_fn, mesh=None,
                        config: TrainConfig = TrainConfig(), kernels=None):
    """Loss over ONE packed micro-batch.

    ``(params, graph, targets) -> (loss, components)`` where ``graph`` is
    a ``pack_structures`` super-graph (placement matching ``mesh``) and
    ``targets`` the matching :func:`distmlip_tpu.train.data.pack_targets`
    pytree. ``components`` is a fixed-structure dict of fp32 scalars
    (total + per-term) so it scans/accumulates. Per-structure
    normalization matches the legacy single-structure loss: energy term
    ((E - E*)/n)², force term |F - F*|²/(3n) over owned rows, stress term
    mean over the 9 components; all averaged over the REAL structures in
    the batch.
    """
    energy_fn = make_packed_energy_fn(model_energy_fn, mesh,
                                     diff_params=True, kernels=kernels)
    w_e = float(config.w_energy)
    w_f = float(config.w_force)
    w_s = float(config.w_stress)

    def loss_fn(params, graph, targets):
        f32 = jnp.float32
        # master weights pass through UNCAST: with precision="bf16" the
        # model's own compute-dtype switch (cfg.dtype="bfloat16") casts
        # per forward under its curated keep_fp32 list — a blind cast
        # here would downcast fp32-pinned readout heads and species
        # references the model zoo deliberately protects
        p_c = params
        positions = graph.positions
        B_total = max(graph.batch_parts, 1) * graph.batch_size
        strain0 = jnp.zeros((B_total, 3, 3), dtype=positions.dtype)

        # ONE forward + one backward via vjp: the per-structure energies
        # feed the loss directly and the ones-cotangent pullback is the
        # force/stress backward — no duplicated primal readout (a second
        # value_and_grad forward would leave a DEAD structure-sum psum in
        # the program; collectives never DCE). The strain input joins the
        # vjp only when stress trains — otherwise its transpose would
        # ship dead edge-offset scatter work every step.
        if w_f > 0.0 and w_s > 0.0:
            energies, pullback = jax.vjp(
                lambda pos, s: energy_fn(p_c, graph, pos, s),
                positions, strain0)
            g_pos, g_strain = pullback(jnp.ones_like(energies))
        elif w_f > 0.0:
            energies, pullback = jax.vjp(
                lambda pos: energy_fn(p_c, graph, pos, strain0), positions)
            (g_pos,) = pullback(jnp.ones_like(energies))
            g_strain = None
        elif w_s > 0.0:
            energies, pullback = jax.vjp(
                lambda s: energy_fn(p_c, graph, positions, s), strain0)
            (g_strain,) = pullback(jnp.ones_like(energies))
            g_pos = None
        else:
            energies = energy_fn(p_c, graph, positions, strain0)
            g_pos = g_strain = None

        struct_mask = targets["struct_mask"].astype(f32)
        n_real = jnp.maximum(jnp.sum(struct_mask), 1.0)
        n_atoms = targets["n_atoms"].astype(f32)
        energies = energies.astype(f32)

        e_diff = (energies - targets["energy"].astype(f32)) / n_atoms
        e_term = jnp.sum(struct_mask * e_diff * e_diff) / n_real
        zero = jnp.float32(0.0)
        f_term = s_term = zero
        if w_f > 0.0:
            # owned & real rows carry their structure's flat slot; halo and
            # padded rows carry the B_total sentinel -> weight 0
            slot = targets["atom_slot"]
            owned = slot < B_total
            n_ext = jnp.concatenate([n_atoms, jnp.ones((1,), f32)])
            w_atom = jnp.where(owned, 1.0 / (3.0 * n_ext[slot]), 0.0)
            d = (-g_pos).astype(f32) - targets["forces"].astype(f32)
            f_term = jnp.sum(w_atom[..., None] * d * d) / n_real
        if w_s > 0.0:
            if "stress" not in targets:
                raise ValueError(
                    "w_stress > 0 but the batch carries no stress targets "
                    "(give every Sample a stress, or set w_stress=0)")
            stress = (g_strain.astype(f32)
                      * targets["inv_volume"].astype(f32)[:, None, None])
            ds = stress - targets["stress"].astype(f32)
            s_term = jnp.sum(
                struct_mask[:, None, None] * ds * ds) / (9.0 * n_real)
        loss = w_e * e_term + w_f * f_term + w_s * s_term
        comps = {"loss": loss, "energy": e_term, "force": f_term,
                 "stress": s_term}
        return loss, comps

    return loss_fn


def init_train_state(optimizer, params, mesh=None,
                     config: TrainConfig = TrainConfig(),
                     seed: int = 0) -> TrainState:
    """Fresh state: fp32 master weights, optimizer state (ZeRO-1 layout
    when the placement shards it), EMA mirror, initial loss scale.

    The master weights are COPIES of ``params``: the jitted step donates
    the whole TrainState, and aliasing the caller's arrays into it would
    delete the caller's buffers on the first step (a no-op astype returns
    the same buffer)."""
    params = jax.tree.map(
        lambda x: jnp.array(x, dtype=jnp.float32)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
        else jnp.array(x), params)
    if resolve_zero1(config, mesh):
        flat, _ = ravel_pytree(params)
        bm = mesh_shape(mesh)[0]
        k = -(-flat.size // bm)
        opt_state = optimizer.init(jnp.zeros((bm, k), dtype=flat.dtype))
    else:
        opt_state = optimizer.init(params)
    return TrainState(
        params=params,
        opt_state=opt_state,
        step=jnp.int32(0),
        ema_params=jax.tree.map(jnp.array, params),
        loss_scale=jnp.float32(config.initial_loss_scale),
        good_steps=jnp.int32(0),
        rng=jax.random.PRNGKey(seed),
    )


def _plain_apply(optimizer, grads, opt_state, params):
    # optax's bias-correction scalars (b1 ** count) promote to f64 ONLY
    # under the checker's x64 tracing regime; the runtime's default config
    # keeps the whole update fp32 (audited — tests assert default-config
    # update dtypes are pure fp32)
    # contract: allow(dtype_discipline)
    updates, new_opt = optimizer.update(grads, opt_state, params)
    new_params = jax.tree.map(lambda p, u: p + u, params, updates)
    return new_params, new_opt


def _zero1_apply(optimizer, mesh, grads, opt_state, params):
    """ZeRO-1 sharded update: each batch row owns rows of the (Bm, K)
    raveled master/grad/opt-state layout, updates its shard, and ONE
    tiled all_gather over the batch axis rebuilds the full params."""
    bm = mesh_shape(mesh)[0]
    flat_g, _ = ravel_pytree(grads)
    flat_p, unravel = ravel_pytree(params)
    n = flat_p.size
    k = -(-n // bm)
    pad = bm * k - n
    g2 = jnp.pad(flat_g, (0, pad)).reshape(bm, k)
    p2 = jnp.pad(flat_p, (0, pad)).reshape(bm, k)

    def shard_spec(x):
        return (P(BATCH_AXIS) if getattr(x, "ndim", 0) >= 1
                and x.shape[0] == bm else P())

    opt_specs = jax.tree.map(shard_spec, opt_state)

    def shard_update(g, o, p):
        # g/p: (1, K) — this batch row's shard; optax updates are
        # elementwise, so the sharded step IS the unsharded step on rows.
        # (x64-tracing-only f64 scalars: see _plain_apply)
        # contract: allow(dtype_discipline)
        updates, o2 = optimizer.update(g, o, p)
        p_new = p + updates
        full = jax.lax.all_gather(p_new[0], BATCH_AXIS, axis=0, tiled=False)
        return full, o2

    full_p, new_opt = shard_map(
        shard_update, mesh=mesh,
        in_specs=(P(BATCH_AXIS), opt_specs, P(BATCH_AXIS)),
        out_specs=(P(), opt_specs), **_NO_CHECK)(g2, opt_state, p2)
    new_params = unravel(full_p.reshape(-1)[:n])
    return new_params, new_opt


def make_accum_train_step(model_energy_fn, optimizer, mesh=None,
                          config: TrainConfig = TrainConfig(), kernels=None,
                          donate: bool = True):
    """The jitted accumulated step.

    ``step(state, graphs, targets) -> (state, metrics)`` where
    ``graphs``/``targets`` carry a leading accumulation axis A (a
    ``TrainBatch`` from the loader: ``step(state, batch.graphs,
    batch.targets)``). ``metrics`` is a dict of () fp32/int32 device
    scalars: loss (+components), grad_norm (pre-clip), loss_scale,
    skipped, step. ``donate=True`` donates the input state — the caller
    must not reuse it (the loop checkpoints BEFORE stepping).
    """
    loss_fn = make_packed_loss_fn(model_energy_fn, mesh, config, kernels)
    zero1 = resolve_zero1(config, mesh)
    cfg = config

    def step(state, graphs, targets):
        f32 = jnp.float32
        scale = state.loss_scale
        accum = jax.tree.leaves(graphs)[0].shape[0]

        def scaled_loss(params, graph, tgt):
            loss, comps = loss_fn(params, graph, tgt)
            return loss * scale, comps

        grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)
        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, f32), state.params)
        zero_comps = {"loss": f32(0), "energy": f32(0), "force": f32(0),
                      "stress": f32(0)}

        def micro(carry, xs):
            g_acc, c_acc = carry
            graph, tgt = xs
            (_, comps), grads = grad_fn(state.params, graph, tgt)
            g_acc = jax.tree.map(
                lambda a, g: a + g.astype(f32), g_acc, grads)
            c_acc = jax.tree.map(lambda a, c: a + c, c_acc, comps)
            return (g_acc, c_acc), None

        (g_sum, c_sum), _ = jax.lax.scan(
            micro, (zero_grads, zero_comps), (graphs, targets))
        inv = 1.0 / (accum * scale)
        grads = jax.tree.map(lambda g: g * inv, g_sum)
        comps = jax.tree.map(lambda c: c / accum, c_sum)

        gnorm = global_norm(grads)
        finite = jnp.isfinite(gnorm)
        # a nonfinite norm poisons every arithmetic path through the
        # update; zero the grads on skipped steps so the (discarded)
        # update computes on clean values and NaNs can't leak through
        # the selects below via 0 * NaN corner cases
        safe = jnp.where(finite, 1.0, 0.0)
        if cfg.clip_norm > 0.0:
            factor = jnp.minimum(
                1.0, cfg.clip_norm / (gnorm + 1e-12)) * safe
        else:
            factor = safe
        grads = jax.tree.map(lambda g: g * factor, grads)

        if zero1:
            new_params, new_opt = _zero1_apply(
                optimizer, mesh, grads, state.opt_state, state.params)
        else:
            new_params, new_opt = _plain_apply(
                optimizer, grads, state.opt_state, state.params)

        def keep(new, old):
            return jax.tree.map(
                lambda a, b: jnp.where(finite, a, b), new, old)

        params = keep(new_params, state.params)
        opt_state = keep(new_opt, state.opt_state)
        if cfg.ema_decay > 0.0:
            decay = f32(cfg.ema_decay)
            ema = jax.tree.map(
                lambda e, p: e + (1.0 - decay) * (p - e),
                state.ema_params, params)
            ema = keep(ema, state.ema_params)
        else:
            ema = params

        interval = jnp.int32(max(cfg.scale_growth_interval, 1))
        good = state.good_steps + 1
        grown = jnp.where(
            good >= interval,
            jnp.minimum(scale * cfg.scale_factor, cfg.max_loss_scale),
            scale)
        new_scale = jnp.where(
            finite, grown,
            jnp.maximum(scale / cfg.scale_factor, cfg.min_loss_scale))
        new_good = jnp.where(finite,
                             jnp.where(good >= interval, 0, good),
                             0).astype(jnp.int32)

        rng, _ = jax.random.split(state.rng)
        new_state = TrainState(
            params=params, opt_state=opt_state,
            step=state.step + finite.astype(jnp.int32),
            ema_params=ema, loss_scale=new_scale, good_steps=new_good,
            rng=rng)
        metrics = {**comps, "grad_norm": gnorm, "loss_scale": new_scale,
                   "skipped": (~finite).astype(jnp.int32),
                   "step": new_state.step}
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(model_energy_fn, mesh=None,
                   config: TrainConfig = TrainConfig(), kernels=None):
    """Held-out evaluation over a stacked batch: ``(params, graphs,
    targets) -> components`` dict of fp32 scalars (mean over the leading
    stack axis). Same loss, no gradient — feed ``state.ema_params`` for
    the EMA eval."""
    loss_fn = make_packed_loss_fn(model_energy_fn, mesh, config, kernels)

    @jax.jit
    def evaluate(params, graphs, targets):
        _, comps = jax.vmap(loss_fn, in_axes=(None, 0, 0))(
            params, graphs, targets)
        return jax.tree.map(jnp.mean, comps)

    return evaluate
