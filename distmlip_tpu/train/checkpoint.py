"""Resumable training checkpoints: async, atomic, bitwise.

A checkpoint is ONE npz (``utils/checkpoint`` format — portable,
inspectable with ``np.load``) holding the complete resume story:

- the full :class:`~distmlip_tpu.train.step.TrainState` — fp32 master
  weights, optimizer state (ZeRO-1 sharded layout included: the (Bm, K)
  leaves save/restore like any array), applied-step count, EMA weights,
  dynamic loss scale + its growth counter, and the rng key;
- the data-loader cursor (seed, epoch, step) — with the deterministic
  epoch permutation this replays the EXACT remaining stream, so a resumed
  run's losses are BITWISE identical to the uninterrupted run
  (tests/test_train_subsystem.py pins this mid-epoch).

Writes are async (``utils.checkpoint.AsyncSaver``: host materialization
is synchronous — the only safe point, the step DONATES state buffers —
compression and disk ride a background thread) and atomic (tmp + rename),
with pruned retention and separate best-model tracking.
"""

from __future__ import annotations

import os
import re

import numpy as np

from ..utils.checkpoint import AsyncSaver, load_params

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")
BEST_NAME = "best.npz"


def _loader_state_tree(loader_state: dict | None) -> dict:
    s = loader_state or {}
    # "tier" is the cost-model loader's derived tier coordinate (PR 15);
    # naive loaders save 0 and ignore it on restore, the tiered loader
    # VALIDATES it against its recomputed plan (set_state raises on drift)
    return {"seed": np.int64(s.get("seed", 0)),
            "epoch": np.int64(s.get("epoch", 0)),
            "step": np.int64(s.get("step", 0)),
            "tier": np.int64(s.get("tier", 0))}


def latest_checkpoint(directory: str) -> str | None:
    """Path of the newest ``ckpt-NNNNNNNN.npz`` in ``directory`` (by step
    number, not mtime — a restored-then-resaved old step must not win)."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return None
    best = None
    for name in names:
        m = _CKPT_RE.match(name)
        if m and (best is None or int(m.group(1)) > best[0]):
            best = (int(m.group(1)), name)
    return os.path.join(directory, best[1]) if best else None


class TrainCheckpointer:
    """Periodic + best-model checkpoint writer for one training run.

    ``save(state, loader_state, step)`` enqueues an async atomic write of
    ``ckpt-{step:08d}.npz`` and prunes to the ``keep`` newest;
    ``save_best`` mirrors the state to ``best.npz`` on its own writer
    thread (a periodic write in flight never blocks a best write).
    ``wait()`` joins both writers — call it before reading files back or
    exiting."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = max(int(keep), 1)
        os.makedirs(directory, exist_ok=True)
        self._saver = AsyncSaver()
        self._best_saver = AsyncSaver()
        self.best_metric: float | None = None

    # ---- writing ----

    def _payload(self, state, loader_state):
        # best_metric rides every checkpoint so a RESUMED run keeps the
        # true best: without it, the first (possibly worse) eval after a
        # restore would overwrite best.npz
        best = self.best_metric if self.best_metric is not None else np.inf
        return {"state": state,
                "loader": _loader_state_tree(loader_state),
                "best_metric": np.float64(best)}

    def save(self, state, loader_state: dict | None = None,
             step: int | None = None) -> str:
        step = int(state.step) if step is None else int(step)
        name = f"ckpt-{step:08d}.npz"
        path = os.path.join(self.directory, name)
        self._saver.save(path, self._payload(state, loader_state))
        self._prune(incoming=name)
        return path

    def save_best(self, state, metric: float,
                  loader_state: dict | None = None) -> bool:
        """Write ``best.npz`` iff ``metric`` improves on the best seen
        (lower is better). Returns whether it did."""
        if self.best_metric is not None and metric >= self.best_metric:
            return False
        self.best_metric = float(metric)
        self._best_saver.save(os.path.join(self.directory, BEST_NAME),
                              self._payload(state, loader_state))
        return True

    def _prune(self, incoming: str | None = None) -> None:
        """Keep the ``keep`` newest checkpoints, counting a just-enqueued
        async write as present (its file may not exist yet — pruning by
        listdir alone would leave keep+1 files on disk at steady state)."""
        entries = set()
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                entries.add((int(m.group(1)), name))
        if incoming is not None:
            m = _CKPT_RE.match(incoming)
            if m:
                entries.add((int(m.group(1)), incoming))
        for _, name in sorted(entries)[:-self.keep]:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:  # pragma: no cover - concurrent cleanup
                pass

    def wait(self) -> None:
        self._saver.wait()
        self._best_saver.wait()

    # ---- reading ----

    def _load(self, state_like, path):
        like = self._payload(state_like, None)
        # pre-tier checkpoints (PR 10) lack the loader tier coordinate —
        # restore them with the 3-integer cursor template they were saved
        # with (set_state treats a missing tier as "don't validate")
        with np.load(path, allow_pickle=False) as z:
            if "loader/tier" not in z.files:
                like["loader"].pop("tier", None)
        tree = load_params(path, like=like)
        best = float(tree.get("best_metric", np.inf))
        if np.isfinite(best) and (self.best_metric is None
                                  or best < self.best_metric):
            self.best_metric = best
        return tree["state"], {k: int(v) for k, v in tree["loader"].items()}

    def restore(self, state_like, path: str | None = None):
        """Load ``(state, loader_state)`` from ``path`` (default: the
        newest periodic checkpoint). ``state_like`` is a template
        TrainState (e.g. a freshly built one) fixing tree structure and
        dtypes — exactly what makes the restore bitwise. Also restores
        ``best_metric`` so best-model tracking survives the resume."""
        self.wait()
        if path is None:
            path = latest_checkpoint(self.directory)
            if path is None:
                raise FileNotFoundError(
                    f"no ckpt-*.npz checkpoints in {self.directory!r}")
        return self._load(state_like, path)

    def restore_best(self, state_like):
        self.wait()
        return self._load(state_like,
                          os.path.join(self.directory, BEST_NAME))
