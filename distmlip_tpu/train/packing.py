"""Cost-model-driven batch packing: census, capacity tiers, edge bins.

The MACE chemistry-foundation-model case study (arXiv 2504.10700) found
that training throughput on skewed graph-size distributions is dominated
by DATA DISTRIBUTION, not compute: a loader that freezes one worst-case
capacity for the whole run pays the 99th-percentile padding cost on every
step, and round-robin assignment leaves the device owning the heaviest
micro-batch idle-blocking everyone else. This module is the planning half
of the fix (train/data.PackedBatchLoader consumes it):

- **cost census** — per-structure cost from the analytic FLOP model
  (:mod:`distmlip_tpu.utils.flops`): EDGES are the real unit of work for a
  message-passing potential, not structure counts, so every decision below
  keys on edge-dominated cost, never on "how many structures";
- **capacity tiers** (:func:`assign_tiers`) — instead of ONE frozen
  worst-case capacity, segment the sorted cost histogram into 2–3 tiers by
  exact dynamic programming on the padded-cost objective
  ``sum(len(tier) * max_cost(tier))``: each tier gets its own frozen
  executable sized to ITS worst case, so a single giant outlier inflates
  only the windows that actually contain it (the DP's min-members floor
  keeps every tier able to fill at least one accumulation window);
- **edge-balanced bin-packing** (:func:`plan_epoch`) — deterministic,
  seed-stable first-fit-decreasing on cost into equal-slot micro-batches,
  balancing total edges per micro-batch AND per mesh batch row, with a
  per-epoch shuffle of equal-cost groups so epochs differ while
  ``(seed, epoch)`` fully determines the plan (the bitwise-resume
  contract);
- **predicted waste** (:func:`predicted_plan_waste`) — the analytic
  padding-waste of a plan through THE shared slot-waste definition
  (:func:`distmlip_tpu.partition.slot_waste_frac`), so the audit tool,
  the loader telemetry and the serving pack stats can never disagree on
  what "waste" means.

Everything here is host-side numpy planning — no jax, no chip; the plans
are pure functions of ``(dataset needs, seed, epoch)``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..partition import BucketPolicy, FixedCaps, slot_waste_frac
from ..utils.flops import model_flop_estimate

# the padded dimensions whose slots carry per-row compute — identical to
# the packed_stats slot census (nodes + edges + line-graph edges); bond
# nodes and bond maps are index plumbing, not compute rows
COST_KEYS = ("nodes", "edges", "lines")


def default_cost(need: dict) -> float:
    """Structure cost when no model is in hand: edges (and line-graph
    edges — the angle convolutions run per line) carry the work; nodes
    ride with a small weight so even an edge-free structure costs > 0."""
    return (float(need.get("edges", 0)) + float(need.get("lines", 0))
            + 0.1 * float(need.get("nodes", 0)))


def model_cost_fn(model):
    """Per-structure cost function from the analytic FLOP model: the cost
    of one potential step of ``model`` on the structure's graph shape.
    Falls back to :func:`default_cost` for unknown model families (the
    estimate reads 0 there — a constant-zero cost would erase the
    histogram the tiers are built from)."""

    def cost(need: dict) -> float:
        f = model_flop_estimate(model, float(need.get("nodes", 0)),
                                float(need.get("edges", 0)),
                                float(need.get("lines", 0)))
        return f if f > 0.0 else default_cost(need)

    return cost


def structure_costs(needs, cost_fn=None) -> np.ndarray:
    """(N,) float64 cost of each structure (``cost_fn`` default:
    :func:`default_cost`)."""
    cost_fn = cost_fn or default_cost
    return np.array([cost_fn(n) for n in needs], dtype=np.float64)


@dataclass(frozen=True)
class CostCensus:
    """The dataset's cost histogram, computed once at load time."""

    costs: np.ndarray            # (N,) per-structure cost
    needs: tuple                 # the per-structure capacity-needs dicts

    @classmethod
    def from_needs(cls, needs, cost_fn=None) -> "CostCensus":
        return cls(costs=structure_costs(needs, cost_fn),
                   needs=tuple(needs))

    def percentiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        return {f"p{int(100 * q)}": float(np.quantile(self.costs, q))
                for q in qs}

    def skew(self) -> float:
        """max/mean cost — 1.0 means uniform sizes (tiering buys
        nothing), large means long-tail (tiering is the whole game)."""
        m = float(self.costs.mean()) if len(self.costs) else 0.0
        return float(self.costs.max()) / m if m > 0 else 1.0

    def histogram(self, bins: int = 12):
        """Log-spaced histogram ``(counts, edges)`` over the cost range
        (linear when the range is degenerate)."""
        lo, hi = float(self.costs.min()), float(self.costs.max())
        if lo <= 0 or hi <= lo:
            return np.histogram(self.costs, bins=bins)
        edges = np.geomspace(lo, hi, bins + 1)
        return np.histogram(self.costs, bins=edges)

    def render(self, bins: int = 12, width: int = 40) -> str:
        """ASCII histogram for the audit tool / reports."""
        counts, edges = self.histogram(bins)
        peak = max(int(counts.max()), 1)
        lines = [f"cost census: n={len(self.costs)} "
                 f"mean={self.costs.mean():.3g} max={self.costs.max():.3g} "
                 f"skew={self.skew():.2f}x "
                 + " ".join(f"{k}={v:.3g}"
                            for k, v in self.percentiles().items())]
        for i, cnt in enumerate(counts):
            bar = "#" * max(int(round(width * cnt / peak)), 1 if cnt else 0)
            lines.append(f"  [{edges[i]:>10.3g}, {edges[i + 1]:>10.3g})"
                         f" {int(cnt):>6d} {bar}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# capacity tiers: deterministic 1-D segmentation of the cost histogram
# ---------------------------------------------------------------------------

_MAX_DP_CANDIDATES = 256


def assign_tiers(costs, num_tiers: int, min_members: int = 1):
    """Segment the cost distribution into at most ``num_tiers`` contiguous
    tiers (0 = cheapest) minimizing the padded-cost objective
    ``sum(len(tier) * max_cost(tier))`` — the analytic stand-in for "FLOPs
    a tier's frozen executable spends per epoch" when every member pads to
    the tier's worst case.

    Exact DP over sorted-cost boundaries; boundaries never split an
    equal-cost run (no waste gain), and every tier must hold at least
    ``min_members`` structures (pass ``micro_batch_size * accum_steps`` so
    each tier can fill a whole accumulation window — this is also what
    keeps a single giant outlier from claiming a tier of its own and then
    being dropped as an unfillable tail). Ties prefer FEWER tiers (each
    tier is one frozen executable).

    Returns ``(tier_of, thresholds)``: ``tier_of[i]`` is structure i's
    tier, ``thresholds[t]`` the max cost of tier t.
    """
    costs = np.asarray(costs, dtype=np.float64)
    n = len(costs)
    if n == 0:
        raise ValueError("assign_tiers needs at least one structure")
    min_members = max(int(min_members), 1)
    T = max(min(int(num_tiers), n // min_members), 1)
    order = np.argsort(costs, kind="stable")
    cs = costs[order]

    # candidate segment ends (exclusive prefix lengths): equal-cost run
    # boundaries, quantile-subsampled so the DP stays O(T * C^2) bounded
    ends = np.flatnonzero(np.diff(cs) > 0) + 1
    ends = np.concatenate([ends, [n]]).astype(np.int64)
    if len(ends) > _MAX_DP_CANDIDATES:
        pick = np.linspace(0, len(ends) - 2,
                           _MAX_DP_CANDIDATES - 1).round().astype(np.int64)
        ends = np.unique(np.concatenate([ends[pick], [n]]))
    C = len(ends)

    def seg_cost(a: int, b: int) -> float:
        # prefix [a, b) of the sorted costs, padded to its own max
        return (b - a) * cs[b - 1]

    INF = float("inf")
    # dp[t][j]: min padded cost covering prefix ends[j] with t+1 segments
    dp = np.full((T, C), INF)
    parent = np.full((T, C), -1, dtype=np.int64)
    for j in range(C):
        if ends[j] >= min_members:
            dp[0, j] = seg_cost(0, int(ends[j]))
    for t in range(1, T):
        for j in range(C):
            b = int(ends[j])
            best, arg = INF, -1
            for i in range(j):
                a = int(ends[i])
                if b - a < min_members or dp[t - 1, i] == INF:
                    continue
                cand = dp[t - 1, i] + seg_cost(a, b)
                if cand < best:
                    best, arg = cand, i
            dp[t, j], parent[t, j] = best, arg

    # smallest tier count achieving the optimum (ties -> fewer compiles)
    last = C - 1
    finals = dp[:, last]
    t_star = int(np.flatnonzero(finals <= finals.min() + 1e-9)[0])
    bounds = [int(ends[last])]
    j = last
    for t in range(t_star, 0, -1):
        j = int(parent[t, j])
        bounds.append(int(ends[j]))
    bounds = bounds[::-1]  # ascending exclusive prefix ends, one per tier

    tier_sorted = np.empty(n, dtype=np.int64)
    start = 0
    thresholds = []
    for t, end in enumerate(bounds):
        tier_sorted[start:end] = t
        thresholds.append(float(cs[end - 1]))
        start = end
    tier_of = np.empty(n, dtype=np.int64)
    tier_of[order] = tier_sorted
    return tier_of, thresholds


def tier_caps(needs, tier_of, micro_batch_size: int, batch_parts: int = 1,
              policy=None, *, accum_steps: int = 1, costs=None) -> dict:
    """Frozen :class:`~distmlip_tpu.partition.FixedCaps` per tier, sized
    to the ROUND-PACKING bound rather than the combinatorial top-B worst
    case.

    The epoch packer (:func:`plan_epoch` via :func:`_balance_bins`) hands
    items to bins in strict cost-rank rounds: round ``r`` distributes the
    kept set's cost ranks ``[r * n_bins, (r+1) * n_bins)`` one per bin.
    For ANY epoch's kept subset, the item at kept-rank ``k`` has at least
    ``k`` kept structures at or above its cost, so its cost is bounded by
    the tier's (k+1)-th largest cost VALUE, and its per-name need by
    ``M_name[k]`` — the max need over all tier members whose cost is <=
    that value (tie-collapsed so equal-cost reorderings cannot cheat the
    bound). A bin therefore never needs more than
    ``sum_r M_name[r * n_bins]`` per name (and a batch ROW never more
    than the first ``per_shard`` terms, since a row's j-th largest item
    has bin rank >= j). That bound tracks the tier's cost QUANTILES, not
    its single worst member — with the top-B worst case, the balanced
    bins the packer actually builds would pad to a capacity no epoch can
    reach, and the measured waste showed exactly that.

    ``n_bins`` per tier is fixed (static membership), so the caps hold
    for every epoch of the run; ``FixedCaps`` still hard-fails loudly if
    the invariant were ever violated.
    """
    needs = list(needs)
    tier_arr = np.asarray(tier_of)
    if costs is None:
        costs = structure_costs(needs)
    costs = np.asarray(costs, dtype=np.float64)
    policy = policy or BucketPolicy()
    B = int(micro_batch_size)
    A = max(int(accum_steps), 1)
    per_shard = -(-B // max(int(batch_parts), 1))
    names = set()
    for need in needs:
        names.update(need)
    caps = {}
    for t in sorted(set(int(x) for x in tier_arr)):
        idx = np.flatnonzero(tier_arr == t)
        order = idx[np.argsort(-costs[idx], kind="stable")]
        n_t = len(order)
        n_bins = (n_t // (B * A)) * A
        if n_bins == 0:  # defensive: assign_tiers' min-members floor
            n_bins = 1
        v = costs[order]
        # first index of each equal-cost run (ties collapse upward)
        starts = np.searchsorted(-v, -v, side="left")
        caps_t = {}
        for name in sorted(names):
            vals = np.array([int(needs[i].get(name, 0)) for i in order],
                            dtype=np.int64)
            if not vals.any():
                caps_t[name] = 0
                continue
            sm = np.maximum.accumulate(vals[::-1])[::-1]
            m_bound = sm[starts]
            worst = int(sum(m_bound[min(r * n_bins, n_t - 1)]
                            for r in range(per_shard)))
            caps_t[name] = policy.get(name, worst)
        caps[t] = FixedCaps(caps_t, fallback=policy)
    return caps


# ---------------------------------------------------------------------------
# edge-balanced bin packing: the deterministic per-epoch plan
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MacroStep:
    """One optimizer step of the plan: ``accum_steps`` micro-batches of
    ``micro_batch_size`` structure indices each, all from ONE tier (the
    scan axis stacks them — every micro-batch of a window must share the
    tier's frozen shapes)."""

    tier: int
    micro: tuple  # A tuples of B structure indices


def _balance_bins(members, costs, n_bins: int):
    """Round-based longest-processing-time assignment: round ``r`` hands
    the next ``n_bins`` members (cost ranks ``[r * n_bins,
    (r+1) * n_bins)`` — ``members`` is pre-sorted by descending cost) one
    per bin, heaviest item to the currently cheapest bin. Two properties
    the rest of the pipeline depends on: total cost per bin balances to
    the classic LPT bound, and a bin's round-``r`` item ALWAYS has cost
    rank >= ``r * n_bins`` — the invariant :func:`tier_caps` turns into a
    provable per-epoch capacity bound. Deterministic: ties break on bin
    index."""
    bins = [[] for _ in range(n_bins)]
    totals = np.zeros(n_bins)
    for r0 in range(0, len(members), n_bins):
        chunk = members[r0:r0 + n_bins]
        order = np.argsort(totals, kind="stable")
        for s, b in zip(chunk, order):
            bins[int(b)].append(int(s))
            totals[int(b)] += float(costs[s])
    return bins


def _balance_rows(members, costs, batch_parts: int):
    """Order a micro-batch's members so the mesh packer's contiguous
    shard assignment (structure i -> shard i // ceil(B / batch_parts))
    lands balanced EDGE totals on every batch row — no device idles
    waiting for the heaviest row. (Any row grouping respects the
    tier_caps row bound — a row's j-th largest item has bin rank >= j —
    so balancing is free to optimize for wall clock alone.)"""
    if batch_parts <= 1:
        return list(members)
    order = sorted(members, key=lambda s: (-costs[s], s))
    rows = _balance_bins(order, costs, batch_parts)
    # full rows first: the mesh packer slices contiguous per_shard chunks,
    # so only the TRAILING shard may run short (B % batch_parts != 0)
    rows.sort(key=len, reverse=True)
    return [s for row in rows for s in row]


def plan_epoch(costs, tier_of, *, seed: int, epoch: int,
               micro_batch_size: int, accum_steps: int = 1,
               batch_parts: int = 1, shuffle: bool = True):
    """The deterministic packing plan of one epoch: a pure function of
    ``(costs, tier_of, seed, epoch)`` — what makes the tiered loader's
    cursor resumable — returning a list of :class:`MacroStep`.

    Per tier: a seeded per-epoch permutation picks WHICH structures fill
    this epoch's windows (the dropped tail rotates across epochs, exactly
    like the naive loader's shuffled tail) and breaks equal-cost ties;
    first-fit-decreasing on cost then balances total edges across the
    tier's micro-batches, and within each micro-batch across mesh batch
    rows. Windows of ``accum_steps`` micro-batches stay within one tier
    (one executable per window); the cross-tier step order is a seeded
    interleave so both tiers compile early and resume crosses tier
    boundaries routinely rather than only at epoch edges.
    """
    costs = np.asarray(costs, dtype=np.float64)
    tier_of = np.asarray(tier_of)
    B = int(micro_batch_size)
    A = max(int(accum_steps), 1)
    Bp = max(int(batch_parts), 1)
    windows = []
    for t in sorted(set(int(x) for x in tier_of)):
        idx = np.flatnonzero(tier_of == t)
        rng = np.random.default_rng([int(seed), int(epoch), 211, int(t)])
        if shuffle:
            idx = idx[rng.permutation(len(idx))]
        n_win = len(idx) // (B * A)
        if n_win == 0:
            continue
        keep = idx[:n_win * B * A]
        # decreasing cost; stable sort keeps the shuffled equal-cost order
        keep = keep[np.argsort(-costs[keep], kind="stable")]
        bins = _balance_bins(keep, costs, n_win * A)
        bins = [_balance_rows(b, costs, Bp) for b in bins]
        for w in range(n_win):
            windows.append(MacroStep(
                tier=t,
                micro=tuple(tuple(b) for b in bins[w * A:(w + 1) * A])))
    if shuffle and len(windows) > 1:
        rng = np.random.default_rng([int(seed), int(epoch), 431])
        windows = [windows[i] for i in rng.permutation(len(windows))]
    return windows


def plan_epoch_naive(n: int, *, seed: int, epoch: int,
                     micro_batch_size: int, accum_steps: int = 1,
                     shuffle: bool = True):
    """The single-cap loader's implicit plan (contiguous permutation
    slices, one tier), in :class:`MacroStep` form — lets the audit tool
    predict naive waste through the same machinery it predicts packed
    waste with."""
    from .data import epoch_permutation

    B, A = int(micro_batch_size), max(int(accum_steps), 1)
    order = (epoch_permutation(n, seed, epoch) if shuffle
             else np.arange(n))
    steps = n // (B * A)
    out = []
    for s in range(steps):
        start = s * B * A
        out.append(MacroStep(tier=0, micro=tuple(
            tuple(int(i) for i in order[start + a * B:start + (a + 1) * B])
            for a in range(A))))
    return out


# ---------------------------------------------------------------------------
# predicted waste: the shared slot-waste definition, analytically
# ---------------------------------------------------------------------------


def _caps_dict(caps) -> dict:
    return caps.as_dict() if hasattr(caps, "as_dict") else dict(caps)


def micro_live_slots(needs, members, caps, batch_parts: int = 1):
    """(live, slots) of one micro-batch packed at ``caps`` — the same
    node/edge/line census ``packed_stats`` takes on the built graph, so
    ``slot_waste_frac(live, slots)`` here IS the built pack's
    ``padding_waste_frac``."""
    cd = _caps_dict(caps)
    P = max(int(batch_parts), 1)
    slots = P * (int(cd.get("nodes", 0)) + int(cd.get("edges", 0))
                 + int(cd.get("lines", 0)))
    live = sum(int(needs[s].get(k, 0)) for s in members for k in COST_KEYS)
    return live, slots


def predicted_plan_waste(needs, plan, caps_by_tier, batch_parts: int = 1):
    """Mean predicted ``padding_waste_frac`` over a plan's micro-batches
    (via the shared :func:`~distmlip_tpu.partition.slot_waste_frac`).
    ``caps_by_tier``: {tier: FixedCaps-or-dict}."""
    wastes = []
    for step in plan:
        caps = caps_by_tier[step.tier]
        for members in step.micro:
            live, slots = micro_live_slots(needs, members, caps,
                                           batch_parts)
            wastes.append(slot_waste_frac(live, slots))
    return float(np.mean(wastes)) if wastes else 0.0


def plan_edge_balance(costs, plan) -> float:
    """Worst (min over tiers) mean/max balance of micro-batch cost totals
    within each tier across the whole plan — a tier shares one frozen
    executable, so its heaviest micro-batch is the one every lighter
    sibling's padding pays for. 1.0 means every micro-batch of a tier
    carries equal edge work; the audit-tool counterpart of the loader's
    per-step ``edge_balance`` meta."""
    costs = np.asarray(costs, dtype=np.float64)
    per_tier: dict = {}
    for step in plan:
        for m in step.micro:
            per_tier.setdefault(step.tier, []).append(
                float(costs[list(m)].sum()))
    worst = 1.0
    for tots in per_tier.values():
        if max(tots) > 0:
            worst = min(worst, (sum(tots) / len(tots)) / max(tots))
    return worst
