"""Replay buffer: high-variance served structures become training data.

The buffer is the pipe between the serving path and the trainer: every
escalated, high-variance structure lands here together with its served
label (ensemble/committee energy + forces), and ``to_samples()`` hands
the whole buffer to :func:`distmlip_tpu.train.data.labelled_dataset`-
compatible :class:`~distmlip_tpu.train.data.Sample`s unchanged.

Contracts:

- **dedup** — entries are keyed by the SAME canonical tolerance-bucketed
  structure hash the fleet's content-addressed result cache uses
  (:func:`distmlip_tpu.fleet.result_cache.structure_key`), so a popular
  structure escalated a thousand times is ONE training sample; a
  re-added key refreshes the label and keeps the max variance seen.
- **priority eviction** — over ``capacity``, the LOWEST-variance entry
  is evicted first (the buffer keeps what the model is most unsure
  about); an insert below the current floor is itself the eviction
  victim and never displaces a more uncertain entry.
- **persistent spill** — with ``directory`` set, every add/evict is an
  atomic npz write (tmp + rename) plus an append to a JSONL op log;
  construction replays the log, so a preempted fine-tune host resumes
  with the exact buffer it lost. Memory-only without a directory.

Thread-safe (one lock): the ActiveLoop's escalation pump and a trainer
snapshotting ``to_samples()`` may run concurrently.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from dataclasses import dataclass

import numpy as np

from ..calculators.atoms import Atoms
from ..fleet.result_cache import structure_key

LOG_NAME = "buffer_log.jsonl"


@dataclass
class BufferEntry:
    """One deduplicated labeled structure."""

    key: str
    atoms: Atoms
    energy: float
    forces: np.ndarray
    variance: float
    stress: np.ndarray | None = None
    seq: int = 0                 # insertion order (FIFO tie-break)


class ReplayBuffer:
    """Dedup'd, variance-prioritized, optionally persistent sample store."""

    def __init__(self, capacity: int = 512, tol: float = 1e-5,
                 directory: str | None = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.tol = float(tol)
        self.directory = directory
        self._lock = threading.Lock()
        self._entries: dict[str, BufferEntry] = {}
        self._seq = 0
        self.added = 0
        self.dedup_hits = 0
        self.evictions = 0
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            self._replay_log()

    # ------------------------------------------------------------------
    # core
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def depth(self) -> int:
        return len(self)

    def add(self, atoms, energy: float, forces, variance: float = 0.0,
            stress=None) -> str | None:
        """Insert (or refresh) one labeled structure; returns its key, or
        None when the insert was immediately evicted (buffer full of
        higher-variance entries)."""
        key = structure_key(atoms, tol=self.tol)
        entry = BufferEntry(
            key=key, atoms=atoms.copy(), energy=float(energy),
            forces=np.asarray(forces, dtype=np.float64).copy(),
            variance=float(variance),
            stress=(np.asarray(stress, dtype=np.float64).copy()
                    if stress is not None else None))
        with self._lock:
            prior = self._entries.get(key)
            if prior is not None:
                # dedup: refresh the label, keep the max variance seen
                entry.variance = max(entry.variance, prior.variance)
                entry.seq = prior.seq
                self._entries[key] = entry
                self.dedup_hits += 1
                self._persist_add(entry)
                return key
            entry.seq = self._seq
            self._seq += 1
            self._entries[key] = entry
            self.added += 1
            self._persist_add(entry)
            evicted = self._evict_over_capacity_locked()
        return None if key in evicted else key

    def _evict_over_capacity_locked(self) -> set:
        evicted = set()
        while len(self._entries) > self.capacity:
            victim = min(self._entries.values(),
                         key=lambda e: (e.variance, e.seq))
            del self._entries[victim.key]
            evicted.add(victim.key)
            self.evictions += 1
            self._persist_evict(victim.key)
        return evicted

    def variances(self) -> np.ndarray:
        with self._lock:
            return np.array(sorted(e.variance
                                   for e in self._entries.values()))

    def to_samples(self) -> list:
        """The buffer as training data, highest variance first (so a
        step-bounded fine-tune sees the most uncertain structures even
        when it doesn't consume the whole buffer)."""
        from ..train.data import Sample

        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: (-e.variance, e.seq))
        return [Sample(e.atoms, e.energy,
                       np.asarray(e.forces, np.float32), e.stress)
                for e in entries]

    def stats(self) -> dict:
        with self._lock:
            vs = [e.variance for e in self._entries.values()]
            return {
                "depth": len(self._entries),
                "capacity": self.capacity,
                "added": self.added,
                "dedup_hits": self.dedup_hits,
                "evictions": self.evictions,
                "variance_max": max(vs) if vs else 0.0,
                "variance_min": min(vs) if vs else 0.0,
            }

    # ------------------------------------------------------------------
    # persistence (atomic npz per entry + append-only JSONL op log)
    # ------------------------------------------------------------------

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key[:24]}.npz")

    def _persist_add(self, entry: BufferEntry) -> None:
        if self.directory is None:
            return
        payload = {
            "positions": entry.atoms.positions,
            "numbers": entry.atoms.numbers,
            "cell": entry.atoms.cell,
            "pbc": entry.atoms.pbc,
            "energy": np.float64(entry.energy),
            "forces": entry.forces,
            "variance": np.float64(entry.variance),
        }
        if entry.stress is not None:
            payload["stress"] = entry.stress
        path = self._entry_path(entry.key)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **payload)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._log({"op": "add", "key": entry.key,
                   "file": os.path.basename(path),
                   "variance": entry.variance,
                   "info": {k: v for k, v in entry.atoms.info.items()
                            if isinstance(v, (str, int, float, bool))}})

    def _persist_evict(self, key: str) -> None:
        if self.directory is None:
            return
        try:
            os.unlink(self._entry_path(key))
        except OSError:
            pass
        self._log({"op": "evict", "key": key})

    def _log(self, record: dict) -> None:
        with open(os.path.join(self.directory, LOG_NAME), "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")

    def _replay_log(self) -> None:
        """Rebuild the in-memory state by replaying the op log (corrupt /
        truncated lines and missing npz files are skipped — a killed
        writer must never wedge the resume)."""
        path = os.path.join(self.directory, LOG_NAME)
        try:
            lines = open(path).read().splitlines()
        except OSError:
            return
        live: dict[str, dict] = {}
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("op") == "add":
                live[rec["key"]] = rec
            elif rec.get("op") == "evict":
                live.pop(rec.get("key"), None)
        for key, rec in live.items():
            try:
                with np.load(self._entry_path(key)) as z:
                    atoms = Atoms(numbers=z["numbers"],
                                  positions=z["positions"],
                                  cell=z["cell"], pbc=z["pbc"],
                                  info=rec.get("info") or {})
                    entry = BufferEntry(
                        key=key, atoms=atoms,
                        energy=float(z["energy"]),
                        forces=np.asarray(z["forces"]),
                        variance=float(z["variance"]),
                        stress=(np.asarray(z["stress"])
                                if "stress" in z.files else None),
                        seq=self._seq)
            except (OSError, KeyError, ValueError):
                continue
            self._seq += 1
            self._entries[key] = entry
            self.added += 1
