"""Active learning: the uncertainty-routed serve -> train -> serve loop.

The subsystem that turns the repo from "a model server" into a
self-improving potential service (the ROADMAP's closed-loop item):

- :mod:`.uncertainty` — :class:`EnsembleBatchedPotential`, a
  ``BatchedPotential`` whose ``calculate`` serves the cheap primary
  member while ``calculate_with_variance`` re-evaluates the same packed
  batch under every member in ONE vmapped launch (zero extra
  collectives — pinned by ``tools/contract_check.py``), plus the
  cheap-first :class:`EscalationPolicy`;
- :mod:`.buffer` — :class:`ReplayBuffer`: dedup'd (the fleet result
  cache's canonical structure hash), variance-prioritized, atomically
  spilled to JSONL+npz, and directly consumable by the trainer;
- :mod:`.trigger` — :class:`FineTuneTrigger` threshold policies (buffer
  size / variance drift / wall-clock cadence) and the gated,
  preemption-safe :func:`run_finetune` job (a worse model never ships);
- :mod:`.hotswap` — zero-recompile pure-pytree weight swap into live
  ``ServeEngine``/``FleetRouter`` replicas with result/AOT cache keys
  rolled forward (stale entries can never serve the new weights);
- :mod:`.loop` — :class:`ActiveLoop`, the controller: route -> buffer
  -> trigger -> train -> validate -> swap, synchronous and
  clock-injectable, with ``active_*`` telemetry rendered by
  ``telemetry_report``.

Quick start::

    from distmlip_tpu.active import (ActiveLoop, EnsembleBatchedPotential,
                                     EscalationPolicy, ReplayBuffer)
    from distmlip_tpu.serve import ServeEngine

    ens = EnsembleBatchedPotential(model, [serving_params, *member_params])
    engine = ServeEngine(ens, max_batch=8)      # serves the primary member
    loop = ActiveLoop(engine, ens, ReplayBuffer(capacity=512),
                      policy=EscalationPolicy(sample_rate=0.05),
                      finetune_kwargs={"steps": 200,
                                       "loader_kwargs": {...}})
    fut = loop.submit(atoms)                    # same Future contract
    loop.tick()                                 # pump + maybe fine-tune/swap

Smoke/gate: ``python tools/load_test.py --fleet 2 --active --check``
(mid-burst hot-swap, zero lost requests, zero recompiles).
"""

from .buffer import BufferEntry, ReplayBuffer
from .hotswap import (HotSwapError, check_swappable, hot_swap,
                      hot_swap_engine, hot_swap_router, params_digest,
                      swap_potential_params)
from .loop import ActiveLoop, ActiveStats
from .trigger import (FineTuneReport, FineTuneTrigger, TriggerPolicy,
                      holdout_split, run_finetune)
from .uncertainty import (EnsembleBatchedPotential, EscalationPolicy,
                          variance_score)

__all__ = [
    "ActiveLoop",
    "ActiveStats",
    "EnsembleBatchedPotential",
    "EscalationPolicy",
    "variance_score",
    "ReplayBuffer",
    "BufferEntry",
    "FineTuneTrigger",
    "TriggerPolicy",
    "FineTuneReport",
    "run_finetune",
    "holdout_split",
    "hot_swap",
    "hot_swap_engine",
    "hot_swap_router",
    "swap_potential_params",
    "check_swappable",
    "params_digest",
    "HotSwapError",
]
