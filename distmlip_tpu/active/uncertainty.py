"""Uncertainty on the serving path: the batched ensemble evaluator.

``EnsembleBatchedPotential`` is a :class:`~distmlip_tpu.calculators.
batched.BatchedPotential` whose ``calculate`` serves the PRIMARY member's
weights exactly as before (the cheap path every request rides), plus a
``calculate_with_variance`` that re-evaluates the same packed batch under
ALL members in ONE device launch — ``jax.vmap`` over the stacked member
parameter pytrees riding the existing packed program, the same one-launch
trick ``EnsemblePotential.stacked`` plays for ``DistPotential``
(calculators/calculator.py). Because both paths share the potential's
pack/skin cache (``_prepare_batch``), escalating a just-served batch
costs one vmapped dispatch — no repack, no second graph upload, and
ZERO additional collectives vs the single-member program (pinned by
``tools/contract_check.py``'s ``ensemble[...]`` program).

The cheap-first escalation policy lives in :class:`EscalationPolicy`:
serve the single model always; re-evaluate under the ensemble only when
a sampling policy fires or the caller opts in (``ActiveLoop.submit(...,
escalate=True)``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..calculators.atoms import EV_A3_TO_GPA
from ..calculators.batched import BatchedPotential
from ..telemetry import annotate


@dataclass
class EscalationPolicy:
    """When a served request is re-evaluated under the ensemble, and when
    a re-evaluated structure is admitted to the replay buffer.

    ``sample_rate`` is the fraction of served requests escalated by the
    sampling policy (callers can always force/suppress escalation per
    request). ``energy_var_floor`` / ``force_var_floor`` gate buffer
    admission: a structure lands in the buffer when its ensemble energy
    variance (eV², per structure) or max per-component force variance
    ((eV/Å)²) reaches its floor — both 0 admits every escalated
    structure. ``max_pending`` bounds the escalation queue (oldest
    dropped first; the loop counts drops)."""

    sample_rate: float = 0.0
    energy_var_floor: float = 0.0
    force_var_floor: float = 0.0
    max_pending: int = 1024

    def admits(self, energy_var: float, force_var_max: float) -> bool:
        if self.energy_var_floor <= 0.0 and self.force_var_floor <= 0.0:
            return True
        return (0.0 < self.energy_var_floor <= energy_var
                or 0.0 < self.force_var_floor <= force_var_max)


def variance_score(result: dict) -> float:
    """The scalar priority the buffer/trigger machinery ranks by: the max
    per-component force variance (forces are what MD/relax consume, and
    the force field is where MLIP uncertainty actually bites), falling
    back to the energy variance for empty structures."""
    fv = np.asarray(result.get("forces_var", 0.0))
    if fv.size:
        return float(fv.max())
    return float(result.get("energy_var", 0.0))


class EnsembleBatchedPotential(BatchedPotential):
    """Batched potential with an M-member uncertainty lane.

    ``params_list[0]`` is the PRIMARY (serving) member: ``calculate``
    behaves exactly like a ``BatchedPotential`` over those weights, so a
    ``ServeEngine`` can use this object as its shared potential with no
    behavior change. ``calculate_with_variance`` evaluates every member
    over the same packed graph via one vmapped dispatch and returns
    per-structure mean/variance plus the per-member stacks.

    ``set_primary`` is the hot-swap hook: a pure pytree swap of the
    serving weights (and the member-0 slice of the stacked params) that
    by construction reuses every compiled executable — the swap refuses
    any tree whose structure/shapes/dtypes differ from the live one.
    """

    def __init__(self, model, params_list, **kwargs):
        params_list = list(params_list)
        if not params_list:
            raise ValueError("params_list must be non-empty")
        super().__init__(model, params_list[0], **kwargs)
        self.member_count = len(params_list)
        self._stack_members(params_list)
        self._vpot = None

    # ---- member management ----

    def _stack_members(self, params_list) -> None:
        import jax
        import jax.numpy as jnp

        treedefs = {str(jax.tree.structure(p)) for p in params_list}
        if len(treedefs) != 1:
            raise ValueError("ensemble members must share one param "
                             "pytree structure")
        self.stacked_params = jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *params_list)

    def member_params(self, k: int):
        """Member ``k``'s parameter pytree (unstacked view)."""
        import jax

        if not 0 <= k < self.member_count:
            raise IndexError(f"member {k} outside [0, {self.member_count})")
        return jax.tree.map(lambda s: s[k], self.stacked_params)

    def set_primary(self, new_params) -> None:
        """Install new PRIMARY weights (member 0) as a pure pytree swap.

        Thread-safe against a concurrent ``calculate`` (takes the same
        lock the scheduler thread serializes on) and recompile-free by
        construction: the tree structure, leaf shapes and dtypes must
        match the live params exactly, so every jitted executable —
        including AOT-rehydrated ones — keeps serving unchanged."""
        import jax
        import jax.numpy as jnp

        from .hotswap import check_swappable

        check_swappable(self.params, new_params)
        with self._lock:
            self.params = new_params
            self.stacked_params = jax.tree.map(
                lambda s, p: s.at[0].set(jnp.asarray(p, s.dtype)),
                self.stacked_params, new_params)

    # ---- the vmapped uncertainty lane ----

    def _ensure_vpot(self):
        if self._vpot is None:
            import jax

            # vmap the underlying jit, not the AOT dispatcher wrapper
            # (exported executables don't batch; the jit retraces once
            # for the member-stacked shapes and caches like any bucket)
            fn = getattr(self._potential, "_jit", self._potential)
            self._vpot = jax.vmap(fn, in_axes=(0, None, None))
        return self._vpot

    def calculate_with_variance(self, structures) -> list:
        """Evaluate the batch under EVERY member in one vmapped launch.

        Returns one dict per input structure: ensemble-mean ``energy`` /
        ``forces`` / ``stress`` (same keys ``calculate`` produces), plus
        ``energy_var``, ``forces_var`` (per-atom, per-component),
        ``energies`` (M,), ``forces_all`` (M, n, 3) and
        ``committee_energy``/``committee_forces`` — the mean over the
        NON-primary members, the label an active-learning buffer wants
        when the primary itself is the model being corrected (falls back
        to the full mean for M == 1)."""
        structures = list(structures)
        if not structures:
            return []
        with self._lock:
            return self._variance_locked(structures)

    def _variance_locked(self, structures) -> list:
        graph, host, positions, reused, refreshed, rebuild_s, \
            (t0, t1, t2) = self._prepare_batch(structures)
        vpot = self._ensure_vpot()
        with annotate("distmlip/ensemble_batched"):
            out = vpot(self.stacked_params, graph, positions)
        M = self.member_count
        slots = host.structure_slots
        energies = np.asarray(out["energies"], dtype=np.float64)[:, slots]
        strain_grad = np.asarray(out["strain_grad"])[:, slots]
        forces_by_member = [
            host.gather_per_structure(np.asarray(out["forces"])[k])
            for k in range(M)]
        results = []
        for b in range(len(structures)):
            f_all = np.stack([forces_by_member[k][b] for k in range(M)])
            e_all = energies[:, b]
            vol = max(host.volumes[b], 1e-30)
            s_all = strain_grad[:, b] / vol
            stress = s_all.mean(axis=0)
            res = {
                "energy": float(e_all.mean()),
                "free_energy": float(e_all.mean()),
                "forces": f_all.mean(axis=0),
                "stress": stress,
                "stress_GPa": stress * EV_A3_TO_GPA,
                "energy_var": float(e_all.var()),
                "forces_var": f_all.var(axis=0),
                "energies": e_all,
                "forces_all": f_all,
            }
            if M > 1:
                res["committee_energy"] = float(e_all[1:].mean())
                res["committee_forces"] = f_all[1:].mean(axis=0)
            else:
                res["committee_energy"] = res["energy"]
                res["committee_forces"] = res["forces"]
            results.append(res)
        t3 = time.perf_counter()
        self.last_timings = {
            "neighbor_s": (t1 - t0) - rebuild_s, "partition_s": t2 - t1,
            "device_s": t3 - t2, "total_s": t3 - t0,
        }
        if refreshed:
            self.last_timings["rebuild_s"] = rebuild_s
        self.last_stats = dict(host.stats or {})
        self.last_stats.update(
            batch_size=len(structures), member_count=M,
            rebuild_count=int(not reused),
            rebuild_on_device=int(refreshed),
            rebuild_overflow_count=self.rebuild_overflow_count)
        from ..utils.memory import device_memory_stats

        self._emit_record(host, len(structures), reused, refreshed,
                          t3 - t0, device_memory_stats(),
                          kind="ensemble_batched", member_count=M)
        return results
