"""ActiveLoop: the closed serve -> buffer -> train -> validate -> swap loop.

One controller object ties the subsystem together over a live serving
surface (a :class:`~distmlip_tpu.serve.ServeEngine` or a
:class:`~distmlip_tpu.fleet.FleetRouter`):

- ``submit()`` forwards to the serving surface unchanged (same Future
  contract) and, per the :class:`~.uncertainty.EscalationPolicy` (or an
  explicit ``escalate=`` override), queues the structure for ensemble
  re-evaluation;
- ``pump()`` drains the escalation queue in packed batches through the
  :class:`~.uncertainty.EnsembleBatchedPotential` — one vmapped launch
  per batch — and routes high-variance structures with their served
  labels into the :class:`~.buffer.ReplayBuffer`;
- ``maybe_finetune()`` consults the :class:`~.trigger.FineTuneTrigger`;
  when due, runs the gated :func:`~.trigger.run_finetune` job and, if
  the candidate beats the live weights on holdout, hot-swaps it into
  the serving surface AND the ensemble's primary member
  (:mod:`~.hotswap` — zero recompiles, zero dropped requests, cache
  keys rolled forward);
- ``tick()`` = pump + maybe_finetune, the one call a driver loop needs.

Everything is synchronous and clock-injectable: tests drive the loop
deterministically, production drivers call ``tick()`` from their own
cadence (a cron thread, the serving idle loop, a sidecar).

Telemetry: ``active_escalate`` / ``active_finetune`` / ``active_swap``
StepRecords (swap count, buffer depth, variance percentiles, escalation
rate riding ``extra``) rendered by ``telemetry_report``'s "active
learning" section.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..obs import runtime as obsrt
from ..telemetry import StepRecord
from .buffer import ReplayBuffer
from .hotswap import hot_swap, params_digest
from .trigger import FineTuneTrigger, run_finetune
from .uncertainty import EscalationPolicy, variance_score


@dataclass
class ActiveStats:
    """Cumulative loop counters (reads under the loop lock)."""

    submitted: int = 0
    escalated: int = 0
    escalation_dropped: int = 0   # queue overflow (max_pending)
    evaluated: int = 0            # structures re-evaluated under the ensemble
    buffered: int = 0
    finetunes: int = 0
    shipped: int = 0
    rejected_models: int = 0      # candidates the holdout gate refused
    swaps: int = 0

    def snapshot(self) -> dict:
        return dict(vars(self))


class ActiveLoop:
    """Uncertainty-routed active-learning controller.

    Parameters
    ----------
    serving : ServeEngine or FleetRouter — where traffic goes. May share
        its potential with ``ensemble`` (the single-host shape: the
        engine serves the ensemble's primary member) or not (a fleet
        with a standalone evaluator).
    ensemble : EnsembleBatchedPotential — the uncertainty lane; member 0
        is the live serving weights.
    buffer : ReplayBuffer (a fresh in-memory one by default).
    policy : EscalationPolicy — sampling rate + buffer admission floors.
    trigger : FineTuneTrigger (default: fires on 16 fresh buffer
        entries).
    finetune : callable(samples, params) -> FineTuneReport overriding the
        built-in job, or None to use :func:`~.trigger.run_finetune` with
        ``finetune_kwargs`` (``loader_kwargs`` etc.).
    label : "committee" (default — label with the mean of the
        NON-primary members, the right teacher when the primary is the
        model being corrected) or "mean" (full ensemble mean).
    escalation_batch : max structures per vmapped escalation launch
        (default: the ensemble's packed ladder decides; 8).
    seed / clock : deterministic sampling + injectable time.
    """

    def __init__(self, serving, ensemble, buffer: ReplayBuffer | None = None,
                 *, policy: EscalationPolicy | None = None,
                 trigger: FineTuneTrigger | None = None,
                 finetune=None, finetune_kwargs: dict | None = None,
                 label: str = "committee", escalation_batch: int = 8,
                 telemetry=None, clock=None, seed: int = 0):
        if label not in ("committee", "mean"):
            raise ValueError(f"label must be 'committee' or 'mean', "
                             f"got {label!r}")
        self.serving = serving
        self.ensemble = ensemble
        self.buffer = buffer if buffer is not None else ReplayBuffer()
        self.policy = policy or EscalationPolicy()
        self._clock = clock or time.monotonic
        self.trigger = trigger or FineTuneTrigger(clock=self._clock)
        self._finetune = finetune
        self.finetune_kwargs = dict(finetune_kwargs or {})
        self.label = label
        self.escalation_batch = max(int(escalation_batch), 1)
        self.telemetry = telemetry
        self.stats = ActiveStats()
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._pending: list = []     # structures awaiting ensemble eval
        self._step = itertools.count(1)

    # ------------------------------------------------------------------
    # serving path
    # ------------------------------------------------------------------

    def submit(self, atoms, escalate: bool | None = None, **kwargs):
        """Forward to the serving surface; returns its Future unchanged.
        ``escalate`` overrides the sampling policy for this request."""
        fut = self.serving.submit(atoms, **kwargs)
        decide = (bool(escalate) if escalate is not None
                  else bool(self._rng.random() < self.policy.sample_rate))
        with self._lock:
            self.stats.submitted += 1
            if decide:
                self.stats.escalated += 1
                self._pending.append(atoms.copy())
                while len(self._pending) > self.policy.max_pending:
                    self._pending.pop(0)
                    self.stats.escalation_dropped += 1
        if decide:
            mx = obsrt.metrics()
            if mx is not None:
                mx.counter("distmlip_active_escalations_total",
                           "requests routed to ensemble evaluation").inc()
        return fut

    @property
    def pending_escalations(self) -> int:
        with self._lock:
            return len(self._pending)

    # ------------------------------------------------------------------
    # escalation pump
    # ------------------------------------------------------------------

    def pump(self, max_batches: int | None = None) -> int:
        """Drain queued escalations through the ensemble in packed
        batches; returns the number of structures evaluated."""
        done = 0
        batches = 0
        while max_batches is None or batches < max_batches:
            with self._lock:
                if not self._pending:
                    break
                batch = self._pending[:self.escalation_batch]
                del self._pending[:len(batch)]
            done += self._evaluate_batch(batch)
            batches += 1
        return done

    def _evaluate_batch(self, batch) -> int:
        tr = obsrt.tracer()
        # its own (batch-level) trace; the ensemble's vmapped record
        # stamps these ids via the ambient context
        with (tr.span("active.escalate", new_trace=True,
                      attrs={"batch_size": len(batch)})
              if tr is not None else contextlib.nullcontext()):
            results = self.ensemble.calculate_with_variance(batch)
        scores = []
        added = 0
        for atoms, res in zip(batch, results):
            score = variance_score(res)
            scores.append(score)
            self.trigger.observe_variance(score)
            if self.policy.admits(res["energy_var"],
                                  float(np.asarray(
                                      res["forces_var"]).max(initial=0.0))):
                if self.label == "committee":
                    energy, forces = (res["committee_energy"],
                                      res["committee_forces"])
                else:
                    energy, forces = res["energy"], res["forces"]
                self.buffer.add(atoms, energy, forces, variance=score)
                added += 1
        with self._lock:
            self.stats.evaluated += len(batch)
            self.stats.buffered += added
        mx = obsrt.metrics()
        if mx is not None:
            mx.counter("distmlip_active_evaluated_total",
                       "structures re-evaluated under the ensemble").inc(
                           len(batch))
            mx.gauge("distmlip_active_buffer_size",
                     "replay-buffer depth").set(len(self.buffer))
        self._emit("active_escalate", batch_size=len(batch), extra={
            "variances": [round(float(s), 9) for s in scores],
            "buffer_added": added,
            "buffer_depth": len(self.buffer),
            "escalated_total": self.stats.escalated,
            "submitted_total": self.stats.submitted,
            "drift_ratio": self.trigger.drift_ratio(),
        })
        return len(batch)

    # ------------------------------------------------------------------
    # fine-tune + swap
    # ------------------------------------------------------------------

    def maybe_finetune(self) -> dict | None:
        """Run the gated fine-tune when the trigger says so. Returns a
        report dict (``shipped`` tells whether a swap happened), or None
        when not due."""
        depth = len(self.buffer)
        reason = self.trigger.due(depth)
        if reason is None:
            return None
        return self.finetune_now(reason=reason)

    def finetune_now(self, reason: str = "forced") -> dict:
        """Unconditionally fine-tune from the current buffer, gate on
        holdout, and hot-swap on improvement."""
        depth = len(self.buffer)
        samples = self.buffer.to_samples()
        self.trigger.note_fired(depth)
        with self._lock:
            self.stats.finetunes += 1
        if self._finetune is not None:
            report = self._finetune(samples, self.ensemble.params)
        else:
            report = run_finetune(self.ensemble.model, self.ensemble.params,
                                  samples, telemetry=self.telemetry,
                                  **self.finetune_kwargs)
        report.reason = reason
        out = {k: v for k, v in vars(report).items() if k != "params"}
        if report.shipped and report.params is not None:
            with self._lock:
                self.stats.shipped += 1
            swap = self.swap_now(report.params)
            out["swap"] = swap
        else:
            with self._lock:
                self.stats.rejected_models += 1
        self._emit("active_finetune", extra={
            "reason": reason, "shipped": bool(report.shipped),
            "val_before": report.val_before, "val_after": report.val_after,
            "finetune_steps": report.steps, "buffer_depth": depth,
            "finetunes_total": self.stats.finetunes,
        })
        return out

    def swap_now(self, new_params) -> dict:
        """Hot-swap ``new_params`` into the serving surface and the
        ensemble's primary member. Zero recompiles (asserted inside
        :mod:`~.hotswap`), zero dropped requests, result/AOT cache keys
        rolled forward on a router."""
        tr = obsrt.tracer()
        with (tr.span("active.hotswap", new_trace=True)
              if tr is not None else contextlib.nullcontext()):
            swap = hot_swap(self.serving, new_params)
        # a standalone evaluator (not the engine's own potential) needs
        # its primary rolled too; set_primary is idempotent when the
        # engine swap already installed the weights
        self.ensemble.set_primary(new_params)
        with self._lock:
            self.stats.swaps += 1
        mx = obsrt.metrics()
        if mx is not None:
            mx.counter("distmlip_active_swaps_total",
                       "zero-recompile hot swaps shipped").inc()
        self._emit("active_swap", extra={
            "swap_count": self.stats.swaps,
            "model_digest": params_digest(new_params),
            "model_id": swap.get("model_id", ""),
            "buffer_depth": len(self.buffer),
        })
        return swap

    # ------------------------------------------------------------------
    # driver surface
    # ------------------------------------------------------------------

    def tick(self) -> dict:
        """One controller beat: drain escalations, fine-tune if due."""
        evaluated = self.pump()
        report = self.maybe_finetune()
        return {"evaluated": evaluated, "finetune": report,
                "buffer_depth": len(self.buffer)}

    def snapshot(self) -> dict:
        with self._lock:
            out = {"stats": self.stats.snapshot(),
                   "pending_escalations": len(self._pending)}
        out["buffer"] = self.buffer.stats()
        out["drift_ratio"] = self.trigger.drift_ratio()
        return out

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------

    def _emit(self, kind: str, batch_size: int = 0,
              extra: dict | None = None) -> None:
        tel = self.telemetry
        if tel is None or not tel.wants_records():
            return
        tel.emit(StepRecord(
            step=next(self._step), kind=kind, batch_size=batch_size,
            member_count=self.ensemble.member_count,
            extra=dict(extra or {})))
