"""Zero-recompile hot-swap: install fine-tuned weights into live serving.

A fine-tuned parameter pytree with the SAME tree structure, leaf shapes
and dtypes as the live one is a pure runtime input to every compiled
executable — the bucket ladder, the jit cache, and any AOT-rehydrated
executables all keep serving unchanged. So a swap is: take the
potential's lock (no batch is mid-dispatch), assign the pytree, release.
``compile_count`` is snapshotted around the swap and asserted unchanged;
queued requests keep their heap order and in-flight Futures resolve
normally — a request dispatched before the swap returns old-weight
results, one dispatched after returns new-weight results, and nothing is
ever dropped or reordered.

Cache-key roll-forward (the stale-entry contract): a
:class:`~distmlip_tpu.fleet.router.FleetRouter` keys its
content-addressed result cache by ``model_id``. The swap first installs
the new weights on EVERY replica, then rolls ``router.model_id`` to a
new identity (caller-supplied, or the old id stamped with a digest of
the new parameter VALUES). Ordering matters: after the roll, every new
submission keys under the new id — and since every replica already
serves the new weights, no old-weight result can ever be computed under
(or served from) the new id. Results computed with the old weights stay
keyed under the old id, which no future submission can reach. The AOT
cache's model fingerprint is re-derived from the new params the same way
(:func:`~distmlip_tpu.fleet.aot.model_fingerprint`) — unchanged for a
pure value swap, because exported executables take params as runtime
arguments and are weight-agnostic by construction; the roll keeps the
invariant that the cache key always describes the live model, so a swap
that DID alter the program shape could never rehydrate a stale
executable.
"""

from __future__ import annotations

import hashlib

import numpy as np


class HotSwapError(RuntimeError):
    """The candidate params cannot be installed as a pure pytree swap
    (tree structure / leaf shape / dtype mismatch — installing them
    would retrace and recompile, or silently misread buffers)."""


def params_digest(params) -> str:
    """Short content digest of the parameter VALUES — the model-identity
    suffix the result-cache key rolls forward on a swap."""
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        arr = np.asarray(leaf)
        h.update(arr.shape.__repr__().encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:12]


def check_swappable(live_params, new_params) -> None:
    """Raise :class:`HotSwapError` unless ``new_params`` is a pure
    drop-in for ``live_params`` (same treedef, leaf shapes, dtypes)."""
    import jax

    live_leaves, live_def = jax.tree.flatten(live_params)
    new_leaves, new_def = jax.tree.flatten(new_params)
    if live_def != new_def:
        raise HotSwapError(
            f"param tree structure changed: {new_def} vs live {live_def}")
    for i, (a, b) in enumerate(zip(live_leaves, new_leaves)):
        sa, sb = np.shape(a), np.shape(b)
        da = np.asarray(a).dtype
        db = np.asarray(b).dtype
        if sa != sb or da != db:
            raise HotSwapError(
                f"param leaf {i} changed: {sb}/{db} vs live {sa}/{da} — "
                f"a hot-swap must not alter the traced program")


def swap_potential_params(pot, new_params) -> None:
    """Install ``new_params`` on one potential as a pure pytree swap,
    serialized against any in-flight ``calculate`` on the potential's
    own lock. Works for ``BatchedPotential``, ``DistPotential`` and
    ``EnsembleBatchedPotential`` (whose stacked member-0 slice follows
    the primary)."""
    set_primary = getattr(pot, "set_primary", None)
    if set_primary is not None:
        set_primary(new_params)
        return
    check_swappable(pot.params, new_params)
    lock = getattr(pot, "_lock", None)
    if lock is not None:
        with lock:
            pot.params = new_params
    else:
        pot.params = new_params


def hot_swap_engine(engine, new_params) -> dict:
    """Swap one ``ServeEngine``'s serving weights in place.

    Swaps the shared batched potential, the engine-owned spatial lane
    and any explicit fallback whose params are drop-in compatible (an
    incompatible user-owned fallback is left alone and reported).
    Returns a report dict; raises :class:`HotSwapError` (nothing
    swapped) when the primary potential rejects the tree."""
    pot = engine.potential
    compile_before = engine.compile_count
    check_swappable(pot.params, new_params)  # validate BEFORE any mutation
    swap_potential_params(pot, new_params)
    swapped_lanes = ["potential"]
    skipped_lanes = []
    for name in ("_spatial_lane", "fallback"):
        lane = getattr(engine, name, None)
        if lane is None:
            continue
        try:
            swap_potential_params(lane, new_params)
            swapped_lanes.append(name.lstrip("_"))
        except HotSwapError:
            # a user-owned fallback may legitimately run a different
            # model; leave it serving its own weights
            skipped_lanes.append(name.lstrip("_"))
    aot = getattr(pot, "aot_cache", None)
    if aot is not None:
        from ..fleet.aot import model_fingerprint

        aot.fingerprint = model_fingerprint(pot.model, new_params)
    compile_after = engine.compile_count
    if compile_after != compile_before:
        raise HotSwapError(
            f"hot swap changed compile_count {compile_before} -> "
            f"{compile_after}; the swap must reuse every executable")
    return {"compile_count": compile_after,
            "swapped_lanes": swapped_lanes,
            "skipped_lanes": skipped_lanes}


def hot_swap_router(router, new_params, *, model_id: str | None = None
                    ) -> dict:
    """Swap every ALIVE replica's weights, then roll the cache identity.

    Replicas first, identity last: once ``model_id`` changes, every new
    submission keys (and coalesces) under the new identity against
    replicas that all already serve the new weights — a stale old-weight
    result can never be computed or served under the new id, and entries
    under the old id become unreachable. Dead replicas are skipped (a
    failed-over engine serves nothing; killing its stale weights is
    moot). Returns a report with the new ``model_id`` and per-replica
    swap reports."""
    base_id = router.model_id.split("#", 1)[0]
    new_id = (str(model_id) if model_id is not None
              else f"{base_id}#{params_digest(new_params)}")
    # validate EVERY alive replica before mutating ANY: a mixed fleet
    # (some replicas on new weights, some refusing) under one model_id
    # is exactly the cache-aliasing state this module exists to prevent.
    # After this loop the per-replica swap can only fail on its
    # compile-count assertion, which a pure assignment cannot trip.
    for rid, rep in router.replicas.items():
        if rep.alive:
            check_swappable(rep.engine.potential.params, new_params)
    replicas = {}
    for rid, rep in router.replicas.items():
        if not rep.alive:
            replicas[rid] = {"skipped": "dead"}
            continue
        replicas[rid] = hot_swap_engine(rep.engine, new_params)
    old_id, router.model_id = router.model_id, new_id
    return {"model_id": new_id, "previous_model_id": old_id,
            "replicas": replicas}


def hot_swap(target, new_params, **kwargs) -> dict:
    """Dispatch on the serving surface: a FleetRouter (swap + cache-key
    roll), a ServeEngine (swap all lanes), or a bare potential."""
    if hasattr(target, "replicas") and hasattr(target, "model_id"):
        return hot_swap_router(target, new_params, **kwargs)
    if hasattr(target, "potential") and hasattr(target, "compile_count"):
        return hot_swap_engine(target, new_params, **kwargs)
    swap_potential_params(target, new_params)
    return {"swapped_lanes": ["potential"]}
