"""Fine-tune triggering + the gated, preemption-safe fine-tune job.

:class:`FineTuneTrigger` decides WHEN the loop fine-tunes — any of three
threshold policies firing is enough:

- **buffer size** — the replay buffer reached ``min_buffer`` fresh
  (not-yet-trained-on) entries;
- **variance drift** — the recent mean escalation variance exceeds
  ``variance_drift`` x the run's baseline (the first observation
  window), i.e. the live traffic drifted away from what the model
  knows;
- **wall-clock cadence** — ``interval_s`` elapsed since the last
  fine-tune (on the injectable clock).

``cooldown_s`` spaces fine-tunes regardless of which policy fires.

:func:`run_finetune` is the job itself: split the buffer into
train/holdout, run a :class:`~distmlip_tpu.train.loop.Trainer` through
the existing ``PackedBatchLoader``/checkpoint machinery (pass
``checkpoint_dir`` and an interrupted job resumes from its newest
checkpoint — the Trainer's bitwise-resume contract makes preemption
free), and GATE on held-out improvement: the candidate (EMA) weights
ship only if their holdout loss beats the CURRENT weights' holdout loss
— a worse model never ships.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class TriggerPolicy:
    """Thresholds for :class:`FineTuneTrigger` (0/None disables each)."""

    min_buffer: int = 16           # fresh buffered structures to fire on
    interval_s: float = 0.0        # wall-clock cadence (0: disabled)
    variance_drift: float = 0.0    # recent/baseline variance ratio (0: off)
    drift_window: int = 16         # observations per drift window
    cooldown_s: float = 0.0        # min spacing between fine-tunes


class FineTuneTrigger:
    """Threshold machine over buffer depth / variance drift / wall clock."""

    def __init__(self, policy: TriggerPolicy | None = None, clock=None):
        self.policy = policy or TriggerPolicy()
        self._clock = clock or time.monotonic
        self._recent = deque(maxlen=max(self.policy.drift_window, 1))
        self._baseline: float | None = None
        self._last_fired: float | None = None
        # the interval cadence anchors at CONSTRUCTION, not at "never":
        # a fresh trigger fires its first interval fine-tune interval_s
        # after startup, not on the first tick
        self._interval_anchor = self._clock()
        self._consumed_depth = 0      # buffer entries already trained on
        self.fired = 0

    def observe_variance(self, score: float) -> None:
        """Feed one escalation's variance score into the drift tracker.
        The FIRST full window becomes the baseline; later windows are
        compared against it."""
        self._recent.append(float(score))
        if (self._baseline is None
                and len(self._recent) == self._recent.maxlen):
            self._baseline = sum(self._recent) / len(self._recent)
            self._recent.clear()

    def drift_ratio(self) -> float:
        """Recent mean variance / baseline (0.0 until a baseline and a
        fresh observation exist)."""
        if not self._baseline or not self._recent:
            return 0.0
        return (sum(self._recent) / len(self._recent)) / self._baseline

    def due(self, buffer_depth: int) -> str | None:
        """The reason a fine-tune should run now, or None. Never fires
        on an empty buffer — there is nothing to train on."""
        now = self._clock()
        p = self.policy
        if buffer_depth < 1:
            return None
        if (p.cooldown_s > 0.0 and self._last_fired is not None
                and now - self._last_fired < p.cooldown_s):
            return None
        fresh = buffer_depth - self._consumed_depth
        if p.min_buffer > 0 and fresh >= p.min_buffer:
            return f"buffer_size ({fresh} fresh >= {p.min_buffer})"
        if p.variance_drift > 0.0:
            ratio = self.drift_ratio()
            if ratio >= p.variance_drift:
                return (f"variance_drift ({ratio:.2f}x baseline >= "
                        f"{p.variance_drift:.2f}x)")
        if p.interval_s > 0.0:
            since = now - (self._last_fired if self._last_fired is not None
                           else self._interval_anchor)
            if since >= p.interval_s:
                return f"interval ({p.interval_s:.0f}s cadence)"
        return None

    def note_fired(self, buffer_depth: int) -> None:
        self._last_fired = self._clock()
        self._consumed_depth = int(buffer_depth)
        self.fired += 1


@dataclass
class FineTuneReport:
    """What one fine-tune job did. ``params`` is None when the holdout
    gate rejected the candidate (the live model stays)."""

    params: object = None
    shipped: bool = False
    val_before: float = float("nan")
    val_after: float = float("nan")
    steps: int = 0
    n_train: int = 0
    n_holdout: int = 0
    resumed_step: int = 0
    reason: str = ""
    history: list = field(default_factory=list)


def holdout_split(samples, holdout_frac: float = 0.25,
                  min_holdout: int = 1):
    """Deterministic train/holdout split of a buffer snapshot: every
    ``round(1/holdout_frac)``-th sample (by buffer priority order) is
    held out, so both sides span the variance range."""
    n = len(samples)
    k = max(int(round(n * holdout_frac)), min_holdout)
    if n < 2 or k >= n:
        return list(samples), list(samples[:max(n, 1)])
    stride = max(n // k, 2)
    hold_idx = set(range(0, n, stride))
    holdout = [s for i, s in enumerate(samples) if i in hold_idx]
    train = [s for i, s in enumerate(samples) if i not in hold_idx]
    return train, holdout


def run_finetune(model, params, samples, *, optimizer=None,
                 steps: int = 50, holdout_frac: float = 0.25,
                 learning_rate: float = 1e-3, min_improvement: float = 0.0,
                 checkpoint_dir: str | None = None,
                 config=None, micro_batch_size=None,
                 loader_kwargs: dict | None = None,
                 telemetry=None) -> FineTuneReport:
    """One gated fine-tune of ``params`` on buffered samples.

    Builds a Trainer over the train split (``loader_kwargs`` carries the
    model-specific plumbing — ``species_fn``, ``use_bond_graph``/
    ``bond_cutoff``), resumes from ``checkpoint_dir`` when an
    interrupted job left a checkpoint there, runs ``steps`` optimizer
    steps, and evaluates holdout loss before/after on the weights that
    would ship (EMA when enabled). The candidate ships only when
    ``val_after < val_before * (1 - min_improvement)``."""
    import optax

    from ..train import TrainConfig, Trainer
    from ..train.checkpoint import latest_checkpoint

    train_set, holdout = holdout_split(samples, holdout_frac)
    lk = dict(loader_kwargs or {})
    # default: NO EMA — an active-learning fine-tune is short (tens of
    # steps), and an EMA over so few steps is still mostly the initial
    # (drifted) weights; pass a config with ema_decay > 0 for long jobs
    cfg = config or TrainConfig(ema_decay=0.0)
    if micro_batch_size is None:
        micro_batch_size = max(min(len(train_set) // cfg.accum_steps, 4), 1)
    trainer = Trainer(
        model.energy_fn, params, optimizer or optax.adam(learning_rate),
        train_set, float(model.cfg.cutoff),
        micro_batch_size=micro_batch_size, config=cfg,
        val_samples=holdout, checkpoint_dir=checkpoint_dir,
        checkpoint_every=max(steps // 2, 1) if checkpoint_dir else 0,
        telemetry=telemetry, loader_kwargs=lk)
    try:
        # the gate's baseline is the LIVE serving weights — evaluated
        # BEFORE any checkpoint restore, so a resumed job that was
        # mid-divergence when preempted is still compared against what
        # is actually serving, not against its own bad checkpoint
        val_before = trainer.evaluate()["loss"]
        resumed = 0
        if checkpoint_dir and latest_checkpoint(checkpoint_dir) is not None:
            # preemption recovery: a killed job's newest checkpoint
            # carries the full TrainState + loader cursor — continue,
            # don't restart
            resumed = trainer.restore()
        remaining = max(steps - resumed, 0)
        history = trainer.fit(steps=remaining) if remaining else []
        val_after = trainer.evaluate()["loss"]
        candidate = (trainer.state.ema_params if cfg.ema_decay > 0.0
                     else trainer.state.params)
        shipped = val_after < val_before * (1.0 - float(min_improvement))
        return FineTuneReport(
            params=candidate if shipped else None, shipped=shipped,
            val_before=float(val_before), val_after=float(val_after),
            steps=remaining, n_train=len(train_set),
            n_holdout=len(holdout), resumed_step=resumed,
            history=[h.get("loss") for h in history])
    finally:
        trainer.close()
