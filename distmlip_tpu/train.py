"""Training: energy/force/stress matching over the graph-parallel mesh.

The reference is inference-only (training stays in upstream libraries,
reference README.md:53); here training is first-class: the loss
differentiates through the same sharded potential (halo exchanges included),
so gradients w.r.t. parameters aggregate across partitions with a psum —
graph parallelism doubles as data parallelism over space.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .parallel.runtime import make_total_energy


def make_loss_fn(model_energy_fn, mesh, w_energy=1.0, w_force=1.0, w_stress=0.0):
    """Loss: (params, graph, positions, targets) -> scalar.

    targets: dict with 'energy' (),
             'forces' (P, N_cap, 3) in the graph's local layout,
             optional 'stress' (3, 3).
    Forces are compared on owned rows only (halo rows belong to a peer).
    """
    total_energy = make_total_energy(model_energy_fn, mesh)

    def loss_fn(params, graph, positions, targets):
        strain = jnp.zeros((3, 3), dtype=positions.dtype)
        if w_force > 0.0 or w_stress > 0.0:
            energy, (g_pos, g_strain) = jax.value_and_grad(
                total_energy, argnums=(2, 3)
            )(params, graph, positions, strain)
            forces = -g_pos
        else:
            energy = total_energy(params, graph, positions, strain)
            forces = None
        n_atoms = jnp.maximum(graph.n_total_nodes.astype(energy.dtype), 1.0)
        loss = w_energy * ((energy - targets["energy"]) / n_atoms) ** 2
        if w_force > 0.0:
            mask = graph.owned_mask[..., None]
            diff = jnp.where(mask, forces - targets["forces"], 0.0)
            loss = loss + w_force * jnp.sum(diff**2) / (3.0 * n_atoms)
        if w_stress > 0.0:
            vol = jnp.abs(jnp.linalg.det(graph.lattice.astype(energy.dtype)))
            stress = g_strain / vol
            loss = loss + w_stress * jnp.mean((stress - targets["stress"]) ** 2)
        return loss

    return loss_fn


def make_train_step(model_energy_fn, mesh, optimizer, w_energy=1.0, w_force=1.0,
                    w_stress=0.0):
    """Jitted SGD/optax step over the sharded loss.

    Returns step(params, opt_state, graph, positions, targets) ->
    (params, opt_state, loss).
    """
    loss_fn = make_loss_fn(model_energy_fn, mesh, w_energy, w_force, w_stress)

    @jax.jit
    def step(params, opt_state, graph, positions, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, graph, positions, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step
