"""Serving fleet: multi-replica routing, tenancy, caching, AOT restart.

The production serving layer over :mod:`distmlip_tpu.serve`: N
``ServeEngine`` replicas (in-process for tests and single-host serving;
one process + chip grant each in real deployments) behind a
:class:`FleetRouter` with per-tenant admission quotas and weighted
fairness, a content-addressed :class:`ResultCache` so duplicate
screening traffic never touches a chip, wedge-detecting health monitoring
with zero-request-loss failover (:class:`ReplicaHealth`), and an
:class:`AotExecutableCache` that rehydrates a restarted replica's whole
bucket ladder with zero recompiles.

Quick start::

    from distmlip_tpu.calculators import BatchedPotential
    from distmlip_tpu.fleet import ResultCache, make_fleet

    router = make_fleet(
        2, lambda i: BatchedPotential(model, params),
        aot_cache_dir="/var/cache/distmlip-aot",
        result_cache=ResultCache(max_bytes=256 * 2**20),
        model_id="mace-mp0", precision="float32")
    fut = router.submit(atoms, tenant="interactive", priority=-1)
    result = fut.result()      # survives any single replica dying
    router.close()

Chaos drill / gate: ``python tools/load_test.py --fleet 2
--chaos kill-replica --check``.
"""

from .aot import AotExecutableCache, install_aot_cache, model_fingerprint
from .replica import Replica, ReplicaHealth
from .result_cache import ResultCache, cache_key, structure_key
from .router import FleetError, FleetRouter, FleetStats, make_fleet
from .tenancy import FairScheduler, TenantConfig, TokenBucket

__all__ = [
    "FleetRouter",
    "FleetStats",
    "FleetError",
    "make_fleet",
    "Replica",
    "ReplicaHealth",
    "ResultCache",
    "cache_key",
    "structure_key",
    "TenantConfig",
    "TokenBucket",
    "FairScheduler",
    "AotExecutableCache",
    "install_aot_cache",
    "model_fingerprint",
]
