"""AOT executable cache: restart a replica with ZERO recompiles.

At production scale cold-start compiles ARE the outage: a restarted
replica that has to re-trace and re-compile its whole bucket ladder
serves nothing for minutes (the export/AOT discipline of arXiv
2504.16068 is the pattern this module reproduces). So every bucket
executable a :class:`~distmlip_tpu.calculators.batched.BatchedPotential`
compiles is serialized to disk via ``jax.export`` and rehydrated by the
next replica that needs the same bucket:

- **key** = ``(bucket_key, model fingerprint, capacity-ladder
  fingerprint, jax version + backend)``. The bucket key pins the padded
  shapes, the model fingerprint pins the traced program (config + param
  tree structure/shapes/dtypes — NOT param values, which are runtime
  inputs), the ladder fingerprint (``BucketPolicy.fingerprint``) pins the
  quantization that produced the shapes, and the jax/backend pair pins
  the StableHLO dialect + target. ANY mismatch is a clean miss.
- **rehydrate** (:func:`install_aot_cache`): the potential's jitted
  callable is wrapped by a dispatcher that serves a cached bucket through
  the deserialized executable — the jit NEVER traces, so
  ``BatchedPotential.compile_count`` stays 0 (the cold-start acceptance
  gate) — and falls back to the normal JIT transparently on a miss,
  a corrupt entry, or a call-time mismatch (stale pytree layout).
- **save**: after a fresh JIT compile of a new bucket, the program is
  exported (``jit.lower`` — an abstract trace, no second device compile)
  and written atomically. Best-effort: an export failure never fails the
  batch (mesh-sharded programs, for example, may not serialize on every
  jax build — they simply stay JIT-only).

Numerics: the deserialized executable runs the SAME StableHLO the JIT
path compiles, so rehydrated results are fp-identical to a cold compile
on the same backend (pinned by tests/test_fleet_cache.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time

from ..obs import profiling as _profiling


_EXPORT_REGISTERED = False


def _ensure_export_registrations() -> None:
    """Teach jax.export to serialize the PartitionedGraph pytree node.

    ``register_dataclass`` flattens the graph with its meta fields as a
    flat auxdata tuple — (num_partitions, shifts, has_bond_graph, n_cap,
    e_cap, b_cap, e_split, batch_size, spatial_parts) — which encodes to
    JSON directly; only ``shifts`` needs its tuple-ness restored on the
    way back (pytree auxdata equality is by value AND type)."""
    global _EXPORT_REGISTERED
    if _EXPORT_REGISTERED:
        return
    from jax import export as jax_export

    from ..partition.graph import PartitionedGraph

    def _ser(aux) -> bytes:
        return json.dumps(list(aux)).encode()

    def _des(data: bytes):
        aux = json.loads(data.decode())
        aux[1] = tuple(aux[1])  # shifts
        return tuple(aux)

    try:
        jax_export.register_pytree_node_serialization(
            PartitionedGraph,
            serialized_name="distmlip_tpu.partition.graph.PartitionedGraph",
            serialize_auxdata=_ser, deserialize_auxdata=_des)
    except ValueError:
        pass  # already registered by another cache instance
    _EXPORT_REGISTERED = True


def model_fingerprint(model, params) -> str:
    """Digest of everything that shapes the traced program besides the
    packed graph: model class + config, and the param pytree's structure
    with leaf shapes/dtypes (values are call arguments, not constants)."""
    import jax

    h = hashlib.sha256()
    h.update(type(model).__name__.encode())
    cfg = getattr(model, "cfg", None)
    if cfg is not None:
        for k, v in sorted(vars(cfg).items()):
            h.update(f"{k}={v!r};".encode())
    leaves, treedef = jax.tree.flatten(params)
    h.update(str(treedef).encode())
    for leaf in leaves:
        h.update(f"{getattr(leaf, 'shape', ())}:"
                 f"{getattr(leaf, 'dtype', type(leaf).__name__)};".encode())
    return h.hexdigest()[:16]


def backend_fingerprint() -> str:
    import jax

    return f"jax{jax.__version__}:{jax.default_backend()}"


class AotExecutableCache:
    """Disk cache of serialized bucket executables (one file per key).

    ``fingerprint`` is the model digest (:func:`model_fingerprint`);
    ``ladder`` the capacity-policy fingerprint. Counters: ``rehydrated``
    (buckets served from disk), ``saved``, ``misses`` (bucket had no
    usable entry), ``errors`` (corrupt/stale entries that fell back to
    JIT)."""

    def __init__(self, cache_dir: str, fingerprint: str = "",
                 ladder: str = ""):
        self.cache_dir = str(cache_dir)
        self.fingerprint = fingerprint
        self.ladder = ladder
        os.makedirs(self.cache_dir, exist_ok=True)
        self._lock = threading.Lock()
        self.rehydrated = 0
        self.saved = 0
        self.misses = 0
        self.errors = 0
        # dispatches that fell through to a REAL jit trace+compile (the
        # per-replica fresh-vs-aot split; rehydrated counts the aot side)
        self.fresh_compiles = 0

    @classmethod
    def for_potential(cls, cache_dir: str, pot) -> "AotExecutableCache":
        """Key the cache on a BatchedPotential's model/params/ladder."""
        fp = getattr(pot.caps, "fingerprint", None)
        return cls(cache_dir,
                   fingerprint=model_fingerprint(pot.model, pot.params),
                   ladder=fp() if fp is not None else "")

    def entry_key(self, bucket_key: str) -> str:
        raw = (f"{bucket_key}|{self.fingerprint}|{self.ladder}|"
               f"{backend_fingerprint()}")
        return hashlib.sha256(raw.encode()).hexdigest()[:32]

    def _path(self, bucket_key: str) -> str:
        return os.path.join(self.cache_dir,
                            f"{self.entry_key(bucket_key)}.jaxexp")

    def load(self, bucket_key: str) -> bytes | None:
        try:
            with open(self._path(bucket_key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def save(self, bucket_key: str, payload: bytes) -> None:
        """Atomic write (tmp + rename) so a concurrently restarting
        replica never deserializes a half-written entry."""
        path = self._path(bucket_key)
        fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # sidecar index line (human debugging: which bucket is which file)
        try:
            with open(os.path.join(self.cache_dir, "index.jsonl"), "a") as f:
                f.write(json.dumps({"bucket": bucket_key,
                                    "file": os.path.basename(path),
                                    "model": self.fingerprint,
                                    "ladder": self.ladder,
                                    "backend": backend_fingerprint()}) + "\n")
        except OSError:
            pass

    def stats(self) -> dict:
        with self._lock:
            return {"rehydrated": self.rehydrated, "saved": self.saved,
                    "misses": self.misses, "errors": self.errors,
                    "fresh_compiles": self.fresh_compiles}


class _AotDispatcher:
    """Drop-in wrapper around a BatchedPotential's jitted callable.

    Per call: resolve the packed graph's bucket key; serve from a
    deserialized executable when the cache has the bucket (the wrapped
    jit never traces — ``_cache_size`` stays 0), else run the jit and
    export the freshly compiled bucket for the next restart.
    ``last_dispatch_aot`` reports which path the LAST call took
    (BatchedPotential plumbs it into ``last_stats``/telemetry as
    ``aot_rehydrated``)."""

    # BatchedPotential checks this duck-type flag: the dispatcher records
    # its own compile events (fresh AND aot, with the true split), so the
    # batched layer must not double-record them
    _records_compiles = True

    def __init__(self, jit_fn, cache: AotExecutableCache, save: bool = True):
        self._jit = jit_fn
        self._cache = cache
        self._save = bool(save)
        self._loaded: dict[str, object] = {}   # bucket_key -> jitted call
        self._failed: set[str] = set()         # buckets proven unusable
        self._saved: set[str] = set()          # buckets exported this run
        self._lock = threading.Lock()
        self.last_dispatch_aot = False
        # compile telemetry of the LAST dispatch (0.0/"" = warm, no
        # compile happened); BatchedPotential stamps these onto the
        # StepRecord as compile_s/compile_kind
        self.last_dispatch_compile_s = 0.0
        self.last_dispatch_kind = ""

    # BatchedPotential.compile_count reads this: only REAL jit traces
    # count — a rehydrated bucket must keep the counter at zero
    def _cache_size(self) -> int:
        size_fn = getattr(self._jit, "_cache_size", None)
        return int(size_fn()) if size_fn is not None else 0

    def _rehydrate(self, key: str):
        import jax
        from jax import export as jax_export

        _ensure_export_registrations()
        t0 = time.perf_counter()
        data = self._cache.load(key)
        if data is None:
            with self._cache._lock:
                self._cache.misses += 1
            return None
        try:
            exp = jax_export.deserialize(data)
            # jit the exported call so the StableHLO compiles once and
            # subsequent batches of this bucket hit the executable
            fn = jax.jit(exp.call)
        except Exception:  # noqa: BLE001 - corrupt/stale entry: JIT wins
            with self._cache._lock:
                self._cache.errors += 1
            return None
        with self._cache._lock:
            self._cache.rehydrated += 1
        self.last_dispatch_compile_s = time.perf_counter() - t0
        self.last_dispatch_kind = _profiling.KIND_AOT
        _profiling.record_compile(
            site="aot_dispatch", kind=_profiling.KIND_AOT,
            wall_s=self.last_dispatch_compile_s, bucket_key=key,
            executable_bytes=len(data))
        return fn

    def __call__(self, params, graph, positions):
        from ..partition.batch import bucket_key as _bucket_key

        key = _bucket_key(graph)
        self.last_dispatch_compile_s = 0.0
        self.last_dispatch_kind = ""
        with self._lock:
            fn = self._loaded.get(key)
            known_bad = key in self._failed
        if fn is None and not known_bad:
            fn = self._rehydrate(key)   # stamps last_dispatch_* on success
            with self._lock:
                if fn is not None:
                    self._loaded[key] = fn
                else:
                    self._failed.add(key)
        if fn is not None:
            try:
                out = fn(params, graph, positions)
                self.last_dispatch_aot = True
                return out
            except Exception:  # noqa: BLE001 - stale layout: fall back
                with self._lock:
                    self._loaded.pop(key, None)
                    self._failed.add(key)
                with self._cache._lock:
                    self._cache.errors += 1
                self.last_dispatch_compile_s = 0.0
                self.last_dispatch_kind = ""
        self.last_dispatch_aot = False
        n0 = self._cache_size()
        t0 = time.perf_counter()
        out = self._jit(params, graph, positions)
        if self._cache_size() > n0:
            # a REAL trace+lower+compile ran inside this dispatch (wall
            # includes the bucket's first execution — same convention as
            # the batched engine's compile-step device_s)
            self.last_dispatch_compile_s = time.perf_counter() - t0
            self.last_dispatch_kind = _profiling.KIND_FRESH
            with self._cache._lock:
                self._cache.fresh_compiles += 1
            _profiling.record_compile(
                site="aot_dispatch", kind=_profiling.KIND_FRESH,
                wall_s=self.last_dispatch_compile_s, bucket_key=key)
        if self._save:
            with self._lock:
                fresh = key not in self._saved
                self._saved.add(key)
            if fresh:
                self._export(key, params, graph, positions)
        return out

    def _export(self, key, params, graph, positions) -> None:
        """Serialize the just-compiled bucket program (abstract re-trace,
        no second device compile). Best-effort by contract."""
        try:
            from jax import export as jax_export

            _ensure_export_registrations()
            exp = jax_export.export(self._jit)(params, graph, positions)
            self._cache.save(key, exp.serialize())
            with self._cache._lock:
                self._cache.saved += 1
        except Exception:  # noqa: BLE001 - export must never fail a batch
            pass


def install_aot_cache(pot, cache: AotExecutableCache | str,
                      save: bool = True):
    """Wrap ``pot``'s jitted potential with the AOT dispatcher.

    ``cache`` may be a ready :class:`AotExecutableCache` or a directory
    path (keyed automatically via :meth:`AotExecutableCache.
    for_potential`). Returns ``pot`` (mutated in place): its
    ``compile_count`` keeps counting only real JIT traces, and
    ``pot.aot_cache`` exposes the cache for stats/assertions.

    Note: a bucket served purely from the AOT cache never runs the
    static HBM calibration trace (that rides the fresh-compile path), so
    a rehydrated replica's bytes model starts uncalibrated — identical
    to a cold replica's first batch, and self-correcting on the first
    genuinely new bucket."""
    if not isinstance(cache, AotExecutableCache):
        cache = AotExecutableCache.for_potential(str(cache), pot)
    if isinstance(pot._potential, _AotDispatcher):   # idempotent
        pot._potential._cache = cache
        pot.aot_cache = cache
        return pot
    pot._potential = _AotDispatcher(pot._potential, cache, save=save)
    pot.aot_cache = cache
    return pot
