"""Content-addressed result cache: duplicate traffic never touches a chip.

Screening workloads are duplicate-heavy — the same relaxed candidates come
back through different pipelines, the same benchmark structures are
re-submitted by every tenant — and an MLIP result is a pure function of
``(structure, model, requested properties, precision)``. So the fleet
router fronts every dispatch with this cache:

- **structure hashing** (:func:`structure_key`): canonical-order,
  tolerance-bucketed. Positions are wrapped into the cell along periodic
  axes (a wrapped copy of a structure is the SAME structure), quantized
  onto a ``tol``-sized grid (coordinates within the same bucket hash
  equal; exact bucket-boundary straddles legitimately differ — the
  quantization is ``round(x / tol)``, documented and pinned by tests),
  and atoms are sorted by (species, quantized coordinates) so input
  order never matters. The cell, pbc flags and scalar ``atoms.info``
  conditioning (UMA charge/spin/dataset change the energy!) fold into
  the digest.
- **full cache key** (:func:`cache_key`): structure digest x model id x
  canonical requested-properties tuple x precision. An energy-only entry
  therefore can NEVER serve a forces request — different key, clean miss.
- **LRU byte bound**: entries cost their numpy payload bytes; inserts
  evict least-recently-used entries until the bound holds. Oversized
  single results are simply not cached.
- **copy-on-return**: ``get``/``put`` deep-copy array payloads, so a
  caller mutating a returned forces array can never corrupt the cached
  entry (or another caller's view of it).

Thread-safe (one lock; the router's dispatch callbacks and submit path
share it). Hit/miss/eviction counters ride ``stats()`` and the fleet
telemetry records.
"""

from __future__ import annotations

import hashlib
import threading

import numpy as np

FULL_PROPERTIES = "full"


def _quantize(x: np.ndarray, tol: float) -> np.ndarray:
    return np.round(np.asarray(x, dtype=np.float64) / tol).astype(np.int64)


def structure_key(atoms, tol: float = 1e-5) -> str:
    """Canonical-order, tolerance-bucketed content hash of a structure.

    ``tol`` is the coordinate bucket width in Å (cell entries use the
    same grid). Invariant under atom reordering and under wrapping
    positions by whole lattice vectors along periodic axes; sensitive to
    species, cell, pbc, and any scalar ``atoms.info`` entries (model
    conditioning)."""
    pos = np.asarray(atoms.positions, dtype=np.float64)
    cell = np.asarray(atoms.cell, dtype=np.float64)
    pbc = np.asarray(atoms.pbc, dtype=bool)
    numbers = np.asarray(atoms.numbers, dtype=np.int64)
    if pbc.any() and abs(np.linalg.det(cell)) > 1e-12:
        # wrap along the periodic axes only: fractional coords mod 1 for
        # pbc axes, untouched otherwise — then back to Cartesian so the
        # tolerance grid is isotropic in Å regardless of cell shape
        frac = pos @ np.linalg.inv(cell)
        frac[:, pbc] -= np.floor(frac[:, pbc])
        # numeric wrap hygiene: 1.0 - eps floors to 0 after quantization
        # only if we re-quantize in Cartesian space (done below)
        pos = frac @ cell
    qpos = _quantize(pos, tol)
    qcell = _quantize(cell, tol)
    order = np.lexsort((qpos[:, 2], qpos[:, 1], qpos[:, 0], numbers))
    h = hashlib.sha256()
    h.update(np.int64(len(numbers)).tobytes())
    h.update(numbers[order].tobytes())
    h.update(qpos[order].tobytes())
    h.update(qcell.tobytes())
    h.update(pbc.astype(np.int8).tobytes())
    info = getattr(atoms, "info", None) or {}
    for k in sorted(info):
        v = info[k]
        if isinstance(v, (str, int, float, bool, np.integer, np.floating)):
            h.update(f"{k}={v!r};".encode())
    return h.hexdigest()


def canonical_properties(properties) -> str:
    """Stable id of the requested property set (None = the full result
    dict): sorted, deduplicated, 'energy' always included (the engine
    always returns it)."""
    if properties is None:
        return FULL_PROPERTIES
    return ",".join(sorted(set(properties) | {"energy"}))


def cache_key(atoms, model_id: str, properties=None,
              precision: str = "float32", tol: float = 1e-5) -> str:
    """The full content address: (structure, model, properties, precision).

    Property sets are part of the KEY, so an entry computed for one set
    never serves a request for another (an energy-only entry must not
    answer a forces request with a dict that lacks forces)."""
    return (f"{structure_key(atoms, tol=tol)}|{model_id}|"
            f"{canonical_properties(properties)}|{precision}")


def _copy_result(result: dict) -> dict:
    return {k: (v.copy() if isinstance(v, np.ndarray) else v)
            for k, v in result.items()}


def _result_bytes(result: dict) -> int:
    n = 128  # dict + key overhead
    for v in result.values():
        n += v.nbytes if isinstance(v, np.ndarray) else 32
    return n


class ResultCache:
    """LRU result cache with a byte bound and copy-on-return semantics.

    ``max_bytes`` bounds the summed numpy payload of the live entries
    (default 256 MiB); inserts evict from the least-recently-used end.
    ``get``/``put`` both copy array payloads — the cache's arrays are
    never aliased by any caller."""

    def __init__(self, max_bytes: int = 256 * 2**20):
        if max_bytes < 1:
            raise ValueError("max_bytes must be >= 1")
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}     # insertion order = LRU order
        self._bytes: dict[str, int] = {}
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.skipped_oversize = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: str):
        """The cached result (a fresh copy) or None. Counts hit/miss."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            # LRU touch: move to the most-recent end
            del self._entries[key]
            self._entries[key] = entry
            self.hits += 1
            return _copy_result(entry)

    def put(self, key: str, result: dict) -> bool:
        """Store a copy of ``result``; returns False when it alone exceeds
        the byte bound (not cached). Replacing an existing key refreshes
        its LRU position."""
        nbytes = _result_bytes(result)
        if nbytes > self.max_bytes:
            with self._lock:
                self.skipped_oversize += 1
            return False
        entry = _copy_result(result)
        with self._lock:
            if key in self._entries:
                self.total_bytes -= self._bytes.pop(key)
                del self._entries[key]
            while self.total_bytes + nbytes > self.max_bytes and self._entries:
                old_key = next(iter(self._entries))
                del self._entries[old_key]
                self.total_bytes -= self._bytes.pop(old_key)
                self.evictions += 1
            self._entries[key] = entry
            self._bytes[key] = nbytes
            self.total_bytes += nbytes
        return True

    def hit_rate(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.total_bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / total if total else 0.0,
                "evictions": self.evictions,
                "skipped_oversize": self.skipped_oversize,
            }
