"""Serving replicas and the wedge-detecting health monitor.

A :class:`Replica` wraps one :class:`~distmlip_tpu.serve.ServeEngine`
(its own ``BatchedPotential``, its own compile cache, in real
deployments its own process + chip grant) with the fleet-facing state
the router needs: an id, an alive flag, and the dispatch bookkeeping for
least-loaded routing.

:class:`ReplicaHealth` watches every replica with the same suspicion
discipline bench.py uses on wedged chip grants
(:class:`~distmlip_tpu.utils.health.ReprobePolicy`): a replica whose
scheduler thread died, or which holds queued/in-flight work without
making dispatch progress for ``stall_budget_s`` (the BENCH_r03–r05
signature — a grant that neither serves nor fails), is marked SUSPECT;
bounded re-probes with backoff either observe recovery or confirm the
wedge, at which point the monitor fails the replica over through the
router — reclaiming its queued requests and re-dispatching them on
survivors, so the wedge costs latency, never Futures."""

from __future__ import annotations

import threading
import time

from ..obs import runtime as obsrt
from ..utils.health import ReprobePolicy


class Replica:
    """One engine behind the router."""

    def __init__(self, engine, replica_id: str):
        self.engine = engine
        self.replica_id = str(replica_id)
        self.alive = True
        # router-side dispatch bookkeeping (guarded by the ROUTER lock)
        self.outstanding = 0
        self.dispatched_total = 0

    def health_snapshot(self) -> dict:
        snap_fn = getattr(self.engine, "health_snapshot", None)
        if snap_fn is None:
            return {"scheduler_alive": True, "queue_depth": 0,
                    "inflight": 0, "last_progress_age_s": 0.0}
        return snap_fn()

    def healthy(self, stall_budget_s: float) -> bool:
        """Liveness + progress: the scheduler thread is serving, and any
        held work has seen dispatch progress within the stall budget."""
        if not self.alive:
            return False
        snap = self.health_snapshot()
        if not snap["scheduler_alive"]:
            return False
        busy = snap["queue_depth"] > 0 or snap["inflight"] > 0
        return not (busy and snap["last_progress_age_s"] > stall_budget_s)


class ReplicaHealth:
    """Poll replicas; confirm wedges via bounded re-probe; fail over.

    ``router`` must expose ``replicas`` (id -> Replica) and
    ``fail_over(replica_id, reason=...)``. ``poll_once()`` is the
    deterministic test surface; ``start()`` runs it on a daemon thread
    every ``interval_s``. ``clock`` is injectable (tests share a fake
    clock with the engines so stall ages and backoff windows advance
    together).

    ``stall_budget_s`` (default 300 s) MUST exceed the fleet's worst
    cold-start compile: a replica JIT-compiling its first bucket makes
    no dispatch progress and is indistinguishable from a wedge by this
    probe — an AOT-cache-warmed fleet can run a much tighter budget
    than a cold one. As a backstop, the monitor never auto-fails-over
    the LAST alive replica (killing it converts "slow" into a total
    self-inflicted outage; a confirmed wedge there is reported as
    ``"wedged"`` for the operator, and ``router.fail_over`` remains
    available as an explicit action)."""

    def __init__(self, router, interval_s: float = 1.0,
                 stall_budget_s: float = 300.0, max_reprobes: int = 1,
                 backoff_s: float = 1.0, clock=None, start: bool = False):
        self.router = router
        self.interval_s = float(interval_s)
        self.stall_budget_s = float(stall_budget_s)
        self.max_reprobes = int(max_reprobes)
        self.backoff_s = float(backoff_s)
        self._clock = clock or time.monotonic
        self._policies: dict[str, ReprobePolicy] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.failovers = 0
        if start:
            self.start()

    def _policy(self, replica_id: str) -> ReprobePolicy:
        pol = self._policies.get(replica_id)
        if pol is None:
            pol = ReprobePolicy(max_reprobes=self.max_reprobes,
                                backoff_s=self.backoff_s, clock=self._clock)
            self._policies[replica_id] = pol
        return pol

    def poll_once(self) -> dict:
        """One probe sweep; returns {replica_id: "healthy" | "suspect" |
        "wedged" | "dead"} (dead = already failed over / killed)."""
        verdicts = {}
        for rid, replica in list(self.router.replicas.items()):
            if not replica.alive:
                verdicts[rid] = "dead"
                continue
            verdict = self._policy(rid).observe(
                replica.healthy(self.stall_budget_s))
            verdicts[rid] = verdict
            if verdict in ("suspect", "wedged"):
                # first wedge SUSPICION is already flight-recorder
                # material: by the time the wedge is confirmed and the
                # failover reclaims the queue, the interesting state
                # (span trees of the stalled requests, queue-depth
                # gauges) is gone. Rate-limited inside the recorder.
                fl = obsrt.flight()
                if fl is not None:
                    fl.capture(
                        f"replica {rid} {verdict}: no dispatch progress "
                        f"within {self.stall_budget_s:.0f}s",
                        attrs={"replica": rid,
                               **replica.health_snapshot()})
            if verdict == "wedged":
                alive_others = any(
                    r.alive for other_id, r in self.router.replicas.items()
                    if other_id != rid)
                if not alive_others:
                    continue    # never auto-kill the last alive replica
                self.failovers += 1
                self.router.fail_over(
                    rid, reason=(f"health monitor: no dispatch progress "
                                 f"within {self.stall_budget_s:.0f}s after "
                                 f"{self.max_reprobes} re-probe(s)"))
        return verdicts

    # ---- background thread ----

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="distmlip-fleet-health", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the monitor must survive
                pass

    def close(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
