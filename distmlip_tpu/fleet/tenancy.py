"""Per-tenant admission quotas and weighted fair scheduling.

Two pure, thread-unsafe-by-design primitives (the router serializes
access under its own lock; tests drive them directly with fake clocks):

- :class:`TokenBucket` — the admission quota. A tenant's submissions
  spend tokens that refill at ``rate_hz`` up to ``burst``; an empty
  bucket means the submit is REJECTED at the router door
  (``ServeRejected``), so one screening firehose exhausts its own quota
  instead of the fleet's queues. ``rate_hz=None`` disables the quota
  (interactive tenants are typically unmetered and protected by
  fairness, not by a cap).

- :class:`FairScheduler` — weighted fair queuing over per-tenant FIFO
  queues via stride scheduling: each tenant carries a virtual ``pass``
  value advanced by ``1/weight`` per dispatched request, and ``pop()``
  always serves the backlogged tenant with the smallest pass. A
  weight-3 tenant therefore gets 3x the dispatch slots of a weight-1
  tenant under contention, and ANY backlogged tenant is served within
  one full rotation — no starvation, regardless of how deep another
  tenant's backlog is. An idle tenant's pass is clamped forward on its
  next enqueue so sleeping never banks credit.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass


@dataclass
class TenantConfig:
    """Declarative per-tenant policy (router ``tenants=`` mapping).

    ``weight``: fair-share weight under contention (default 1.0).
    ``rate_hz``: token-bucket refill rate in requests/sec; None = no
    quota. ``burst``: bucket capacity (default: 2 s worth of rate,
    minimum 1)."""

    weight: float = 1.0
    rate_hz: float | None = None
    burst: float | None = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.rate_hz is not None and self.rate_hz <= 0:
            raise ValueError(f"rate_hz must be > 0, got {self.rate_hz}")


class TokenBucket:
    """Classic token bucket on an injectable monotonic clock."""

    def __init__(self, rate_hz: float, burst: float | None = None,
                 clock=None):
        if rate_hz <= 0:
            raise ValueError("rate_hz must be > 0")
        self.rate_hz = float(rate_hz)
        self.burst = float(burst) if burst is not None \
            else max(2.0 * rate_hz, 1.0)
        self._clock = clock or time.monotonic
        self.tokens = self.burst
        self._t_last = self._clock()

    def _refill(self) -> None:
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t_last) * self.rate_hz)
        self._t_last = now

    def take(self, n: float = 1.0) -> bool:
        """Spend ``n`` tokens if available; False = over quota."""
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class _TenantState:
    __slots__ = ("name", "weight", "bucket", "queue", "pass_value",
                 "submitted", "dispatched", "quota_rejects")

    def __init__(self, name: str, config: TenantConfig, clock):
        self.name = name
        self.weight = float(config.weight)
        self.bucket = (TokenBucket(config.rate_hz, config.burst, clock=clock)
                       if config.rate_hz is not None else None)
        self.queue: deque = deque()
        self.pass_value = 0.0
        self.submitted = 0
        self.dispatched = 0
        self.quota_rejects = 0


class FairScheduler:
    """Stride-scheduled weighted fair queuing over named tenant queues."""

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._tenants: dict[str, _TenantState] = {}
        self._global_pass = 0.0

    def tenant(self, name: str,
               config: TenantConfig | None = None) -> _TenantState:
        """Get-or-create a tenant (unknown tenants get default policy)."""
        st = self._tenants.get(name)
        if st is None:
            st = _TenantState(name, config or TenantConfig(), self._clock)
            # late joiners start at the current virtual time, not at 0 —
            # otherwise a new tenant would monopolize dispatch until its
            # pass catches up with the long-running tenants'
            st.pass_value = self._global_pass
            self._tenants[name] = st
        return st

    def configure(self, name: str, config: TenantConfig) -> None:
        st = self.tenant(name, config)
        st.weight = float(config.weight)
        st.bucket = (TokenBucket(config.rate_hz, config.burst,
                                 clock=self._clock)
                     if config.rate_hz is not None else None)

    def admit(self, name: str) -> bool:
        """Charge the tenant's quota for one submission; False = over."""
        st = self.tenant(name)
        if st.bucket is not None and not st.bucket.take(1.0):
            st.quota_rejects += 1
            return False
        st.submitted += 1
        return True

    def enqueue(self, name: str, item, front: bool = False) -> None:
        """Queue an admitted item. ``front=True`` re-queues a reclaimed
        (failover) item at the head WITHOUT a fresh pass charge — a
        request should not lose its place because its replica died."""
        st = self.tenant(name)
        if front:
            st.queue.appendleft(item)
            # refund the stride the original dispatch charged
            st.pass_value = max(st.pass_value - 1.0 / st.weight,
                                self._global_pass - 1.0 / st.weight)
        else:
            if not st.queue:
                # waking from idle: clamp forward so sleeping banks nothing
                st.pass_value = max(st.pass_value, self._global_pass)
            st.queue.append(item)

    def pop(self):
        """``(tenant_name, item)`` of the next fair dispatch, or None.

        Serves the backlogged tenant with the smallest pass value
        (ties: name order, deterministic) and advances its pass by
        ``1/weight``."""
        best = None
        for st in self._tenants.values():
            if not st.queue:
                continue
            if best is None or (st.pass_value, st.name) < (best.pass_value,
                                                           best.name):
                best = st
        if best is None:
            return None
        item = best.queue.popleft()
        best.pass_value += 1.0 / best.weight
        best.dispatched += 1
        self._global_pass = max(self._global_pass, best.pass_value)
        return best.name, item

    def backlog(self) -> int:
        return sum(len(st.queue) for st in self._tenants.values())

    def queued(self, name: str) -> int:
        st = self._tenants.get(name)
        return len(st.queue) if st is not None else 0

    def queue_depths(self) -> dict[str, int]:
        """Per-tenant backlog snapshot (the router's live queue-depth
        gauges read this under its own lock)."""
        return {name: len(st.queue)
                for name, st in self._tenants.items()}

    def stats(self) -> dict:
        return {name: {"weight": st.weight,
                       "submitted": st.submitted,
                       "dispatched": st.dispatched,
                       "queued": len(st.queue),
                       "quota_rejects": st.quota_rejects}
                for name, st in self._tenants.items()}
