"""FleetRouter: N serving replicas behind one fair, cached, failover door.

The production serving shape the ROADMAP names: one ``ServeEngine`` on
one chip grant is a single point of failure (a wedged grant took
BENCH_r03–r05 down for ~28 min) and a single queue is a single victim
for any firehose tenant. The router fronts N replicas with:

- **submit(atoms, tenant=, priority=, deadline=, properties=)** — the
  ServeEngine surface plus tenancy. Returns a Future that ALWAYS
  resolves: with a result, or with an explicit per-request error. No
  submitted Future is ever lost, including across replica death (the
  chaos acceptance gate).
- **routing** — least-loaded-then-fair: requests queue per tenant under
  stride-scheduled weighted fair queuing (:mod:`.tenancy`), and each
  dispatch goes to the alive replica with the fewest outstanding
  requests (ties broken by total dispatch count, then id). Per-tenant
  token buckets reject over-quota submissions at the door.
- **result cache** — every submission is content-addressed
  (:mod:`.result_cache`); a hit resolves the Future immediately with a
  copy, touching NO replica (the engines' dispatch counters pin this).
  Identical requests already in flight COALESCE onto the running
  computation instead of dispatching twice.
- **failover** — ``fail_over()`` (called by :class:`.replica.
  ReplicaHealth` on a confirmed wedge, or by ``kill_replica()`` in
  chaos drills) marks the replica dead, reclaims its queued requests
  via ``ServeEngine.extract_pending()`` AND its dispatched-but-
  unresolved requests, and re-enqueues them at the head of their
  tenants' queues for dispatch on survivors. A slow original that
  resolves anyway still wins (first resolution takes the Future; the
  duplicate is dropped before dispatch when possible).

Telemetry: one ``StepRecord`` (kind ``fleet_request``) per completed
request carrying ``tenant`` / ``replica_id`` / ``cache_hit``, rendered
by ``telemetry_report``'s "fleet" section (``aot_rehydrated`` rides the
engine/batched records, snapshotted at dispatch time).
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

from ..obs import runtime as obsrt
from ..serve.engine import EngineClosed, ServeRejected
from ..telemetry import StepRecord
from .replica import Replica
from .result_cache import ResultCache, _copy_result, cache_key
from .tenancy import FairScheduler, TenantConfig

DEFAULT_TENANT = "default"


class FleetError(RuntimeError):
    """Explicit per-request failure after the router exhausted its
    re-dispatch budget (every surviving replica refused or died)."""


class _Routed:
    """One routed request: the caller's Future plus re-dispatch state."""

    __slots__ = ("atoms", "properties", "priority", "deadline_abs",
                 "tenant", "future", "key", "t_submit", "attempts",
                 "current", "replica_id", "done", "waiters", "trace")

    def __init__(self, atoms, properties, priority, deadline_abs, tenant,
                 key, t_submit, trace=None):
        self.atoms = atoms
        self.properties = properties
        self.priority = priority
        self.deadline_abs = deadline_abs
        self.tenant = tenant
        self.future: Future = Future()
        self.key = key
        self.t_submit = t_submit
        self.attempts = 0
        self.current = None          # authoritative engine Future
        self.replica_id = ""
        self.done = False
        # coalesced callers: (future, submit time, RequestTrace | None) —
        # each carries its OWN request trace, resolved when this one is
        self.waiters: list[tuple[Future, float, object]] = []
        self.trace = trace           # obs RequestTrace (router-owned root)


@dataclass
class FleetStats:
    """Cumulative router counters (reads under the router lock)."""

    submitted: int = 0
    completed: int = 0
    failed: int = 0
    quota_rejected: int = 0
    cache_hits: int = 0
    coalesced: int = 0
    redispatches: int = 0
    failovers: int = 0

    def snapshot(self) -> dict:
        return dict(vars(self))


class FleetRouter:
    """Route submissions across replicas with fairness, caching, failover.

    Parameters
    ----------
    engines : list of ServeEngine (wrapped as in-process replicas with
        ids r0..rN-1) or ready :class:`.replica.Replica` objects.
    tenants : optional {name: TenantConfig} — weights and quotas.
        Unknown tenants are admitted with the default config.
    result_cache : a :class:`ResultCache`, or None to disable caching.
    model_id / precision : fold into the cache key — results from
        different models/dtypes must never alias.
    cache_tol : coordinate bucket width (Å) for structure hashing.
    max_redispatch : failover re-dispatch budget per request before its
        Future fails with :class:`FleetError` (still an EXPLICIT error —
        resolved, never lost).
    max_outstanding : per-replica dispatched-but-unresolved bound (None:
        2x the engine's max_batch, min 8). Backpressure lives HERE: the
        per-tenant queues absorb bursts, so fairness decides dispatch
        order under contention.
    telemetry : optional Telemetry hub for fleet_request records.
    clock : injectable monotonic clock (tests).
    """

    def __init__(self, engines, *, tenants: dict | None = None,
                 result_cache: ResultCache | None = None,
                 model_id: str = "model", precision: str = "float32",
                 cache_tol: float = 1e-5, max_redispatch: int = 3,
                 max_outstanding: int | None = None, telemetry=None,
                 clock=None):
        self._clock = clock or time.monotonic
        self._cv = threading.Condition()
        self.replicas: dict[str, Replica] = {}
        self._caps: dict[str, int] = {}
        for i, item in enumerate(engines):
            rep = item if isinstance(item, Replica) \
                else Replica(item, f"r{i}")
            if rep.replica_id in self.replicas:
                raise ValueError(f"duplicate replica id {rep.replica_id!r}")
            self.replicas[rep.replica_id] = rep
            if max_outstanding is not None:
                cap = int(max_outstanding)
            else:
                cap = max(2 * int(getattr(rep.engine, "max_batch", 4)), 8)
            self._caps[rep.replica_id] = cap
        if not self.replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.cache = result_cache
        self.model_id = str(model_id)
        self.precision = str(precision)
        self.cache_tol = float(cache_tol)
        self.max_redispatch = int(max_redispatch)
        self.telemetry = telemetry
        self.stats = FleetStats()
        self._sched = FairScheduler(clock=self._clock)
        for name, cfg in (tenants or {}).items():
            self._sched.configure(name, cfg if isinstance(cfg, TenantConfig)
                                  else TenantConfig(**cfg))
        self._routed_by_future: dict[Future, _Routed] = {}
        self._inflight_by_key: dict[str, _Routed] = {}
        self._closed = False
        self._step_counter = itertools.count(1)
        self._rr = 0    # round-robin tie-break cursor
        mx = obsrt.metrics()
        if mx is not None:
            alive = mx.gauge("distmlip_replica_alive",
                             "replica liveness (1 = serving)",
                             labels=("replica",))
            for rid in self.replicas:
                alive.labels(replica=rid).set(1)

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def submit(self, atoms, properties=None, tenant: str = DEFAULT_TENANT,
               priority: int = 0, deadline: float | None = None) -> Future:
        """Route one structure; the returned Future resolves with the
        same result dict ``ServeEngine.submit`` delivers (or an explicit
        per-request exception). Raises ``ServeRejected`` synchronously
        when the tenant is over its admission quota and ``EngineClosed``
        after ``close()``."""
        now = self._clock()
        tr = obsrt.tracer()
        mx = obsrt.metrics()
        # one ROOT span per submission — cache hits and coalesced
        # duplicates get their own (short) trace too, so span-tree count
        # is conserved: N submissions in, N future.resolve terminals out
        trace = (tr.start_request("fleet.submit",
                                  attrs={"tenant": tenant,
                                         "n_atoms": len(atoms)})
                 if tr is not None else None)
        key = (cache_key(atoms, self.model_id, properties, self.precision,
                         tol=self.cache_tol)
               if self.cache is not None else None)
        # cache lookup outside the router lock (the cache has its own)
        hit = None
        if key is not None and not self._closed:
            hit = self.cache.get(key)
        if hit is not None:
            with self._cv:
                if self._closed:
                    # "rejected" = closed-without-a-Future: the span
                    # gate exempts these roots from the terminal rule
                    self._trace_abort(trace, "rejected")
                    raise EngineClosed("submit() on a closed router")
                self.stats.cache_hits += 1
            if mx is not None:
                self._count_request(mx, tenant)
                mx.counter("distmlip_fleet_cache_hits_total",
                           "submissions served from the result cache"
                           ).inc()
            fut = Future()
            if tr is not None:
                tr.emit("cache.hit", parent=trace.ctx,
                        t_start=trace.t_submit)
                tr.finish_request(trace, "ok")
            fut.set_result(hit)
            self._emit(tenant, "", [0.0], cache_hit=True, trace=trace)
            return fut
        with self._cv:
            if self._closed:
                self._trace_abort(trace, "error")
                raise EngineClosed("submit() on a closed router")
            if key is not None:
                routed = self._inflight_by_key.get(key)
                if routed is not None and not routed.done:
                    # identical request already computing: coalesce
                    fut = Future()
                    routed.waiters.append((fut, now, trace))
                    self.stats.coalesced += 1
                    if mx is not None:
                        self._count_request(mx, tenant)
                        mx.counter(
                            "distmlip_fleet_coalesced_total",
                            "submissions coalesced onto an in-flight "
                            "computation").inc()
                    return fut
            t_adm = tr.now() if tr is not None else 0.0
            if not self._sched.admit(tenant):
                self.stats.quota_rejected += 1
                if mx is not None:
                    mx.counter("distmlip_fleet_quota_rejects_total",
                               "submissions rejected at the tenant "
                               "quota door", labels=("tenant",)
                               ).labels(tenant=tenant).inc()
                if tr is not None:
                    tr.emit("tenancy.admit", parent=trace.ctx,
                            t_start=t_adm, status="rejected",
                            attrs={"tenant": tenant})
                    # rejected at the door: the root closes WITHOUT a
                    # terminal (no Future was ever handed out)
                    tr.end(trace.root, status="rejected")
                raise ServeRejected(
                    f"tenant {tenant!r} is over its admission quota "
                    f"(token bucket empty); retry later")
            if tr is not None:
                tr.emit("tenancy.admit", parent=trace.ctx, t_start=t_adm,
                        attrs={"tenant": tenant})
            routed = _Routed(
                atoms=atoms,
                properties=(tuple(properties) if properties is not None
                            else None),
                priority=int(priority),
                deadline_abs=(now + float(deadline)
                              if deadline is not None else None),
                tenant=tenant, key=key, t_submit=now, trace=trace)
            self.stats.submitted += 1
            if mx is not None:
                self._count_request(mx, tenant)
                mx.gauge("distmlip_tenant_queue_depth",
                         "requests queued per tenant",
                         labels=("tenant",)).labels(tenant=tenant).set(
                             self._sched.queued(tenant) + 1)
            if key is not None:
                self._inflight_by_key[key] = routed
            self._sched.enqueue(tenant, routed)
        self._pump()
        return routed.future

    @staticmethod
    def _count_request(mx, tenant: str) -> None:
        mx.counter("distmlip_fleet_requests_total",
                   "submissions accepted per tenant (routed, cache hits "
                   "and coalesced alike)", labels=("tenant",)
                   ).labels(tenant=tenant).inc()

    @staticmethod
    def _trace_abort(trace, status: str) -> None:
        """Close a root whose submission raised before a Future existed."""
        tr = obsrt.tracer()
        if tr is not None and trace is not None and trace.root is not None:
            tr.end(trace.root, status=status)

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _pick_replica_locked(self) -> Replica | None:
        """Least-loaded alive replica with a free outstanding slot."""
        best = None
        candidates = sorted(self.replicas.values(),
                            key=lambda r: r.replica_id)
        n = len(candidates)
        for k in range(n):
            rep = candidates[(self._rr + k) % n]
            if not rep.alive or rep.outstanding >= self._caps[rep.replica_id]:
                continue
            if best is None or (rep.outstanding, rep.dispatched_total) < \
                    (best.outstanding, best.dispatched_total):
                best = rep
        return best

    def _refresh_tenant_gauges_locked(self) -> None:
        """Sync the per-tenant queue-depth gauges with the scheduler
        (called when the pump runs dry — the backlog just changed)."""
        mx = obsrt.metrics()
        if mx is None:
            return
        gauge = mx.gauge("distmlip_tenant_queue_depth",
                         "requests queued per tenant", labels=("tenant",))
        for name, depth in self._sched.queue_depths().items():
            gauge.labels(tenant=name).set(depth)

    def _pump(self) -> None:
        """Dispatch while a replica slot and a fair pick both exist."""
        while True:
            with self._cv:
                rep = self._pick_replica_locked()
                if rep is None:
                    self._refresh_tenant_gauges_locked()
                    return
                nxt = self._sched.pop()
                if nxt is None:
                    self._refresh_tenant_gauges_locked()
                    return
                _tenant, routed = nxt
                if routed.done:
                    continue    # resolved while queued (slow original won)
                rep.outstanding += 1    # reserve before dropping the lock
                self._rr += 1
            self._dispatch(routed, rep)

    def _dispatch(self, routed: _Routed, rep: Replica) -> None:
        deadline = None
        if routed.deadline_abs is not None:
            deadline = max(routed.deadline_abs - self._clock(), 1e-3)
        tr = obsrt.tracer()
        route_span = None
        if tr is not None and routed.trace is not None:
            # retroactive tenant-queue wait: submit -> this dispatch
            # attempt (a failover re-dispatch re-covers from the original
            # submit — the critical-path union handles the overlap)
            tr.emit("router.queue", parent=routed.trace.ctx,
                    t_start=routed.trace.t_submit,
                    attrs={"tenant": routed.tenant,
                           "attempt": routed.attempts})
            route_span = tr.begin(
                "router.route", parent=routed.trace.ctx,
                attrs={"replica": rep.replica_id,
                       "attempt": routed.attempts})
        try:
            if route_span is not None:
                # ambient context hands the request trace to the engine:
                # its engine.queue span parents under this route span
                with tr.use(route_span):
                    fut = rep.engine.submit(
                        routed.atoms, properties=routed.properties,
                        priority=routed.priority, deadline=deadline)
                tr.end(route_span)
            else:
                fut = rep.engine.submit(
                    routed.atoms, properties=routed.properties,
                    priority=routed.priority, deadline=deadline)
        except EngineClosed:
            # the replica died between the pick and the submit: put the
            # request back at the head of its tenant queue and retry on
            # a survivor
            if route_span is not None:
                tr.end(route_span, status="engine_closed")
            with self._cv:
                rep.outstanding -= 1
            self._note_dead(rep, reason="engine closed under dispatch")
            self._requeue(routed)
            return
        except Exception as e:  # noqa: BLE001 - explicit per-request error
            if route_span is not None:
                tr.end(route_span, status="error")
            with self._cv:
                rep.outstanding -= 1
            self._finish(routed, exc=e)
            self._pump()
            return
        with self._cv:
            routed.current = fut
            routed.replica_id = rep.replica_id
            rep.dispatched_total += 1
            self._routed_by_future[fut] = routed
            died_under_us = not rep.alive
        fut.add_done_callback(
            lambda f, r=routed, rp=rep: self._on_engine_done(r, rp, f))
        if died_under_us:
            # the replica was failed over BETWEEN our submit and this
            # bookkeeping: its extract_pending may have reclaimed the
            # engine request before we appeared in the routed map, so
            # nothing would ever resolve this dispatch — reclaim it
            # ourselves (idempotent: guarded on `current`)
            self._reclaim_dispatch(routed, rep, fut)

    def _on_engine_done(self, routed: _Routed, rep: Replica,
                        fut: Future) -> None:
        with self._cv:
            was_tracked = self._routed_by_future.pop(fut, None) is not None
            if was_tracked:
                rep.outstanding = max(rep.outstanding - 1, 0)
            authoritative = routed.current is fut
            self._cv.notify_all()
        exc = None if fut.cancelled() else fut.exception()
        if exc is None and not fut.cancelled():
            # first resolution wins — a reclaimed original beating its
            # re-dispatched copy is a success, not a conflict
            self._finish(routed, result=fut.result())
        elif not authoritative:
            pass    # a failover already re-dispatched this request
        elif isinstance(exc, EngineClosed):
            # replica died with this request queued on it: re-dispatch
            self._note_dead(rep, reason="engine closed mid-request")
            self._requeue(routed)
        elif exc is not None:
            self._finish(routed, exc=exc)
        else:   # cancelled engine future (not a caller-visible state)
            self._requeue(routed)
        self._pump()

    def _reclaim_dispatch(self, routed: _Routed, rep: Replica,
                          fut: Future) -> None:
        """Withdraw one dispatched request from a dead replica (idempotent
        — a no-op unless ``fut`` is still the authoritative dispatch)."""
        with self._cv:
            if routed.done or routed.current is not fut:
                return
            if self._routed_by_future.pop(fut, None) is not None:
                rep.outstanding = max(rep.outstanding - 1, 0)
            routed.current = None
        self._requeue(routed)

    def _requeue(self, routed: _Routed) -> None:
        """Put a reclaimed request back at the head of its tenant queue,
        bounded by the re-dispatch budget."""
        with self._cv:
            if routed.done:
                return
            routed.attempts += 1
            routed.current = None
            routed.replica_id = ""
            alive = any(r.alive for r in self.replicas.values())
            if routed.attempts > self.max_redispatch or not alive:
                budget = (f"re-dispatch budget ({self.max_redispatch}) "
                          f"exhausted" if alive else "no replica alive")
                exc = FleetError(
                    f"request could not be re-dispatched after replica "
                    f"failure: {budget}")
            else:
                self.stats.redispatches += 1
                self._sched.enqueue(routed.tenant, routed, front=True)
                exc = None
        tr = obsrt.tracer()
        if tr is not None and routed.trace is not None:
            tr.emit("router.requeue", parent=routed.trace.ctx,
                    status="ok" if exc is None else "exhausted",
                    attrs={"attempt": routed.attempts})
        mx = obsrt.metrics()
        if mx is not None and exc is None:
            mx.counter("distmlip_fleet_redispatches_total",
                       "failover re-dispatches").inc()
        if exc is not None:
            self._finish(routed, exc=exc)
        else:
            self._pump()

    # ------------------------------------------------------------------
    # completion
    # ------------------------------------------------------------------

    def _finish(self, routed: _Routed, result=None, exc=None) -> None:
        # cache fill BEFORE the done transition (and outside the router
        # lock — ResultCache has its own): a submit racing this window
        # gets a hit instead of missing both the cache and coalescing
        if exc is None and routed.key is not None and not routed.done:
            self.cache.put(routed.key, result)
        with self._cv:
            if routed.done:
                return
            routed.done = True
            if routed.key is not None and \
                    self._inflight_by_key.get(routed.key) is routed:
                del self._inflight_by_key[routed.key]
            waiters = list(routed.waiters)
            if exc is None:
                self.stats.completed += 1 + len(waiters)
            else:
                self.stats.failed += 1 + len(waiters)
            now = self._clock()
            lats = [now - routed.t_submit] + [now - t for _, t, _w in
                                             waiters]
            self._cv.notify_all()
        status = "ok" if exc is None else "error"
        # terminal spans BEFORE resolution: a caller returning from
        # Future.result() must already see its complete span tree
        tr = obsrt.tracer()
        if tr is not None:
            if routed.trace is not None:
                tr.finish_request(routed.trace, status,
                                  attrs={"replica": routed.replica_id})
            for _fut, _t, wtrace in waiters:
                if wtrace is not None:
                    tr.emit("coalesce", parent=wtrace.ctx,
                            t_start=wtrace.t_submit,
                            links=((routed.trace.ctx,)
                                   if routed.trace is not None else ()))
                    tr.finish_request(wtrace, status)
        mon = obsrt.slo()
        if mon is not None:
            for x in lats:
                mon.observe(routed.tenant, x, ok=exc is None)
        mx = obsrt.metrics()
        if mx is not None:
            name = ("distmlip_fleet_completed_total" if exc is None
                    else "distmlip_fleet_failed_total")
            mx.counter(name, "resolved fleet requests per tenant",
                       labels=("tenant",)).labels(
                           tenant=routed.tenant).inc(1 + len(waiters))
            hist = mx.histogram("distmlip_fleet_request_latency_seconds",
                                "submit-to-resolve latency per tenant",
                                labels=("tenant",)).labels(
                                    tenant=routed.tenant)
            for x in lats:
                hist.observe(x)
        # resolution + telemetry outside the lock: done-callbacks and
        # sink writes must not serialize every replica's completions
        if exc is None:
            routed.future.set_result(result)
            for fut, _t, _w in waiters:
                # each coalesced caller gets its OWN copy: one caller
                # mutating a forces array must not corrupt another's
                fut.set_result(_copy_result(result))
        else:
            routed.future.set_exception(exc)
            for fut, _t, _w in waiters:
                fut.set_exception(exc)
        self._emit(routed.tenant, routed.replica_id, lats, cache_hit=False,
                   trace=routed.trace)

    # ------------------------------------------------------------------
    # failover / chaos
    # ------------------------------------------------------------------

    def _note_dead(self, rep: Replica, reason: str = "") -> None:
        with self._cv:
            if not rep.alive:
                return
            rep.alive = False
            self.stats.failovers += 1
            self._cv.notify_all()
        self._obs_failover(rep.replica_id, reason)

    @staticmethod
    def _obs_failover(replica_id: str, reason: str) -> None:
        mx = obsrt.metrics()
        if mx is not None:
            mx.counter("distmlip_fleet_failovers_total",
                       "replicas failed over").inc()
            mx.gauge("distmlip_replica_alive",
                     "replica liveness (1 = serving)",
                     labels=("replica",)).labels(replica=replica_id).set(0)
        fl = obsrt.flight()
        if fl is not None:
            fl.capture(f"replica {replica_id} failed over: "
                       f"{reason or 'unspecified'}",
                       attrs={"replica": replica_id})

    def fail_over(self, replica_id: str, reason: str = "",
                  reclaim_inflight: bool = True) -> int:
        """Mark a replica dead and move its work to survivors.

        Reclaims (1) every request still QUEUED on the replica's engine
        (``extract_pending`` — Futures unresolved by contract) and (2),
        with ``reclaim_inflight``, every request DISPATCHED to it but
        not yet resolved — a wedged engine may never resolve them, and a
        merely-slow one that does resolve later still wins the Future
        (the duplicate is dropped). Returns the number of requests
        re-enqueued; their Futures stay live throughout."""
        with self._cv:
            rep = self.replicas.get(replica_id)
            if rep is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            if not rep.alive:
                return 0
            rep.alive = False
            self.stats.failovers += 1
        self._obs_failover(replica_id, reason)
        # (1) requests still queued on the engine: their Futures are
        # unresolved by extract_pending's contract, so reclaiming is the
        # ONLY way they ever resolve
        reclaim: list[tuple[_Routed, Future]] = []
        for req in rep.engine.extract_pending():
            with self._cv:
                routed = self._routed_by_future.get(req.future)
            if routed is not None:
                reclaim.append((routed, req.future))
        # (2) requests dispatched to the replica and not yet resolved: a
        # wedged engine may never resolve them; a merely-slow one that
        # does still wins the Future (first resolution takes it)
        if reclaim_inflight:
            seen = {id(r) for r, _ in reclaim}
            with self._cv:
                reclaim.extend(
                    (r, f) for f, r in list(self._routed_by_future.items())
                    if r.replica_id == replica_id and not r.done
                    and r.current is f and id(r) not in seen)
        # head-of-queue requeue in REVERSE so the original dispatch order
        # is preserved at the front of each tenant queue
        n = 0
        for routed, fut in reversed(reclaim):
            before = routed.done
            self._reclaim_dispatch(routed, rep, fut)
            n += int(not before)
        self._pump()
        return n

    def kill_replica(self, replica_id: str,
                     timeout: float | None = 30.0) -> int:
        """Chaos drill: the replica loses its chips mid-flight.

        Fails the replica over (queued + dispatched requests move to
        survivors), then force-closes its engine without draining. An
        in-process engine's in-flight batch still completes — if it
        resolves before the re-dispatched copy, that result wins and the
        copy is dropped. Returns the number of requests re-enqueued."""
        n = self.fail_over(replica_id, reason="chaos: replica killed")
        self.replicas[replica_id].engine.close(drain=False, timeout=timeout)
        return n

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def backlog(self) -> int:
        with self._cv:
            return self._sched.backlog()

    @property
    def outstanding(self) -> int:
        with self._cv:
            return sum(r.outstanding for r in self.replicas.values())

    def drain(self, timeout: float | None = None) -> bool:
        """Wait until every accepted request has resolved (router queues
        empty, no dispatched request outstanding). False on timeout."""
        for rep in self.replicas.values():
            if rep.alive:
                rep.engine.kick()
        with self._cv:
            return self._cv.wait_for(
                lambda: self._sched.backlog() == 0
                and not self._routed_by_future
                and all(r.outstanding == 0
                        for r in self.replicas.values()),
                timeout=timeout)

    def close(self, drain: bool = True,
              timeout: float | None = None) -> None:
        """Stop accepting work; optionally drain; close every engine."""
        with self._cv:
            if self._closed:
                closed_already = True
            else:
                closed_already = False
                self._closed = True
        if drain and not closed_already:
            self.drain(timeout=timeout)
        if closed_already:
            return
        # fail anything still queued (drain=False, or drain timed out)
        while True:
            with self._cv:
                nxt = self._sched.pop()
            if nxt is None:
                break
            self._finish(nxt[1], exc=EngineClosed(
                "router closed before this request was dispatched"))
        for rep in self.replicas.values():
            rep.engine.close(drain=drain, timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------
    # introspection / telemetry
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """Cumulative fleet state: router counters, per-tenant scheduler
        stats, per-replica dispatch/load, cache stats."""
        with self._cv:
            out = {
                "stats": self.stats.snapshot(),
                "tenants": self._sched.stats(),
                "replicas": {
                    rid: {"alive": rep.alive,
                          "outstanding": rep.outstanding,
                          "dispatched_total": rep.dispatched_total,
                          "compile_count": getattr(
                              rep.engine, "compile_count", 0)}
                    for rid, rep in self.replicas.items()},
            }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    def _emit(self, tenant: str, replica_id: str,
              latencies: list[float], cache_hit: bool,
              trace=None) -> None:
        """Emit one fleet_request record. Called OUTSIDE the router lock
        (sink writes must not serialize completions); the step counter is
        its own atomic source. ``aot_rehydrated`` is deliberately NOT set
        here — per-request attribution from the potential's mutable
        ``last_dispatch_aot`` races the next dispatch; the engine's
        ``serve_batch`` and the potential's ``batched_calculate`` records
        carry the flag snapshotted at dispatch time, and the report
        counts those."""
        tel = self.telemetry
        if tel is None or not tel.wants_records():
            return
        rec = StepRecord(
            step=next(self._step_counter), kind="fleet_request",
            timings={"total_s": max(latencies)},
            trace_id=trace.trace_id if trace is not None else "",
            span_id=trace.span_id if trace is not None else "",
            tenant=tenant, replica_id=replica_id, cache_hit=cache_hit,
            batch_size=len(latencies),
            request_latency_s=[round(x, 6) for x in latencies],
            extra={"failover_count": self.stats.failovers,
                   "cache_hit_count": self.stats.cache_hits,
                   "coalesced_count": self.stats.coalesced,
                   "redispatch_count": self.stats.redispatches,
                   "cache_evictions": (self.cache.evictions
                                       if self.cache is not None else 0)},
        )
        tel.emit(rec)


def make_fleet(n_replicas: int, potential_factory, *, engine_kwargs=None,
               aot_cache_dir: str | None = None, **router_kwargs
               ) -> FleetRouter:
    """Convenience constructor for an IN-PROCESS fleet (tests, demos,
    single-host serving): ``potential_factory(i)`` builds replica ``i``'s
    ``BatchedPotential`` (each replica needs its OWN — independent
    compile caches model independent chip grants), an optional shared
    AOT cache directory rehydrates every replica's bucket ladder, and
    ``engine_kwargs`` feed each ``ServeEngine``."""
    from ..serve import ServeEngine
    from .aot import install_aot_cache

    if n_replicas < 1:
        raise ValueError("n_replicas must be >= 1")
    engines = []
    for i in range(n_replicas):
        pot = potential_factory(i)
        if aot_cache_dir is not None:
            install_aot_cache(pot, aot_cache_dir)
        engines.append(ServeEngine(pot, **dict(engine_kwargs or {})))
    return FleetRouter(engines, **router_kwargs)
