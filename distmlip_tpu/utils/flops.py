"""Analytic per-model FLOP estimates and MFU accounting.

Chip-independent cost model for the telemetry ``mfu`` field (VERDICT weak
#2): given a model and the live graph shape (atoms / edges / line-graph
edges per step), estimate the floating-point work of one full potential
evaluation (energy + forces [+ stress]) and divide by device time x peak
FLOPs to get model FLOP utilization. Everything here is an ESTIMATE —
dominant GEMM terms only, elementwise/gather glue ignored — intended for
trending and cross-run comparison, not absolute accounting (expect ~±20%).

Conventions:
- a dense [m -> n] layer over R rows costs ``2 R m n`` FLOPs (MACs x 2);
- gated MLPs (CHGNet) run two parallel stacks -> 2x their dense cost;
- the backward pass of reverse-mode E+F costs ~2x the forward's GEMMs, so
  a potential step is ``FWD_BWD_FACTOR = 3`` x the forward estimate (the
  full-remat configurations re-run the forward once more; callers may
  scale by 4/3 when cfg.remat is True — we fold that in automatically).
"""

from __future__ import annotations

import os

FWD_BWD_FACTOR = 3.0  # forward + ~2x forward for the reverse pass

# peak dense FLOP/s per device by device_kind substring (bf16 MXU numbers
# for TPUs; fp32 tensor numbers would be ~half). Extend as chips appear.
_PEAK_TABLE = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 394e12),
    ("v5litepod", 394e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _mlp_flops(dims, rows: float) -> float:
    """Dense chain [d0 -> d1 -> ... -> dk] over ``rows`` rows."""
    return 2.0 * rows * sum(a * b for a, b in zip(dims[:-1], dims[1:]))


def _gated_mlp_flops(dims, rows: float) -> float:
    return 2.0 * _mlp_flops(dims, rows)


def chgnet_flops(cfg, n_atoms: float, n_edges: float, n_lines: float = 0.0,
                 n_bonds: float | None = None) -> float:
    """CHGNet forward: atom-conv gated MLPs per edge, bond/angle-conv gated
    MLPs per line-graph edge, bases + readouts."""
    C, R = cfg.units, cfg.num_rbf
    if n_bonds is None:
        n_bonds = n_edges  # bond nodes ~ in-cutoff directed edges
    ah = list(cfg._atom_hidden)
    bh = list(cfg._bond_hidden)
    gh = list(cfg.angle_update_hidden)
    fl = list(cfg._final_hidden)
    f = _mlp_flops([R, C], n_edges)                       # bond embedding
    f += _mlp_flops([cfg.angle_dim, C], n_lines)          # angle embedding
    # shared rbf weight linears
    n_shared = (2 if cfg.shared_bond_weights in ("bond", "both") else 0) + (
        1 if cfg.shared_bond_weights in ("threebody", "both") else 0)
    f += n_shared * _mlp_flops([R, C], n_edges)
    for _ in range(cfg.num_blocks):
        f += _gated_mlp_flops([3 * C] + ah + [C], n_edges)   # node messages
        f += _mlp_flops([C, C], n_atoms)                     # node_out
        if cfg.bond_update_hidden is not None:
            f += _gated_mlp_flops(
                [3 * C] + list(cfg.bond_update_hidden) + [C], n_edges)
            f += _mlp_flops([C, C], n_edges)
    if cfg.use_bond_graph:
        for _ in range(max(cfg.num_blocks - 1, 0)):
            f += _gated_mlp_flops([4 * C] + bh + [C], n_lines)  # bond conv
            f += _mlp_flops([C, C], n_bonds)                    # node_out
        # the angle update after the LAST bond conv feeds nothing and is
        # skipped (dead_compute contract pass)
        for _ in range(max(cfg.num_blocks - 2, 0)):
            f += _gated_mlp_flops([4 * C] + gh + [C], n_lines)  # angle conv
    f += _mlp_flops([C] + fl + [1], n_atoms)              # final readout
    f += _mlp_flops([C, cfg.num_site_targets], n_atoms)   # sitewise
    return f


def mace_flops(cfg, n_atoms: float, n_edges: float, model=None) -> float:
    """MACE forward: radial MLPs + density projection per edge, symmetric
    contraction per node. Uses the model's precomputed path tables when
    available; otherwise falls back to l_max-based estimates."""
    C = cfg.channels
    S_Y = (cfg.l_max + 1) ** 2
    f = 0.0
    for t in range(cfg.num_interactions):
        if model is not None and hasattr(model, "proj"):
            proj = model.proj[t]
            S_h, nQ = proj["S_h"], proj["W"].shape[1]
            n_paths = len(model.msg_paths[t])
        else:  # crude: first interaction sees scalars only
            S_h = 1 if t == 0 else (min(cfg.hidden_lmax, cfg.l_max) + 1) ** 2
            nQ = S_h * (cfg.l_max + 1)
            n_paths = nQ
        # radial MLP: bessel -> radial_mlp^2 -> n_paths (upstream 3-layer)
        f += _mlp_flops([cfg.num_bessel, cfg.radial_mlp, cfg.radial_mlp,
                         n_paths * C], n_edges)
        # density projection: T = Y x W (channel-free), M = T x h_src
        f += 2.0 * n_edges * S_Y * S_h * nQ
        f += 2.0 * n_edges * S_h * nQ * C
        # per-path node mixing + symmetric contraction (correlation-order
        # Horner over the U-matrix basis) — dominated by nQ*C GEMM terms
        f += 2.0 * n_atoms * nQ * C * C
        f += 2.0 * n_atoms * cfg.correlation * nQ * C * S_h
    return f


def tensornet_flops(cfg, n_atoms: float, n_edges: float) -> float:
    C = cfg.units
    f = _mlp_flops([2 * C, C], n_edges)      # Zij edge embedding
    f += 3 * _mlp_flops([cfg.num_rbf, C], n_edges)
    # per layer: scalar MLPs on edges + 6 channel mixes + 3x3 matmuls
    n_layers = getattr(cfg, "num_layers", 2)
    per_layer = (_mlp_flops([cfg.num_rbf, C, 3 * C], n_edges)
                 + 6 * 2.0 * n_atoms * 9 * C * C
                 + 2 * 2.0 * n_atoms * 27 * C)
    f += n_layers * per_layer
    f += _mlp_flops([3 * C, C, 1], n_atoms)  # readout stack (approx)
    return f


def pair_flops(cfg, n_atoms: float, n_edges: float) -> float:
    return 50.0 * n_edges  # elementwise pair math; negligible by design


def edge_aggregate_flops(n_edges: float, w_in: float, w_out: float) -> float:
    """Analytic FLOPs of the canonical gather -> edge-MLP -> scatter
    pipeline (the tools/kernel_bench.py workload): one (w_in, w_out) GEMM
    per edge (2*w_in*w_out), the silu gate (~4*w_in) and the masked
    dst-scatter accumulation (2*w_out). Shared by the fused and unfused
    arms so their MFU numbers are comparable."""
    return float(n_edges) * (2.0 * float(w_in) * float(w_out)
                             + 4.0 * float(w_in) + 2.0 * float(w_out))


def escn_flops(cfg, n_atoms: float, n_edges: float) -> float:
    """eSCN/UMA: Wigner rotations + SO(2) convolutions per edge."""
    C = getattr(cfg, "channels", getattr(cfg, "sphere_channels", 128))
    lmax = getattr(cfg, "l_max", getattr(cfg, "lmax", 2))
    S = (lmax + 1) ** 2
    n_layers = getattr(cfg, "num_layers", 2)
    per_edge = 4.0 * S * S * C + 4.0 * S * C * C  # rotate in/out + SO(2) GEMMs
    return n_layers * n_edges * per_edge


def model_flop_estimate(model, n_atoms: float, n_edges: float,
                        n_lines: float = 0.0) -> float:
    """One potential step's estimated FLOPs (energy + forces [+ stress])
    for ``model`` on a graph of the given shape; 0.0 when the model family
    is unknown (mfu then reads 0 rather than lying)."""
    cfg = getattr(model, "cfg", None)
    if cfg is None:
        return 0.0
    name = type(model).__name__.lower()
    if "chgnet" in name:
        fwd = chgnet_flops(cfg, n_atoms, n_edges, n_lines)
    elif "mace" in name:
        fwd = mace_flops(cfg, n_atoms, n_edges, model=model)
    elif "tensornet" in name:
        fwd = tensornet_flops(cfg, n_atoms, n_edges)
    elif "escn" in name or "uma" in name:
        fwd = escn_flops(cfg, n_atoms, n_edges)
    elif "pair" in name:
        fwd = pair_flops(cfg, n_atoms, n_edges)
    else:
        return 0.0
    factor = FWD_BWD_FACTOR
    if getattr(cfg, "remat", False) is True:
        factor += 1.0  # full remat re-runs the forward inside the backward
    return factor * fwd


def peak_flops_per_device(default: float = 0.0) -> float:
    """Peak dense FLOP/s of one local device. ``DISTMLIP_PEAK_FLOPS``
    overrides; otherwise the device_kind lookup table; 0.0 when unknown
    (CPU test runs) so downstream mfu stays 0 instead of fabricated."""
    env = os.environ.get("DISTMLIP_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 - no backend, no peak
        return default
    for key, peak in _PEAK_TABLE:
        if key in kind:
            return peak
    return default


def mfu(flops_per_step: float, device_s: float, n_devices: int,
        peak: float | None = None) -> float:
    """Model FLOP utilization in [0, 1]; 0.0 whenever any input is unknown."""
    if peak is None:
        peak = peak_flops_per_device()
    if flops_per_step <= 0 or device_s <= 0 or peak <= 0 or n_devices <= 0:
        return 0.0
    return flops_per_step / (device_s * n_devices * peak)
