from .checkpoint import save_params, load_params
from .flops import mfu, model_flop_estimate, peak_flops_per_device
from .memory import (device_bytes_limit, device_memory_stats,
                     hbm_usage_frac, measured_peak_bytes)
from .profiling import StepTimer, device_trace

__all__ = ["save_params", "load_params", "StepTimer", "device_trace",
           "model_flop_estimate", "peak_flops_per_device", "mfu",
           "device_memory_stats", "hbm_usage_frac", "device_bytes_limit",
           "measured_peak_bytes"]
