from .checkpoint import save_params, load_params
from .profiling import StepTimer, device_trace

__all__ = ["save_params", "load_params", "StepTimer", "device_trace"]
