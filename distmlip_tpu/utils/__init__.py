from .checkpoint import save_params, load_params
from .flops import mfu, model_flop_estimate, peak_flops_per_device
from .profiling import StepTimer, device_trace

__all__ = ["save_params", "load_params", "StepTimer", "device_trace",
           "model_flop_estimate", "peak_flops_per_device", "mfu"]
