"""Wedge detection and bounded re-probing: the shared health machinery.

Two consumers, one implementation:

- **bench.py** probes a chip grant with a disposable canary subprocess
  before claiming in-process (:class:`CanaryProber` — extracted verbatim
  from the bench so the BENCH_r03–r05 hardening lives in ONE place). A
  canary that neither exits nor fails within budget means the grant is
  wedged: the canary's process group is killed (TERM → grace → KILL) and
  ONE bounded re-probe with backoff runs before the backend is declared
  unavailable. Behavior and env knobs (``BENCH_CLAIM_TIMEOUT_S``,
  ``BENCH_RETRIES``, ``BENCH_RETRY_BACKOFF_S``, ``BENCH_WEDGE_REPROBES``,
  ``BENCH_WEDGE_REPROBE_TIMEOUT_S``, ``BENCH_CANARY_KILL_GRACE_S``) are
  byte-identical to the pre-extraction bench — tests/test_bench_watchdog.py
  pins them.

- **the serving fleet** (:mod:`distmlip_tpu.fleet`) watches N live engine
  replicas with the same suspicion discipline via :class:`ReprobePolicy`:
  a failed heartbeat marks a replica SUSPECT (not dead), bounded re-probes
  with backoff either clear the suspicion or confirm the wedge — exactly
  the canary's kill-then-reprobe shape, applied to an in-process replica
  instead of a subprocess.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field


def kill_process_group(proc, grace_s: float | None = None) -> None:
    """TERM -> grace -> KILL a subprocess's whole process group.

    The target must run in its own session (``start_new_session=True``),
    so its pgid == its pid and any children it spawned die with it.
    Escalates to SIGKILL after ``grace_s`` (default: env
    ``BENCH_CANARY_KILL_GRACE_S``, 10 s) and always reaps the subprocess
    handle so no zombie outlives the caller."""
    import signal

    if grace_s is None:
        grace_s = float(os.environ.get("BENCH_CANARY_KILL_GRACE_S", "10"))
    try:
        pgid = os.getpgid(proc.pid)
    except (ProcessLookupError, PermissionError):
        proc.poll()
        return
    for sig, wait_s in ((signal.SIGTERM, grace_s), (signal.SIGKILL, 5.0)):
        try:
            os.killpg(pgid, sig)
        except (ProcessLookupError, PermissionError):
            break
        try:
            proc.wait(timeout=wait_s)
            break
        except subprocess.TimeoutExpired:
            continue
    proc.poll()  # reap


@dataclass
class ProbeConfig:
    """Budgets of one canary-probe campaign (the bench's env knobs)."""

    claim_budget_s: float = 420.0
    retries: int = 3
    backoff_s: float = 30.0
    max_reprobes: int = 1
    reprobe_budget_s: float = 120.0
    poll_s: float = 2.0

    @classmethod
    def from_env(cls) -> "ProbeConfig":
        """The bench's knob set, read at call time (tests set env late)."""
        return cls(
            claim_budget_s=float(
                os.environ.get("BENCH_CLAIM_TIMEOUT_S", "420")),
            retries=max(1, int(os.environ.get("BENCH_RETRIES", "3"))),
            backoff_s=float(os.environ.get("BENCH_RETRY_BACKOFF_S", "30")),
            max_reprobes=max(
                0, int(os.environ.get("BENCH_WEDGE_REPROBES", "1"))),
            reprobe_budget_s=float(
                os.environ.get("BENCH_WEDGE_REPROBE_TIMEOUT_S", "120")),
        )


class CanaryProber:
    """Probe a risky resource with a DISPOSABLE subprocess before claiming.

    Round-4 lesson (VERDICT r4 weak #1): ``jax.devices()`` on a wedged
    axon grant HANGS, and the PARENT dying mid-claim — e.g. a bench
    os._exit'ing under its own watchdog — renews the server-side lease
    wedge. So the risky first claim happens in a canary subprocess: if it
    exits 0 the resource is healthy and the parent claims in-process; if
    it raises we retry/fail structured; if it neither exits nor fails
    within the budget the resource is wedged and the canary is KILLED
    (process-group TERM -> grace -> KILL, reported as ``canary: killed``).
    Round-6 lesson (BENCH_r05): the earlier leave-it-running policy leaked
    the pid — the orphan held its pending claim long after the round
    ended, serializing against the NEXT round's probe. Killing the
    disposable canary is safe precisely because the parent never started
    a claim of its own. Killing the stuck claimer can itself release the
    server-side lease, so a bounded re-probe with backoff runs before the
    resource is declared unavailable.

    ``launch()`` must return a started ``subprocess.Popen`` (in its own
    session); ``telemetry`` is a dict updated in place with the bench's
    artifact keys (``probe_attempts``, ``canary``, ``wedge_suspected``,
    ``wedge_reprobes``, ``canary_elapsed_s``, ``canary_pid``); ``phase``
    (optional) re-arms a watchdog deadline; ``log_path`` is where the
    canary's output lands (its tail rides failure details).

    ``run()`` returns ``(ok: bool, detail: str)``. Never raises.
    """

    def __init__(self, launch, config: ProbeConfig | None = None,
                 telemetry: dict | None = None, phase=None,
                 log_path: str = ""):
        self.launch = launch
        self.config = config
        self.telemetry = telemetry if telemetry is not None else {}
        self.phase = phase or (lambda msg, budget_s: None)
        self.log_path = log_path

    def _log_tail(self, n: int = 400) -> str:
        if not self.log_path:
            return ""
        try:
            with open(self.log_path, "rb") as f:
                return f.read()[-n:].decode("utf-8", "replace")
        except OSError:
            return ""

    def run(self) -> tuple[bool, str]:
        cfg = self.config or ProbeConfig.from_env()
        tel = self.telemetry
        tel.setdefault("probe_attempts", 0)
        tel.setdefault("wedge_reprobes", 0)
        claim_budget = cfg.claim_budget_s
        t_end = time.monotonic() + claim_budget
        # backup only — the poll loop below enforces the budget without
        # hanging
        self.phase(
            f"canary claim phase overran {claim_budget + 60:.0f}s",
            claim_budget + 60)
        detail = "canary never launched"
        attempt = 0
        while attempt < cfg.retries:
            tel["probe_attempts"] += 1
            t0 = time.monotonic()
            proc = self.launch()
            while time.monotonic() < t_end:
                rc = proc.poll()
                if rc is not None:
                    break
                time.sleep(cfg.poll_s)
            elapsed = time.monotonic() - t0
            tel["canary_elapsed_s"] = round(elapsed, 1)
            rc = proc.poll()
            if rc is None:
                # Budget exhausted, canary still mid-claim: the resource
                # is wedged. Kill the disposable canary's process group
                # instead of leaking it (BENCH_r05's `left_running` pid).
                kill_process_group(proc)
                tel["canary"] = "killed"
                tel["wedge_suspected"] = True
                tel["canary_pid"] = proc.pid
                detail = (
                    f"canary claim still pending after {elapsed:.0f}s "
                    f"(chip grant wedged; canary pid {proc.pid} killed, "
                    f"log {self.log_path})")
                if tel["wedge_reprobes"] < cfg.max_reprobes:
                    # killing the stuck claimer can itself release the
                    # server-side lease — ONE bounded re-probe with backoff
                    # before declaring the backend unavailable, so a
                    # transient wedge doesn't cost the whole round. The
                    # re-probe gets its own (clamped) budget; a second
                    # wedge fails for good.
                    tel["wedge_reprobes"] += 1
                    reprobe_budget = min(cfg.reprobe_budget_s, claim_budget)
                    wait = min(cfg.backoff_s, max(claim_budget / 4.0, 1.0))
                    print(f"# {detail}; re-probing once in {wait:.0f}s "
                          f"(budget {reprobe_budget:.0f}s)", file=sys.stderr)
                    self.phase(
                        f"wedge re-probe overran "
                        f"{reprobe_budget + wait + 60:.0f}s",
                        reprobe_budget + wait + 60)
                    time.sleep(wait)
                    t_end = time.monotonic() + reprobe_budget
                    continue  # relaunch without consuming a regular retry
                return False, detail
            if rc == 0:
                tel["canary"] = "ok"
                return True, f"canary healthy in {elapsed:.0f}s"
            # canary raised (e.g. UNAVAILABLE fast-fail): retry in budget
            tel["canary"] = "unavailable"
            tail = self._log_tail()
            detail = (f"canary exited rc={rc} after {elapsed:.0f}s "
                      f"(attempt {attempt + 1}/{cfg.retries}): "
                      f"{tail.strip()[-200:]}")
            print(f"# {detail}", file=sys.stderr)
            attempt += 1
            wait = cfg.backoff_s * attempt
            # only launch a retry canary if the remaining budget could
            # actually see it through (scaled by how long this one took to
            # fail) — a canary launched into seconds of budget would be
            # misreported as left_running/wedged when the resource was
            # merely slow-failing
            need = max(60.0, 1.5 * elapsed)
            if attempt < cfg.retries and \
                    time.monotonic() + wait + need < t_end:
                time.sleep(wait)
            else:
                break  # out of claim budget; fail structured, don't hang
        return False, detail


@dataclass
class ReprobePolicy:
    """Bounded suspicion-then-confirm discipline for a LIVE resource.

    The in-process analogue of the canary's kill-then-reprobe shape: a
    failed probe marks the resource SUSPECT rather than dead; the policy
    then requires ``max_reprobes`` FURTHER consecutive failures, each at
    least ``backoff_s`` apart (backing off between looks instead of
    hammering a struggling replica), before confirming the wedge. Any
    successful probe clears the suspicion entirely.

    Drive it with :meth:`observe`; it returns ``"healthy"``,
    ``"suspect"`` or ``"wedged"``. ``clock`` is injectable so tests step
    time deterministically.
    """

    max_reprobes: int = 1
    backoff_s: float = 1.0
    clock: object = time.monotonic

    failures: int = field(default=0, init=False)
    _last_look: float = field(default=float("-inf"), init=False)

    def observe(self, healthy: bool) -> str:
        now = self.clock()
        if healthy:
            self.failures = 0
            self._last_look = now
            return "healthy"
        if self.failures > 0 and now - self._last_look < self.backoff_s:
            # inside the backoff window: the previous verdict stands —
            # a rapid poll loop must not burn re-probes faster than the
            # resource could plausibly recover
            return "suspect" if self.failures <= self.max_reprobes \
                else "wedged"
        self.failures += 1
        self._last_look = now
        return "suspect" if self.failures <= self.max_reprobes else "wedged"

    def reset(self) -> None:
        self.failures = 0
        self._last_look = float("-inf")
