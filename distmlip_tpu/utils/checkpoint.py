"""Parameter checkpointing: save/load arbitrary parameter pytrees.

The reference has no checkpointing of its own (model state comes from
upstream loaders, SURVEY.md §5); here it is first-class since this framework
also trains. Zero-dependency format: npz with slash-joined tree paths, so
checkpoints are portable and inspectable (np.load). Orbax can be layered on
later for multi-host async checkpointing.
"""

from __future__ import annotations

import os
import threading

import numpy as np

# In-memory tensor-layout era the saved parameters assume. Version 2 is the
# channels-last flip (eSCN per-m flatten (C, nl)->(nl, C), edge-degree
# reshape (C, l_max+1)->(l_max+1, C); commits 27d14ea/89c9bed): parameter
# SHAPES are unchanged across that flip, so a pre-flip checkpoint would load
# cleanly and silently compute wrong energies. The sentinel makes the
# mismatch loud instead.
LAYOUT_VERSION = 2
_LAYOUT_KEY = "__distmlip_layout_version__"


def _flatten_with_paths(tree, prefix=""):
    import jax

    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_with_paths(v, f"{prefix}{i}/"))
    elif tree is None:
        pass  # empty subtree (jax pytree convention); restored from template
    else:
        out[prefix[:-1]] = np.asarray(jax.device_get(tree))
    return out


def save_params(path: str, params) -> None:
    """Atomic save: write to a sibling tmp file, then rename. A crash
    mid-write never leaves a torn checkpoint at ``path`` — the previous
    one (if any) survives intact."""
    flat = _flatten_with_paths(params)
    flat[_LAYOUT_KEY] = np.int64(LAYOUT_VERSION)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        np.savez_compressed(tmp, **flat)
        # np.savez appends .npz when the target lacks it
        written = tmp if os.path.exists(tmp) else tmp + ".npz"
        os.replace(written, path)
    except BaseException:
        for cand in (tmp, tmp + ".npz"):
            if os.path.exists(cand):
                os.remove(cand)
        raise


class AsyncSaver:
    """Background-thread checkpoint writer for training loops.

    ``save()`` materializes the pytree on the host SYNCHRONOUSLY (cheap:
    device->host copies; also the only correct point — a donated
    ``TrainState`` buffer is invalid the moment the next step dispatches)
    and hands the compress+write to a worker thread, so the device never
    idles on gzip/disk. One write in flight at a time: a new ``save()``
    joins the previous one first (checkpoints are ordered); ``wait()``
    joins the tail and re-raises any writer error.
    """

    def __init__(self):
        self._thread = None
        self._error = None

    def save(self, path: str, params) -> None:
        import jax

        self.wait()
        host_tree = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), params)

        def _write():
            try:
                save_params(path, host_tree)
            except BaseException as e:  # noqa: BLE001 - surfaced by wait()
                self._error = e

        self._thread = threading.Thread(
            target=_write, name="distmlip-ckpt-writer", daemon=True)
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight write (if any); re-raise a writer failure."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def load_params(path: str, like=None, *, allow_legacy_layout: bool = False):
    """Load a checkpoint; if ``like`` (a template pytree) is given, restore
    the exact tree structure (lists vs dicts) and dtypes.

    Refuses checkpoints from an older tensor-layout era (missing or stale
    ``LAYOUT_VERSION`` sentinel) unless ``allow_legacy_layout=True`` —
    shapes match across layout flips, so silent loading would be wrong.
    """
    data = dict(np.load(path, allow_pickle=False))
    ver = int(data.pop(_LAYOUT_KEY, 0))
    if ver != LAYOUT_VERSION and not allow_legacy_layout:
        raise ValueError(
            f"checkpoint {path!r} has layout version {ver}, this build "
            f"expects {LAYOUT_VERSION} (channels-last flip changed in-memory "
            f"flatten order without changing parameter shapes). Re-export "
            f"the checkpoint, or pass allow_legacy_layout=True if you know "
            f"it was saved by this layout era."
        )

    if like is None:
        # rebuild nested dicts; integer keys become dicts too
        root: dict = {}
        for key, val in data.items():
            parts = key.split("/")
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = val
        return root

    import jax

    def rebuild(template, prefix=""):
        if isinstance(template, dict):
            return {k: rebuild(v, f"{prefix}{k}/") for k, v in template.items()}
        if isinstance(template, (list, tuple)):
            seq = [rebuild(v, f"{prefix}{i}/") for i, v in enumerate(template)]
            if isinstance(template, tuple):
                # NamedTuples (e.g. optax optimizer states) construct from
                # positional fields, plain tuples from one iterable
                return (type(template)(*seq) if hasattr(template, "_fields")
                        else tuple(seq))
            return seq
        if template is None:
            return None  # None leaves are not saved (empty subtrees)
        key = prefix[:-1]
        if key not in data:
            raise KeyError(f"checkpoint missing parameter {key!r}")
        arr = data[key]
        t = jax.device_get(template)
        if np.shape(t) != arr.shape:
            raise ValueError(
                f"shape mismatch for {key!r}: checkpoint {arr.shape} vs "
                f"template {np.shape(t)}"
            )
        return arr.astype(np.asarray(t).dtype)

    return rebuild(like)
