"""Legacy profiling shims — superseded by ``distmlip_tpu.telemetry``.

.. deprecated::
    ``StepTimer`` is subsumed by ``telemetry.AggregatingSink`` (same
    aggregation, plus percentiles, occupancy, halo volumes, and the shared
    ``StepRecord`` schema), and ``device_trace`` now lives in
    ``telemetry.trace`` where it also enables host-side TraceAnnotations.
    Both remain importable from here so existing scripts keep working; new
    code should attach a ``telemetry.Telemetry`` hub to ``DistPotential``
    instead of reading ``last_timings``.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

from ..telemetry.trace import device_trace  # noqa: F401 - re-export

__all__ = ["StepTimer", "device_trace"]


class StepTimer:
    """Aggregates named phase timings across steps; prints a summary.

    .. deprecated:: use ``telemetry.AggregatingSink`` (accepts the same
        ``add(timings)`` dict surface and full StepRecords).
    """

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def add(self, timings: dict[str, float]):
        for k, v in timings.items():
            self.totals[k] += v
            self.counts[k] += 1

    def summary(self) -> str:
        lines = ["phase                    total_s   mean_ms  calls"]
        for k in sorted(self.totals, key=self.totals.get, reverse=True):
            n = max(self.counts[k], 1)
            lines.append(
                f"{k:<24} {self.totals[k]:8.3f} {1e3 * self.totals[k] / n:9.2f} {n:6d}"
            )
        return "\n".join(lines)
