"""Profiling / tracing utilities.

Reference analogues: C TIMING macros + torch.profiler ranges (SURVEY.md §5).
Here: jax.profiler traces for device timelines plus a lightweight host-side
step timer that aggregates the per-phase breakdown DistPotential records.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict


@contextlib.contextmanager
def device_trace(logdir: str):
    """jax.profiler trace context; view with tensorboard or xprof."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Aggregates named phase timings across steps; prints a summary."""

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def add(self, timings: dict[str, float]):
        for k, v in timings.items():
            self.totals[k] += v
            self.counts[k] += 1

    def summary(self) -> str:
        lines = ["phase                    total_s   mean_ms  calls"]
        for k in sorted(self.totals, key=self.totals.get, reverse=True):
            n = max(self.counts[k], 1)
            lines.append(
                f"{k:<24} {self.totals[k]:8.3f} {1e3 * self.totals[k] / n:9.2f} {n:6d}"
            )
        return "\n".join(lines)
