"""Shared device-memory statistics.

ONE implementation of the backend memory-stat parsing used by the
calculator's prefetch HBM guard, the batched engine's headroom telemetry,
the telemetry report's device-memory rendering and the static HBM planner
(``analysis/memory.py`` consumers) — historically two private helpers on
``calculators/calculator.py``, deduplicated here so every consumer agrees
on what "worst-device occupancy" means.

CPU backends report no stats: every function degrades to ``{}``/``None``
(telemetry must never fail a step)."""

from __future__ import annotations


def device_memory_stats() -> dict:
    """Per-device ``bytes_in_use`` (and ``peak_bytes_in_use``/``bytes_limit``
    where reported) from backends that expose memory stats (TPU/GPU; CPU
    returns {}). Keys are ``dev<i>_bytes_in_use``-style."""
    import jax

    out = {}
    try:
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and "bytes_in_use" in stats:
                out[f"dev{d.id}_bytes_in_use"] = int(stats["bytes_in_use"])
                if "peak_bytes_in_use" in stats:
                    out[f"dev{d.id}_peak_bytes_in_use"] = int(
                        stats["peak_bytes_in_use"])
                if "bytes_limit" in stats:
                    out[f"dev{d.id}_bytes_limit"] = int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 - telemetry must never fail a step
        return {}
    return out


def hbm_usage_frac(stats: dict | None = None) -> float | None:
    """Worst-device ``bytes_in_use / bytes_limit``, or None when the
    backend reports no limits (CPU). ``stats`` lets callers reuse one
    snapshot (and the report parse recorded ``device_memory`` dicts)."""
    stats = device_memory_stats() if stats is None else stats
    worst = None
    for k, used in stats.items():
        if not k.endswith("_bytes_in_use") or "peak" in k:
            continue
        limit = stats.get(k.replace("_bytes_in_use", "_bytes_limit"), 0)
        if limit > 0:
            frac = used / limit
            worst = frac if worst is None else max(worst, frac)
    return worst


def measured_peak_bytes(stats: dict | None = None) -> int | None:
    """Worst-device measured peak residency: ``peak_bytes_in_use`` where
    the backend reports it, else current ``bytes_in_use``. None when no
    device reports either (CPU). What the static planner's
    ``est_peak_bytes`` is compared against for estimator-drift checks.

    Caveat: ``peak_bytes_in_use`` is a PROCESS-LIFETIME high-water mark,
    not the last program's peak — on a mixed run it may reflect an
    earlier, larger phase. Drift checks therefore only trust the ratio
    in the direction the mark bounds: measured >= any true program peak,
    so est >> measured is a sound over-estimation signal while
    est << measured is inconclusive."""
    stats = device_memory_stats() if stats is None else stats
    peaks = [v for k, v in stats.items()
             if k.endswith("_peak_bytes_in_use")]
    if peaks:
        return max(peaks)
    used = [v for k, v in stats.items()
            if k.endswith("_bytes_in_use") and "peak" not in k]
    return max(used) if used else None


def device_bytes_limit(stats: dict | None = None) -> int | None:
    """Smallest per-device ``bytes_limit`` (the binding constraint on a
    homogeneous mesh), or None when no device reports one (CPU). The HBM
    budget every memory-aware consumer plans against."""
    stats = device_memory_stats() if stats is None else stats
    limits = [v for k, v in stats.items() if k.endswith("_bytes_limit")]
    return min(limits) if limits else None


__all__ = ["device_memory_stats", "hbm_usage_frac", "device_bytes_limit",
           "measured_peak_bytes"]
