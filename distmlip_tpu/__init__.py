"""DistMLIP-TPU: a TPU-native graph-parallel framework for machine-learning
interatomic potentials (MLIPs).

A ground-up JAX/XLA re-design of the capabilities of DistMLIP
(reference: /root/reference, survey: SURVEY.md): periodic neighbor-graph
construction on the host (C++/OpenMP), spatial graph partitioning with halo
regions, and graph-parallel GNN inference/training over a
``jax.sharding.Mesh`` with halo exchange as XLA collectives
(``shard_map`` + ``ppermute``) instead of cross-GPU tensor copies.

Dtype policy (reference: DistMLIP/__init__.py:9-33): a process-global default
float/int width used by graph construction and models. On TPU the compute
dtype additionally supports bfloat16 for the matmul-heavy paths.
"""

from __future__ import annotations

import numpy as np

__version__ = "0.1.0"

# Latency-hiding XLA flags for the overlap-aware halo pipeline must be in
# the environment BEFORE the XLA backend initializes — and nearly every
# entry point (DistPotential.__init__, bench.py) touches jax.devices()
# long before the first graph_mesh() call. Importing distmlip_tpu is the
# one hook that reliably precedes backend init, so apply them here
# (no-op unless a TPU platform is requested / DISTMLIP_LATENCY_HIDING=1 —
# see parallel/mesh.py).
from .parallel.mesh import ensure_latency_hiding_flags as _lh

_lh()
del _lh

# ---------------------------------------------------------------------------
# Global dtype registry.
#
# float_np/int_np: host-side (numpy) graph arrays.
# float_jax: device-side feature/parameter dtype.
# Neighbor search always runs in float64 on the host regardless of this
# setting (matches the reference's C layer, fpis.c).
# ---------------------------------------------------------------------------
float_np = np.float32
int_np = np.int32
_compute_dtype = "float32"  # "float32" | "bfloat16"


def set_default_dtype(type_: str = "float", size: int = 32) -> None:
    """Set the process-global default dtypes.

    Mirrors the reference API (DistMLIP/__init__.py:15-33) but without a
    torch dependency: sets numpy dtypes used for graph arrays.
    """
    global float_np, int_np
    if type_ != "float":
        raise ValueError(f"Unsupported type {type_!r}; only 'float'.")
    if size == 32:
        float_np, int_np = np.float32, np.int32
    elif size == 64:
        float_np, int_np = np.float64, np.int64
    else:
        raise ValueError(f"Unsupported float size {size}; use 32 or 64.")


def set_compute_dtype(name: str) -> None:
    """Set the on-device compute dtype ("float32" or "bfloat16")."""
    global _compute_dtype
    if name not in ("float32", "bfloat16"):
        raise ValueError(name)
    _compute_dtype = name


def compute_dtype():
    import jax.numpy as jnp

    return jnp.bfloat16 if _compute_dtype == "bfloat16" else jnp.float32


from . import geometry  # noqa: E402,F401
from . import telemetry  # noqa: E402,F401
